"""Mesh/parallelism package.

Exports resolve LAZILY (PEP 562): model-zoo leaf modules (models/bst.py,
models/wide_tower.py) import `paddlebox_tpu.parallel.*` primitives, and an
eager package __init__ would cycle back through sharded_trainer →
train.trainer → models/__init__ → those same leaves.
"""

_EXPORTS = {
    "make_mesh": ("paddlebox_tpu.parallel.mesh", "make_mesh"),
    "device_mesh_1d": ("paddlebox_tpu.parallel.mesh", "device_mesh_1d"),
    "device_mesh_2d": ("paddlebox_tpu.parallel.mesh", "device_mesh_2d"),
    "GPipeRunner": ("paddlebox_tpu.parallel.pipeline", "GPipeRunner"),
    "PipelineConfig": ("paddlebox_tpu.parallel.pipeline", "PipelineConfig"),
    "mlp_stage_apply": ("paddlebox_tpu.parallel.pipeline",
                        "mlp_stage_apply"),
    "CtrPipelineRunner": ("paddlebox_tpu.parallel.pipeline",
                          "CtrPipelineRunner"),
    "ShardedCtrPipelineRunner": ("paddlebox_tpu.parallel.pipeline",
                                 "ShardedCtrPipelineRunner"),
    "ShardedPassTable": ("paddlebox_tpu.parallel.sharded_table",
                         "ShardedPassTable"),
    "ShardedBatchIndex": ("paddlebox_tpu.parallel.sharded_table",
                          "ShardedBatchIndex"),
    "ShardedBoxTrainer": ("paddlebox_tpu.parallel.sharded_trainer",
                          "ShardedBoxTrainer"),
    "MeshTowerTrainer": ("paddlebox_tpu.parallel.mesh_tower",
                         "MeshTowerTrainer"),
    "SeqCtrTrainer": ("paddlebox_tpu.parallel.seq_trainer",
                      "SeqCtrTrainer"),
    "ring_attention": ("paddlebox_tpu.parallel.ring_attention",
                       "ring_attention"),
    "ulysses_attention": ("paddlebox_tpu.parallel.ring_attention",
                          "ulysses_attention"),
    "tp_mlp_apply": ("paddlebox_tpu.parallel.tensor_parallel",
                     "tp_mlp_apply"),
    "tp_loss_scale": ("paddlebox_tpu.parallel.tensor_parallel",
                      "tp_loss_scale"),
    "tp_fix_grads": ("paddlebox_tpu.parallel.tensor_parallel",
                     "tp_fix_grads"),
    "ep_experts_apply": ("paddlebox_tpu.parallel.tensor_parallel",
                         "ep_experts_apply"),
    "ep_gate_psum": ("paddlebox_tpu.parallel.tensor_parallel",
                     "ep_gate_psum"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    return getattr(importlib.import_module(mod_name), attr)


def __dir__():
    return __all__
