"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no sequence parallelism (SURVEY.md §2.8/§5.7 — a CTR
framework pools slots instead of attending over tokens), but this framework
treats long-context as first-class: if attention models join the zoo (e.g.
behavior-sequence rank models), these primitives slot into the same 1D mesh
axis the sparse table shards over.

ring_attention: K/V blocks rotate around the ICI ring via ppermute while
each device keeps its Q shard, accumulating an online-softmax (flash-style
m/l/o state) — sequence length scales linearly with devices and memory
stays O(T_local). Differentiable (scan+ppermute transpose cleanly).

ulysses_attention: all_to_all re-shards [B, T/P, H, Dh] → [B, T, H/P, Dh]
so each device runs full-sequence attention on a head slice, then a2a back
(head-parallel attention; one a2a pair instead of P-1 ring hops — better
when heads ≥ devices and the a2a fits ICI).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _block_attn_update(q, k, v, m, l, o, k_pos, q_pos, causal, scale):
    """One flash-attention accumulation step against a K/V block.

    q: [B, Tq, H, Dh]; k/v: [B, Tk, H, Dh]; m/l: [B, H, Tq]; o like q.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = q_pos[None, None, :, None] >= k_pos[None, None, None, :]
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) → nan
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    if causal:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr[..., None].swapaxes(1, 2) + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = False,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Blockwise ring attention over a sequence-sharded axis.

    q, k, v: [B, T_local, H, Dh] per device (call inside shard_map).
    Returns [B, T_local, H, Dh].
    """
    P = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, T, H, Dh = q.shape
    scale = scale if scale is not None else 1.0 / (Dh ** 0.5)
    q_pos = idx * T + jnp.arange(T)

    # pcast-to-varying: the scan carry becomes device-varying (k_pos
    # depends on axis_index), so the initial constants must carry the
    # same vma type
    m0 = jax.lax.pcast(jnp.full((B, H, T), -jnp.inf, q.dtype),
                       (axis_name,), to="varying")
    l0 = jax.lax.pcast(jnp.zeros((B, H, T), q.dtype),
                       (axis_name,), to="varying")
    o0 = jnp.zeros_like(q)
    perm = [(i, (i + 1) % P) for i in range(P)]

    def body(carry, step):
        kb, vb, m, l, o = carry
        src = (idx - step) % P  # which device's block we now hold
        k_pos = src * T + jnp.arange(T)
        m, l, o = _block_attn_update(q, kb, vb, m, l, o, k_pos, q_pos,
                                     causal, scale)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (kb, vb, m, l, o), None

    (kb, vb, m, l, o), _ = jax.lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(P))
    l_safe = jnp.where(l > 0, l, 1.0)
    return o / l_safe[..., None].swapaxes(1, 2)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str, causal: bool = False,
                      scale: Optional[float] = None) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses style) sequence→head re-sharding.

    q, k, v: [B, T_local, H, Dh] with H divisible by the axis size.
    """
    P = jax.lax.axis_size(axis_name)
    B, T, H, Dh = q.shape
    if H % P:
        raise ValueError(f"heads {H} not divisible by axis size {P}")

    def seq2head(x):  # [B, T, H, Dh] → [B, T*P, H/P, Dh]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def head2seq(x):  # inverse
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    Tg = qg.shape[1]
    scale = scale if scale is not None else 1.0 / (Dh ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg) * scale
    if causal:
        pos = jnp.arange(Tg)
        s = jnp.where(pos[None, None, :, None] >= pos[None, None, None, :],
                      s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vg)
    return head2seq(out)
