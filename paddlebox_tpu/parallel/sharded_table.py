"""Pod-sharded pass table: the multi-chip BoxPS/HeterComm engine on ICI.

Re-design of HeterComm (paddle/fluid/framework/fleet/heter_ps/heter_comm_inl.h)
for the TPU: the reference shards its hash table by ``key % num_devices``
(split_input_to_shard, inl:1117) and moves key/value traffic over explicit
p2p copies (walk_to_dest/walk_to_src, inl:273,1296-1445). Here:

  * each mesh device owns one dense per-pass shard slab [shard_cap, width]
    (the feed pass gives the exact key set per shard — same dense-slab
    trick as the single-chip PassTable);
  * the host packer pre-buckets each batch's keys by destination shard into
    fixed [num_shards, bucket_cap] local-id buckets + a restore index
    (the DedupKeysAndFillIdx analog, host-side);
  * pull = all_to_all(id buckets) → local gather → all_to_all(values) →
    restore; push = scatter-merge grads into buckets → all_to_all →
    local dedup + in-table optimizer. The two all_to_alls ARE
    walk_to_dest/walk_to_src, riding ICI as XLA collectives.

Everything device-side is static-shaped and lives inside ONE shard_map'd
train step (parallel/sharded_trainer.py), so XLA overlaps the a2a with the
dense compute where profitable.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config.configs import TableConfig
from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
from paddlebox_tpu.embedding.native_store import make_host_store
from paddlebox_tpu.utils.stats import stat_add


@dataclasses.dataclass
class ShardedBatchIndex:
    """Host-built routing for one batch's keys (static shapes).

    buckets:  [P, KB] int32 — per-destination-shard LOCAL ids (dedup'd per
              batch); padding slots hold shard_cap-1 (the trash row)
    restore:  [K] int32 — flattened bucket slot (s*KB + j) for each of the
              batch's K key positions (occurrences of the same key share a
              slot); invalid key positions point at slot 0 and must be
              masked by the batch's `valid`
    overflow: keys dropped because a shard bucket filled up
    """

    buckets: np.ndarray
    restore: np.ndarray
    overflow: int


class ShardedPassTable:
    """Host-side orchestration of P shard slabs with the BoxPS pass cadence.

    Device arrays are produced per pass as a stacked [P, shard_cap, width]
    global array to be sharded over the mesh axis; the device compute lives
    in sharded_trainer's shard_map step.
    """

    def __init__(self, table: TableConfig, num_shards: int,
                 bucket_cap: int, seed: int = 0) -> None:
        self.config = table
        self.layout = ValueLayout(table.embedx_dim, table.optimizer.optimizer)
        self.push_layout = PushLayout(table.embedx_dim)
        self.num_shards = num_shards
        self.bucket_cap = bucket_cap
        if table.pass_capacity % num_shards:
            raise ValueError("pass_capacity must divide evenly into shards")
        self.shard_cap = table.pass_capacity // num_shards
        self.stores = [make_host_store(self.layout, table, seed + s)
                       for s in range(num_shards)]
        self._feed_keys: List[np.ndarray] = []
        self._shard_keys: Optional[List[np.ndarray]] = None  # sorted unique per shard
        self._in_feed_pass = False
        self._test_mode = False

    # ------------------------------------------------------- pass lifecycle
    def begin_feed_pass(self) -> None:
        if self._in_feed_pass:
            raise RuntimeError("feed pass already open")
        self._feed_keys = []
        self._in_feed_pass = True

    def add_keys(self, keys: np.ndarray) -> None:
        if not self._in_feed_pass:
            raise RuntimeError("add_keys outside feed pass")
        self._feed_keys.append(np.asarray(keys, dtype=np.uint64))

    def end_feed_pass(self) -> None:
        if not self._in_feed_pass:
            raise RuntimeError("end_feed_pass without begin_feed_pass")
        allk = (np.unique(np.concatenate(self._feed_keys))
                if self._feed_keys else np.empty(0, np.uint64))
        P = np.uint64(self.num_shards)
        self._shard_keys = []
        for s in range(self.num_shards):
            ks = allk[allk % P == np.uint64(s)]  # sorted (allk sorted)
            if ks.size > self.shard_cap - 1:
                raise RuntimeError(
                    f"shard {s} working set {ks.size} exceeds shard capacity "
                    f"{self.shard_cap} (raise TableConfig.pass_capacity)")
            self._shard_keys.append(ks)
        self._feed_keys = []
        self._in_feed_pass = False

    def build_slabs(self) -> np.ndarray:
        """BeginPass: promote all shards' working sets → [P, C, W] host array
        (caller device_puts it with the mesh sharding)."""
        if self._shard_keys is None:
            raise RuntimeError("build_slabs before feed pass completed")
        P, C, W = self.num_shards, self.shard_cap, self.layout.width
        slabs = np.zeros((P, C, W), dtype=np.float32)
        for s, ks in enumerate(self._shard_keys):
            if ks.size:
                rows = (self.stores[s].lookup(ks) if self._test_mode
                        else self.stores[s].lookup_or_create(ks))
                slabs[s, :ks.size] = rows
        return slabs

    def write_back(self, slabs: np.ndarray) -> None:
        """EndPass: [P, C, W] host array → shard stores."""
        if self._test_mode:
            return
        for s, ks in enumerate(self._shard_keys or []):
            if ks.size:
                self.stores[s].write_back(ks, slabs[s, :ks.size])

    def set_test_mode(self, test: bool) -> None:
        self._test_mode = test

    @property
    def pass_size(self) -> int:
        return sum(k.size for k in self._shard_keys or [])

    # ---------------------------------------------------------- batch index
    def bucketize(self, keys: np.ndarray, valid: np.ndarray) -> ShardedBatchIndex:
        """Route one batch's keys: shard = key % P (split_input_to_shard,
        heter_comm_inl.h:1117), local id by searchsorted in the shard's
        sorted pass key list, batch-level dedup into bucket slots."""
        if self._shard_keys is None:
            raise RuntimeError("no active pass key set")
        P, KB = self.num_shards, self.bucket_cap
        trash = self.shard_cap - 1
        buckets = np.full((P, KB), trash, dtype=np.int32)
        restore = np.zeros(keys.shape[0], dtype=np.int32)
        fill = np.zeros(P, dtype=np.int64)
        # per-batch dedup: map key → assigned slot
        slot_of: dict = {}
        overflow = 0
        kv = keys.tolist()
        sv = (keys % np.uint64(P)).tolist()
        for i in range(keys.shape[0]):
            if not valid[i]:
                continue
            k = kv[i]
            slot = slot_of.get(k)
            if slot is None:
                s = sv[i]
                if fill[s] >= KB:
                    overflow += 1
                    valid[i] = False
                    continue
                sk = self._shard_keys[s]
                pos = np.searchsorted(sk, k)
                if pos >= sk.size or sk[pos] != k:
                    raise KeyError(f"key {k} not registered in feed pass")
                j = int(fill[s])
                buckets[s, j] = pos
                fill[s] += 1
                slot = s * KB + j
                slot_of[k] = slot
            restore[i] = slot
        if overflow:
            stat_add("sharded_bucket_overflow", overflow)
        return ShardedBatchIndex(buckets=buckets, restore=restore,
                                 overflow=overflow)

    # ------------------------------------------------------------ lifecycle
    def shrink_table(self) -> int:
        return sum(st.shrink() for st in self.stores)

    def save(self, path_prefix: str) -> None:
        for s, st in enumerate(self.stores):
            st.save(f"{path_prefix}.shard{s:03d}")

    def load(self, path_prefix: str) -> None:
        for s, st in enumerate(self.stores):
            st.load(f"{path_prefix}.shard{s:03d}")
