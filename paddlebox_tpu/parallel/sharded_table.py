"""Pod-sharded pass table: the multi-chip BoxPS/HeterComm engine on ICI.

Re-design of HeterComm (paddle/fluid/framework/fleet/heter_ps/heter_comm_inl.h)
for the TPU: the reference shards its hash table by ``key % num_devices``
(split_input_to_shard, inl:1117) and moves key/value traffic over explicit
p2p copies (walk_to_dest/walk_to_src, inl:273,1296-1445). Here:

  * each mesh device owns one dense per-pass shard slab [shard_cap, width]
    (the feed pass gives the exact key set per shard — same dense-slab
    trick as the single-chip PassTable);
  * the host packer pre-buckets each batch's keys by destination shard into
    fixed [num_shards, bucket_cap] local-id buckets + a restore index
    (the DedupKeysAndFillIdx analog, host-side);
  * pull = all_to_all(id buckets) → local gather → all_to_all(values) →
    restore; push = scatter-merge grads into buckets → all_to_all →
    local dedup + in-table optimizer. The two all_to_alls ARE
    walk_to_dest/walk_to_src, riding ICI as XLA collectives.

Everything device-side is static-shaped and lives inside ONE shard_map'd
train step (parallel/sharded_trainer.py), so XLA overlaps the a2a with the
dense compute where profitable.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.config.configs import TableConfig
from paddlebox_tpu.embedding.accessor import (PushLayout, ValueLayout,
                                              decode_slab_rows_np,
                                              encode_slab_rows_np)
from paddlebox_tpu.embedding.native_store import make_host_store
from paddlebox_tpu.obs import beat as obs_beat
from paddlebox_tpu.obs.tracer import record_span
from paddlebox_tpu.utils.stats import hist_observe, stat_add
from paddlebox_tpu.utils.lockwatch import make_lock


_warned_numpy_route = False


def _route_lib():
    """Native router (route.cc) or None → vectorized numpy fallback.
    The fallback is LOUD (warn once + stat): numpy manages ~1M keys/s vs
    the native router's ~13M, which at pass scale is a real regression."""
    from paddlebox_tpu.native.build import get_lib
    lib = get_lib()
    if lib is not None and hasattr(lib, "rt_bucketize"):
        return lib
    global _warned_numpy_route
    if not _warned_numpy_route:
        _warned_numpy_route = True
        import logging
        logging.getLogger("paddlebox_tpu").warning(
            "sharded route: native router unavailable — numpy bucketize "
            "fallback active (~13x slower key routing)")
        stat_add("route_numpy_fallback")
    return None


@dataclasses.dataclass
class ShardedBatchIndex:
    """Host-built routing for one batch's keys (static shapes).

    buckets:  [P, KB] int32 — per-destination-shard LOCAL ids (dedup'd per
              batch); padding slots hold shard_cap-1 (the trash row)
    restore:  [K] int32 — flattened bucket slot (s*KB + j) for each of the
              batch's K key positions (occurrences of the same key share a
              slot); invalid key positions point at slot 0 and must be
              masked by the batch's `valid`
    overflow: keys dropped because a shard bucket filled up
    """

    buckets: np.ndarray
    restore: np.ndarray
    overflow: int


def exchange_outgoing_buckets(buckets_local: np.ndarray,
                              local_positions: List[int],
                              num_devices: int,
                              all_gather) -> np.ndarray:
    """Cluster-wide per-step bucket exchange (round-5 verdict item 2):
    every process contributes its LOCAL source devices' outgoing id
    buckets and receives the GLOBAL [num_devices(src), P, KB] array in
    mesh-device order — which makes each destination shard's incoming
    a2a ids host-known everywhere, so the scatter-free push (host dedup +
    pos maps) works at jax.process_count() > 1. This is the host-plane
    twin of the device a2a (the reference routes cluster-wide on device:
    dedup_keys_and_fillidx + split_input_to_shard,
    heter_comm_inl.h:2231,1117).

    buckets_local: [n_local, P, KB] int32, in local-position order.
    all_gather: fleet.all_gather (any rank order — each part carries its
    own global positions in a header, so fleet rank need not equal jax
    process index).
    """
    import time as _time
    bl = np.ascontiguousarray(buckets_local, np.int32)
    n_local, P, KB = bl.shape
    t0 = _time.perf_counter()
    header = np.array([n_local, P, KB] + list(local_positions), np.int32)
    payload = np.concatenate([header, bl.ravel()])
    out = np.empty((num_devices, P, KB), np.int32)
    seen = np.zeros(num_devices, bool)
    gathered = all_gather(payload)
    for part in gathered:
        part = np.asarray(part, np.int32)
        nl, p2, kb2 = part[0], part[1], part[2]
        if (p2, kb2) != (P, KB):
            raise ValueError(
                f"bucket-exchange shape mismatch: peer sent P={p2},"
                f"KB={kb2}, local is P={P},KB={KB}")
        pos = part[3:3 + nl]
        bufs = part[3 + nl:].reshape(nl, P, KB)
        out[pos] = bufs
        seen[pos] = True
    if not seen.all():
        raise RuntimeError(
            "bucket exchange incomplete: no contribution for device "
            f"positions {np.nonzero(~seen)[0].tolist()}")
    # wire attribution (weak #6): this rank writes its payload once and
    # reads every rank's back through the central store
    t1 = _time.perf_counter()
    stat_add("hostplane_exchange_bytes",
             int(payload.nbytes) * (1 + len(gathered)))
    stat_add("hostplane_exchange_us", int((t1 - t0) * 1e6))
    stat_add("hostplane_exchange_steps")
    hist_observe("hostplane_exchange_us", (t1 - t0) * 1e6)
    record_span("hostplane_store_exchange", t0, t1)
    # the store funnel is the progress boundary on the hostplane=store
    # plane (the p2p plane beats inside MeshComm.exchange)
    obs_beat("store_exchange")
    return out


def _mesh_dest_plan(mesh, local_positions, num_devices: int, policy=None):
    """Per-peer destination lists for the p2p exchanges. Round 13: the
    plan is POLICY-OWNED (parallel/sharding.py) — the policy decides
    which peers a rank exchanges with; `None` keeps the validated
    owner-map default every shipped policy rides (and the pre-policy
    behavior, bit-for-bit)."""
    from paddlebox_tpu.parallel.sharding import default_dest_plan
    plan = policy.dest_plan if policy is not None else default_dest_plan
    return plan(mesh, local_positions, num_devices)


def exchange_incoming_p2p(buckets_local: np.ndarray,
                          local_positions: List[int],
                          num_devices: int, mesh, policy=None):
    """P2P twin of exchange_outgoing_buckets (the tentpole a2a): rank r
    ships the owner of destination shard d ONLY its buckets[:, d, :]
    column — O(W*P*KB) direct bytes per step instead of every rank's full
    [n_local, P, KB] set bouncing through the central store
    (O(W^2*P*KB) through one NIC). Returns {d: [num_devices, KB] int32}
    incoming-id arrays in global source-device order for this process's
    OWNED destinations — exactly the concatenation stage_push_dedup's
    per-destination dedup consumes, so the staging products stay
    bit-identical to the store path.
    """
    import time as _time
    bl = np.ascontiguousarray(buckets_local, np.int32)
    n_local, P, KB = bl.shape
    dest_of_rank = _mesh_dest_plan(mesh, local_positions, num_devices,
                                   policy)
    t0 = _time.perf_counter()
    parts = {}
    for r, dests in enumerate(dest_of_rank):
        # header: n_local, KB, n_dests, src positions..., dest positions...
        header = np.array([n_local, KB, len(dests)]
                          + list(local_positions) + list(dests), np.int32)
        parts[r] = np.concatenate(
            [header, bl[:, dests, :].ravel()])
    got = mesh.exchange(parts)
    mine = dest_of_rank[mesh.rank]
    out = {d: np.empty((num_devices, KB), np.int32) for d in mine}
    seen = np.zeros(num_devices, bool)
    for part in got.values():
        part = np.asarray(part, np.int32)
        nl, kb2, nd = int(part[0]), int(part[1]), int(part[2])
        if kb2 != KB:
            raise ValueError("p2p bucket exchange KB mismatch: peer sent "
                             "KB=%d, local is KB=%d" % (kb2, KB))
        srcs = part[3:3 + nl]
        dests = part[3 + nl:3 + nl + nd]
        if sorted(dests.tolist()) != sorted(mine):
            raise ValueError(
                "p2p bucket exchange routed to the wrong owner: got "
                "destinations %s, own %s" % (dests.tolist(), mine))
        block = part[3 + nl + nd:].reshape(nl, nd, KB)
        for j, d in enumerate(dests.tolist()):
            out[d][srcs] = block[:, j, :]
        seen[srcs] = True
    if not seen.all():
        raise RuntimeError(
            "p2p bucket exchange incomplete: no contribution for source "
            f"positions {np.nonzero(~seen)[0].tolist()}")
    # like-for-like NIC accounting with the store path (which counts its
    # 1 write + W reads): sends to W-1 peers PLUS receives from W-1 peers
    wire = sum(int(p.nbytes) for r, p in parts.items() if r != mesh.rank) \
        + sum(int(p.nbytes) for r, p in got.items() if r != mesh.rank)
    t1 = _time.perf_counter()
    stat_add("hostplane_exchange_bytes", wire)
    stat_add("hostplane_exchange_us", int((t1 - t0) * 1e6))
    stat_add("hostplane_exchange_steps")
    hist_observe("hostplane_exchange_us", (t1 - t0) * 1e6)
    record_span("hostplane_p2p_exchange", t0, t1)
    return out


def exchange_push_uids_p2p(buckets_local: np.ndarray,
                           local_positions: List[int], num_devices: int,
                           shard_cap: int, mesh, pool=None, policy=None):
    """Dedup BEFORE the network (composes the round-8 uid wire with the
    p2p mesh): for every destination shard this rank sorts-uniques its
    LOCAL contribution and ships the owner only that vector; the owner
    unions the per-source vectors — the same id set, hence bit-identical
    dedup_uids_sorted products, as deduping the full concatenation after
    a raw exchange, at a fraction of the wire bytes (duplicates never
    travel). Returns {d: uids[num_devices*KB] int32} for owned
    destinations, tail-padded exactly like dedup_uids_sorted.

    pool: optional thread pool for the num_devices sender-side np.unique
    calls (the dominant pre-wire cost; the sort releases the GIL) — the
    runners pass their stager pool.

    policy (round 13): a parallel/sharding.ShardingPolicy — owns the
    per-peer dest plan, and when it carries a frozen replicated hot tier
    (2d-grid) the hot local ids are DROPPED from every shipped vector
    and re-added whole by the owner: replicated rows never travel, and
    since the hot set is cluster-agreed at the pass freeze the union
    still covers every id the destination's device a2a will carry. The
    staged vector over-approximates by hot ids that skipped this step —
    their merged gradients are zero, a value-level no-op in the
    in-table optimizer (the replication premise: hot rows are touched
    essentially every step)."""
    import time as _time
    bl = np.ascontiguousarray(buckets_local, np.int32)
    n_local, P, KB = bl.shape
    K = num_devices * KB
    # same contract dedup_uids_sorted enforces on the post-wire path: a
    # negative id would sort FIRST and silently shift every device-side
    # searchsorted mapping instead of failing loud
    if bl.size and int(bl.min()) < 0:
        raise ValueError("exchange_push_uids_p2p expects nonnegative "
                         "int32 pass-local ids")
    dest_of_rank = _mesh_dest_plan(mesh, local_positions, num_devices,
                                   policy)
    hot_of = (policy.hot_local_ids if policy is not None
              else (lambda d: None))
    t0 = _time.perf_counter()
    mapper = pool.map if pool is not None else map

    def uniq_dest(d):
        from paddlebox_tpu.embedding.pass_table import sorted_member
        u = np.unique(bl[:, d, :])
        hot = hot_of(d)
        if hot is not None and hot.size and u.size:
            # replicated ids never travel: both vectors sorted, one
            # membership probe
            u = u[~sorted_member(hot, u)[1]]
        return u

    uniq_of = list(mapper(uniq_dest, range(num_devices)))
    parts = {}
    for r, dests in enumerate(dest_of_rank):
        uniqs = [uniq_of[d] for d in dests]
        lens = [u.size for u in uniqs]
        header = np.array([KB, len(dests)] + list(dests) + lens, np.int32)
        parts[r] = np.concatenate([header] + uniqs)
    got = mesh.exchange(parts)
    mine = dest_of_rank[mesh.rank]
    vecs = {d: [] for d in mine}
    for part in got.values():
        part = np.asarray(part, np.int32)
        kb2, nd = int(part[0]), int(part[1])
        if kb2 != KB:
            raise ValueError("p2p uid exchange KB mismatch: peer sent "
                             "KB=%d, local is KB=%d" % (kb2, KB))
        dests = part[2:2 + nd].tolist()
        lens = part[2 + nd:2 + 2 * nd]
        offs = np.concatenate([[0], np.cumsum(lens)]) + 2 + 2 * nd
        for j, d in enumerate(dests):
            vecs[d].append(part[offs[j]:offs[j + 1]])
    out = {}
    for d in mine:
        hot = hot_of(d)
        if hot is not None and hot.size:
            # the owner re-adds its whole replicated set (sorted int32)
            vecs[d].append(np.asarray(hot, np.int32))
        uniq = np.unique(np.concatenate(vecs[d]))
        uids = np.empty(K, np.int32)
        n = uniq.size
        if n > K:
            raise RuntimeError(
                "p2p uid exchange: union of %d incoming + replicated "
                "ids exceeds the staged vector length %d for dest %d — "
                "sharding_hot_cap/bucket_cap are inconsistent" % (n, K, d))
        uids[:n] = uniq
        uids[n:] = shard_cap + np.arange(K - n, dtype=np.int32)
        out[d] = uids
    # sends + receives, matching the store path's 1-write + W-reads count
    wire = sum(int(p.nbytes) for r, p in parts.items() if r != mesh.rank) \
        + sum(int(p.nbytes) for r, p in got.items() if r != mesh.rank)
    t1 = _time.perf_counter()
    stat_add("hostplane_exchange_bytes", wire)
    stat_add("hostplane_exchange_us", int((t1 - t0) * 1e6))
    stat_add("hostplane_exchange_steps")
    hist_observe("hostplane_exchange_us", (t1 - t0) * 1e6)
    record_span("hostplane_uid_exchange", t0, t1)
    return out


def stage_push_dedup(buckets, local_positions, num_devices: int,
                     shard_cap: int, multiprocess: bool, all_gather,
                     rebuild: bool, pool, note_touched=None,
                     uid_only: bool = False, mesh=None,
                     sort_uids: bool = False, policy=None):
    """Per-destination push-dedup staging shared by BOTH sharded runners
    (trainer's _step_host_arrays + pipeline's device_batch): makes each
    shard's incoming a2a ids host-known (exchange_outgoing_buckets when
    multi-process), then fans per-destination dedup (+ rebuild pos maps)
    onto the stager pool. Returns {"push_uids": [...], "push_perm": ...,
    "push_inv": ..., ["push_pos": ...]} in destination order (owned
    destinations only in a multi-process job — the process-local piece
    of the [P, ...] global arrays).

    uid_only (h2d_uid_wire, round 8): stage ONLY the per-destination
    SORTED uid vector — the device step already holds each shard's
    incoming ids (the a2a'd buckets) and derives perm/inv (and the
    rebuild pos) by searchsorted against the sorted uids
    (push_sparse_uidwire). Cuts the per-step staged push wire from
    3-4 [P, P*KB]-shaped arrays to one, and the host dedup to one
    np.unique per destination; composes with the multi-process bucket
    exchange unchanged (the uids must still be host-known cluster-wide
    for the touched-row accounting and writeback delta).

    mesh (hostplane=p2p, round 9): a fleet MeshComm — the multi-process
    exchange rides the persistent p2p socket mesh instead of the store
    allgather: raw bucket columns a2a for the full-product wire, or the
    per-destination PRE-DEDUPED sorted uid vectors under uid_only (dedup
    moves before the network). Staging products are bit-identical to the
    store path either way. None = the store allgather (the loud-fallback
    target).

    policy (round 13): the ShardingPolicy that routed these buckets —
    the p2p exchanges ride its dest plan and (2d-grid) its replicated
    hot-key wire filter. None = the key-mod-equivalent default plan
    (bit-identical to the pre-policy path)."""
    from paddlebox_tpu.embedding.pass_table import (dedup_ids,
                                                    dedup_uids_sorted,
                                                    pos_for_rebuild)
    uids_by_dest = inc = global_buckets = None
    if multiprocess:
        dests = local_positions
        if mesh is not None and uid_only:
            uids_by_dest = exchange_push_uids_p2p(
                np.stack(buckets), local_positions, num_devices,
                shard_cap, mesh, pool=pool, policy=policy)
        elif mesh is not None:
            inc = exchange_incoming_p2p(
                np.stack(buckets), local_positions, num_devices, mesh,
                policy=policy)
        else:
            global_buckets = exchange_outgoing_buckets(
                np.stack(buckets), local_positions, num_devices,
                all_gather)
    else:
        global_buckets = buckets
        dests = range(num_devices)
    if inc is not None:
        incoming_of = lambda d: inc[d].reshape(-1)  # noqa: E731
    else:
        incoming_of = lambda d: np.concatenate(  # noqa: E731
            [global_buckets[src][d] for src in range(num_devices)])

    def dedup_dest(d):
        if uids_by_dest is not None:
            uids = uids_by_dest[d]
            perm = inv = None
        elif uid_only:
            uids = dedup_uids_sorted(incoming_of(d), shard_cap)
            perm = inv = None
        else:
            # sort_uids: push_write='blocked' consumes these products and
            # its device bucketize trusts sorted uids (see dedup_ids)
            uids, perm, inv = dedup_ids(incoming_of(d), shard_cap,
                                        sort=sort_uids)
        if note_touched is not None:
            # every id this destination shard will push rides these uids —
            # the per-pass touched-row accumulation point (incremental
            # EndPass writes back only these rows)
            note_touched(d, uids)
        pos = (pos_for_rebuild(uids, shard_cap)
               if rebuild and not uid_only else None)
        return uids, perm, inv, pos

    out = {"push_uids": []}
    if not uid_only:
        out.update(push_perm=[], push_inv=[])
    for uids, perm, inv, pos in pool.map(dedup_dest, dests):
        out["push_uids"].append(uids)
        if perm is not None:
            out["push_perm"].append(perm)
            out["push_inv"].append(inv)
        if pos is not None:
            out.setdefault("push_pos", []).append(pos)
    return out


class ShardedPassTable:
    """Host-side orchestration of P shard slabs with the BoxPS pass cadence.

    Device arrays are produced per pass as a stacked [P, shard_cap, width]
    global array to be sharded over the mesh axis; the device compute lives
    in sharded_trainer's shard_map step.
    """

    def __init__(self, table: TableConfig, num_shards: int,
                 bucket_cap: int, seed: int = 0,
                 owned_shards: Optional[List[int]] = None,
                 store_factory=None, policy=None) -> None:
        """owned_shards: in a multi-process job each process hosts the full
        store only for the shards whose mesh device it owns (the reference's
        per-node PS shard layout); None = own all (single process). Routing
        state (_shard_keys) is always GLOBAL — any batch may reference any
        shard.

        store_factory(layout, table, seed) -> store overrides the default
        local host store — e.g. embedding.ps_store.ps_store_factory puts
        the distributed CPU PS behind every shard (the GPUPS BuildPull/
        EndPass composition, ps_gpu_wrapper.cc:337,983).

        policy (round 13): the parallel/sharding.ShardingPolicy that owns
        key->shard routing (feed-pass assignment, per-batch bucketize,
        promote prefetch, checkpoint views all route through it); None =
        resolve from the sharding_policy flag (default key-mod, bit-
        identical to the pre-policy key % P path)."""
        from paddlebox_tpu.parallel.sharding import resolve_sharding_policy
        self.policy = policy or resolve_sharding_policy(num_shards)
        if self.policy.num_shards != num_shards:
            raise ValueError(
                "sharding policy built for %d shards, table has %d"
                % (self.policy.num_shards, num_shards))
        self.config = table
        from paddlebox_tpu.embedding.pass_table import _slab_embed_dtype
        self.layout = ValueLayout(table.embedx_dim, table.optimizer.optimizer,
                                  expand_dim=table.expand_embed_dim,
                                  embed_dtype=_slab_embed_dtype())
        self.push_layout = PushLayout(table.embedx_dim,
                                      table.expand_embed_dim)
        self.num_shards = num_shards
        self.bucket_cap = bucket_cap
        if table.pass_capacity % num_shards:
            raise ValueError("pass_capacity must divide evenly into shards")
        self.shard_cap = table.pass_capacity // num_shards
        self.owned_shards = (list(owned_shards) if owned_shards is not None
                             else list(range(num_shards)))
        owned = set(self.owned_shards)
        make_store = store_factory or make_host_store
        # the LIST is immutable after this line (ref-grabs and is-None
        # presence probes are lock-free by design); the store OBJECTS'
        # contents move under spill/resize, so any lookup/write_back while
        # a PromotePrefetcher can be live holds store_lock. Lock-free
        # boundary sites carry an explicit boxlint disable + rationale.
        self.stores = [make_store(self.layout, table, seed + s)  # guarded-by: store_lock
                       if s in owned else None
                       for s in range(num_shards)]
        self._feed_keys: List[np.ndarray] = []
        self._shard_keys: Optional[List[np.ndarray]] = None  # sorted unique per shard
        self._in_feed_pass = False
        self._test_mode = False
        self._route_index = None  # native pass index handle
        self._overflow_warned = False  # one warning per pass (reset per feed)
        # incremental pass lifecycle (per-shard host residency cache):
        # _res_keys[s]/_res_rows[s] mirror the rows the store holds for the
        # last built pass, so the next _build_one promotes only the key
        # DELTA (numpy row moves instead of store hash-gathers) and the
        # end-of-pass writeback touches only rows the pass pushed.
        self._res_keys: dict = {}
        self._res_rows: dict = {}
        self._touched_sh: Optional[dict] = None  # shard -> bool[shard_cap]
        self._touch_seen = False  # any mark this pass? (else full writeback)
        self._staged_sh: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self.store_lock = make_lock("ShardedPassTable.store_lock")
        # touched-row journal (round 15): when attached, every end-of-pass
        # write-back also appends its (keys, rows) delta, and the
        # out-of-cadence lifecycle mutations append event records
        self._journal = None

    # ------------------------------------------------------------- journal
    # setup-time wiring, called before any worker thread exists
    def attach_journal(self, journal) -> None:  # boxlint: disable=BX401
        """Attach a train.journal.TouchedRowJournal: end-of-pass write-
        backs append their touched (keys, rows) delta; end_day/shrink
        append their deterministic event records; local-store spill and
        fault-in append MOVE records through each owned store's journal
        sink (installed here). Spill on a store WITHOUT a sink (PS-backed
        shards — server-side tier) and external loads still taint (see
        journal.py for the replay contract)."""
        self._journal = journal
        for st in self.stores:
            set_sink = getattr(st, "set_journal_sink", None)
            if set_sink is not None:
                set_sink(None if journal is None else journal.append_move)

    def _journal_rows(self, keys: np.ndarray, rows: np.ndarray) -> None:
        if self._journal is not None:
            self._journal.append_rows(keys, rows)

    def _journal_event(self, code: int) -> None:
        if self._journal is not None:
            self._journal.append_event(code)

    def _drop_route_index(self) -> None:
        from paddlebox_tpu.native.build import destroy_route_index
        destroy_route_index(self._route_index)
        self._route_index = None

    def __del__(self):
        try:
            self._drop_route_index()
        except Exception:  # rationale: __del__ may run with a
            # half-torn-down interpreter where even logging fails;
            # close() is the loud path, this is the last-resort guard
            pass

    # ------------------------------------------------------- pass lifecycle
    def begin_feed_pass(self) -> None:
        if self._in_feed_pass:
            raise RuntimeError("feed pass already open")
        self._feed_keys = []
        self._in_feed_pass = True

    def add_keys(self, keys: np.ndarray) -> None:
        if not self._in_feed_pass:
            raise RuntimeError("add_keys outside feed pass")
        keys = np.asarray(keys, dtype=np.uint64)
        self._feed_keys.append(keys)
        if self.policy.wants_observe:
            # the 2d-grid hot tier's frequency stream (reader threads;
            # the sketch locks internally). Rank-local counts are summed
            # cluster-wide at end_feed_pass before the hot set freezes.
            self.policy.observe(keys)

    def end_feed_pass(self, allgather=None) -> None:
        """allgather: optional host collective (fleet.all_gather) used to
        union the pass key set across processes — each process feeds its own
        data files but every process must agree on the global per-shard key
        lists (the role the shared PS plays in the reference's feed pass,
        box_wrapper.h:1201-1278)."""
        if not self._in_feed_pass:
            raise RuntimeError("end_feed_pass without begin_feed_pass")
        local = (np.unique(np.concatenate(self._feed_keys))
                 if self._feed_keys else np.empty(0, np.uint64))
        if allgather is not None:
            parts = allgather(local)
            allk = np.unique(np.concatenate(
                [np.asarray(p, np.uint64) for p in parts]))
        else:
            allk = local
        # policy-owned shard assignment (round 13): key-mod reproduces
        # allk % P bit-for-bit; selecting by mask keeps each shard's
        # list sorted (allk is sorted)
        shard = self.policy.shard_of(allk)
        self._shard_keys = []
        for s in range(self.num_shards):
            ks = allk[shard == s]
            if ks.size > self.shard_cap - 1:
                raise RuntimeError(
                    f"shard {s} working set {ks.size} exceeds shard capacity "
                    f"{self.shard_cap} (raise TableConfig.pass_capacity)")
            self._shard_keys.append(ks)
        # the replicated hot tier (2d-grid) freezes HERE — the one
        # boundary where every rank agrees on the global key set; the
        # rank-local sketches merge over the same collective first so
        # the frozen hot sets are cluster-identical
        if allgather is not None and self.policy.wants_observe:
            self.policy.merge_observations(allgather)
        self.policy.freeze_hot(self._shard_keys)
        self._drop_route_index()
        # native pass index (key → slab-local id hash map): built once here,
        # amortized over every batch of the pass
        from paddlebox_tpu.native.build import create_route_index
        self._route_index = create_route_index(self._shard_keys)
        self._feed_keys = []
        self._in_feed_pass = False
        self._overflow_warned = False  # fresh warning budget per pass

    @staticmethod
    def _incremental() -> bool:
        from paddlebox_tpu.config import flags
        return bool(flags.get_flag("incremental_pass"))

    def _staged_rows_for(self, missing: np.ndarray, rows: np.ndarray
                         ) -> np.ndarray:
        """Fill `rows` from the preload promote stage where possible;
        returns the mask of positions still needing a store read."""
        from paddlebox_tpu.embedding.pass_table import sorted_member
        need = np.ones(missing.size, bool)
        if self._staged_sh is not None and not self._test_mode:
            skeys, srows = self._staged_sh
            pos, hit = sorted_member(skeys, missing)
            if hit.any():
                rows[hit] = srows[pos[hit]]
                need = ~hit
                stat_add("pass_rows_promote_prefetched", int(hit.sum()))
        return need

    def _build_one(self, s: int) -> np.ndarray:
        """One shard's BeginPass promote. Incremental mode reuses the
        host residency cache for keys that were in the last pass (pure
        numpy row moves) and reads only NEW keys from the store —
        compaction instead of reallocation; the tail beyond the working
        set zeroes either way (never a full-capacity memset)."""
        C, W = self.shard_cap, self.layout.width
        ks = self._shard_keys[s]
        n = ks.size
        slab = np.empty((C, W), dtype=np.float32)
        store = self.stores[s]  # boxlint: disable=BX401 (ref-grab; uses below are locked)
        res_k = self._res_keys.get(s)
        base = self._res_rows.get(s)
        if (self._incremental() and res_k is not None and base is not None
                and store is not None and n):
            from paddlebox_tpu.embedding.pass_table import sorted_member
            pos, hit = sorted_member(res_k, ks)
            slab[:n][hit] = base[pos[hit]]
            miss = ks[~hit]
            rows = np.empty((miss.size, W), np.float32)
            need = self._staged_rows_for(miss, rows)
            if need.any():
                with self.store_lock:
                    got = (store.lookup(miss[need]) if self._test_mode
                           else store.lookup_or_create(miss[need]))
                rows[need] = got
            slab[:n][~hit] = rows
            # journal the promote delta: lookup_or_create CREATES missing
            # features (init rows the touched write-back may never
            # revisit) — replay must see them; re-recording store-present
            # non-resident rows is an idempotent upsert of equal bits
            if not self._test_mode:
                self._journal_rows(miss, rows)
            stat_add("pass_rows_promote_hit", int(hit.sum()))
            stat_add("pass_rows_promote_new", int(miss.size))
        elif n:
            if store is None:
                raise RuntimeError(f"shard {s} store not owned by this "
                                   "process")
            with self.store_lock:
                rows = (store.lookup(ks) if self._test_mode
                        else store.lookup_or_create(ks))
            slab[:n] = rows
            # full build: every shard key may have been created just now
            if not self._test_mode:
                self._journal_rows(ks, rows)
        slab[n:] = 0.0
        if self._incremental() and not self._test_mode and store is not None:
            # the cache tracks what the store holds for this pass's rows;
            # end-of-pass delta writeback refreshes only touched entries
            self._res_keys[s] = ks
            self._res_rows[s] = slab
        return slab

    def _begin_pass_state(self) -> None:
        """Per-pass promote bookkeeping shared by both build entry points:
        allocate the touched bitmaps (train mode, incremental only) and
        consume the staged promote rows."""
        self._touch_seen = False
        if self._incremental():
            if not self._test_mode:
                self._touched_sh = {s: np.zeros(self.shard_cap, bool)
                                    for s in self.owned_shards}
            else:
                self._touched_sh = None
        else:
            self._touched_sh = None
            # with the flag off the caches stop being maintained — drop
            # them now or a later re-enable would delta-build from stale
            # rows (PassTable's non-incremental end_pass does the same)
            self.invalidate_residency()

    def build_slabs(self) -> np.ndarray:
        """BeginPass: promote all shards' working sets → [P, C, W] host array
        (caller device_puts it with the mesh sharding). Single-process only
        — multi-process callers use build_owned_slabs."""
        if self._shard_keys is None:
            raise RuntimeError("build_slabs before feed pass completed")
        self._begin_pass_state()
        # promote boundary: the host residency mirror (_res_rows) stays
        # f32; only the DEVICE-bound copy encodes (identity for f32)
        out = encode_slab_rows_np(
            np.stack([self._build_one(s) for s in range(self.num_shards)]),
            self.layout)
        if not self._test_mode:
            self._staged_sh = None
        return out

    def build_owned_slabs(self) -> np.ndarray:
        """[len(owned), C, W] for this process's shards, in owned order —
        the process-local piece of the global [P, C, W] array
        (jax.make_array_from_process_local_data)."""
        if self._shard_keys is None:
            raise RuntimeError("build_owned_slabs before feed pass completed")
        self._begin_pass_state()
        out = encode_slab_rows_np(
            np.stack([self._build_one(s) for s in self.owned_shards]),
            self.layout)
        if not self._test_mode:
            self._staged_sh = None
        return out

    def note_touched(self, dest: int, uids: np.ndarray) -> None:
        """OR one push's dedup'd local ids into destination shard `dest`'s
        touched bitmap (stage_push_dedup calls this per staged step).
        Padding uids (>= shard_cap) drop; the trash row is cleared at
        writeback. Idempotent True stores — stager-thread safe. The delta
        writeback engages only if at least one mark arrived this pass —
        raw-slab callers that push outside the staged path (probes,
        oracle tests) still get the full writeback."""
        t = self._touched_sh
        if t is None:
            return
        m = t.get(dest)
        if m is None:
            return
        m[uids[uids < self.shard_cap]] = True
        self._touch_seen = True

    def _touched_idx(self, s: int, n: int) -> Optional[np.ndarray]:
        """Touched row indices within [0, n) for shard s, or None when the
        pass ran without touched accounting (full writeback required)."""
        t = self._touched_sh
        if t is None or not self._touch_seen:
            return None
        m = t.get(s)
        if m is None:
            return None
        m[self.shard_cap - 1] = False  # trash row never reaches the store
        return np.nonzero(m[:n])[0]

    def write_back(self, slabs: np.ndarray) -> None:
        """EndPass: [P, C, W] host array → shard stores (single process).
        Incremental mode writes back only touched rows per shard."""
        if self._test_mode:
            self._touched_sh = None
            return
        for s, ks in enumerate(self._shard_keys or []):
            if ks.size and self.stores[s] is not None:  # boxlint: disable=BX401 (presence probe)
                self._write_back_rows(s, ks, slabs[s])
        self._touched_sh = None

    def _write_back_rows(self, s: int, ks: np.ndarray,
                         slab_host: np.ndarray) -> None:
        """Store one shard's end-of-pass rows from a HOST [C, W] array:
        touched delta when the pass accounted touches, full otherwise.
        slab_host carries the DEVICE layout (encoded u16 under the bf16
        diet) — the writeback boundary decodes here, so the stores and
        the f32 residency mirror never see encoded bits."""
        slab_host = decode_slab_rows_np(slab_host, self.layout)
        idx = self._touched_idx(s, ks.size)
        if idx is None:
            # slab_host[:n] is a view — append_rows copies only when a
            # journal is actually attached
            self._journal_rows(ks, slab_host[:ks.size])
        with self.store_lock:
            if idx is None:
                self.stores[s].write_back(ks, slab_host[:ks.size])
                if self._incremental():
                    self._res_keys[s] = ks
                    self._res_rows[s] = np.array(slab_host)
                else:
                    # flag off mid-pass: this cache entry is no longer
                    # maintained — a stale read on re-enable is corruption
                    self._res_keys.pop(s, None)
                    self._res_rows.pop(s, None)
            else:
                if idx.size:
                    rows = np.ascontiguousarray(slab_host[idx])
                    # ONE gather serves both (journal-less runs pay no
                    # extra copy; the journal's own lock is leaf-level,
                    # no path back into store_lock)
                    self._journal_rows(ks[idx], rows)
                    self.stores[s].write_back(ks[idx], rows)
                    cache = self._res_rows.get(s)
                    if cache is not None:
                        cache[idx] = rows
                stat_add("pass_rows_written_back", int(idx.size))
                stat_add("pass_rows_writeback_skipped",
                         int(ks.size) - int(idx.size))

    def write_back_shard(self, s: int, slab: np.ndarray) -> None:
        """EndPass for ONE owned shard: [C, W] device-fetched slab → store
        (multi-process path: each process writes only its addressable
        shards)."""
        if self._test_mode:
            return
        ks = self._shard_keys[s]
        if ks.size:
            self._write_back_rows(s, ks, slab)

    def _write_back_shard_dev(self, s: int, dev) -> None:
        """EndPass for one shard straight from its single-device [1, C, W]
        buffer: with touched accounting, gather + D2H ONLY the touched
        rows (the incremental lifecycle's delta transfer); otherwise the
        classic full-shard fetch."""
        ks = self._shard_keys[s]
        if not ks.size or self.stores[s] is None:  # boxlint: disable=BX401 (presence probe)
            return
        from paddlebox_tpu.obs.device import account_d2h
        idx = self._touched_idx(s, ks.size)
        if idx is None:
            full = np.asarray(dev)[0]
            account_d2h(full.nbytes)  # full-shard D2H
            self.write_back_shard(s, full)
            return
        if idx.size:
            import jax.numpy as jnp
            dev_rows = np.asarray(jnp.asarray(dev)[0][jnp.asarray(idx)])
            account_d2h(dev_rows.nbytes)  # touched-row delta D2H
            rows = decode_slab_rows_np(dev_rows, self.layout)
            self._journal_rows(ks[idx], rows)
            with self.store_lock:
                self.stores[s].write_back(ks[idx], rows)
            cache = self._res_rows.get(s)
            if cache is not None:
                cache[idx] = rows
        stat_add("pass_rows_written_back", int(idx.size))
        stat_add("pass_rows_writeback_skipped",
                 int(ks.size) - int(idx.size))

    def write_back_addressable(self, slabs) -> None:
        """EndPass over a jax [P, C, W] global array: dump THIS process's
        addressable shards (the one owner of the shard-index-from-
        addressable-shard idiom — trainers call this instead of walking
        .addressable_shards themselves). With touched accounting only the
        touched rows cross the device→host wire; single-process callers
        get the same delta through end_pass_write_back."""
        if self._test_mode:
            self._touched_sh = None
            return
        for sh in slabs.addressable_shards:
            pos = sh.index[0]
            s = (pos.start or 0) if isinstance(pos, slice) else int(pos)
            self._write_back_shard_dev(int(s), sh.data)
        self._touched_sh = None

    def end_pass_write_back(self, slabs) -> None:
        """Single-process EndPass over the device [P, C, W] global array:
        per-shard touched-row gather + D2H (all shards are addressable in
        one process, so this shares write_back_addressable's path). The
        pre-incremental equivalent was write_back(np.asarray(slabs)) — a
        full-slab transfer every pass."""
        self.write_back_addressable(slabs)

    def invalidate_residency(self) -> None:
        """Drop the per-shard residency caches and staged promote rows.
        Must follow ANY store mutation outside the pass cadence (aging,
        shrink decay, spill, checkpoint stat rewrites, load) — the next
        build falls back to full store reads."""
        self._res_keys = {}
        self._res_rows = {}
        self._staged_sh = None

    # ------------------------------------------------- preload promote hooks
    def promote_prefetch_ctx(self):
        """(known_fn, store_facade, lock) for preload.PromotePrefetcher,
        or None (flag off, test mode, no active pass). The facade routes
        lookup_present by key % P over the owned shards; shards whose
        store lacks lookup_present (e.g. PS-backed) report found=False and
        fall through to the boundary's lookup_or_create."""
        from paddlebox_tpu.config import flags
        if (not flags.get_flag("incremental_pass")
                or not flags.get_flag("preload_promote")
                or self._test_mode or self._shard_keys is None):
            return None
        if not any(st is not None and hasattr(st, "lookup_present")
                   for st in self.stores):  # boxlint: disable=BX401 (capability probe, pre-handoff)
            return None
        # numpy snapshot diff, NOT the native route index: the index
        # handle can be destroyed by an interleaved eval pass while the
        # prefetch thread is mid-probe; the arrays stay alive here
        snapshot = [np.asarray(k) for k in self._shard_keys]
        policy = self.policy

        def known(keys: np.ndarray) -> np.ndarray:
            from paddlebox_tpu.embedding.pass_table import sorted_member
            out = np.zeros(keys.size, bool)
            shard = policy.shard_of(keys)
            for s in range(self.num_shards):
                m = shard == s
                if m.any():
                    out[m] = sorted_member(snapshot[s], keys[m])[1]
            return out

        return known, _ShardLookupFacade(self), self.store_lock

    def accept_staged_rows(self, keys: np.ndarray, rows: np.ndarray) -> None:
        """Install the promote stager's prefetched (key, row) pairs for the
        next train build. keys must be sorted unique."""
        if keys.size:
            self._staged_sh = (keys, rows)

    @property
    def test_mode(self) -> bool:
        return self._test_mode

    def set_test_mode(self, test: bool) -> None:
        self._test_mode = test

    @property
    def pass_size(self) -> int:
        return sum(k.size for k in self._shard_keys or [])

    # ---------------------------------------------------------- batch index
    def bucketize(self, keys: np.ndarray, valid: np.ndarray) -> ShardedBatchIndex:
        """Route one batch's keys: shard = policy.shard_of(key) (key-mod
        default = split_input_to_shard, heter_comm_inl.h:1117), local id
        by searchsorted in the shard's sorted pass key list, batch-level
        dedup into bucket slots.

        Native route.cc when built (pass-indexed hash, ~13M keys/sec at
        the reference's 1800×2048 budget): the key-mod policy keeps the
        legacy rt_bucketize (identical code path = pre-policy
        bit-parity); every other policy pre-mixes its per-key shard
        array vectorized and runs rt_bucketize_sharded — the native
        dedup/bucket loop at rate under any routing. Vectorized numpy
        fallback (the host analog of the reference's on-device
        dedup_keys_and_fillidx, heter_comm_inl.h:2231; the round-1
        per-key dict loop managed ~0.5M).
        Mutates `valid` in place to drop occurrences of overflowed keys.
        WHICH keys overflow when a shard bucket fills is unspecified (native
        drops late first-occurrences, numpy drops the largest key values) —
        size bucket_cap so overflow never happens in normal operation."""
        if self._shard_keys is None:
            raise RuntimeError("no active pass key set")
        P, KB = self.num_shards, self.bucket_cap
        trash = self.shard_cap - 1
        buckets = np.full((P, KB), trash, dtype=np.int32)
        restore = np.zeros(keys.shape[0], dtype=np.int32)

        native = _route_lib()
        keymod = self.policy.native_keymod
        if (native is not None and self._route_index is not None
                and (keymod or hasattr(native, "rt_bucketize_sharded"))):
            import ctypes
            c = ctypes
            keys_c = np.ascontiguousarray(keys, dtype=np.uint64)
            if valid.dtype != np.bool_ or not valid.flags.c_contiguous:
                raise TypeError("valid must be a contiguous bool array")
            missing = np.zeros(1, np.uint64)
            if keymod:
                rc = native.rt_bucketize(
                    self._route_index,
                    keys_c.ctypes.data_as(c.POINTER(c.c_uint64)),
                    valid.view(np.uint8).ctypes.data_as(
                        c.POINTER(c.c_uint8)),
                    keys_c.size, P, KB,
                    buckets.ctypes.data_as(c.POINTER(c.c_int32)),
                    restore.ctypes.data_as(c.POINTER(c.c_int32)),
                    missing.ctypes.data_as(c.POINTER(c.c_uint64)))
            else:
                shard_c = np.ascontiguousarray(
                    self.policy.shard_of(keys_c), np.int32)
                rc = native.rt_bucketize_sharded(
                    self._route_index,
                    keys_c.ctypes.data_as(c.POINTER(c.c_uint64)),
                    shard_c.ctypes.data_as(c.POINTER(c.c_int32)),
                    valid.view(np.uint8).ctypes.data_as(
                        c.POINTER(c.c_uint8)),
                    keys_c.size, P, KB,
                    buckets.ctypes.data_as(c.POINTER(c.c_int32)),
                    restore.ctypes.data_as(c.POINTER(c.c_int32)),
                    missing.ctypes.data_as(c.POINTER(c.c_uint64)))
            if rc == -1:
                raise KeyError(
                    f"key {int(missing[0])} not registered in feed pass")
            if rc == -3:
                raise ValueError(
                    "sharding policy %s produced an out-of-range shard "
                    "for key %d" % (self.policy.name, int(missing[0])))
            if rc < 0:
                raise MemoryError("rt_bucketize scratch allocation failed")
            if rc:
                self._note_overflow(int(rc))
            return ShardedBatchIndex(buckets=buckets, restore=restore,
                                     overflow=int(rc))

        idx = np.nonzero(valid)[0]
        if idx.size == 0:
            return ShardedBatchIndex(buckets=buckets, restore=restore,
                                     overflow=0)
        uniq, inv = np.unique(keys[idx], return_inverse=True)
        shard = self.policy.shard_of(uniq).astype(np.int64)
        counts = np.bincount(shard, minlength=P)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        # uniq is sorted, so a stable sort by shard keeps keys sorted within
        # each shard group — groups are contiguous [starts[s], starts[s]+n)
        order = np.argsort(shard, kind="stable")
        rank = np.arange(uniq.size, dtype=np.int64) - starts[shard[order]]

        # per-unique-key slot (s*KB + rank) in np.unique order; overflow = -1
        slot_of_uniq = np.empty(uniq.size, dtype=np.int64)
        kept = rank < KB
        slot_of_uniq[order] = np.where(kept, shard[order] * KB + rank, -1)

        # local ids: one searchsorted per shard over its contiguous group
        for s in range(P):
            lo, n = starts[s], counts[s]
            group = uniq[order[lo:lo + n]]
            n_keep = min(int(n), KB)
            g = group[:n_keep]
            sk = self._shard_keys[s]
            pos = np.searchsorted(sk, g)
            if n_keep and (pos.max(initial=0) >= sk.size
                           or not np.array_equal(sk[pos], g)):
                if sk.size == 0:
                    missing = g[0]
                else:
                    bad = (pos >= sk.size) | (sk[np.minimum(
                        pos, sk.size - 1)] != g)
                    missing = g[bad][0]
                raise KeyError(f"key {missing} not registered in feed pass")
            buckets[s, :n_keep] = pos

        occ_slots = slot_of_uniq[inv]
        overflow = int((occ_slots < 0).sum())
        if overflow:
            valid[idx[occ_slots < 0]] = False
            self._note_overflow(overflow)
        restore[idx] = np.where(occ_slots >= 0, occ_slots, 0)
        return ShardedBatchIndex(buckets=buckets, restore=restore,
                                 overflow=overflow)

    def _note_overflow(self, count: int) -> None:
        """Bucket overflow means those keys' GRADIENTS ARE DROPPED this
        batch — never let that pass silently (the PADDLE_ENFORCE
        discipline, box_wrapper_impl.h:139): stat counter always, one
        warning per feed pass, and a hard error under the
        strict_bucket_overflow flag. Runs on stager threads — the warn
        latch race is at worst a double log line."""
        stat_add("sharded_bucket_overflow", count)
        from paddlebox_tpu.config import flags
        if flags.get_flag("strict_bucket_overflow"):
            raise RuntimeError(
                f"sharded bucket overflow: {count} keys dropped this "
                f"batch (bucket_cap={self.bucket_cap} too small for this "
                "key skew) — their gradients would be silently lost; "
                "raise bucket_cap or unset strict_bucket_overflow")
        if not self._overflow_warned:
            self._overflow_warned = True
            import logging
            logging.getLogger("paddlebox_tpu").warning(
                "sharded bucket overflow: %d keys dropped this batch "
                "(their gradients are LOST); bucket_cap=%d is too small "
                "for this key skew — further overflows this pass count "
                "in stats.sharded_bucket_overflow only", count,
                self.bucket_cap)

    def check_need_limit_mem(self) -> int:
        """Per-shard pass-cadence spill (CheckNeedLimitMem/ShrinkResource,
        box_wrapper.h:627-629); budget divides evenly across owned shards
        — except table-wide backends (PS-backed shards), which receive the
        WHOLE budget once through their primary. Any spill drops the
        incremental residency caches (rows left the stores)."""
        budget = self.config.ssd_max_resident_rows(self.layout.width)
        if budget is None:
            return 0
        per_shard = budget // max(1, len(self.owned_shards))
        total = 0
        unsound = 0
        # under the lock: a concurrent PromotePrefetcher lookup_present
        # must never observe a spill mid-flight (native stores have no
        # internal lock — arena rows move)
        with self.store_lock:
            for st in self.stores:
                if st is None or not hasattr(st, "spill"):
                    continue
                n = st.spill(budget if getattr(st, "spill_table_wide",
                                               False) else per_shard)
                total += n
                # local tier stores journal their own MV_SPILL records
                # via the sink; a store without one (PS-backed — the
                # tier lives server-side, invisible to this journal)
                # makes the epoch unreplayable
                if n and not hasattr(st, "set_journal_sink"):
                    unsound += n
        if total:
            self.invalidate_residency()
            if unsound and self._journal is not None:
                self._journal.taint(
                    f"{unsound} rows spilled on a server-side tier "
                    "(outside the journaled MOVE cadence)")
        return total

    def shrink_table(self) -> int:
        self.invalidate_residency()  # decay rewrites every store row
        with self.store_lock:
            n = sum(st.shrink() for st in self.stores if st is not None)
        from paddlebox_tpu.train.journal import EV_SHRINK
        self._journal_event(EV_SHRINK)
        return n

    def end_day(self, age: bool = True) -> int:
        """Day boundary over the owned shards: age unseen_days, then
        shrink (see PassTable.end_day for the age=False/save_base rule).
        PS-backed shards age server-side through their primary."""
        self.invalidate_residency()
        from paddlebox_tpu.train.journal import (EV_AGE_DAYS,
                                                 EV_TICK_SPILL_AGE)
        # event appends INSIDE the store_lock hold: a concurrent promote
        # prefetcher journals MV_FAULT_IN under the same lock, and replay
        # must see record order == mutation order (tier epoch parity)
        with self.store_lock:
            for st in self.stores:
                if st is None:
                    continue
                if age:
                    st.age_unseen_days()
                else:
                    st.tick_spill_age()
            self._journal_event(EV_AGE_DAYS if age else EV_TICK_SPILL_AGE)
        return self.shrink_table()

    # checkpoint boundary: the driver serializes save/load against
    # passes, so no prefetch thread can be live in these three
    def save(self, path_prefix: str) -> None:  # boxlint: disable=BX401
        for s, st in enumerate(self.stores):
            if st is not None:
                st.save(f"{path_prefix}.shard{s:03d}")

    def load(self, path_prefix: str) -> None:  # boxlint: disable=BX401
        self.invalidate_residency()
        if self._journal is not None:
            self._journal.taint("per-shard store load outside the "
                                "checkpoint plane")
        for s, st in enumerate(self.stores):
            if st is not None:
                st.load(f"{path_prefix}.shard{s:03d}")

    def load_ssd_to_mem(self) -> int:  # boxlint: disable=BX401
        """LoadSSD2Mem over the owned shards (box_wrapper.cc:1319)."""
        self.invalidate_residency()  # fault-in applies missed days
        return sum(st.load_spilled() for st in self.stores
                   if st is not None and hasattr(st, "load_spilled"))

    def store_view(self) -> "ShardedStoreView":
        """One store-shaped facade over the owned shards, so the
        CheckpointManager/run_day day cadence drives the sharded table
        with the same code as the single-host PassTable. PS-backed shards
        checkpoint server-side (PSClient.save) and reject this view."""
        from paddlebox_tpu.embedding.ps_store import PSBackedStore
        # type/presence probe only (checkpoint boundary; no row access)
        for st in self.stores:  # boxlint: disable=BX401
            if st is None:
                # a DONE-marked base model missing the non-owned shards'
                # rows would read as complete — fail here instead
                raise TypeError(
                    "store_view needs every shard local (single process); "
                    "multi-process jobs checkpoint per owned shard via "
                    "table.save()")
            if isinstance(st, PSBackedStore):
                raise TypeError("PS-backed shards checkpoint server-side "
                                "(PSClient.save), not through store_view")
        return ShardedStoreView(self)


class _ShardLookupFacade:
    """Single-store lookup_present view over a ShardedPassTable's owned
    shards (the preload promote stager's read interface): keys route by
    key % P; shards without lookup_present (non-owned, PS-backed) report
    found=False so those keys resolve at the pass boundary instead."""

    def __init__(self, table: "ShardedPassTable") -> None:
        self._table = table

    def lookup_present(self, keys: np.ndarray):
        t = self._table
        out = np.zeros((keys.size, t.layout.width), np.float32)
        found = np.zeros(keys.size, bool)
        shard = t.policy.shard_of(keys)
        for s in t.owned_shards:
            st = t.stores[s]
            if st is None or not hasattr(st, "lookup_present"):
                continue
            m = shard == s
            if m.any():
                out[m], found[m] = st.lookup_present(keys[m])
        return out, found


class ShardedStoreView:
    """state_items/write_back/spilled_snapshot/load over a
    ShardedPassTable's OWNED shard stores — the store protocol subset the
    checkpoint tier consumes. Keys route by the table's sharding POLICY
    (identical to the a2a routing), so a view round trip lands every row
    in its owning store — and a checkpoint written under one policy
    redistributes automatically when loaded under another (write_back/
    load route by the live policy, not the one that wrote the blob)."""

    def __init__(self, table: ShardedPassTable) -> None:
        self._table = table

    def _owned(self):
        return [(s, st) for s, st in enumerate(self._table.stores)
                if st is not None]

    def state_items(self) -> Tuple[np.ndarray, np.ndarray]:
        parts = [st.state_items() for _, st in self._owned()]
        keys = np.concatenate([k for k, _ in parts]) if parts else \
            np.empty(0, np.uint64)
        vals = (np.vstack([v for _, v in parts]) if parts else
                np.empty((0, self._table.layout.width), np.float32))
        return keys, vals

    def spilled_snapshot(self) -> Tuple[np.ndarray, np.ndarray]:
        ks, vs = [], []
        for _, st in self._owned():
            snap = getattr(st, "spilled_snapshot", None)
            if snap is None:
                continue
            k, v = snap()
            if k.size:
                ks.append(k)
                vs.append(v)
        if not ks:
            return (np.empty(0, np.uint64),
                    np.empty((0, self._table.layout.width), np.float32))
        return np.concatenate(ks), np.vstack(vs)

    def spilled_count(self) -> int:
        """Summed SSD-tier rows over the owned shards."""
        total = 0
        for _, st in self._owned():
            probe = getattr(st, "spilled_count", None)
            if probe is not None:
                total += probe()
        return total

    def spilled_keys(self) -> np.ndarray:
        """Every live tier key over the owned shards (save_base's anchor
        MV_SPILL record set)."""
        parts = []
        for _, st in self._owned():
            fn = getattr(st, "spilled_keys", None)
            if fn is not None:
                k = fn()
                if k.size:
                    parts.append(k)
        return (np.concatenate(parts) if parts
                else np.empty(0, np.uint64))

    def rebase_spill_ages(self) -> None:
        """Pin each owned shard tier's lazy-aging span boundary (the
        full-save anchor; see SpillTier.rebase)."""
        for _, st in self._owned():
            fn = getattr(st, "rebase_spill_ages", None)
            if fn is not None:
                fn()

    def write_back(self, keys: np.ndarray, values: np.ndarray) -> None:
        # checkpoint stat rewrites land here — the residency caches no
        # longer mirror the stores afterwards
        self._table.invalidate_residency()
        keys = np.asarray(keys, np.uint64)
        shard = self._table.policy.shard_of(keys)
        for s, st in self._owned():
            m = shard == s
            if m.any():
                st.write_back(keys[m], values[m])

    def update_stat_after_save(self, table_cfg, param: int) -> None:
        """Checkpoint stat rewrite, per shard in place (every shard
        store applies the same accessor rule to its own resident rows —
        routing is irrelevant, the union is the table)."""
        self._table.invalidate_residency()
        from paddlebox_tpu.train.journal import apply_stat_after_save
        for _, st in self._owned():
            apply_stat_after_save(st, table_cfg, param)

    def load(self, path: str) -> None:
        """Split a single checkpoint — columnar manifest (loaded through
        the reader pool) or legacy pickle, sniffed — across the shard
        stores; keys route by the LIVE sharding policy, so a checkpoint
        written under one policy redistributes on load under another."""
        from paddlebox_tpu.embedding.ckpt_store import load_sparse_any
        self.load_blob(load_sparse_any(path))

    def load_blob(self, blob: dict) -> None:
        """The post-deserialize half of load (their load_blob handles
        index reset, stale-spill clearing, and layout validation) — one
        blob split across shards without re-serializing."""
        self._table.invalidate_residency()
        keys = np.asarray(blob["keys"], np.uint64)
        shard = self._table.policy.shard_of(keys)
        for s, st in self._owned():
            m = shard == s
            st.load_blob(dict(blob, keys=keys[m], values=blob["values"][m]))
