"""Pluggable 2-D sparse sharding policies over the sharded pass table.

"Two-dimensional Sparse Parallelism" (PAPERS.md) shows that the flat
key-mod layout — every key hashes to a random device, so every rank
talks to every rank for every table — is what caps DLRM sparse scaling,
and that a table-axis x row-axis grid is what scales past it: a table's
traffic confines to its grid sub-axis, and hot long-tail tables can be
REPLICATED instead of routed (HierarchicalKV's cache-semantics store in
PAPERS.md is the model for the replicated hot tier).

This module owns the three decisions that used to be baked into
parallel/sharded_table.py as ``key % P``:

  (a) ROUTE   — which shard position owns a key (``shard_of``), consumed
      by the batch bucketize on both its native tier (route.cc
      ``rt_bucketize`` for key-mod bit-parity; the policy-parameterized
      ``rt_bucketize_sharded`` for everything else — the per-key shard
      is pre-mixed vectorized in numpy so the native dedup/bucket loop
      keeps its rate) and its numpy fallback, plus every host-side
      router twin (feed-pass shard assignment, promote prefetch,
      checkpoint store view).
  (b) EXCHANGE — which peers a rank exchanges with (``dest_plan``: the
      per-peer destination lists the p2p host plane ships along), plus
      the replicated-hot-key wire filter (``hot_local_ids``): globally
      replicated hot rows never travel — senders drop them pre-wire and
      owners re-add them from the replicated set.
  (c) LAYOUT  — how the device-side [P, C, W] slab stack is laid out
      (``slab_spec``/``slab_sharding``, the GSPMD NamedSharding idiom
      from SNIPPETS.md [2]/[3]): key-mod shards dim 0 over the flat box
      axis; the 2-D grid expresses the same linearized layout over
      dedicated ``table`` x ``row`` mesh axes when the mesh declares
      them.

Three shipped policies:

  key-mod     shard = key % P. Bit-identical to the pre-policy path on
              both wire modes (pinned by tests/test_sharding_policy.py)
              — the parity oracle every other policy is measured
              against.
  table-wise  shard = table(key) % P: each table lives WHOLE on one
              shard, so a table's sparse traffic flows only to its
              owner (zero cross-group traffic per table). Total routed
              bytes are conserved vs key-mod (every occurrence still
              reaches one owner) but the per-table confinement is what
              unlocks heterogeneous worlds — big tables on few ranks.
  2d-grid     shard = table_group(key) * R + (key % R): table axis x
              row axis. Row-wise splitting inside a table group
              rebalances the skew table-wise alone concentrates, and
              the frequency-sketch hot tier (the serving cache's
              TinyLFU sketch machinery, serving/cache.py) marks the
              long tail's hot keys for replication: frozen per pass,
              filtered off the uid wire, mirrored by ReplicatedHotTier.

The table id of a key is ``(key >> sharding_table_shift) %
sharding_num_tables`` — the feasign's slot/table field rides the high
bits (the reference packs feasigns the same way); generators that don't
can set shift 0 to fold the low bits instead.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
from paddlebox_tpu.utils.lockwatch import make_lock

# the 2-D grid's dedicated mesh axes — declared here so the BX2xx
# collective-axis vocabulary (tools/boxlint/collectives.py collects
# *AXIS* module constants) admits collectives/specs over them
TABLE_AXIS = "table"
ROW_AXIS = "row"


def default_dest_plan(mesh, local_positions: Sequence[int],
                      num_devices: int) -> List[List[int]]:
    """Per-peer destination lists for the p2p exchanges, validated
    against the rendezvous'd ownership map: every mesh position must
    have exactly one owner or the a2a would silently drop shards. This
    is the owner-map plan every shipped policy rides (routing decides
    WHAT flows; the plan decides WHERE) — a policy with structural
    no-traffic guarantees can override ``dest_plan`` to shrink it."""
    owner = mesh.rank_of_position()
    missing = [d for d in range(num_devices) if d not in owner]
    if missing:
        raise RuntimeError(
            "p2p host plane: mesh positions %s have no owning rank "
            "(rendezvous positions incomplete)" % missing)
    if sorted(mesh.positions_of.get(mesh.rank, [])) != sorted(
            local_positions):
        raise RuntimeError(
            "p2p host plane: this rank rendezvous'd positions %s but is "
            "staging for %s" % (mesh.positions_of.get(mesh.rank),
                                list(local_positions)))
    return [mesh.positions_of[r] for r in range(mesh.world)]


def partition_pull(policy: "ShardingPolicy", keys: np.ndarray,
                   hot_keys: Optional[np.ndarray] = None,
                   hot_dest: int = 0) -> List[np.ndarray]:
    """Client-side pull partitioning (round 21): the serving-fleet twin
    of the dest plan — ``policy.shard_of`` decides WHAT each box owns
    (identically to the training exchange, so a box's filtered view is
    exactly the slab its trainer rank held), and this splits one pull's
    key vector into per-box position lists. ``hot_keys`` (sorted unique
    uint64 — the replicated hot tier every box additionally holds) are
    re-routed to ``hot_dest % num_shards`` instead of their owner:
    head keys would otherwise converge every pull on one box; rotating
    hot_dest per pull spreads exactly the skewed head that 2-D grid
    row-rebalancing spreads in training. Returns one positions array
    per shard (some possibly empty); their concatenation is a
    permutation of arange(len(keys))."""
    keys = np.asarray(keys, np.uint64).reshape(-1)
    dest = np.asarray(policy.shard_of(keys), np.int64).copy()
    if hot_keys is not None and len(hot_keys) and keys.size:
        hot_keys = np.asarray(hot_keys, np.uint64)
        idx = np.searchsorted(hot_keys, keys)
        hot = (idx < hot_keys.size) & (
            hot_keys[np.minimum(idx, hot_keys.size - 1)] == keys)
        dest[hot] = int(hot_dest) % policy.num_shards
    return [np.nonzero(dest == s)[0]
            for s in range(policy.num_shards)]


class FreqSketch:
    """Bounded frequency sketch with halving decay — the serving hot-key
    cache's TinyLFU admission machinery (serving/cache.py ``_freq``)
    lifted to a reusable class: counts live in a bounded dict; past
    ``cap`` entries every count halves and zeros drop, so memory stays
    O(cap) and stale keys age out instead of pinning forever.

    ``observe`` rides the feed-pass load path (ShardedPassTable.add_keys
    runs on reader threads), so it is locked and vectorized: one
    np.unique over the batch, then a dict update per UNIQUE — a zipf
    batch pays for its distinct keys, not its occurrences."""

    def __init__(self, cap: int = 1 << 16) -> None:
        import threading
        self.cap = int(cap)
        self._lock = make_lock("FreqSketch._lock")
        self._freq: Dict[int, int] = {}  # guarded-by: _lock

    def observe(self, keys: np.ndarray) -> None:
        keys = np.asarray(keys, np.uint64)
        if not keys.size:
            return
        uniq, counts = np.unique(keys, return_counts=True)
        with self._lock:
            freq = self._freq
            for k, c in zip(uniq.tolist(), counts.tolist()):
                freq[k] = freq.get(k, 0) + c
            if len(freq) > self.cap:
                self._freq = {k: c >> 1 for k, c in freq.items()
                              if c >> 1}

    def items(self):
        """(keys [n] uint64, counts [n] int64) snapshot — the wire form
        the cross-rank sketch merge ships."""
        with self._lock:
            ks = np.fromiter(self._freq.keys(), np.uint64,
                             len(self._freq))
            cs = np.fromiter(self._freq.values(), np.int64,
                             len(self._freq))
        return ks, cs

    @classmethod
    def summed(cls, parts, cap: int) -> "FreqSketch":
        """A NEW sketch holding the element-wise SUM of the given
        (keys, counts) snapshots — every rank summing the same part set
        (any order; addition commutes) holds an IDENTICAL view. The
        inputs are NOT mutated: each rank's local sketch keeps only its
        own observation history, so re-merging full local histories at
        every pass boundary counts each occurrence exactly once (a
        merge that overwrote the local sketch with the global sum would
        re-sum it W-fold per pass and inflate every count)."""
        total: Dict[int, int] = {}
        for ks, cs in parts:
            for k, c in zip(np.asarray(ks, np.uint64).tolist(),
                            np.asarray(cs, np.int64).tolist()):
                total[k] = total.get(k, 0) + c
        if len(total) > cap:
            # keep the heaviest cap entries (deterministic: count desc,
            # key asc tiebreak) so every rank truncates identically
            keep = sorted(total.items(), key=lambda kv: (-kv[1], kv[0]))
            total = dict(keep[:cap])
        out = cls(cap)
        out._freq = total
        return out

    def hot_keys(self, threshold: int) -> np.ndarray:
        """Sorted unique keys whose estimate reached ``threshold``."""
        if threshold <= 0:
            return np.empty(0, np.uint64)
        with self._lock:
            ks = [k for k, c in self._freq.items() if c >= threshold]
        return np.sort(np.asarray(ks, np.uint64))


class ShardingPolicy:
    """Owner of route / exchange-plan / device-layout for the sharded
    pass table. Policies are immutable during a pass: the hot tier (the
    only mutable piece) freezes at ``freeze_hot`` — the feed-pass
    boundary, where every rank already agrees on the global key set —
    because senders drop hot uids the OWNERS re-add, so a mid-pass
    hot-set change on one rank would silently corrupt the lockstep
    exchange products."""

    name = "abstract"
    #: True only when ``shard_of`` is exactly ``key % num_shards`` — the
    #: bucketize then keeps the legacy rt_bucketize fast path, which is
    #: the bit-parity guarantee for the pre-policy behavior
    native_keymod = False

    def __init__(self, num_shards: int) -> None:
        if num_shards <= 0:
            raise ValueError("num_shards must be positive")
        self.num_shards = int(num_shards)

    # ------------------------------------------------------------- route
    def shard_of(self, keys: np.ndarray) -> np.ndarray:
        """[K] uint64 feasigns -> [K] int64 owning shard positions in
        [0, num_shards). Vectorized numpy — this runs per batch ahead of
        the native bucketize loop."""
        raise NotImplementedError

    # ---------------------------------------------------------- exchange
    def dest_plan(self, mesh, local_positions: Sequence[int],
                  num_devices: int) -> List[List[int]]:
        """Per-peer destination lists the p2p exchanges ship along."""
        return default_dest_plan(mesh, local_positions, num_devices)

    # ------------------------------------------------------------ layout
    def slab_spec(self, mesh, axis):
        """PartitionSpec for the [P, C, W] slab stack's dim 0 on `mesh`
        (`axis` = the runner's flat table axis name or tuple)."""
        from jax.sharding import PartitionSpec
        return PartitionSpec(axis)

    def slab_sharding(self, mesh, axis):
        from jax.sharding import NamedSharding
        return NamedSharding(mesh, self.slab_spec(mesh, axis))

    # ---------------------------------------------------------- hot tier
    #: True when the policy wants the feed-pass occurrence stream
    #: (ShardedPassTable.add_keys feeds observe); False short-circuits
    #: the hot-path call entirely
    wants_observe = False

    def observe(self, keys: np.ndarray) -> None:
        """Feed key occurrences to the policy's frequency model (no-op
        unless the policy carries a sketch)."""

    def merge_observations(self, allgather) -> None:
        """Cross-rank sketch merge at the feed-pass union (end_feed_pass,
        right before freeze_hot): rank-local observation streams differ,
        so every rank allgathers its sketch snapshot and loads the SUM —
        identical sketches, hence identical frozen hot sets, on every
        rank. No-op for policies without a sketch."""

    def freeze_hot(self, shard_keys: Sequence[np.ndarray]) -> None:
        """Pass boundary: resolve the sketch against the new pass's
        per-shard sorted key lists into per-shard hot LOCAL id sets.
        No-op for policies without a hot tier."""

    def hot_local_ids(self, dest: int) -> Optional[np.ndarray]:
        """Sorted int32 pass-local ids replicated for shard `dest`, or
        None. These ids are dropped from the uid wire by senders and
        re-added by the owner (exchange_push_uids_p2p)."""
        return None

    # -------------------------------------------------------- validation
    def describe(self) -> str:
        """Stable identity string for cross-rank rendezvous validation
        (fleet/mesh_comm.py): ranks running different policies would
        route the same key to different owners and silently corrupt the
        exchange — the rendezvous compares these and fails loud."""
        return "%s/%d" % (self.name, self.num_shards)


class KeyModPolicy(ShardingPolicy):
    """shard = key % P — the BoxPS/HeterComm layout
    (split_input_to_shard, heter_comm_inl.h:1117) and the parity oracle:
    bit-identical to the pre-policy path on both wire modes."""

    name = "key-mod"
    native_keymod = True

    def shard_of(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.uint64)
        return (keys % np.uint64(self.num_shards)).astype(np.int64)


class TableWisePolicy(ShardingPolicy):
    """Each table pinned WHOLE to one shard: shard = table(key) % P.
    A table's sparse traffic flows only to its owner rank — zero
    cross-group traffic per table — at the cost of concentrating skewed
    tables' load on their owners (the imbalance the 2-D grid's row axis
    exists to fix)."""

    name = "table-wise"

    def __init__(self, num_shards: int, num_tables: int,
                 table_shift: int = 48) -> None:
        super().__init__(num_shards)
        if num_tables <= 0:
            raise ValueError("num_tables must be positive")
        if not 0 <= int(table_shift) < 64:
            raise ValueError("table_shift must be in [0, 64)")
        self.num_tables = int(num_tables)
        self.table_shift = int(table_shift)

    def table_of(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.uint64)
        return ((keys >> np.uint64(self.table_shift))
                % np.uint64(self.num_tables)).astype(np.int64)

    def shard_of(self, keys: np.ndarray) -> np.ndarray:
        return self.table_of(keys) % self.num_shards

    def describe(self) -> str:
        return "%s/%d/t%d>>%d" % (self.name, self.num_shards,
                                  self.num_tables, self.table_shift)


class TwoDGridPolicy(TableWisePolicy):
    """Table axis x row axis: shard = table_group * R + (key % R).

    The grid linearizes onto the runner's flat device axis (position
    t*R + r), and ``slab_spec`` expresses the same layout over dedicated
    (table, row) mesh axes when the mesh declares them — the GSPMD
    NamedSharding idiom. Row-wise splitting inside a table group spreads
    a skewed table over R shards (the rebalance table-wise lacks), and
    the hot tier replicates the long tail's hottest keys so they never
    travel the wire at all:

      * ``observe`` feeds the TinyLFU-style FreqSketch (the serving
        cache's machinery) from the feed-pass occurrence stream —
        ShardedPassTable.add_keys calls it whenever ``wants_observe``;
      * ``merge_observations`` (end_feed_pass, over the same allgather
        that unions the pass keys) sums every rank's sketch so the
        frozen hot sets agree cluster-wide even though the observation
        streams were rank-local;
      * ``freeze_hot`` resolves keys at/above ``hot_threshold`` against
        the new pass's shard key lists ONCE per pass;
      * exchange_push_uids_p2p drops hot uids pre-wire and the owner
        re-adds its full hot set: the staged uid vector over-approximates
        by hot ids that skipped a step, whose merged gradients are zero
        (a value-level no-op in the in-table optimizer) — that is the
        replication premise: hot rows are touched essentially every
        step.
    """

    name = "2d-grid"

    def __init__(self, num_shards: int, num_tables: int, rows: int,
                 table_shift: int = 48, hot_threshold: int = 0,
                 hot_cap: int = 1024, sketch_cap: int = 1 << 16) -> None:
        super().__init__(num_shards, num_tables, table_shift)
        if rows <= 0 or num_shards % rows:
            raise ValueError(
                "grid rows (%d) must divide num_shards (%d) evenly"
                % (rows, num_shards))
        self.rows = int(rows)
        self.table_groups = self.num_shards // self.rows
        self.hot_threshold = int(hot_threshold)
        self.hot_cap = int(hot_cap)
        self.sketch = FreqSketch(sketch_cap)
        # the cross-rank merged view (merge_observations); the LOCAL
        # sketch above keeps only this rank's history so every pass's
        # re-merge counts each occurrence exactly once
        self._merged_sketch: Optional[FreqSketch] = None
        self._hot_local: Dict[int, np.ndarray] = {}
        self._hot_keys = np.empty(0, np.uint64)

    def shard_of(self, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.uint64)
        group = self.table_of(keys) % self.table_groups
        row = (keys % np.uint64(self.rows)).astype(np.int64)
        return group * self.rows + row

    def slab_spec(self, mesh, axis):
        from jax.sharding import PartitionSpec
        names = tuple(getattr(mesh, "axis_names", ()))
        if TABLE_AXIS in names and ROW_AXIS in names:
            # grid mesh: dim 0 shards over (table, row) — position
            # t*R + r lands on mesh coordinate (t, r), exactly the
            # linearized flat-axis layout (pinned by test)
            return PartitionSpec((TABLE_AXIS, ROW_AXIS))
        return PartitionSpec(axis)

    # ---------------------------------------------------------- hot tier
    @property
    def wants_observe(self) -> bool:
        return self.hot_threshold > 0

    def observe(self, keys: np.ndarray) -> None:
        if self.hot_threshold > 0:
            self.sketch.observe(keys)

    def merge_observations(self, allgather) -> None:
        if self.hot_threshold <= 0:
            return
        ks, cs = self.sketch.items()
        payload = np.concatenate([np.array([ks.size], np.uint64), ks,
                                  cs.view(np.uint64)])
        parts = []
        for p in allgather(payload):
            p = np.asarray(p, np.uint64)
            n = int(p[0])
            parts.append((p[1:1 + n], p[1 + n:1 + 2 * n].view(np.int64)))
        # a fresh summed VIEW; the local sketch is untouched, so next
        # pass's merge re-sums local histories, not prior global sums
        self._merged_sketch = FreqSketch.summed(parts, self.sketch.cap)

    def freeze_hot(self, shard_keys: Sequence[np.ndarray]) -> None:
        self._hot_local = {}
        self._hot_keys = np.empty(0, np.uint64)
        if self.hot_threshold <= 0:
            return
        sk = (self._merged_sketch if self._merged_sketch is not None
              else self.sketch)   # single-process: local IS global
        hot = sk.hot_keys(self.hot_threshold)
        if not hot.size:
            return
        shard = self.shard_of(hot)
        kept = []
        for s in range(self.num_shards):
            hk = hot[shard == s]
            if not hk.size:
                continue
            sk = np.asarray(shard_keys[s])
            pos = np.searchsorted(sk, hk)
            ok = (pos < sk.size)
            ok[ok] = sk[pos[ok]] == hk[ok]  # only keys IN this pass
            if not ok.any():
                continue
            local = pos[ok].astype(np.int32)
            if local.size > self.hot_cap:
                raise ValueError(
                    "2d-grid hot tier: shard %d has %d hot keys, over "
                    "sharding_hot_cap=%d — raise the cap or the "
                    "threshold (an unbounded replicated set defeats "
                    "the wire saving it exists for)"
                    % (s, local.size, self.hot_cap))
            self._hot_local[s] = local  # searchsorted output: ascending
            kept.append(hk[ok])
        if kept:
            self._hot_keys = np.concatenate(kept)
            self._hot_keys.sort()

    def hot_local_ids(self, dest: int) -> Optional[np.ndarray]:
        return self._hot_local.get(dest)

    def hot_keys_frozen(self) -> np.ndarray:
        """Sorted unique hot keys of the frozen pass (the replicated
        set ReplicatedHotTier mirrors)."""
        return self._hot_keys

    def describe(self) -> str:
        # hot_cap rides the identity too: a split cap makes freeze_hot
        # raise on SOME ranks only — the divergence class this string
        # exists to kill at bring-up
        return "%s/%d/t%d>>%d/r%d/h%d/c%d" % (
            self.name, self.num_shards, self.num_tables,
            self.table_shift, self.rows, self.hot_threshold,
            self.hot_cap)


class ReplicatedHotTier:
    """Host-side mirror of the frozen hot keys' rows — the replicated
    read tier of the 2-D grid (HierarchicalKV's cache-semantics store is
    the model): ``refresh`` gathers each hot key's row from its OWNING
    shard store once per pass; ``lookup`` then serves any subset without
    touching the owners — bit-identical rows to a direct owner-store
    read (pinned by tests/test_sharding_policy.py)."""

    def __init__(self, policy: TwoDGridPolicy) -> None:
        self.policy = policy
        self._keys = np.empty(0, np.uint64)
        self._rows = np.empty((0, 0), np.float32)

    def refresh(self, stores: Sequence) -> int:
        """Mirror the policy's frozen hot keys from their owner stores
        (None entries — shards this process doesn't own — are skipped:
        each process mirrors what it can address; a full replica needs
        either all shards local or a store plane that serves remote
        reads). Returns mirrored row count."""
        hot = self.policy.hot_keys_frozen()
        if not hot.size:
            self._keys = np.empty(0, np.uint64)
            self._rows = np.empty((0, 0), np.float32)
            return 0
        shard = self.policy.shard_of(hot)
        keys_out, rows_out = [], []
        for s in range(self.policy.num_shards):
            st = stores[s] if s < len(stores) else None
            if st is None:
                continue
            hk = hot[shard == s]
            if hk.size:
                keys_out.append(hk)
                rows_out.append(np.asarray(st.lookup(hk), np.float32))
        if not keys_out:
            self._keys = np.empty(0, np.uint64)
            self._rows = np.empty((0, 0), np.float32)
            return 0
        keys = np.concatenate(keys_out)
        rows = np.vstack(rows_out)
        order = np.argsort(keys, kind="stable")
        self._keys, self._rows = keys[order], rows[order]
        return int(keys.size)

    def lookup(self, keys: np.ndarray):
        """(rows [K, W], found [K]) — found=False rows are zero (the
        caller falls through to the routed path for them)."""
        from paddlebox_tpu.embedding.pass_table import sorted_member
        keys = np.asarray(keys, np.uint64)
        W = self._rows.shape[1] if self._rows.size else 0
        rows = np.zeros((keys.size, W), np.float32)
        pos, found = sorted_member(self._keys, keys)
        if found.any():
            rows[found] = self._rows[pos[found]]
        return rows, found


def validate_policy_agreement(fleet, policy: ShardingPolicy) -> None:
    """Cross-rank policy-identity check for the STORE host plane
    (hostplane=store, or the collective p2p fallback): the p2p
    rendezvous validates this itself, but a job on the store funnel
    never rendezvouses — and ranks on different policies route the same
    key to different owners on either plane. One allgather of
    describe() at construction; raises MeshPolicyMismatch naming every
    identity seen. Collective: every rank must call it (the runners do,
    gated identically by the shared hostplane flag)."""
    from paddlebox_tpu.fleet.mesh_comm import MeshPolicyMismatch
    mine = policy.describe()
    parts = fleet.all_gather(
        np.frombuffer(mine.encode("utf-8"), np.uint8).copy())
    seen = sorted({bytes(np.asarray(p, np.uint8)).decode("utf-8")
                   for p in parts})
    if seen != [mine]:
        raise MeshPolicyMismatch(
            "sharding-policy mismatch across ranks: cluster published "
            "%s — set the sharding_policy flag identically on every "
            "rank" % seen)


def resolve_sharding_policy(num_shards: int,
                            name: Optional[str] = None) -> ShardingPolicy:
    """Build the policy the ``sharding_policy`` flag (or `name`) selects.
    A typo'd value would otherwise silently train on the wrong layout —
    fail loud instead."""
    from paddlebox_tpu.config import flags
    v = str(name if name is not None
            else flags.get_flag("sharding_policy")).strip().lower()
    if v in ("key-mod", "keymod", "key_mod"):
        return KeyModPolicy(num_shards)
    num_tables = int(flags.get_flag("sharding_num_tables"))
    shift = int(flags.get_flag("sharding_table_shift"))
    if v in ("table-wise", "tablewise", "table_wise"):
        return TableWisePolicy(num_shards, num_tables, table_shift=shift)
    if v in ("2d-grid", "2d_grid", "2dgrid", "grid"):
        rows = int(flags.get_flag("sharding_grid_rows"))
        if rows <= 0:
            # auto: largest divisor of P not above sqrt(P) — a square-ish
            # grid balances table confinement against row rebalancing
            rows = max(r for r in range(1, int(num_shards ** 0.5) + 1)
                       if num_shards % r == 0)
        return TwoDGridPolicy(
            num_shards, num_tables, rows, table_shift=shift,
            hot_threshold=int(flags.get_flag("sharding_hot_threshold")),
            hot_cap=int(flags.get_flag("sharding_hot_cap")))
    raise ValueError(
        "sharding_policy must be 'key-mod', 'table-wise' or '2d-grid', "
        "got %r" % v)
