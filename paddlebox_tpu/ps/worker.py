"""CPU-PS training loop: the Downpour worker path.

Analog of DownpourWorker::TrainFiles (framework/downpour_worker.cc; the
CPU-PS counterpart of the Box loop, SURVEY.md §2.4): per batch the worker
FillSparseValue-pulls the batch's feature rows from the PS, runs the fused
jitted step, and pushes RAW sparse gradients back — the optimizer rule
runs server-side (sparse_sgd_rule.cc), unlike the Box path's in-slab
update. Dense grads go to a PS dense table through the same client.

Side machinery mirrors the reference:
  * `Communicator` — background sparse-grad aggregation + send thread
    (distributed/ps/service/communicator/communicator.{h,cc}): pushes
    queue up, get key-merged, and flush on a batch-count threshold.
  * `PullDenseWorker` — background dense-param refresh
    (framework/pull_dense_worker.cc).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.config.configs import (DataFeedConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data.dataset import BoxDataset
from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
from paddlebox_tpu.metrics.auc import MetricRegistry
from paddlebox_tpu.utils.lockwatch import make_lock


class Communicator:
    def __init__(self, client, table_id: int, push_width: int,
                 send_batch_threshold: int = 4,
                 send_interval: float = 0.05) -> None:
        self.client = client
        self.table_id = table_id
        self.push_width = push_width
        self.threshold = send_batch_threshold
        self.interval = send_interval
        self._pending: List[Tuple[np.ndarray, np.ndarray]] = []  # guarded-by: _lock
        self._lock = make_lock("Communicator._lock")
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()

    def push(self, keys: np.ndarray, grads: np.ndarray) -> None:
        with self._lock:
            self._pending.append((keys, grads))
            n = len(self._pending)
        if n >= self.threshold:
            self._kick.set()

    def _drain(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            if not self._pending:
                return None
            batch, self._pending = self._pending, []
        keys = np.concatenate([k for k, _ in batch])
        grads = np.concatenate([g for _, g in batch])
        # pre-merge duplicate keys so one RPC row per key reaches the PS
        uniq, inv = np.unique(keys, return_inverse=True)
        merged = np.zeros((uniq.size, grads.shape[1]), np.float32)
        np.add.at(merged, inv, grads)
        push = PushLayout(0)  # SLOT col index is layout-independent
        merged[inv, push.SLOT] = grads[:, push.SLOT]  # tag, not additive
        return uniq, merged

    def _send_loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.interval)
            self._kick.clear()
            item = self._drain()
            if item is not None:
                self.client.push_sparse(self.table_id, item[0], item[1])

    def flush(self) -> None:
        item = self._drain()
        if item is not None:
            self.client.push_sparse(self.table_id, item[0], item[1])

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        self._thread.join()
        self.flush()


class PullDenseWorker:
    def __init__(self, client, name: str, interval: float = 0.05) -> None:
        self.client = client
        self.name = name
        self.interval = interval
        self._value = client.pull_dense(name)  # guarded-by: _lock
        self._lock = make_lock("PullDenseWorker._lock")
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @property
    def value(self) -> np.ndarray:
        with self._lock:
            return self._value

    def refresh(self) -> np.ndarray:
        v = self.client.pull_dense(self.name)
        with self._lock:
            self._value = v
        return v

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.refresh()
            except (ConnectionError, OSError, RuntimeError):
                return

    def stop(self) -> None:
        self._stop.set()
        self._thread.join()


class DownpourTrainer:
    """Per-batch PS-pull / PS-push trainer over a fused jitted step. The
    client may be a PsLocalClient (single process) or TcpPSClient (real
    server) — the test tier uses both, mirroring ps_local_client.h vs
    brpc_service tests."""

    DENSE_TABLE = "downpour_dense"
    SPARSE_TABLE = 0

    def __init__(self, model, table_cfg: TableConfig, feed: DataFeedConfig,
                 client, trainer_cfg: Optional[TrainerConfig] = None,
                 seed: int = 0, create_tables: bool = True,
                 use_cvm: bool = True, sync_comm: bool = False) -> None:
        """sync_comm=True flushes sparse pushes and refreshes dense params
        every batch (the Communicator's sync mode, communicator.h) —
        deterministic, at the cost of the async pipeline overlap."""
        import jax
        import jax.flatten_util

        self.model = model
        self.cfg = trainer_cfg or TrainerConfig()
        self.feed = feed
        self.client = client
        self.table_cfg = table_cfg
        self.layout = ValueLayout(
            embedx_dim=table_cfg.embedx_dim,
            optimizer=table_cfg.optimizer.optimizer)
        self.push_layout = PushLayout(self.layout.embedx_dim)
        self.metrics = MetricRegistry()
        self.num_slots = len(feed.used_sparse_slots())
        params0 = model.init(jax.random.PRNGKey(seed))
        flat0, self._unravel = jax.flatten_util.ravel_pytree(params0)
        if create_tables:
            client.create_sparse_table(self.SPARSE_TABLE, table_cfg,
                                       seed=seed)
            client.create_dense_table(self.DENSE_TABLE,
                                      size=int(flat0.size), rule="adam",
                                      lr=self.cfg.dense_lr,
                                      init=np.asarray(flat0))
        self.pull_dense_worker = PullDenseWorker(client, self.DENSE_TABLE)
        self.communicator = Communicator(client, self.SPARSE_TABLE,
                                         self.push_layout.width)
        self.sync_comm = sync_comm
        self._step, self._eval_step = self._build_step()
        self._shuffle_rng = np.random.RandomState(seed + 1)
        self.multi_task = len(getattr(model, "task_names", ("ctr",))) > 1

    # ------------------------------------------------------------------ step
    def _build_step(self):
        import jax
        import jax.flatten_util
        import jax.numpy as jnp
        import optax

        from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
        from paddlebox_tpu.ops.sparse import build_push_grads, pull_sparse

        model = self.model
        layout = self.layout
        B = self.feed.batch_size
        S = self.num_slots

        from paddlebox_tpu.obs.device import instrument_jit

        def step(slab, params, batch):
            def loss_fn(params, emb):
                pooled = fused_seqpool_cvm(emb, batch["segments"],
                                           batch["valid"], B, S)
                logits = model.apply(params, pooled, batch.get("dense"))
                lab = batch["labels"].astype(jnp.float32)
                bce = optax.sigmoid_binary_cross_entropy(logits, lab)
                denom = jnp.maximum(batch["ins_valid"].sum(), 1.0)
                loss = jnp.where(batch["ins_valid"], bce, 0.0).sum() / denom
                return loss, jax.nn.sigmoid(logits)

            emb = pull_sparse(slab, batch["ids"], layout)
            grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1),
                                         has_aux=True)
            (loss, preds), (dparams, demb) = grad_fn(params, emb)
            flat_g = jax.flatten_util.ravel_pytree(dparams)[0]
            clicks = batch["labels"][batch["segments"] // S]
            push_rows = build_push_grads(demb, batch["slots"], clicks,
                                         batch["valid"])
            return flat_g, push_rows, loss, preds

        def eval_step(slab, params, batch):
            pooled = fused_seqpool_cvm(
                pull_sparse(slab, batch["ids"], layout), batch["segments"],
                batch["valid"], B, S)
            return jax.nn.sigmoid(
                model.apply(params, pooled, batch.get("dense")))

        return (instrument_jit(step, "ps_step", example_count=B),
                instrument_jit(eval_step, "ps_eval", example_count=B))

    # ------------------------------------------------------------- pass loop
    def _prepare_batch(self, b, create: bool = True):
        """FillSparseValue (downpour_worker.cc): batch keys → PS rows →
        per-batch dense slab + id remap + device batch dict."""
        import jax.numpy as jnp

        uniq, inv = np.unique(b.keys[b.valid], return_inverse=True)
        rows = self.client.pull_sparse(self.SPARSE_TABLE, uniq,
                                       create=create)
        slab = np.vstack([rows,
                          np.zeros((1, self.layout.width), np.float32)])
        ids = np.full(b.keys.shape[0], rows.shape[0], np.int64)
        ids[b.valid] = inv
        batch = {
            "ids": jnp.asarray(ids),
            "slots": jnp.asarray(b.slots),
            "segments": jnp.asarray(b.segments),
            "valid": jnp.asarray(b.valid),
            "ins_valid": jnp.asarray(b.ins_valid),
            "labels": jnp.asarray(b.labels),
        }
        if b.dense is not None:
            batch["dense"] = jnp.asarray(b.dense)
        return jnp.asarray(slab), batch

    def train_pass(self, dataset: BoxDataset) -> Dict[str, float]:
        import jax.numpy as jnp

        if len(dataset) == 0:
            dataset.load_into_memory()
        dataset.local_shuffle(self._shuffle_rng.randint(1 << 31))
        losses = []
        for b in dataset.split_batches(num_workers=1)[0]:
            slab, batch = self._prepare_batch(b)
            dense = (self.pull_dense_worker.refresh() if self.sync_comm
                     else self.pull_dense_worker.value)
            params = self._unravel(jnp.asarray(dense))
            flat_g, push_rows, loss, preds = self._step(slab, params, batch)
            push_rows = np.asarray(push_rows)
            keys = b.keys[b.valid]
            self.communicator.push(keys, push_rows[b.valid])
            if self.sync_comm:
                self.communicator.flush()
            self.client.push_dense(self.DENSE_TABLE, np.asarray(flat_g))  # boxlint: BX931 ok (dense push is a host RPC; per-batch D2H is the Downpour contract)
            # device scalar: np.mean at the pass boundary pays the D2H once
            losses.append(loss)
            self._add_metrics(np.asarray(preds), b)  # boxlint: BX931 ok (streaming metrics consume host preds per batch; device-collect mode is the sharded runner's job)
        self.communicator.flush()
        self.pull_dense_worker.refresh()
        return {"loss": float(np.mean(losses)) if losses else 0.0,
                "batches": len(losses), "instances": len(dataset)}

    def _add_metrics(self, preds: np.ndarray, b) -> None:
        if not self.metrics.metric_names():
            return
        self.metrics.add_batch({"pred": preds, "label": b.labels,
                                "mask": b.ins_valid})

    def predict_pass(self, dataset: BoxDataset):
        """Test-mode inference (SetTestMode pulls, box_wrapper.cc:183):
        forward-only jitted step, create=False pulls (missing keys read as
        zero rows, nothing inserted server-side), no sparse/dense push.
        Returns (preds, labels) over valid instances."""
        import jax.numpy as jnp

        if len(dataset) == 0:
            dataset.load_into_memory()
        preds_all, labels_all = [], []
        params = self._unravel(jnp.asarray(self.pull_dense_worker.refresh()))
        for b in dataset.split_batches(num_workers=1)[0]:
            slab, batch = self._prepare_batch(b, create=False)
            preds = np.asarray(self._eval_step(slab, params, batch))  # boxlint: BX931 ok (predict returns host preds; per-batch D2H bounds device memory over the pass)
            preds_all.append(preds[b.ins_valid])
            labels_all.append(b.labels[b.ins_valid])
        if not preds_all:
            return np.empty(0, np.float32), np.empty(0, np.int32)
        return np.concatenate(preds_all), np.concatenate(labels_all)

    def close(self) -> None:
        self.communicator.stop()
        self.pull_dense_worker.stop()
