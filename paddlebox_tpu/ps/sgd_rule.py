"""NumPy sparse SGD rules for the CPU parameter server.

Host-side mirror of the in-table optimizer semantics
(distributed/ps/table/sparse_sgd_rule.cc SparseAdaGradSGDRule /
SparseNaiveSGDRule + ctr_accessor.cc CtrCommonAccessor::Update): the CPU PS
applies pushes on the server thread, so the rule runs in numpy rather than
as the Pallas/XLA `apply_push` used on-device — with identical math
(parity-tested against `embedding.optimizers.apply_push`).
"""

from __future__ import annotations

import numpy as np

from paddlebox_tpu.config.configs import SparseOptimizerConfig
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout


def _adagrad_np(w, g2sum, g, scale, lr, initial_g2sum, min_b, max_b):
    scaled = g / scale
    add_g2 = np.mean(scaled * scaled, axis=-1, keepdims=True)
    ratio = lr * np.sqrt(initial_g2sum / (initial_g2sum + g2sum))
    neww = np.clip(w + ratio * scaled, min_b, max_b)
    return neww, g2sum + add_g2


def numpy_apply_push(values: np.ndarray, grads: np.ndarray,
                     rng: np.random.RandomState, layout: ValueLayout,
                     conf: SparseOptimizerConfig) -> np.ndarray:
    """Apply merged per-key gradients to value rows, in place semantics of
    the device `apply_push` (embedding/optimizers.py) for the adagrad and
    naive rules. values: [N, layout.width]; grads: [N, push.width]."""
    if layout.optimizer not in ("adagrad", "naive"):
        raise NotImplementedError(
            "CPU PS rule supports adagrad/naive; got " + layout.optimizer)
    push = PushLayout(layout.embedx_dim, layout.expand_dim)
    D = layout.embedx_dim
    out = values.copy()
    g_show = grads[:, push.SHOW:push.SHOW + 1]
    g_click = grads[:, push.CLICK:push.CLICK + 1]
    active = g_show > 0
    scale = np.where(active, g_show, 1.0)

    out[:, acc.SLOT:acc.SLOT + 1] = np.where(
        active, grads[:, push.SLOT:push.SLOT + 1],
        values[:, acc.SLOT:acc.SLOT + 1])
    show = values[:, acc.SHOW:acc.SHOW + 1] + g_show
    click = values[:, acc.CLICK:acc.CLICK + 1] + g_click
    out[:, acc.SHOW:acc.SHOW + 1] = show
    out[:, acc.CLICK:acc.CLICK + 1] = click
    out[:, acc.DELTA_SCORE:acc.DELTA_SCORE + 1] += (
        conf.nonclk_coeff * (g_show - g_click) + conf.clk_coeff * g_click)
    out[:, acc.UNSEEN_DAYS:acc.UNSEEN_DAYS + 1] = np.where(
        active, 0.0, values[:, acc.UNSEEN_DAYS:acc.UNSEEN_DAYS + 1])

    w = values[:, acc.EMBED_W:acc.EMBED_W + 1]
    g = grads[:, push.EMBED_G:push.EMBED_G + 1]
    es = layout.embed_state
    xw0 = layout.embedx_w
    xs = layout.embedx_state
    xg = grads[:, push.embedx_g:push.embedx_g + D]
    embedx = values[:, xw0:xw0 + D]

    if layout.optimizer == "adagrad":
        lr = np.where(
            values[:, acc.SLOT:acc.SLOT + 1] == float(conf.nodeid_slot),
            conf.mf_learning_rate, conf.feature_learning_rate)
        neww, newg2 = _adagrad_np(
            w, values[:, es:es + 1], g, scale, lr,
            conf.mf_initial_g2sum, conf.mf_min_bound, conf.mf_max_bound)
        out[:, acc.EMBED_W:acc.EMBED_W + 1] = neww
        out[:, es:es + 1] = newg2
        newx, newxg2 = _adagrad_np(
            embedx, values[:, xs:xs + 1], xg, scale,
            np.full_like(w, conf.mf_learning_rate),
            conf.mf_initial_g2sum, conf.mf_min_bound, conf.mf_max_bound)
        state_updates = {xs: newxg2}
    else:  # naive
        out[:, acc.EMBED_W:acc.EMBED_W + 1] = np.clip(
            w + conf.learning_rate * (g / scale),
            conf.min_bound, conf.max_bound)
        newx = np.clip(embedx + conf.mf_learning_rate * (xg / scale),
                       conf.mf_min_bound, conf.mf_max_bound)
        state_updates = {}

    # lazy embedx creation (dy_mf_update_value, optimizer.cuh.h:105-133)
    mf_size = values[:, acc.MF_SIZE:acc.MF_SIZE + 1]
    score = conf.nonclk_coeff * (show - click) + conf.clk_coeff * click
    create = (mf_size == 0) & (score >= conf.mf_create_thresholds) & active
    fresh = rng.uniform(0.0, conf.mf_initial_range,
                        embedx.shape).astype(np.float32)
    has_mf = mf_size > 0
    out[:, xw0:xw0 + D] = np.where(
        create, fresh, np.where(has_mf & active, newx, embedx))
    for col, newstate in state_updates.items():
        wdt = newstate.shape[-1]
        oldstate = values[:, col:col + wdt]
        out[:, col:col + wdt] = np.where(has_mf & active, newstate, oldstate)
    out[:, acc.MF_SIZE:acc.MF_SIZE + 1] = np.where(create, float(D), mf_size)

    # expand-embedding block shares the creation gate
    E = layout.expand_dim
    if E:
        ew0 = layout.expand_w
        expand = values[:, ew0:ew0 + E]
        eg = grads[:, push.expand_g:push.expand_g + E]
        if layout.optimizer == "adagrad":
            es2 = layout.expand_state
            newe, newe_g2 = _adagrad_np(
                expand, values[:, es2:es2 + 1], eg, scale,
                np.full_like(w, conf.mf_learning_rate),
                conf.mf_initial_g2sum, conf.mf_min_bound, conf.mf_max_bound)
            out[:, es2:es2 + 1] = np.where(
                has_mf & active, newe_g2, values[:, es2:es2 + 1])
        else:
            newe = np.clip(expand + conf.mf_learning_rate * (eg / scale),
                           conf.mf_min_bound, conf.mf_max_bound)
        fresh_e = rng.uniform(0.0, conf.mf_initial_range,
                              expand.shape).astype(np.float32)
        out[:, ew0:ew0 + E] = np.where(
            create, fresh_e, np.where(has_mf & active, newe, expand))

    return np.where(active, out, values).astype(np.float32)
