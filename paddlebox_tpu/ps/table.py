"""Parameter-server tables: sharded sparse + dense.

SparseTable = N shards of HostEmbeddingStore routed by key % shard_num
(MemorySparseTable's shard layout, memory_sparse_table.cc; the SSD tier
comes with the store's spill support = SSDSparseTable role). Push applies
the numpy SGD rule server-side (sparse_sgd_rule.cc semantics). DenseTable
mirrors MemoryDenseTable: a flat float vector with adam/sgd/summary update
rules applied on push.
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.config.configs import TableConfig
from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
from paddlebox_tpu.embedding.native_store import make_host_store
from paddlebox_tpu.ps.sgd_rule import numpy_apply_push
from paddlebox_tpu.utils.lockwatch import make_lock


class SparseTable:
    def __init__(self, table: TableConfig, shard_num: int = 8,
                 seed: int = 0) -> None:
        self.config = table
        self.layout = ValueLayout(
            embedx_dim=table.embedx_dim, expand_dim=table.expand_embed_dim,
            optimizer=table.optimizer.optimizer)
        self.push_layout = PushLayout(self.layout.embedx_dim,
                                      self.layout.expand_dim)
        self.shard_num = shard_num
        # native C++ store when it builds (bulk C calls per RPC instead of
        # per-key Python dict loops), Python fallback otherwise — identical
        # creation rng, so either backend serves the same rows
        self.shards = [make_host_store(self.layout, table, seed=seed + i)
                       for i in range(shard_num)]
        self._locks = [threading.Lock() for _ in range(shard_num)]
        self._rngs = [np.random.RandomState(seed + 101 + i)
                      for i in range(shard_num)]

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    def _route(self, keys: np.ndarray) -> np.ndarray:
        return (keys % np.uint64(self.shard_num)).astype(np.int64)

    # -------------------------------------------------------------- pull/push
    def pull(self, keys: np.ndarray, create: bool = True) -> np.ndarray:
        """Full value rows for (not necessarily unique) keys — the PS-side
        half of PullSparse (brpc_ps_server PullSparse handler).
        create=False is the test-mode pull (SetTestMode,
        box_wrapper.cc:183): missing keys read as zero rows, nothing is
        inserted server-side."""
        keys = np.asarray(keys, dtype=np.uint64)
        out = np.empty((keys.size, self.layout.width), np.float32)
        shard_of = self._route(keys)
        for s in range(self.shard_num):
            m = shard_of == s
            if not m.any():
                continue
            uniq, inv = np.unique(keys[m], return_inverse=True)
            with self._locks[s]:
                rows = (self.shards[s].lookup_or_create(uniq) if create
                        else self.shards[s].lookup(uniq))
            out[m] = rows[inv]
        return out

    def push(self, keys: np.ndarray, grads: np.ndarray) -> None:
        """Apply push-layout grads; duplicate keys are merged first
        (show-summed), like the worker-side dedup before PushSparse."""
        keys = np.asarray(keys, dtype=np.uint64)
        grads = np.asarray(grads, dtype=np.float32)
        shard_of = self._route(keys)
        for s in range(self.shard_num):
            m = shard_of == s
            if not m.any():
                continue
            uniq, inv = np.unique(keys[m], return_inverse=True)
            merged = np.zeros((uniq.size, grads.shape[1]), np.float32)
            np.add.at(merged, inv, grads[m])
            # slot is a tag, not additive: take any contributor's slot
            merged[inv, self.push_layout.SLOT] = grads[
                m, self.push_layout.SLOT]
            with self._locks[s]:
                rows = self.shards[s].lookup_or_create(uniq)
                newrows = numpy_apply_push(rows, merged, self._rngs[s],
                                           self.layout, self.config.optimizer)
                self.shards[s].write_back(uniq, newrows)

    def assign(self, keys: np.ndarray, values: np.ndarray) -> None:
        """Overwrite full value rows (creating missing keys) — the PS half
        of the pass-end HBM→CPU dump (PSGPUWrapper::EndPass →
        HeterComm::dump_to_cpu, ps_gpu_wrapper.cc:983+): the device slab
        already applied the optimizer, so rows are stored verbatim.
        Duplicate keys collapse to the FIRST occurrence's value."""
        keys = np.asarray(keys, dtype=np.uint64)
        values = np.asarray(values, dtype=np.float32)
        uniq, first = np.unique(keys, return_index=True)
        keys, values = uniq, values[first]
        shard_of = self._route(keys)
        for s in range(self.shard_num):
            m = shard_of == s
            if not m.any():
                continue
            with self._locks[s]:
                self.shards[s].assign(keys[m], values[m])

    # ------------------------------------------------------------- lifecycle
    def shrink(self) -> int:
        total = 0
        for s, lock in zip(self.shards, self._locks):
            with lock:
                total += s.shrink()
        return total

    def age_unseen_days(self) -> None:
        """Server-side day boundary: advance every feature's unseen_days
        (the delete_after_unseen_days clock)."""
        for s, lock in zip(self.shards, self._locks):
            with lock:
                s.age_unseen_days()

    def check_need_limit_mem(self, max_resident: Optional[int] = None) -> int:
        """Server-side DRAM budget (CheckNeedLimitMem/ShrinkResource,
        box_wrapper.h:627-629, on the SSDSparseTable tier): spill the
        coldest rows beyond the budget to the table's ssd_dir. Budget
        defaults from the config's ssd_threshold_mb; divided evenly across
        the server shards. Returns rows spilled."""
        budget = (max_resident if max_resident is not None
                  else self.config.ssd_max_resident_rows(self.layout.width))
        if budget is None:
            return 0
        per = budget // max(1, self.shard_num)
        total = 0
        for s, lock in zip(self.shards, self._locks):
            with lock:
                total += s.spill(per)
        return total

    def save(self, dirpath: str) -> List[str]:
        """Per-shard files (MemorySparseTable::Save shard file layout)."""
        os.makedirs(dirpath, exist_ok=True)
        paths = []
        for i, (s, lock) in enumerate(zip(self.shards, self._locks)):
            p = os.path.join(dirpath, "shard-%05d.pkl" % i)
            with lock:
                s.save(p)
            paths.append(p)
        return paths

    def load(self, dirpath: str) -> None:
        for i, (s, lock) in enumerate(zip(self.shards, self._locks)):
            p = os.path.join(dirpath, "shard-%05d.pkl" % i)
            with lock:
                s.load(p)


class DenseTable:
    """Flat dense parameter vector with a server-side optimizer
    (MemoryDenseTable: adam / sgd / summary rules)."""

    def __init__(self, size: int, rule: str = "adam", lr: float = 1e-3,
                 init: Optional[np.ndarray] = None) -> None:
        if rule not in ("adam", "sgd", "summary"):
            raise ValueError(rule)
        self.rule = rule
        self.lr = lr
        self.params = (np.array(init, np.float32) if init is not None
                       else np.zeros(size, np.float32))  # guarded-by: _lock
        self._mom1 = np.zeros_like(self.params)  # guarded-by: _lock
        self._mom2 = np.zeros_like(self.params)  # guarded-by: _lock
        self._t = 0  # guarded-by: _lock
        self._lock = make_lock("DenseTable._lock")

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.params.copy()

    def push(self, grad: np.ndarray) -> None:
        g = np.asarray(grad, np.float32)
        with self._lock:
            if self.rule == "summary":
                self.params += g  # running-sum semantics (data-norm stats)
                return
            if self.rule == "sgd":
                self.params -= self.lr * g
                return
            self._t += 1
            self._mom1 = 0.9 * self._mom1 + 0.1 * g
            self._mom2 = 0.999 * self._mom2 + 0.001 * g * g
            bc1 = 1 - 0.9 ** self._t
            bc2 = 1 - 0.999 ** self._t
            self.params -= (self.lr * (self._mom1 / bc1)
                            / (np.sqrt(self._mom2 / bc2) + 1e-8))

    def state(self) -> dict:
        with self._lock:
            return {"params": self.params.copy(), "mom1": self._mom1.copy(),
                    "mom2": self._mom2.copy(), "t": self._t,
                    "rule": self.rule, "lr": self.lr}

    def load_state(self, st: dict) -> None:
        with self._lock:
            self.params = np.asarray(st["params"], np.float32).copy()
            self._mom1 = np.asarray(st["mom1"], np.float32).copy()
            self._mom2 = np.asarray(st["mom2"], np.float32).copy()
            self._t = int(st["t"])
