"""PS service: core dispatch + in-process and TCP clients.

Shape of distributed/ps/service/: `PSCore` plays PsService (the handler
table behind brpc_ps_server.cc), `PsLocalClient` is the in-process client
fake (ps_local_client.h — single-process PS semantics for tests and
single-node runs), and `PSServer`/`TcpPSClient` stand in for the brpc
server/client pair with length-prefixed pickled frames over TCP (the trust
domain is the training cluster, as with the reference's brpc channel).
"""

from __future__ import annotations

import io
import pickle
import socket
import struct
import threading
from typing import Any, Dict, Optional

import numpy as np

from paddlebox_tpu.config.configs import TableConfig
from paddlebox_tpu.ps.table import DenseTable, SparseTable

_LEN = struct.Struct("<I")


class _RestrictedUnpickler(pickle.Unpickler):
    """Frames only ever carry numpy arrays, plain containers, and the two
    config dataclasses — refuse to resolve anything else (the codec is a
    cluster-internal channel like the reference's brpc/protobuf, but there
    is no reason to allow arbitrary class construction)."""

    def find_class(self, module, name):
        if module.split(".")[0] == "numpy":
            return super().find_class(module, name)
        if module == "paddlebox_tpu.config.configs" and name in (
                "TableConfig", "SparseOptimizerConfig"):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            "refusing to unpickle %s.%s" % (module, name))


def _loads(data: bytes) -> Any:
    return _RestrictedUnpickler(io.BytesIO(data)).load()


# ---------------------------------------------------------------------------
# Core (server-side handler table)
# ---------------------------------------------------------------------------


class PSCore:
    def __init__(self) -> None:
        self.sparse: Dict[int, SparseTable] = {}
        self.dense: Dict[str, DenseTable] = {}
        self._barrier_lock = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0

    # ---- table management
    def create_sparse_table(self, table_id: int, table: TableConfig,
                            shard_num: int = 8, seed: int = 0) -> None:
        self.sparse[table_id] = SparseTable(table, shard_num, seed=seed)

    def create_dense_table(self, name: str, size: int = 0, rule: str = "adam",
                           lr: float = 1e-3,
                           init: Optional[np.ndarray] = None) -> None:
        self.dense[name] = DenseTable(size, rule, lr, init)

    # ---- sparse
    def pull_sparse(self, table_id: int, keys: np.ndarray) -> np.ndarray:
        return self.sparse[table_id].pull(keys)

    def push_sparse(self, table_id: int, keys: np.ndarray,
                    grads: np.ndarray) -> None:
        self.sparse[table_id].push(keys, grads)

    def shrink(self, table_id: int) -> int:
        return self.sparse[table_id].shrink()

    def sparse_size(self, table_id: int) -> int:
        return len(self.sparse[table_id])

    # ---- dense
    def pull_dense(self, name: str) -> np.ndarray:
        return self.dense[name].pull()

    def push_dense(self, name: str, grad: np.ndarray) -> None:
        self.dense[name].push(grad)

    # ---- checkpoint
    def save(self, dirpath: str) -> None:
        import os
        for tid, t in self.sparse.items():
            t.save(os.path.join(dirpath, "sparse-%d" % tid))
        dense_state = {n: t.state() for n, t in self.dense.items()}
        with open(os.path.join(dirpath, "dense.pkl"), "wb") as f:
            pickle.dump(dense_state, f)

    def load(self, dirpath: str) -> None:
        import os
        for tid, t in self.sparse.items():
            t.load(os.path.join(dirpath, "sparse-%d" % tid))
        p = os.path.join(dirpath, "dense.pkl")
        if os.path.exists(p):
            with open(p, "rb") as f:
                for n, st in pickle.load(f).items():
                    if n in self.dense:
                        self.dense[n].load_state(st)

    # ---- barrier (BarrierTable role, barrier_table_test.cc)
    def barrier(self, world: int, timeout: float = 120.0) -> None:
        with self._barrier_lock:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= world:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_lock.notify_all()
                return
            ok = self._barrier_lock.wait_for(
                lambda: self._barrier_gen != gen, timeout)
            if not ok:
                raise TimeoutError("ps barrier timed out")


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------


class PsLocalClient:
    """In-process client: dispatches straight into a PSCore
    (ps_local_client.h pattern)."""

    def __init__(self, core: Optional[PSCore] = None) -> None:
        self.core = core or PSCore()

    def __getattr__(self, name):
        return getattr(self.core, name)

    def stop_server(self) -> None:
        pass


class TcpPSClient:
    """Framed request/response client (brpc_ps_client stand-in)."""

    def __init__(self, host: str, port: int, timeout: float = 300.0) -> None:
        self._sock = socket.create_connection((host, port), timeout=60.0)
        self._sock.settimeout(timeout)
        self._lock = threading.Lock()

    def _call(self, method: str, **kwargs) -> Any:
        payload = pickle.dumps({"method": method, "args": kwargs},
                               protocol=pickle.HIGHEST_PROTOCOL)
        with self._lock:
            self._sock.sendall(_LEN.pack(len(payload)) + payload)
            hdr = _recv_exact(self._sock, _LEN.size)
            if hdr is None:
                raise ConnectionError("ps server closed connection")
            (length,) = _LEN.unpack(hdr)
            body = _recv_exact(self._sock, length)
        resp = _loads(body)
        if not resp["ok"]:
            raise RuntimeError("ps rpc %s failed: %s" % (method,
                                                         resp["error"]))
        return resp.get("result")

    # mirror the PSClient interface
    def create_sparse_table(self, table_id, table, shard_num=8, seed=0):
        return self._call("create_sparse_table", table_id=table_id,
                          table=table, shard_num=shard_num, seed=seed)

    def create_dense_table(self, name, size=0, rule="adam", lr=1e-3,
                           init=None):
        return self._call("create_dense_table", name=name, size=size,
                          rule=rule, lr=lr, init=init)

    def pull_sparse(self, table_id, keys):
        return self._call("pull_sparse", table_id=table_id, keys=keys)

    def push_sparse(self, table_id, keys, grads):
        return self._call("push_sparse", table_id=table_id, keys=keys,
                          grads=grads)

    def pull_dense(self, name):
        return self._call("pull_dense", name=name)

    def push_dense(self, name, grad):
        return self._call("push_dense", name=name, grad=grad)

    def shrink(self, table_id):
        return self._call("shrink", table_id=table_id)

    def sparse_size(self, table_id):
        return self._call("sparse_size", table_id=table_id)

    def save(self, dirpath):
        return self._call("save", dirpath=dirpath)

    def load(self, dirpath):
        return self._call("load", dirpath=dirpath)

    def barrier(self, world, timeout=120.0):
        return self._call("barrier", world=world, timeout=timeout)

    def stop_server(self):
        try:
            self._call("__stop__")
        except (ConnectionError, OSError):
            pass

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class PSServer:
    """TCP server over a PSCore; one thread per client connection (the
    brpc_ps_server.cc role; barrier calls may block their conn thread)."""

    def __init__(self, core: Optional[PSCore] = None,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.core = core or PSCore()
        self._stop = threading.Event()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(64)
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.getsockname()[1]

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                hdr = _recv_exact(conn, _LEN.size)
                if hdr is None:
                    return
                (length,) = _LEN.unpack(hdr)
                body = _recv_exact(conn, length)
                if body is None:
                    return
                req = _loads(body)
                method = req["method"]
                if method == "__stop__":
                    self._send(conn, {"ok": True})
                    self.stop()
                    return
                try:
                    result = getattr(self.core, method)(**req["args"])
                    self._send(conn, {"ok": True, "result": result})
                except Exception as e:  # surface to the client
                    self._send(conn, {"ok": False, "error": repr(e)})
        finally:
            conn.close()

    @staticmethod
    def _send(conn: socket.socket, obj: Any) -> None:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        conn.sendall(_LEN.pack(len(payload)) + payload)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
