"""PS service: core dispatch + in-process and TCP clients.

Shape of distributed/ps/service/: `PSCore` plays PsService (the handler
table behind brpc_ps_server.cc), `PsLocalClient` is the in-process client
fake (ps_local_client.h — single-process PS semantics for tests and
single-node runs), and `PSServer`/`TcpPSClient` stand in for the brpc
server/client pair over the shared framed-RPC transport (utils/rpc.py;
the trust domain is the training cluster, as with the reference's brpc
channel — unpickling is restricted to numpy + the two config dataclasses).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Dict, Optional

import numpy as np

from paddlebox_tpu.config.configs import TableConfig
from paddlebox_tpu.ps.table import DenseTable, SparseTable
from paddlebox_tpu.utils.rpc import FramedClient, FramedServer, make_loads


def _allow(module: str, name: str) -> bool:
    if module.split(".")[0] == "numpy":
        return True
    return module == "paddlebox_tpu.config.configs" and name in (
        "TableConfig", "SparseOptimizerConfig")


_loads = make_loads(_allow)


# ---------------------------------------------------------------------------
# Core (server-side handler table)
# ---------------------------------------------------------------------------


class PSCore:
    def __init__(self) -> None:
        self.sparse: Dict[int, SparseTable] = {}
        self.dense: Dict[str, DenseTable] = {}
        self._barrier_lock = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0

    # ---- table management
    def create_sparse_table(self, table_id: int, table: TableConfig,
                            shard_num: int = 8, seed: int = 0) -> None:
        self.sparse[table_id] = SparseTable(table, shard_num, seed=seed)

    def create_dense_table(self, name: str, size: int = 0, rule: str = "adam",
                           lr: float = 1e-3,
                           init: Optional[np.ndarray] = None) -> None:
        self.dense[name] = DenseTable(size, rule, lr, init)

    # ---- sparse
    def pull_sparse(self, table_id: int, keys: np.ndarray,
                    create: bool = True) -> np.ndarray:
        return self.sparse[table_id].pull(keys, create=create)

    def push_sparse(self, table_id: int, keys: np.ndarray,
                    grads: np.ndarray) -> None:
        self.sparse[table_id].push(keys, grads)

    def assign_sparse(self, table_id: int, keys: np.ndarray,
                      values: np.ndarray) -> None:
        self.sparse[table_id].assign(keys, values)

    def shrink(self, table_id: int) -> int:
        return self.sparse[table_id].shrink()

    def age_unseen_days(self, table_id: int) -> None:
        self.sparse[table_id].age_unseen_days()

    def limit_mem(self, table_id: int,
                  max_resident: Optional[int] = None) -> int:
        return self.sparse[table_id].check_need_limit_mem(max_resident)

    def sparse_size(self, table_id: int) -> int:
        return len(self.sparse[table_id])

    # ---- dense
    def pull_dense(self, name: str) -> np.ndarray:
        return self.dense[name].pull()

    def push_dense(self, name: str, grad: np.ndarray) -> None:
        self.dense[name].push(grad)

    # ---- checkpoint
    def save(self, dirpath: str) -> None:
        import os
        for tid, t in self.sparse.items():
            t.save(os.path.join(dirpath, "sparse-%d" % tid))
        dense_state = {n: t.state() for n, t in self.dense.items()}
        with open(os.path.join(dirpath, "dense.pkl"), "wb") as f:
            pickle.dump(dense_state, f)

    def load(self, dirpath: str) -> None:
        import os
        for tid, t in self.sparse.items():
            t.load(os.path.join(dirpath, "sparse-%d" % tid))
        p = os.path.join(dirpath, "dense.pkl")
        if os.path.exists(p):
            with open(p, "rb") as f:
                for n, st in pickle.load(f).items():
                    if n in self.dense:
                        self.dense[n].load_state(st)

    # ---- barrier (BarrierTable role, barrier_table_test.cc)
    def barrier(self, world: int, timeout: float = 120.0) -> None:
        with self._barrier_lock:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count >= world:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_lock.notify_all()
                return
            ok = self._barrier_lock.wait_for(
                lambda: self._barrier_gen != gen, timeout)
            if not ok:
                raise TimeoutError("ps barrier timed out")


# ---------------------------------------------------------------------------
# Clients
# ---------------------------------------------------------------------------


class PsLocalClient:
    """In-process client: dispatches straight into a PSCore
    (ps_local_client.h pattern)."""

    def __init__(self, core: Optional[PSCore] = None) -> None:
        self.core = core or PSCore()

    def __getattr__(self, name):
        return getattr(self.core, name)

    def stop_server(self) -> None:
        pass


class TcpPSClient:
    """Framed request/response client (brpc_ps_client stand-in)."""

    def __init__(self, host: str, port: int, timeout: float = 300.0) -> None:
        self._rpc = FramedClient(host, port, _loads, timeout)

    def _call(self, method: str, _op_timeout=None, **kwargs) -> Any:
        return self._rpc.call({"method": method, "args": kwargs},
                              op_timeout=_op_timeout)

    # mirror the PSClient interface
    def create_sparse_table(self, table_id, table, shard_num=8, seed=0):
        return self._call("create_sparse_table", table_id=table_id,
                          table=table, shard_num=shard_num, seed=seed)

    def create_dense_table(self, name, size=0, rule="adam", lr=1e-3,
                           init=None):
        return self._call("create_dense_table", name=name, size=size,
                          rule=rule, lr=lr, init=init)

    def pull_sparse(self, table_id, keys, create=True):
        return self._call("pull_sparse", table_id=table_id, keys=keys,
                          create=create)

    def push_sparse(self, table_id, keys, grads):
        return self._call("push_sparse", table_id=table_id, keys=keys,
                          grads=grads)

    def assign_sparse(self, table_id, keys, values):
        return self._call("assign_sparse", table_id=table_id, keys=keys,
                          values=values)

    def pull_dense(self, name):
        return self._call("pull_dense", name=name)

    def push_dense(self, name, grad):
        return self._call("push_dense", name=name, grad=grad)

    def shrink(self, table_id):
        return self._call("shrink", table_id=table_id)

    def age_unseen_days(self, table_id):
        return self._call("age_unseen_days", table_id=table_id)

    def limit_mem(self, table_id, max_resident=None):
        return self._call("limit_mem", table_id=table_id,
                          max_resident=max_resident)

    def sparse_size(self, table_id):
        return self._call("sparse_size", table_id=table_id)

    def save(self, dirpath):
        return self._call("save", dirpath=dirpath)

    def load(self, dirpath):
        return self._call("load", dirpath=dirpath)

    def barrier(self, world, timeout=120.0):
        return self._call("barrier", _op_timeout=timeout, world=world,
                          timeout=timeout)

    def stop_server(self):
        try:
            self._call("__stop__")
        except (ConnectionError, OSError):
            pass

    def close(self):
        self._rpc.close()


class PSServer:
    """TCP server over a PSCore via the shared framed transport (the
    brpc_ps_server.cc role; barrier calls may block their conn thread)."""

    def __init__(self, core: Optional[PSCore] = None,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.core = core or PSCore()
        self._rpc = FramedServer(self._handle, _loads, host, port)

    @property
    def port(self) -> int:
        return self._rpc.port

    def _handle(self, req: dict) -> Any:
        method = req["method"]
        if method == "__stop__":
            # stop() only closes the LISTENER; the live connection still
            # delivers this frame's ack before its serve loop exits
            self.stop()
            return True
        return getattr(self.core, method)(**req["args"])

    def stop(self) -> None:
        self._rpc.stop()
