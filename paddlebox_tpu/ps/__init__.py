"""CPU distributed parameter server ("the-one-ps" analog).

TPU-native re-design of paddle/fluid/distributed/ps/: sharded host sparse
tables with CTR accessor semantics (table/memory_sparse_table.cc,
ctr_accessor.cc, sparse_sgd_rule.cc), dense tables
(memory_dense_table.cc), a PSClient interface (service/ps_client.h) with an
in-process local client (service/ps_local_client.h) and a TCP
server/client pair standing in for the brpc service
(service/brpc_ps_server.cc / brpc_ps_client.cc).
"""

from paddlebox_tpu.ps.sgd_rule import numpy_apply_push
from paddlebox_tpu.ps.table import DenseTable, SparseTable
from paddlebox_tpu.ps.service import (PSCore, PSServer, PsLocalClient,
                                      TcpPSClient)

__all__ = [
    "numpy_apply_push",
    "DenseTable",
    "SparseTable",
    "PSCore",
    "PSServer",
    "PsLocalClient",
    "TcpPSClient",
]
