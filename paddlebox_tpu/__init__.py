"""paddlebox_tpu: a TPU-native ultra-large-scale sparse CTR training framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of Baidu PaddleBox
(reference: shang1017/PaddleBox): a pod-sharded sparse embedding parameter
server with pass-cadenced HBM working sets and host-DRAM/SSD spill, exposed as
differentiable pull_sparse/push_sparse ops, an async multi-threaded data
pipeline, ICI-collective dense sync, streaming AUC metrics, and two-tier
(batch model + serving delta) checkpoints.

Layer map (TPU-native analog of reference SURVEY.md §1):
  models/     CTR model zoo (flax-free functional modules)      ~ L7 python API
  train/      trainer + pass loop + checkpoint                  ~ L5 trainer/worker runtime
  data/       slot records, parsers, packer, dataset            ~ L4 data pipeline
  ops/        sparse pull/push, seqpool+cvm, data_norm, ...     ~ L3 op library
  embedding/  sparse table: accessor, optimizers, pass slab,
              host store, sharded table                         ~ L2 BoxPS/HeterPS
  parallel/   mesh, collectives, ZeRO-1 sharding, pipeline,
              ring attention                                    ~ L1/§2.8 parallelism
  utils/      timers, stat registry, channels, flags            ~ L1 platform
"""

from paddlebox_tpu.version import __version__

from paddlebox_tpu.config import flags  # noqa: F401
# jax compat shims apply when jax itself is imported — NOT eagerly here:
# the package import stays jax-free (serving replicas, host tools), while
# every jax-using flow still sees the patched spellings before first use
from paddlebox_tpu.utils.compat_hook import install_deferred as _icd

_icd()
