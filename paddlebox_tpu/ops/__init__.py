from paddlebox_tpu.ops.sparse import (
    pull_sparse,
    build_push_grads,
    pull_sparse_differentiable,
    pull_sparse_extended,
    build_push_grads_extended,
)
from paddlebox_tpu.ops.seqpool import (
    fused_seqpool_cvm,
    fused_seqpool_cvm_tradew,
    fused_seqpool_cvm_with_conv,
    fused_seqpool_cvm_with_credit,
    fused_seqpool_cvm_with_diff_thres,
    fused_seqpool_cvm_with_pcoc,
    cvm_transform,
    cvm_conv_transform,
)
from paddlebox_tpu.ops.data_norm import (
    data_norm,
    data_norm_summary_update,
    masked_data_norm,
    masked_data_norm_stat_update,
)
from paddlebox_tpu.ops.rank_attention import rank_attention, batch_fc

__all__ = [
    "pull_sparse",
    "build_push_grads",
    "pull_sparse_differentiable",
    "pull_sparse_extended",
    "build_push_grads_extended",
    "fused_seqpool_cvm",
    "fused_seqpool_cvm_tradew",
    "fused_seqpool_cvm_with_conv",
    "fused_seqpool_cvm_with_credit",
    "fused_seqpool_cvm_with_diff_thres",
    "fused_seqpool_cvm_with_pcoc",
    "cvm_transform",
    "cvm_conv_transform",
    "data_norm",
    "data_norm_summary_update",
    "masked_data_norm",
    "masked_data_norm_stat_update",
    "rank_attention",
    "batch_fc",
]
