from paddlebox_tpu.ops.sparse import (
    pull_sparse,
    build_push_grads,
    pull_sparse_differentiable,
)
from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm, cvm_transform
from paddlebox_tpu.ops.data_norm import data_norm, data_norm_summary_update

__all__ = [
    "pull_sparse",
    "build_push_grads",
    "pull_sparse_differentiable",
    "fused_seqpool_cvm",
    "cvm_transform",
    "data_norm",
    "data_norm_summary_update",
]
