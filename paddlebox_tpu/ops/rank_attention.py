"""Join-phase pv ops: rank_attention and batch_fc.

TPU-native rank_attention_op (paddle/fluid/operators/rank_attention_op.cc,
rank_attention.cu.h) and batch_fc_op (operators/batch_fc_op.{cc,cu,h}) — the
position/rank attention and per-slot batched FC used in join-phase pv
(search-session) models.

The reference implements forward as two expand kernels
(expand_input_by_rank_kernel, expand_rank_attention_param_kernel) feeding a
batched GEMM, with three hand-written gradient merge kernels. Here both ops
are pure gather + einsum, so XLA autodiff derives the merges and the batched
GEMM tiles straight onto the MXU.

rank_offset row format (built by the rank-offset feed, data_feed.cu:1319):
    col 0:        this instance's rank within its pv, 1-based (<=0 invalid)
    col 2k+1:     rank of the k-th peer ad in the same pv (1-based, 0 absent)
    col 2k+2:     row index of that peer instance in the batch (-1 absent)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def rank_attention(x: jnp.ndarray, rank_offset: jnp.ndarray,
                   rank_param: jnp.ndarray, max_rank: int = 3
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x: [N, F]; rank_offset: [N, 1+2*max_rank] int32;
    rank_param: [max_rank*max_rank*F, out_dim].

    Returns (out [N, out_dim], ins_rank [N, 1]).

    Per instance i with rank r=rank_offset[i,0] and peers k with rank f_k and
    batch row idx_k: out[i] = Σ_k x[idx_k] @ P[(r-1)*max_rank + (f_k-1)] where
    P is rank_param viewed [max_rank², F, out_dim]
    (expand_rank_attention_param_kernel, rank_attention.cu.h:67-95).
    Invalid (r<=0 or f_k<=0) contributions are zero.
    """
    N, F = x.shape
    out_dim = rank_param.shape[1]
    pview = rank_param.reshape(max_rank * max_rank, F, out_dim)

    ins_rank = rank_offset[:, 0].astype(jnp.int32)            # [N] 1-based
    ks = jnp.arange(max_rank)
    peer_rank = rank_offset[:, 2 * ks + 1].astype(jnp.int32)  # [N, R]
    peer_idx = rank_offset[:, 2 * ks + 2].astype(jnp.int32)   # [N, R]

    valid = (ins_rank[:, None] > 0) & (peer_rank > 0)         # [N, R]
    safe_idx = jnp.clip(peer_idx, 0, N - 1)
    # input_help[i, k] = X[peer_idx_k] (expand_input_by_rank_kernel)
    input_help = jnp.where(valid[:, :, None], x[safe_idx], 0.0)  # [N, R, F]

    sel = (ins_rank[:, None] - 1) * max_rank + (peer_rank - 1)   # [N, R]
    sel = jnp.clip(sel, 0, max_rank * max_rank - 1)
    param_help = jnp.where(valid[:, :, None, None], pview[sel], 0.0)

    out = jnp.einsum("nkf,nkfo->no", input_help, param_help)
    return out, ins_rank[:, None].astype(x.dtype)


def batch_fc(x: jnp.ndarray, w: jnp.ndarray, bias: jnp.ndarray) -> jnp.ndarray:
    """Per-slot batched FC (batch_fc_op.cu): x [S, N, in], w [S, in, out],
    bias [S, out] → [S, N, out]. One bmm on the MXU + broadcast bias
    (the reference's blas.BatchedGEMM + add_bias_kernel)."""
    return jnp.einsum("sni,sio->sno", x, w) + bias[:, None, :]
