"""Differentiable sparse pull/push ops.

TPU-native pull_box_sparse / push_box_sparse
(paddle/fluid/operators/pull_box_sparse_op.{cc,h,cu}): the forward is a row
gather from the pass slab producing the per-key pull view
[show, click, embed_w, embedx...]; the backward is NOT a dense slab gradient
but a push-gradient construction (the grad-op-maker wires push as the
backward, pull_box_sparse_op.cc:128-141).

Two integration styles:
  * explicit (recommended, mirrors the reference worker loop): the train step
    calls pull_sparse(), differentiates the dense model w.r.t. the pulled
    embeddings, then builds push grads with build_push_grads() and applies
    them via the table's push kernel. Keeps the slab out of autodiff.
  * full-graph: pull_sparse_differentiable() is a custom_vjp whose cotangent
    w.r.t. the slab is a scatter-add — lets jax.grad flow end-to-end when a
    model wants that (costs a dense slab-shaped cotangent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.embedding import accessor as acc
from paddlebox_tpu.embedding.accessor import (PushLayout, ValueLayout,
                                              decode_slab_rows)


def pull_view_from_rows(rows: jnp.ndarray,
                        layout: ValueLayout) -> jnp.ndarray:
    """Pull view [K, 3+D] (show, click, embed_w, embedx) from already
    gathered full rows — split out so a step can keep the full rows and
    hand them to the push (which needs the state columns too) without a
    second slab-wide gather."""
    D = layout.embedx_dim
    xw0 = layout.embedx_w
    return jnp.concatenate([
        rows[:, acc.SHOW:acc.SHOW + 1],
        rows[:, acc.CLICK:acc.CLICK + 1],
        rows[:, acc.EMBED_W:acc.EMBED_W + 1],
        rows[:, xw0:xw0 + D],
    ], axis=1)


def gather_slab_rows(slab: jnp.ndarray, ids: jnp.ndarray,
                     layout: ValueLayout) -> jnp.ndarray:
    """[K, width] DECODED f32 rows gathered from the device slab — the
    one gather idiom every pull/push row-reuse site shares. Identity
    passthrough of slab[ids] for f32 layouts; under the bf16 slab diet
    (layout.embed_dtype) the gathered uint16 rows decode to f32 here, so
    downstream math (pull views, optimizer, pulled-row reuse) never sees
    encoded bits."""
    return decode_slab_rows(slab[ids], layout)


def pull_sparse(slab: jnp.ndarray, ids: jnp.ndarray,
                layout: ValueLayout) -> jnp.ndarray:
    """Gather per-key pull view [K, 3+D]: show, click, embed_w, embedx."""
    return pull_view_from_rows(gather_slab_rows(slab, ids, layout), layout)


def build_push_grads(d_emb: jnp.ndarray, slots: jnp.ndarray,
                     clicks: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Per-key push rows [K, 4+D] from the model's embedding cotangent.

    d_emb:  [K, 3+D] cotangent of the pull view (cols 0/1 — show/click CVM
            inputs — are dropped, as PushCopy skips the cvm offset,
            box_wrapper.cu:344-…)
    slots:  [K] slot id per key
    clicks: [K] the instance label each key occurrence belongs to
    valid:  [K] bool — False for padding key slots
    g_show is 1 per occurrence; the table's push kernel segment-sums
    duplicates so a key seen in k instances gets g_show=k (PushMergeCopy).
    """
    v = valid.astype(d_emb.dtype)[:, None]
    return jnp.concatenate([
        slots.astype(d_emb.dtype)[:, None],
        v,                                     # show = 1 per occurrence
        clicks.astype(d_emb.dtype)[:, None] * v,
        d_emb[:, 2:] * v,                      # embed_g + embedx_g
    ], axis=1)


def pull_sparse_extended(slab: jnp.ndarray, ids: jnp.ndarray,
                         layout: ValueLayout):
    """pull_box_extended_sparse (operators/pull_box_extended_sparse_op.*):
    dual-output lookup — the base pull view [K, 3+D] plus the expand
    (NN-cross) embedding [K, E]. Requires layout.expand_dim > 0."""
    if not layout.expand_dim:
        raise ValueError("layout has no expand block (expand_dim == 0)")
    rows = gather_slab_rows(slab, ids, layout)
    ew0 = layout.expand_w
    base = jnp.concatenate([
        rows[:, acc.SHOW:acc.SHOW + 1],
        rows[:, acc.CLICK:acc.CLICK + 1],
        rows[:, acc.EMBED_W:acc.EMBED_W + 1],
        rows[:, layout.embedx_w:layout.embedx_w + layout.embedx_dim],
    ], axis=1)
    return base, rows[:, ew0:ew0 + layout.expand_dim]


def build_push_grads_extended(d_emb: jnp.ndarray, d_expand: jnp.ndarray,
                              slots: jnp.ndarray, clicks: jnp.ndarray,
                              valid: jnp.ndarray) -> jnp.ndarray:
    """Push rows [K, 4+D+E] including the expand-block gradient
    (push_box_extended_sparse backward)."""
    v = valid.astype(d_emb.dtype)[:, None]
    return jnp.concatenate([
        slots.astype(d_emb.dtype)[:, None],
        v,
        clicks.astype(d_emb.dtype)[:, None] * v,
        d_emb[:, 2:] * v,
        d_expand * v,
    ], axis=1)


# ---------------------------------------------------------------- full graph
import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def pull_sparse_differentiable(slab, ids, layout: ValueLayout):
    if layout.embed_dtype != "float32":
        # the full-graph path's cotangent is a slab-shaped f32 scatter-add
        # — meaningless against an encoded uint16 slab. The explicit
        # pull/push integration (what the trainers run) supports the diet.
        raise ValueError(
            "pull_sparse_differentiable requires a float32 slab layout; "
            "the bf16 slab diet (slab_embed_dtype) is explicit-path only")
    return pull_sparse(slab, ids, layout)


def _pull_fwd(slab, ids, layout):
    return pull_sparse(slab, ids, layout), (ids, slab.shape)


def _pull_bwd(layout, res, d_out):
    ids, slab_shape = res
    D = layout.embedx_dim
    d_slab = jnp.zeros(slab_shape, d_out.dtype)
    # scatter-add only the trainable columns; show/click cotangents dropped
    d_slab = d_slab.at[ids, acc.EMBED_W].add(d_out[:, 2])
    xw0 = layout.embedx_w
    d_slab = d_slab.at[jnp.expand_dims(ids, 1),
                       jnp.arange(xw0, xw0 + D)[None, :]].add(d_out[:, 3:])
    return d_slab, None


pull_sparse_differentiable.defvjp(_pull_fwd, _pull_bwd)
