"""Fused per-slot sequence pooling + CVM transform.

TPU-native fused_seqpool_cvm (paddle/fluid/operators/fused/
fused_seqpool_cvm_op.*): the reference fuses "sum-pool each slot's
variable-length key list, then handle the CVM (show/click) columns" across
all slots in one CUDA kernel — the main dense-side fusion in CTR models.
Here the same fusion is one XLA segment-sum over the flattened key axis
followed by the CVM log transform; XLA fuses the rest into the surrounding
matmuls. The batch packer pre-computes segment ids (instance*num_slots+slot),
which replaces the LoD machinery with static shapes.

CVM columns follow cvm_op.h: y0 = log(show+1), y1 = log(click+1) - y0.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cvm_transform(pooled: jnp.ndarray, use_cvm: bool = True) -> jnp.ndarray:
    """pooled: [..., 2+E] with cols [show, click, emb...] → CVM columns
    (cvm_op.h semantics). use_cvm=False drops the two counter columns
    (CVMOpKernel's else-branch keeps dims-2)."""
    show = pooled[..., 0:1]
    click = pooled[..., 1:2]
    rest = pooled[..., 2:]
    if not use_cvm:
        return rest
    log_show = jnp.log(show + 1.0)
    log_ctr = jnp.log(click + 1.0) - log_show
    return jnp.concatenate([log_show, log_ctr, rest], axis=-1)


def fused_seqpool_cvm(emb: jnp.ndarray, segments: jnp.ndarray,
                      valid: jnp.ndarray, batch_size: int, num_slots: int,
                      use_cvm: bool = True,
                      pad_empty_zero: bool = True,
                      sorted_segments: bool = False) -> jnp.ndarray:
    """emb: [K, 2+E] per-key pull view; segments: [K] = ins*num_slots+slot;
    valid: [K] bool. Returns [batch, num_slots, out_dim] where out_dim is
    2+E with CVM or E without.

    Empty slots pool to zero (need_filter/padding_value=0 behavior of the
    reference kernel).

    sorted_segments=True asserts `segments` is nondecreasing — true for
    BatchPacker output (CSR order, padding tail pinned to the last segment)
    — letting XLA lower the pool as a sorted segment reduction instead of a
    random scatter-add (the TPU analog of the reference's one-kernel fusion,
    fused_seqpool_cvm_op.cu)."""
    masked = jnp.where(valid[:, None], emb, 0.0)
    pooled = jax.ops.segment_sum(
        masked, segments, num_segments=batch_size * num_slots,
        indices_are_sorted=sorted_segments)
    pooled = pooled.reshape(batch_size, num_slots, emb.shape[-1])
    return cvm_transform(pooled, use_cvm)


def cvm_conv_transform(pooled: jnp.ndarray, use_cvm: bool = True,
                       show_filter: bool = False) -> jnp.ndarray:
    """Conv variant (fused_seqpool_cvm_with_conv_op.cu FusedCVMWithConvKernel*):
    counter cols are [show, click, conv]; output cols
    [log(show+1), log(click+1), log(conv+1)-log(click+1), emb...].
    show_filter drops the show column (KernelWithOutShow)."""
    show = pooled[..., 0:1]
    click = pooled[..., 1:2]
    conv = pooled[..., 2:3]
    rest = pooled[..., 3:]
    if not use_cvm:
        return rest
    log_show = jnp.log(show + 1.0)
    log_click = jnp.log(click + 1.0)
    log_convr = jnp.log(conv + 1.0) - log_click
    cols = ([log_click, log_convr] if show_filter
            else [log_show, log_click, log_convr])
    return jnp.concatenate(cols + [rest], axis=-1)


def seqpool_sum(emb: jnp.ndarray, segments: jnp.ndarray, valid: jnp.ndarray,
                batch_size: int, num_slots: int) -> jnp.ndarray:
    """Plain per-slot sum pooling with NO cvm columns — the
    sequence_pool-SUM the extended (expand/NN-cross) embedding outputs
    feed (pull_box_extended_sparse's consumer pattern). The ONE
    implementation both trainers' expand paths share."""
    pooled = jax.ops.segment_sum(
        jnp.where(valid[:, None], emb, 0.0), segments,
        num_segments=batch_size * num_slots, indices_are_sorted=True)
    return pooled.reshape(batch_size, num_slots, emb.shape[-1])


def fused_seqpool_cvm_with_conv(
        emb: jnp.ndarray, segments: jnp.ndarray, valid: jnp.ndarray,
        batch_size: int, num_slots: int, use_cvm: bool = True,
        need_filter: bool = False, show_coeff: float = 0.2,
        clk_coeff: float = 1.0, threshold: float = 0.96,
        show_filter: bool = False) -> jnp.ndarray:
    """fused_seqpool_cvm_with_conv_op: pull view is [show, click, conv, emb...]
    per key. need_filter drops keys whose show/click score
    (show-click)*show_coeff + click*clk_coeff falls under threshold before
    pooling (FusedSeqpoolWithConvKernelFilter, with_conv_op.cu:58-88)."""
    keep = valid
    if need_filter:
        show = emb[:, 0]
        click = emb[:, 1]
        keep = keep & ((show - click) * show_coeff + click * clk_coeff
                       >= threshold)
    masked = jnp.where(keep[:, None], emb, 0.0)
    pooled = jax.ops.segment_sum(
        masked, segments, num_segments=batch_size * num_slots)
    pooled = pooled.reshape(batch_size, num_slots, emb.shape[-1])
    return cvm_conv_transform(pooled, use_cvm, show_filter)


def _segpool(emb: jnp.ndarray, segments: jnp.ndarray, keep: jnp.ndarray,
             batch_size: int, num_slots: int) -> jnp.ndarray:
    # no indices_are_sorted hint: the packer's trailing PADDING slots carry
    # segment 0 after larger ids, so the ids are not globally sorted (and
    # the hint measured no win on v5e anyway)
    masked = jnp.where(keep[:, None], emb, 0.0)
    pooled = jax.ops.segment_sum(
        masked, segments, num_segments=batch_size * num_slots)
    return pooled.reshape(batch_size, num_slots, emb.shape[-1])


def fused_seqpool_cvm_with_credit(
        emb: jnp.ndarray, segments: jnp.ndarray, valid: jnp.ndarray,
        batch_size: int, num_slots: int, use_cvm: bool = True,
        show_filter: bool = False) -> jnp.ndarray:
    """fused_seqpool_cvm_with_credit_op (with_credit_op.cu:53-110): per-key
    cols [show, click, conv, credit, emb...]; each of the 4 counters maps to
    log(x+1) independently (no ctr-smooth subtraction); show_filter drops
    the show column (KernelWithOutShow); use_cvm=False drops all four."""
    pooled = _segpool(emb, segments, valid, batch_size, num_slots)
    if not use_cvm:
        return pooled[..., 4:]
    counters = jnp.log(pooled[..., :4] + 1.0)
    if show_filter:
        counters = counters[..., 1:]
    return jnp.concatenate([counters, pooled[..., 4:]], axis=-1)


def fused_seqpool_cvm_tradew(
        emb: jnp.ndarray, segments: jnp.ndarray, valid: jnp.ndarray,
        batch_size: int, num_slots: int, trade_num: int,
        trade_id: int = None, use_cvm: bool = True) -> jnp.ndarray:
    """fused_seqpool_cvm_tradew_op (tradew_op.cu:34-131): per-key cols
    [show, click, trade_w[trade_num], emb...]. The embedding part pools
    weighted by the selected trade's weight column (KernelWithTradeId,
    cu:63-88); without a trade_id the trade block is simply skipped
    (KernelNormal). CVM columns follow the standard transform."""
    cvm_part = emb[:, :2]
    emb_part = emb[:, 2 + trade_num:]
    if trade_id is not None:
        w = emb[:, 2 + trade_id:3 + trade_id]
        emb_part = emb_part * w
    pooled = _segpool(jnp.concatenate([cvm_part, emb_part], axis=1),
                      segments, valid, batch_size, num_slots)
    return cvm_transform(pooled, use_cvm)


def fused_seqpool_cvm_with_diff_thres(
        emb: jnp.ndarray, segments: jnp.ndarray, valid: jnp.ndarray,
        slots: jnp.ndarray, batch_size: int, num_slots: int,
        slot_thresholds: jnp.ndarray, use_cvm: bool = True,
        show_coeff: float = 0.2, clk_coeff: float = 1.0,
        xbox_diff_thres_filter: bool = True,
        threshold: float = 0.96) -> jnp.ndarray:
    """fused_seqpool_cvm_with_diff_thres_op (with_diff_thres_op.cu:87-131):
    the base fused op with a PER-SLOT filter threshold vector — keys whose
    show/click score falls under threshold_vec[slot] are dropped before
    pooling (xbox_diff_thres_filter=False falls back to the scalar)."""
    show, click = emb[:, 0], emb[:, 1]
    score = (show - click) * show_coeff + click * clk_coeff
    thres = (jnp.asarray(slot_thresholds)[slots]
             if xbox_diff_thres_filter else threshold)
    keep = valid & (score >= thres)
    pooled = _segpool(emb, segments, keep, batch_size, num_slots)
    return cvm_transform(pooled, use_cvm)


def fused_seqpool_cvm_with_pcoc(
        emb: jnp.ndarray, segments: jnp.ndarray, valid: jnp.ndarray,
        batch_size: int, num_slots: int, pclk_num: int,
        use_cvm: bool = True) -> jnp.ndarray:
    """fused_seqpool_cvm_with_pcoc_op (with_pcoc_op.cu:122-160): per-key
    cols [show, click, show2, clk2, pclk_1..pclk_n, emb...]; output
    counters [log(show+1), log(click+1)-log(show+1),
    (log(pclk_i+1)-log(show2+1))_i, (log(pclk_i+1)-log(clk2+1))_i] then
    the embedding passthrough; use_cvm=False drops every counter col."""
    used = 4 + pclk_num
    pooled = _segpool(emb, segments, valid, batch_size, num_slots)
    if not use_cvm:
        return pooled[..., used:]
    log1p = jnp.log(pooled[..., :used] + 1.0)
    log_show, log_click = log1p[..., 0:1], log1p[..., 1:2]
    log_show2, log_clk2 = log1p[..., 2:3], log1p[..., 3:4]
    log_pclk = log1p[..., 4:used]
    return jnp.concatenate([
        log_show,
        log_click - log_show,
        log_pclk - log_show2,
        log_pclk - log_clk2,
        pooled[..., used:],
    ], axis=-1)
