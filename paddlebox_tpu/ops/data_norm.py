"""Streaming feature normalization (data_norm).

TPU-native data_norm_op (paddle/fluid/operators/data_norm_op.cc): normalizes
each feature column by running summary statistics (BatchSize/BatchSum/
BatchSquareSum), the "summary" params that BoxPSWorker syncs with the
DenseDataNormal mode (boxps_worker.cc:89-95, 389-391).

Forward (data_norm_op.cc:327-355):
    mean  = batch_sum / batch_size
    scale = sqrt(batch_size / batch_square_sum)
    y     = (x - mean) * scale
slot_dim > 0 adds the show-skip rule: within each slot_dim block, instances
whose first column (show) is ~0 emit zeros.

Summary update: the reference routes summary grads through the optimizer with
a decay (summary_decay_rate); data_norm_summary_update applies the same
running-sums rule functionally.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

_MIN_PRECISION = 1e-7


class DataNormState(NamedTuple):
    """Per-column summary params; init mirrors the reference's defaults
    (batch_size=1e4, square_sum=1e4·eps-ish kept simple as ones)."""

    batch_size: jnp.ndarray
    batch_sum: jnp.ndarray
    batch_square_sum: jnp.ndarray

    @classmethod
    def init(cls, dim: int, init_batch_size: float = 1e4) -> "DataNormState":
        return cls(
            batch_size=jnp.full((dim,), init_batch_size, jnp.float32),
            batch_sum=jnp.zeros((dim,), jnp.float32),
            batch_square_sum=jnp.full((dim,), init_batch_size, jnp.float32),
        )


def data_norm(x: jnp.ndarray, state: DataNormState,
              slot_dim: int = 0) -> jnp.ndarray:
    """x: [N, C] → normalized y: [N, C]."""
    mean = state.batch_sum / state.batch_size
    scale = jnp.sqrt(state.batch_size / state.batch_square_sum)
    y = (x - mean) * scale
    if slot_dim > 0:
        C = x.shape[-1]
        shows = x[:, 0::slot_dim]  # first col of each slot block
        block_alive = jnp.abs(shows) >= _MIN_PRECISION  # [N, C/slot_dim]
        alive = jnp.repeat(block_alive, slot_dim, axis=1)[:, :C]
        y = jnp.where(alive, y, 0.0)
    return y


def masked_data_norm(x: jnp.ndarray, mask: jnp.ndarray,
                     state: DataNormState) -> jnp.ndarray:
    """masked_data_norm_op (operators/masked_data_norm_op.cu:39-51): rows with
    mask True are normalized, rows with mask False emit zeros."""
    mean = state.batch_sum / state.batch_size
    scale = jnp.sqrt(state.batch_size / state.batch_square_sum)
    mask = mask.reshape(-1).astype(bool)
    return jnp.where(mask[:, None], (x - mean) * scale, 0.0)


def masked_data_norm_stat_update(state: DataNormState, x: jnp.ndarray,
                                 mask: jnp.ndarray,
                                 decay: float = 0.9999999,
                                 squared_sum_epsilon: float = 1e-4
                                 ) -> DataNormState:
    """KernelMaskedDataNormBPStat + KernelUpdateParam
    (masked_data_norm_op.cu:81-131): per-column stats over masked rows only,
    normalized to batch_size=1; empty batches skip the decay entirely."""
    mask = mask.reshape(-1).astype(bool)
    mean = state.batch_sum / state.batch_size
    n = mask.sum()
    cnt = jnp.maximum(n, 1).astype(jnp.float32)
    xs = jnp.where(mask[:, None], x, 0.0)
    sq = jnp.where(mask[:, None], (x - mean) ** 2, 0.0)
    d_size = jnp.where(n > 0, 1.0, 0.0)
    d_sum = xs.sum(axis=0) / cnt
    d_sq = sq.sum(axis=0) / cnt + squared_sum_epsilon
    keep = n > 0
    return DataNormState(
        batch_size=jnp.where(keep, state.batch_size * decay + d_size,
                             state.batch_size),
        batch_sum=jnp.where(keep, state.batch_sum * decay + d_sum,
                            state.batch_sum),
        batch_square_sum=jnp.where(keep, state.batch_square_sum * decay + d_sq,
                                   state.batch_square_sum),
    )


def data_norm_summary_update(state: DataNormState, x: jnp.ndarray,
                             decay: float = 0.9999999,
                             slot_dim: int = 0) -> DataNormState:
    """Accumulate this batch into the running summaries with decay
    (summary_decay_rate semantics). With slot_dim, dead blocks (show≈0)
    contribute nothing, matching the show-skip rule."""
    mean = state.batch_sum / state.batch_size
    sq = (x - mean) ** 2
    if slot_dim > 0:
        C = x.shape[-1]
        shows = x[:, 0::slot_dim]
        block_alive = jnp.abs(shows) >= _MIN_PRECISION
        alive = jnp.repeat(block_alive, slot_dim, axis=1)[:, :C]
        cnt = alive.sum(axis=0).astype(jnp.float32)
        xs = jnp.where(alive, x, 0.0)
        sq = jnp.where(alive, sq, 0.0)
    else:
        cnt = jnp.full((x.shape[-1],), float(x.shape[0]), jnp.float32)
        xs = x
    return DataNormState(
        batch_size=state.batch_size * decay + cnt,
        batch_sum=state.batch_sum * decay + xs.sum(axis=0),
        batch_square_sum=state.batch_square_sum * decay + sq.sum(axis=0),
    )
