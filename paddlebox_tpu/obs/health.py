"""Cluster health plane: per-rank health scores derived on rank 0.

The elastic fleet (ROADMAP item 5) needs a TRIGGER — "rank 3 is gone,
replace it" — and the aggregation path already sees everything needed to
derive one: report freshness per window (a wedged/killed rank stops
publishing), watchdog-beat age (reporters gauge it), warning/error log
line rates (obs/log counts them into the StatRegistry), channel/pull
queue depths (the chan_*_depth gauges), and the serving tier's
p99-vs-SLO burn gauge. HealthMonitor folds those into one score per
rank each aggregation cadence and rank 0 publishes a ``cluster_health``
record through the same sink/flight machinery as every other report —
fleet and serving health read off ONE schema.

Scoring (documented contract, pinned by tests): each rank starts at 1.0
and loses
  * 0.4  stale this window (no report arrived since the last merge)
  * all  (score = 0.0) stale ``stale_unhealthy`` consecutive windows —
         the "declare it dead" threshold the chaos test pins (a killed
         rank reads unhealthy within 2 cadences)
  * 0.3  error log lines in the window
  * 0.1  warning log lines in the window
  * 0.2  any channel/queue depth gauge above ``depth_warn``
  * 0.3  serving SLO burn above 1.0 (window p99 past serving_slo_us)
  * 0.6  beat age above ``beat_age_warn`` — the rank still REPORTS but
         its step loop stopped beating (wedged exchange/driver thread
         behind a live reporting path), which freshness cannot see
  * 0.6  data-quality drift (round 18): the rank's ``data_drift_score``
         gauge (metrics/drift.py — per-slot coverage collapse, keys/
         record drift, cardinality collapse, label/pred distribution
         drift) at or past ``drift_warn`` — weighted past the healthy
         bar on its own, so a dropped upstream slot turns its victim
         unhealthy the window its gauge lands, even with every systems
         signal green
  * 0.3  miscalibration (round 18): the rank's ``quality_copc`` gauge
         (metrics/quality.py: click over predicted click) outside the
         ``copc_band`` calibration band — the failure that kills a
         production CTR model while every systems signal stays green
  * 0.6  steady-state recompiles (round 20): ``device_recompiles``
         counted in the window (obs/device.py's sentinel — shape/dtype
         churn recompiling a hot jit entry point stalls every step for
         a full XLA compile); weighted past the healthy bar on its own
  * 0.6  donation miss (round 20): ``donation_miss`` counted in the
         window — a donated slab-scale buffer was copied instead of
         aliased, the regime-step mechanism; the step is silently
         paying a slab memcpy, so the rank reads unhealthy even while
         it keeps stepping
  * 0.4  freshness burn (round 20): ``serving_freshness_burn`` above
         1.0 — the report window's p99 feed-to-serve freshness
         (obs/watermark.py, sampled per pull against the journal
         watermark) exceeded ``freshness_slo_secs``; a stalling
         journal tail trips this within two report windows
  * 0.3  tier-hit burn (round 20): ``tier_hit_burn`` above 1.0 — a
         warm store's host-RAM hit rate fell below
         ``tier_hit_rate_warn`` (the SSD tier is thrashing instead of
         absorbing the cold tail)
``healthy`` = score >= 0.5.

Staleness measures TELEMETRY silence, which is the only signal rank 0
has — a rank whose publish transport is down (aggregator backoff skips
a bounded number of publishes) reads stale→unhealthy exactly like a
dead rank until its re-probe lands, then recovers. The elastic-fleet
consumer should therefore act on SUSTAINED unhealthy (``stale_windows``
in the record makes the streak length explicit), not a single flip.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

SCHEMA_VERSION = 1


class HealthMonitor:
    """Rank-0 resident. Thread contract: ``update`` is called from the
    aggregation path only (the reporter driver thread)."""

    def __init__(self, world: int, stale_unhealthy: int = 2,
                 depth_warn: float = 64.0,
                 beat_age_warn: float = 30.0,
                 drift_warn: float = 0.5,
                 copc_band: tuple = (0.8, 1.25)) -> None:
        self.world = int(world)
        self.stale_unhealthy = int(stale_unhealthy)
        self.depth_warn = float(depth_warn)
        self.beat_age_warn = float(beat_age_warn)
        self.drift_warn = float(drift_warn)
        self.copc_band = (float(copc_band[0]), float(copc_band[1]))
        self._stale_windows: Dict[int, int] = {r: 0 for r in range(world)}
        self.last_health: Optional[dict] = None

    # ------------------------------------------------------------- helpers
    def _per_rank(self, merged: dict, metric: str) -> Dict[int, float]:
        m = (merged.get("metrics") or {}).get(metric)
        if not m:
            return {}
        return {int(r): float(v)
                for r, v in (m.get("per_rank") or {}).items()}

    # -------------------------------------------------------------- update
    def update(self, merged: dict) -> dict:
        """Fold one merged cluster_report window into per-rank health;
        returns the cluster_health record (also kept as last_health)."""
        stale = set(merged.get("stale_ranks") or [])
        err = self._per_rank(merged, "stats.log_error_lines")
        rpc_err = self._per_rank(merged, "stats.rpc_handler_errors")
        warn = self._per_rank(merged, "stats.log_warning_lines")
        beat_age = self._per_rank(merged, "gauges.beat_age_s")
        slo_burn = self._per_rank(merged, "gauges.serving_slo_burn")
        fresh_burn = self._per_rank(merged,
                                    "gauges.serving_freshness_burn")
        tier_burn = self._per_rank(merged, "gauges.tier_hit_burn")
        drift = self._per_rank(merged, "gauges.data_drift_score")
        copc = self._per_rank(merged, "gauges.quality_copc")
        recompiles = self._per_rank(merged, "stats.device_recompiles")
        donation = self._per_rank(merged, "stats.donation_miss")
        depths = {}
        for k, m in (merged.get("metrics") or {}).items():
            if (k.startswith("gauges.") and k.endswith("_depth")):
                for r, v in (m.get("per_rank") or {}).items():
                    depths[int(r)] = max(depths.get(int(r), 0.0), float(v))

        ranks = {}
        unhealthy: List[int] = []
        for r in range(self.world):
            if r in stale:
                self._stale_windows[r] = self._stale_windows.get(r, 0) + 1
            else:
                self._stale_windows[r] = 0
            sw = self._stale_windows[r]
            score = 1.0
            flags: List[str] = []
            if sw >= self.stale_unhealthy:
                score = 0.0
                flags.append("stale_%d_windows" % sw)
            elif sw:
                score -= 0.4
                flags.append("stale")
            n_err = err.get(r, 0.0) + rpc_err.get(r, 0.0)
            if n_err > 0:
                score -= 0.3
                flags.append("error_lines")
            if warn.get(r, 0.0) > 0:
                score -= 0.1
                flags.append("warning_lines")
            if depths.get(r, 0.0) > self.depth_warn:
                score -= 0.2
                flags.append("queue_depth")
            if slo_burn.get(r, 0.0) > 1.0:
                score -= 0.3
                flags.append("slo_burn")
            if fresh_burn.get(r, 0.0) > 1.0:
                # feed-to-serve freshness past SLO (round 20): served
                # vectors are older than the promise — a stalled
                # journal tail, a wedged streaming runner, or a
                # refresh watcher that stopped swapping all land here
                score -= 0.4
                flags.append("freshness_burn")
            if tier_burn.get(r, 0.0) > 1.0:
                score -= 0.3
                flags.append("tier_hit_low")
            if beat_age.get(r, 0.0) > self.beat_age_warn:
                # reporting-but-not-beating: the wedge freshness can't
                # see — weighted past the 0.5 healthy bar on its own
                score -= 0.6
                flags.append("beat_stalled")
            if drift.get(r, 0.0) >= self.drift_warn:
                # slot-level data-quality drift (a dropped upstream
                # feature pipeline): weighted past the healthy bar on
                # its own — the victim rank must read unhealthy even
                # while every systems signal is green
                score -= 0.6
                flags.append("data_drift")
            c = copc.get(r)
            if c is not None and c > 0 and not (
                    self.copc_band[0] <= c <= self.copc_band[1]):
                score -= 0.3
                flags.append("miscalibrated")
            if recompiles.get(r, 0.0) > 0:
                # device-plane sentinel (round 20): steady-state
                # recompiles stall every step for a full XLA compile —
                # past the healthy bar on its own
                score -= 0.6
                flags.append("device_recompiles")
            if donation.get(r, 0.0) > 0:
                # donation miss = the step silently pays a slab-sized
                # copy (the regime-step mechanism) — past the bar alone
                score -= 0.6
                flags.append("donation_miss")
            score = max(0.0, min(1.0, score))
            entry = {"score": round(score, 3),
                     "healthy": score >= 0.5,
                     "stale_windows": sw}
            if flags:
                entry["flags"] = flags
            if r in beat_age:
                entry["beat_age_s"] = round(beat_age[r], 3)
            if n_err:
                entry["err_lines"] = n_err
            if r in slo_burn:
                entry["slo_burn"] = round(slo_burn[r], 4)
            if r in fresh_burn:
                entry["freshness_burn"] = round(fresh_burn[r], 4)
            if r in tier_burn:
                entry["tier_hit_burn"] = round(tier_burn[r], 4)
            ranks[str(r)] = entry
            if not entry["healthy"]:
                unhealthy.append(r)

        rec = {"type": "cluster_health", "v": SCHEMA_VERSION,
               "ts": time.time(), "step": int(merged.get("step", 0)),
               "world": self.world, "ranks": ranks,
               "unhealthy_ranks": unhealthy}
        self.last_health = rec
        return rec
