"""Device plane: the obs tier that watches the XLA/device layer.

Every other obs tier (spans, StepReports, flight, health, /metrics)
watches the HOST. The two open perf mysteries live BELOW it: the
>=4M-row regime step is hypothesized to be a donation-miss slab copy
(tools/regime_step_probe.py measured the 1.36x fresh-vs-donated gap),
and every roofline claim rests on one-shot offline runs of
tools/step_audit.py. This module makes the device layer continuously
observable through the UNCHANGED publication machinery:

  * instrument_jit(fn, name, donate_argnums=...) — the one wrapper every
    jit entry point goes through (boxlint BX901 enforces it). Per
    function it keeps compile count + compile wall time, a one-time
    cost_analysis()/memory_analysis() snapshot (the step_audit math,
    shared — see analyze_compiled), and a RECOMPILE SENTINEL: a
    steady-state recompile (same name, more compiles than the
    device_recompile_warmup allowance — shape/dtype churn from a
    mis-staged batch) bumps the ``device_recompiles`` stat, logs loudly
    once per fn, and turns the rank unhealthy through HealthMonitor.
  * donation audit — for donated entry points the wrapper compares the
    donated buffers' unsafe_buffer_pointer() against the outputs'
    (backend-guarded): a donated buffer that did NOT come back as an
    output was copied, not aliased — the regime-step mechanism — and
    bumps the ``donation_miss`` stat. The count is DEBOUNCED per
    executable: a miss is recorded only when the same executable's
    previous audited call also missed. The pass's first step donates
    the host-STAGED slab — a buffer jax zero-copied from numpy memory,
    which cannot be aliased in place and is copied exactly once
    (measured 100% on the CPU backend; alignment-dependent, hence
    flaky without the debounce) — while the regime the alarm exists
    for is the recurring per-step copy, which is counted from its
    second consecutive call. Buffers below device_donation_min_bytes
    are not audited (tiny buffers are aliasing noise; the alarm exists
    for slab-scale copies).
  * transfer ledger — account_h2d/account_d2h: the runners' staging and
    write-back paths count ``device_transfer_bytes_{h2d,d2h}`` and feed
    the ``device_{h2d,d2h}_bytes`` fixed-bucket histograms.
  * HBM/live-buffer ledger — sample_ledger() buckets jax.live_arrays()
    by registered logical owner (slab / dense params / opt state /
    other) into gauges at report cadence, with a monotonic-growth leak
    detector across samples (``device_leak_suspect``).

Everything lands in the StatRegistry, so StepReports carry the deltas,
/metrics exports the series, the flight recorder seals a device
snapshot, cluster aggregation min/med/max's them at rank 0, and the
/device endpoint serves snapshot() live.

Mechanism: the wrapper runs jax.jit through the explicit AOT path —
lower().compile() once per (pytree structure, shape, dtype) signature,
cached here — so compile COUNT and WALL TIME are exact (not inferred
from call latency) and the cost/memory analyses come free with the
executable instead of a second compile. Dispatch parity with the C++
jit fast path is measured in bench.py's device_overhead block (<=2%
bar); instrumented-vs-bare bit-parity on the e2e trainer is pinned by
tests/test_device_obs.py. Signature keying is CONSERVATIVE: python
scalar args re-key by value (jax.jit would retrace only on dtype
change) — none of the instrumented entry points take bare scalars, and
a finer key can only add a counted compile, never reuse a wrong
executable.

Import surface is jax-free (the obs contract): jax is imported lazily
at wrapper construction and ledger sampling, both of which only happen
in jax-using processes. Flag ``device_obs`` off returns bare jax.jit —
the zero-risk escape hatch.
"""

from __future__ import annotations

import inspect
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from paddlebox_tpu.utils.lockwatch import make_lock, make_rlock
from paddlebox_tpu.utils.stats import (gauge_set, hist_observe, stat_add,
                                       stat_peek)

SCHEMA_VERSION = 1

#: compiled-executable signatures retained per instrumented fn (LRU):
#: far above any legitimate signature count; under pathological shape
#: churn the sentinel fires long before the cache evicts.
MAX_SIGNATURES = 32


def _warn(msg: str, **fields) -> None:
    # lazy: obs/__init__ imports this module; importing log at module
    # scope mid-package-init would be order-sensitive
    from paddlebox_tpu.obs import log as obs_log
    obs_log.warning(msg, **fields)


# --------------------------------------------------------- shared analysis

def analyze_compiled(compiled, examples: Optional[int] = None,
                     slab_bytes: Optional[int] = None) -> dict:
    """The ONE copy of the compiled-step cost/memory math (tools/
    step_audit.py refactors onto this; the instrument_jit snapshot uses
    it too). Best-effort per backend: analysis failures land as error
    strings, never raise.

      examples   — examples one call processes; adds *_per_example
                   (cost_analysis counts a scan BODY once = one batch,
                   so scan callers pass the batch size, not chunk*batch)
      slab_bytes — donated slab size; adds temp_includes_slab_copy (the
                   donated slab must never reappear as a temp copy)
    """
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        if ca:
            out["flops"] = float(ca.get("flops", 0.0))
            out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
            if examples:
                out["flops_per_example"] = round(out["flops"] / examples)
                out["bytes_accessed_per_example"] = round(
                    out["bytes_accessed"] / examples)
    except Exception as e:  # noqa: BLE001 — analysis is best-effort per backend
        out["cost_analysis_error"] = repr(e)
    try:
        ma = compiled.memory_analysis()
        out["temp_bytes"] = int(getattr(ma, "temp_size_in_bytes", -1))
        out["arg_bytes"] = int(getattr(ma, "argument_size_in_bytes", -1))
        out["output_bytes"] = int(getattr(ma, "output_size_in_bytes", -1))
        out["alias_bytes"] = int(getattr(ma, "alias_size_in_bytes", -1))
        if slab_bytes and out["temp_bytes"] >= 0:
            out["temp_includes_slab_copy"] = bool(
                out["temp_bytes"] >= int(slab_bytes))
    except Exception as e:  # noqa: BLE001
        out["memory_analysis_error"] = repr(e)
    return out


# ------------------------------------------------------------ the monitor

class _JitEntry:
    """One instrumented entry point's device-plane record. Mutated only
    under the owning wrapper's lock; snapshot() reads are
    field-at-a-time (ints/floats/bools — torn reads are stale, never
    corrupt)."""

    def __init__(self, name: str, donate_argnums: Tuple[int, ...],
                 audit_argnums: Tuple[int, ...]) -> None:
        self.name = name
        self.donate_argnums = donate_argnums
        self.audit_argnums = audit_argnums
        self.compiles = 0
        self.compile_ms_total = 0.0
        self.last_compile_ms = 0.0
        self.steady_recompiles = 0
        self.recompile_flagged = False
        self.donation_checks = 0
        self.donation_misses = 0
        self.donation_flagged = False
        # True (assumed until a pointer read fails; `checks` says whether
        # any call actually verified) / False (nothing to audit) /
        # "unsupported:<err>" (backend without buffer-pointer introspection
        # — e.g. sharded arrays; the audit disables itself for this fn)
        self.donation_supported: Any = bool(audit_argnums)
        self.analysis: Optional[dict] = None
        self.donated_bytes = 0
        self.signatures = 0

    def snapshot(self) -> dict:
        d = {"compiles": self.compiles,
             "compile_ms": round(self.compile_ms_total, 3),
             "last_compile_ms": round(self.last_compile_ms, 3),
             "signatures": self.signatures,
             "steady_recompiles": self.steady_recompiles,
             "recompile_flagged": self.recompile_flagged,
             "donate_argnums": list(self.donate_argnums)}
        if self.audit_argnums:
            d["donation"] = {"checks": self.donation_checks,
                             "misses": self.donation_misses,
                             "supported": self.donation_supported,
                             "donated_bytes": self.donated_bytes}
        if self.analysis is not None:
            d["analysis"] = dict(self.analysis)
        return d


class DeviceMonitor:
    """Process-global registry of instrumented entry points + owner
    getters + the live-buffer ledger state."""

    def __init__(self) -> None:
        # REENTRANT: the fatal-signal flight seal calls snapshot() from a
        # handler that may have interrupted this same thread inside
        # register()/sample_ledger() — a plain lock would deadlock the
        # DYING process instead of sealing (the PR-9 tracer._reg_lock
        # class); make_rlock keeps it visible to debug_lock_order
        self._lock = make_rlock("DeviceMonitor._lock")
        self._entries: Dict[str, _JitEntry] = {}  # guarded-by: _lock
        self._owners: Dict[str, Callable[[], Any]] = {}  # guarded-by: _lock
        self._ledger: Optional[dict] = None  # guarded-by: _lock
        self._growth_streak = 0  # guarded-by: _lock
        self._streak_base = 0  # guarded-by: _lock
        self._prev_total: Optional[int] = None  # guarded-by: _lock

    # -------------------------------------------------------------- entries
    def register(self, entry: _JitEntry) -> None:
        """A fresh wrapper REPLACES the entry under its name (a rebuilt
        trainer starts a fresh compile budget; global stats stay
        cumulative)."""
        with self._lock:
            self._entries[entry.name] = entry

    @property
    def active(self) -> bool:
        with self._lock:
            return bool(self._entries or self._owners)

    # --------------------------------------------------------------- owners
    def register_owner(self, name: str, getter: Callable[[], Any]) -> None:
        """Logical buffer owner for the HBM ledger: getter() returns the
        owner's current array/pytree (or None). Getters must hold weak
        references to their runner — registration must not extend its
        lifetime (the ledger would then CAUSE the leak it detects)."""
        with self._lock:
            self._owners[name] = getter

    def clear_owners(self) -> None:
        with self._lock:
            self._owners.clear()

    # --------------------------------------------------------------- ledger
    def sample_ledger(self) -> Optional[dict]:
        """Bucket jax.live_arrays() by registered owner into gauges +
        run the monotonic-growth leak detector. No-op (None) in a
        process that never imported jax."""
        import sys
        if "jax" not in sys.modules:
            return None
        import jax
        with self._lock:
            owners = dict(self._owners)
        owner_of: Dict[int, str] = {}
        for name, getter in owners.items():
            try:
                tree = getter()
            except Exception:  # noqa: BLE001 — a dead runner's getter must not kill reporting
                continue
            if tree is None:
                continue
            for leaf in jax.tree_util.tree_leaves(tree):
                owner_of[id(leaf)] = name
        buckets: Dict[str, int] = {name: 0 for name in owners}
        buckets["other"] = 0
        total = 0
        count = 0
        try:
            live = jax.live_arrays()
        except Exception:  # noqa: BLE001 — backend-guarded (no live-array introspection)
            return None
        for arr in live:
            nb = int(getattr(arr, "nbytes", 0) or 0)
            total += nb
            count += 1
            buckets[owner_of.get(id(arr), "other")] += nb
        sample = {"ts": time.time(), "total_bytes": total, "arrays": count,
                  "owners": buckets}
        gauge_set("device_live_bytes_total", float(total))
        gauge_set("device_live_arrays", float(count))
        for name, nb in buckets.items():
            gauge_set("device_live_bytes_" + name, float(nb))
        self._leak_check(total, sample)
        with self._lock:
            self._ledger = sample
        return sample

    def _leak_check(self, total: int, sample: dict) -> None:
        from paddlebox_tpu.config import flags
        windows = int(flags.get_flag("device_leak_windows"))
        min_bytes = int(flags.get_flag("device_leak_min_bytes"))
        fire = False
        with self._lock:
            prev = self._prev_total
            self._prev_total = total
            if prev is None or total <= prev:
                self._growth_streak = 0
                self._streak_base = total
            else:
                if self._growth_streak == 0:
                    self._streak_base = prev
                self._growth_streak += 1
                if (self._growth_streak >= windows
                        and total - self._streak_base >= min_bytes):
                    fire = True
                    grew = total - self._streak_base
                    streak = self._growth_streak
                    # a fired streak restarts — one alarm per sustained
                    # climb, not one per additional window
                    self._growth_streak = 0
                    self._streak_base = total
        if fire:
            stat_add("device_leak_suspect", 1)
            sample["leak_suspect"] = True
            _warn("device live-buffer ledger: monotonic growth — "
                  "possible leaked device array",
                  windows=streak, grew_bytes=grew,
                  total_bytes=total)

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        with self._lock:
            entries = {n: e.snapshot() for n, e in self._entries.items()}
            ledger = dict(self._ledger) if self._ledger else None
        # stat_peek, not stat_get: this runs inside the fatal-signal
        # flight seal, which may have interrupted stat_add mid-hold on
        # the registry's plain lock — a locked read would self-deadlock
        return {
            "type": "device_plane", "v": SCHEMA_VERSION,
            "active": bool(entries or ledger),
            "entries": entries,
            "transfers": {
                "h2d_bytes": stat_peek("device_transfer_bytes_h2d"),
                "d2h_bytes": stat_peek("device_transfer_bytes_d2h"),
            },
            "recompiles": stat_peek("device_recompiles"),
            "donation_miss": stat_peek("donation_miss"),
            "leak_suspect": stat_peek("device_leak_suspect"),
            "ledger": ledger,
        }

    def reset(self) -> None:
        """Test isolation: forget entries/owners/ledger state (the
        StatRegistry is reset separately by the conftest fixture)."""
        with self._lock:
            self._entries.clear()
            self._owners.clear()
            self._ledger = None
            self._growth_streak = 0
            self._streak_base = 0
            self._prev_total = None


_MONITOR = DeviceMonitor()


def monitor() -> DeviceMonitor:
    return _MONITOR


def snapshot() -> dict:
    return _MONITOR.snapshot()


def register_owner(name: str, getter: Callable[[], Any]) -> None:
    _MONITOR.register_owner(name, getter)


def sample_ledger() -> Optional[dict]:
    return _MONITOR.sample_ledger()


def on_report() -> None:
    """StepReport assembly hook (obs/report.py): sample the live-buffer
    ledger at report cadence. Near-free when the device plane is idle
    (serving replicas, jax-free processes)."""
    if _MONITOR.active:
        _MONITOR.sample_ledger()


# ----------------------------------------------------------- transfer ledger

def account_h2d(nbytes: int) -> None:
    """One host→device staging transfer (bytes). Counter + histogram —
    the StepReport window carries the delta, /metrics the series."""
    n = int(nbytes)
    if n > 0:
        stat_add("device_transfer_bytes_h2d", n)
        hist_observe("device_h2d_bytes", n)


def account_d2h(nbytes: int) -> None:
    """One device→host write-back/extraction transfer (bytes)."""
    n = int(nbytes)
    if n > 0:
        stat_add("device_transfer_bytes_d2h", n)
        hist_observe("device_d2h_bytes", n)


def tree_nbytes(tree) -> int:
    """Total array bytes of a host pytree (dict/tuple of numpy arrays) —
    the staging paths' one-line accounting helper. jax-free: walks
    plain containers, reads .nbytes."""
    total = 0
    stack = [tree]
    while stack:
        x = stack.pop()
        if isinstance(x, dict):
            stack.extend(x.values())
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        else:
            total += int(getattr(x, "nbytes", 0) or 0)
    return total


# ------------------------------------------------------------ instrument_jit

def _leaf_sig(leaf):
    dt = getattr(leaf, "dtype", None)
    if dt is not None:
        # sharding is part of the executable's input contract: an AOT
        # Compiled REJECTS a same-shape array with a different sharding
        # (where the C++ jit path would recompile), so it must re-key —
        # the 8-virtual-device test mesh exercises this on every runner
        return (leaf.shape, dt, getattr(leaf, "weak_type", False),
                getattr(leaf, "sharding", None))
    # non-array leaf (python scalar / hashable static object): key by
    # VALUE — conservative vs jax.jit (see module docstring)
    return (type(leaf), leaf)


class InstrumentedJit:
    """jax.jit twin with the device plane attached. Call convention,
    donation semantics and results are identical to jax.jit(fn, ...)
    (bit-parity pinned by tests); .lower() passes through for AOT
    consumers (tools/step_audit.py)."""

    def __init__(self, fn: Callable, name: str,
                 donate_argnums: Tuple[int, ...] = (),
                 static_argnums: Tuple[int, ...] = (),
                 static_argnames: Tuple[str, ...] = (),
                 audit_argnums: Optional[Tuple[int, ...]] = None,
                 example_count: Optional[int] = None,
                 recompile_warmup: Optional[int] = None,
                 **jit_kwargs) -> None:
        import jax
        self._fn = fn
        self.name = str(name)
        self._tree_flatten = jax.tree_util.tree_flatten
        self._tree_leaves = jax.tree_util.tree_leaves
        self._tracer_cls = jax.core.Tracer
        kw = dict(jit_kwargs)
        if donate_argnums:
            kw["donate_argnums"] = donate_argnums
        if static_argnums:
            kw["static_argnums"] = static_argnums
        if static_argnames:
            kw["static_argnames"] = static_argnames
        # boxlint: disable=BX901 — this IS the instrumentation layer
        self._jitted = jax.jit(fn, **kw)
        self._example_count = example_count
        self._recompile_warmup = recompile_warmup
        # AOT Compiled objects are called with the DYNAMIC args only
        # (statics are baked into the executable) — resolve static
        # names to positions once so dispatch can strip them
        self._static_argnames = tuple(static_argnames)
        static_pos = set(static_argnums)
        if static_argnames:
            try:
                names = list(inspect.signature(fn).parameters)
                for nm in static_argnames:
                    if nm in names:
                        static_pos.add(names.index(nm))
            except (TypeError, ValueError):
                pass
        self._static_pos = frozenset(static_pos)
        audit = tuple(donate_argnums) if audit_argnums is None \
            else tuple(audit_argnums)
        self._audit_argnums = audit
        self._entry = _JitEntry(self.name, tuple(donate_argnums), audit)
        self._lock = make_lock("InstrumentedJit._lock")
        self._cache: "OrderedDict[Any, Any]" = OrderedDict()
        # per-executable previous-call-missed flag (the audit debounce);
        # guarded-by: _lock, pruned with the cache
        self._last_missed: Dict[Any, bool] = {}
        _MONITOR.register(self._entry)

    # ---------------------------------------------------------- jit surface
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def eval_shape(self, *args, **kwargs):
        return self._jitted.eval_shape(*args, **kwargs)

    @property
    def __wrapped__(self):
        return self._fn

    # ------------------------------------------------------------- dispatch
    def _compile(self, key, args, kwargs):
        from paddlebox_tpu.config import flags
        t0 = time.perf_counter()
        compiled = self._jitted.lower(*args, **kwargs).compile()
        dt_ms = (time.perf_counter() - t0) * 1e3
        hist_observe("device_compile_ms", dt_ms)
        e = self._entry
        warmup = (self._recompile_warmup
                  if self._recompile_warmup is not None
                  else int(flags.get_flag("device_recompile_warmup")))
        with self._lock:
            self._cache[key] = compiled
            while len(self._cache) > MAX_SIGNATURES:
                old_key, _ = self._cache.popitem(last=False)
                self._last_missed.pop(old_key, None)
            e.compiles += 1
            e.compile_ms_total += dt_ms
            e.last_compile_ms = dt_ms
            e.signatures = len(self._cache)
            first = e.compiles == 1
            steady = e.compiles > max(warmup, 1)
            if steady:
                e.steady_recompiles += 1
            flag_now = steady and not e.recompile_flagged
            if flag_now:
                e.recompile_flagged = True
        if first:
            # one-time analysis snapshot: comes free with the executable
            # (the AOT path's whole point — no second compile)
            donated = 0
            for i in self._audit_argnums:
                if i < len(args):
                    donated += sum(
                        int(getattr(l, "nbytes", 0) or 0)
                        for l in self._tree_leaves(args[i]))
            e.donated_bytes = donated
            e.analysis = analyze_compiled(
                compiled, examples=self._example_count,
                slab_bytes=donated or None)
        if steady:
            # the sentinel: a recompile past warmup is shape/dtype churn
            # in what must be a steady-state loop
            stat_add("device_recompiles", 1)
        if flag_now:
            _warn("device recompile sentinel: steady-state recompile "
                  "(shape/dtype churn past warmup) — every recompile "
                  "stalls the step for a full XLA compile",
                  fn=self.name, compiles=e.compiles, warmup=warmup,
                  compile_ms=round(dt_ms, 1))
        return compiled

    def _donated_ptrs(self, args) -> Optional[set]:
        """Buffer pointers of the audited (to-be-donated) args, read
        BEFORE the call — donation deletes the input buffers, so they
        are unreadable after. None disables the check for this call
        (and, on a backend without pointer introspection, for good)."""
        from paddlebox_tpu.config import flags
        min_bytes = int(flags.get_flag("device_donation_min_bytes"))
        try:
            in_ptrs = set()
            for i in self._audit_argnums:
                if i >= len(args):
                    continue
                for leaf in self._tree_leaves(args[i]):
                    if int(getattr(leaf, "nbytes", 0) or 0) < min_bytes:
                        continue
                    in_ptrs.add(leaf.unsafe_buffer_pointer())
            return in_ptrs or None
        except Exception as e_ptr:  # noqa: BLE001 — backend without buffer pointers
            with self._lock:
                self._entry.donation_supported = (
                    "unsupported:" + repr(e_ptr)[:120])
                self._audit_argnums = ()
            return None

    def _verify_donation(self, key, in_ptrs: set, out) -> None:
        e = self._entry
        try:
            out_ptrs = set()
            for leaf in self._tree_leaves(out):
                p = getattr(leaf, "unsafe_buffer_pointer", None)
                if p is not None:
                    out_ptrs.add(p())
        except Exception as e_ptr:  # noqa: BLE001 — backend without buffer pointers
            with self._lock:
                e.donation_supported = "unsupported:" + repr(e_ptr)[:120]
                self._audit_argnums = ()
            return
        missed = in_ptrs - out_ptrs
        with self._lock:
            e.donation_supported = True
            e.donation_checks += 1
            # debounce (module docstring): an isolated miss is the
            # unavoidable one-time copy of a host-staged (zero-copy-from-
            # numpy) input buffer; only a RECURRING miss on the same
            # executable is the slab-copy regime
            counted = bool(missed) and self._last_missed.get(key, False)
            self._last_missed[key] = bool(missed)
            if counted:
                e.donation_misses += 1
            flag_now = counted and not e.donation_flagged
            if flag_now:
                e.donation_flagged = True
        if counted:
            stat_add("donation_miss", 1)
        if flag_now:
            _warn("device donation audit: donated buffer was COPIED, "
                  "not aliased (its pointer is absent from the outputs)"
                  " — the donation-miss slab-copy regime "
                  "(tools/regime_step_probe.py)",
                  fn=self.name, donated_bytes=e.donated_bytes,
                  missed_buffers=len(missed))

    def __call__(self, *args, **kwargs):
        leaves, treedef = self._tree_flatten((args, kwargs))
        tracer = self._tracer_cls
        if any(isinstance(x, tracer) for x in leaves):
            # called INSIDE another trace (the sharded scan traces its
            # instrumented shard step under lax.scan): an AOT Compiled
            # cannot take tracers — delegate to the inner jax.jit, which
            # inlines into the outer trace exactly like the pre-device-
            # plane jit-of-jit did; the OUTER entry point carries the
            # monitoring
            return self._jitted(*args, **kwargs)
        # the ONE cache-key recipe: treedef + per-leaf _leaf_sig
        key = (treedef, tuple(_leaf_sig(x) for x in leaves))
        with self._lock:
            compiled = self._cache.get(key)
            if compiled is not None:
                self._cache.move_to_end(key)
        if compiled is None:
            compiled = self._compile(key, args, kwargs)
        in_ptrs = (self._donated_ptrs(args)
                   if self._audit_argnums else None)
        if self._static_pos or self._static_argnames:
            call_args = tuple(a for i, a in enumerate(args)
                              if i not in self._static_pos)
            call_kwargs = {k: v for k, v in kwargs.items()
                           if k not in self._static_argnames}
            out = compiled(*call_args, **call_kwargs)
        else:
            out = compiled(*args, **kwargs)
        if in_ptrs is not None:
            self._verify_donation(key, in_ptrs, out)
        return out


def instrument_jit(fn: Callable, name: str,
                   donate_argnums: Tuple[int, ...] = (),
                   static_argnums: Tuple[int, ...] = (),
                   static_argnames: Tuple[str, ...] = (),
                   audit_argnums: Optional[Tuple[int, ...]] = None,
                   example_count: Optional[int] = None,
                   recompile_warmup: Optional[int] = None,
                   **jit_kwargs) -> Callable:
    """The one jit entry point (BX901): jax.jit + the device plane.

      name             — stable entry-point name; stats/logs/the /device
                         endpoint key on it
      audit_argnums    — argnums whose donation the audit verifies;
                         defaults to donate_argnums (pass explicitly to
                         audit an entry point that SHOULD donate but
                         doesn't — the deliberately-non-donated twin in
                         tests, or a path where jax declined donation)
      example_count    — examples one call processes (per-example cost
                         normalization in the analysis snapshot)
      recompile_warmup — per-fn override of device_recompile_warmup for
                         entry points whose legitimate signature space
                         is wider than the default allowance
                         (delta_promote compiles once per power-of-two
                         promote bucket)

    Flag ``device_obs`` off returns bare jax.jit(fn, ...) — identical
    call surface minus the monitoring."""
    from paddlebox_tpu.config import flags
    if not flags.get_flag("device_obs"):
        import jax
        kw = dict(jit_kwargs)
        if donate_argnums:
            kw["donate_argnums"] = donate_argnums
        if static_argnums:
            kw["static_argnums"] = static_argnums
        if static_argnames:
            kw["static_argnames"] = static_argnames
        # boxlint: disable=BX901 — the flag-off bare tier of the wrapper
        return jax.jit(fn, **kw)
    if inspect.isgeneratorfunction(fn):
        raise TypeError("instrument_jit cannot wrap a generator")
    return InstrumentedJit(
        fn, name, donate_argnums=tuple(donate_argnums),
        static_argnums=tuple(static_argnums),
        static_argnames=tuple(static_argnames),
        audit_argnums=audit_argnums, example_count=example_count,
        recompile_warmup=recompile_warmup, **jit_kwargs)
