"""Stall watchdog: heartbeat thread that turns silent hangs into reports.

Native successor to tools/tpu_watchdog.sh: the runners mark progress at
step and exchange boundaries (`obs.beat("step")` — one monotonic read +
one tuple store, safe from any thread), and a daemon thread checks the
age of the last beat. When it exceeds the flag-configured threshold
(obs_watchdog_secs) the watchdog dumps, to stderr, everything a hang
post-mortem needs: the last beat label and age, the last-K spans from
the tracer ring, a stack for EVERY live thread (sys._current_frames —
the lockstep exchange_incoming_p2p/collective wedges this was built for
always show as one thread parked in a wait), and the last assembled
StepReport. Optionally (obs_watchdog_action=raise) it then interrupts
the main thread so a wedged job dies loudly instead of burning a TPU
reservation silently.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, List, Optional

from paddlebox_tpu.obs import flight as _flight


class StallWatchdog:
    def __init__(self, threshold_s: float, action: str = "dump",
                 tracer=None, report_fn: Optional[Callable] = None,
                 on_stall: Optional[Callable[[str], None]] = None,
                 stream=None, poll_interval: Optional[float] = None,
                 last_k_spans: int = 48) -> None:
        if action not in ("dump", "raise"):
            raise ValueError("watchdog action must be 'dump' or 'raise', "
                             "got %r" % (action,))
        self.threshold_s = float(threshold_s)
        self.action = action
        self.tracer = tracer
        self.report_fn = report_fn
        self.on_stall = on_stall
        self.stream = stream
        self.last_k_spans = int(last_k_spans)
        self._poll = poll_interval or max(0.05, min(1.0,
                                                    self.threshold_s / 4.0))
        # (monotonic, label): swapped atomically as one tuple — beat() is
        # lock-free and callable from any thread
        self._beat = (time.monotonic(), "start")
        self._fired_at: Optional[tuple] = None
        self.fires = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -------------------------------------------------------------- beats
    def beat(self, label: str) -> None:
        self._beat = (time.monotonic(), label)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "StallWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="pbtpu-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self._poll + 1.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            beat = self._beat
            age = time.monotonic() - beat[0]
            if age < self.threshold_s:
                continue
            if self._fired_at == beat:
                continue            # already reported THIS silence window
            self._fired_at = beat
            self.fire(beat[1], age)

    # --------------------------------------------------------------- dump
    def render_dump(self, label: str, age: float) -> str:
        lines: List[str] = []
        lines.append("=" * 72)
        lines.append("PBTPU STALL WATCHDOG: no progress beat for %.1fs "
                     "(threshold %.1fs); last beat: %r"
                     % (age, self.threshold_s, label))
        if self.tracer is not None:
            lines.append("-- last %d spans (most recent last) --"
                         % self.last_k_spans)
            for name, tid, tname, t0, t1, trace in self.tracer.last_spans(
                    self.last_k_spans):
                lines.append("  %-28s %10.3fms  [%s/%d]%s"
                             % (name, (t1 - t0) * 1e3, tname, tid,
                                " trace=0x%x" % trace
                                if trace is not None else ""))
        lines.append("-- per-thread stacks --")
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            lines.append("  thread %s (%d):" % (names.get(tid, "?"), tid))
            for entry in traceback.format_stack(frame):
                lines.extend("    " + ln for ln in entry.rstrip().splitlines())
        if self.report_fn is not None:
            try:
                rep = self.report_fn()
            except Exception as e:  # noqa: BLE001 — the dump must not die
                rep = {"report_error": repr(e)[:200]}
            if rep is not None:
                import json
                lines.append("-- last StepReport --")
                lines.append("  " + json.dumps(rep))
        lines.append("=" * 72)
        return "\n".join(lines)

    def fire(self, label: str, age: float) -> None:
        self.fires += 1
        text = self.render_dump(label, age)
        stream = self.stream or sys.stderr
        try:
            stream.write(text + "\n")
            stream.flush()
        except (OSError, ValueError):
            pass
        # a stall is a failure the process may not survive (the next
        # event is often a SIGKILL from the scheduler): seal the flight
        # recorder NOW so the black box carries the dump durably
        _flight.seal_active("watchdog_stall:%s" % label, extra_text=text)
        if self.on_stall is not None:
            self.on_stall(text)
        if self.action == "raise":
            import _thread
            _thread.interrupt_main()


# ------------------------------------------------------------- module API
_ACTIVE: Optional[StallWatchdog] = None


def active() -> Optional[StallWatchdog]:
    return _ACTIVE


def set_active(w: Optional[StallWatchdog]) -> Optional[StallWatchdog]:
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, w
    return prev


def beat(label: str) -> None:
    """Progress mark — near-free (two global reads) when neither the
    watchdog nor the flight recorder runs; the flight tier samples
    (>=1s apart), so the per-step cost stays one monotonic read."""
    w = _ACTIVE
    if w is not None:
        w.beat(label)
    fr = _flight._ACTIVE
    if fr is not None:
        fr.on_beat(label)


def ensure_from_flags(tracer=None, report_fn=None) -> Optional[StallWatchdog]:
    """Start (once) the flag-configured watchdog; obs_watchdog_secs<=0 =
    disabled. Later callers refresh the report_fn so the dump always
    shows the LIVE trainer's last report."""
    global _ACTIVE
    from paddlebox_tpu.config import flags
    secs = float(flags.get_flag("obs_watchdog_secs"))
    if secs <= 0:
        return _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = StallWatchdog(
            secs, action=str(flags.get_flag("obs_watchdog_action")),
            tracer=tracer, report_fn=report_fn).start()
    elif report_fn is not None:
        _ACTIVE.report_fn = report_fn
    return _ACTIVE
