"""Rank-prefixed structured logging — the library's replacement for print().

Library code must never bare-print (boxlint BX501 enforces this): a
multi-process run interleaves unattributed lines, and redirection/capture
breaks. This thin layer over stdlib logging gives every line a
``[pbtpu rN HH:MM:SS]`` prefix plus sorted ``key=value`` structured
fields, lands on stderr by default, and stays swappable through normal
logging configuration (the emitted records ride logger
"paddlebox_tpu.obs").
"""

from __future__ import annotations

import logging
import os
import sys
import time
from typing import Optional

_RANK: Optional[int] = None
_LOGGER: Optional[logging.Logger] = None


def set_rank(rank: int) -> None:
    """Pin the rank prefix (the sharded runners call this once the fleet
    rank is known; before that the PBTPU_RANK env / 0 default applies)."""
    global _RANK
    _RANK = int(rank)


def get_rank() -> int:
    if _RANK is not None:
        return _RANK
    try:
        return int(os.environ.get("PBTPU_RANK", "0"))
    except ValueError:
        return 0


class _StderrHandler(logging.StreamHandler):
    """Resolves sys.stderr at EMIT time, not handler construction — so
    test harnesses that swap stderr (pytest capsys) capture our lines."""

    def __init__(self) -> None:
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr


class _ObsTapHandler(logging.Handler):
    """Second handler on the obs logger: counts warning/error lines into
    the StatRegistry (the cluster health plane reads the per-window
    deltas as the error-line rate) and forwards them to the active
    flight recorder so the black box carries the run's complaints."""

    def emit(self, record: logging.LogRecord) -> None:
        if record.levelno < logging.WARNING:
            return
        try:
            from paddlebox_tpu.obs import flight as _flight
            from paddlebox_tpu.utils.stats import stat_add
            stat_add("log_error_lines"
                     if record.levelno >= logging.ERROR
                     else "log_warning_lines")
            fr = _flight.active()
            if fr is not None:
                fr.on_log(record.levelname, record.getMessage())
        except Exception:  # noqa: BLE001 — the tap must never break logging
            pass


class _RankFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        stamp = time.strftime("%H:%M:%S", time.localtime(record.created))
        prefix = "[pbtpu r%d %s] " % (get_rank(), stamp)
        msg = record.getMessage()
        # multi-line payloads (timer reports) get the prefix per line so
        # interleaved multi-rank output stays attributable
        return "\n".join(prefix + ln for ln in msg.splitlines() or [""])


def get_logger() -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        lg = logging.getLogger("paddlebox_tpu.obs")
        if not lg.handlers:
            h = _StderrHandler()
            h.setFormatter(_RankFormatter())
            lg.addHandler(h)
            lg.addHandler(_ObsTapHandler())
            # the parent "paddlebox_tpu" logger keeps its own behavior
            # (warnings via lastResort); don't double-emit through it
            lg.propagate = False
        if lg.level == logging.NOTSET:
            lg.setLevel(logging.INFO)
        _LOGGER = lg
    return _LOGGER


def _fmt(msg: str, fields: dict) -> str:
    if not fields:
        return msg
    tail = " ".join("%s=%s" % (k, fields[k]) for k in sorted(fields))
    return "%s %s" % (msg, tail) if msg else tail


def info(msg: str, **fields) -> None:
    get_logger().info(_fmt(msg, fields))


def warning(msg: str, **fields) -> None:
    get_logger().warning(_fmt(msg, fields))


def error(msg: str, **fields) -> None:
    get_logger().error(_fmt(msg, fields))
