"""Flight recorder: always-on, bounded, per-rank on-disk black box.

The PR-5 telemetry plane is rank-local and in-memory: a SIGKILL'd or
wedged rank takes its spans, reports and stacks to the grave, which is
exactly when they were needed. The flight recorder is the durable tier:
every rank appends compact JSONL records — a flags+env+git-sha header,
StepReports, cluster reports/health, span windows at report cadence,
warning/error log lines, sampled watchdog beats — into segment-rotated
files under ``obs_flight_dir`` (bounded: ``obs_flight_segments`` x
``obs_flight_segment_bytes``, oldest dropped), flushed per record so the
file survives even SIGKILL.

Crash SEALING: on ``sys.excepthook``, a fatal signal (SIGABRT/SIGTERM;
faulthandler covers SIGSEGV-class C crashes into ``fatal_r<rank>.txt``),
or a watchdog fire, the recorder flushes and writes a ``SEALED``
manifest — one JSON bundling the reason, the exception, last-K spans,
EVERY thread's stack, the last few StepReports, and the recent
warning/error log tail. This is the failure artifact ROADMAP item 5
(elastic fleet) names: the replacement-rank decision can be made from
the dead rank's bundle, not from guesswork.

Import surface stays jax-free (the serving replicas run this too).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import IO, List, Optional
from paddlebox_tpu.utils.lockwatch import make_rlock

SCHEMA_VERSION = 1

#: SEALED manifests retained per rank: the first seal usually names the
#: root cause, but a watchdog seal followed by the real crash must not
#: be masked — later seals get numbered siblings, bounded.
MAX_SEALS = 4


def _git_sha(start: Optional[str] = None) -> str:
    """Best-effort HEAD sha by walking ``.git`` upward from ``start`` —
    no subprocess (the recorder must construct in milliseconds and in
    processes with no git on PATH)."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        git = os.path.join(d, ".git")
        if os.path.isdir(git):
            try:
                with open(os.path.join(git, "HEAD")) as fh:
                    head = fh.read().strip()
                if not head.startswith("ref:"):
                    return head[:40]
                ref = head.split(None, 1)[1]
                ref_path = os.path.join(git, ref)
                if os.path.exists(ref_path):
                    with open(ref_path) as fh:
                        return fh.read().strip()[:40]
                packed = os.path.join(git, "packed-refs")
                if os.path.exists(packed):
                    with open(packed) as fh:
                        for ln in fh:
                            if ln.strip().endswith(ref):
                                return ln.split()[0][:40]
            except OSError:
                return ""
            return ""
        parent = os.path.dirname(d)
        if parent == d:
            return ""
        d = parent


def _thread_stacks() -> dict:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for tid, frame in sys._current_frames().items():
        out["%s/%d" % (names.get(tid, "?"), tid)] = [
            ln.rstrip() for entry in traceback.format_stack(frame)
            for ln in entry.splitlines()]
    return out


class FlightRecorder:
    """One rank's black box. Thread contract: ``record`` and friends may
    be called from any thread (one RLock around the file — reentrant so
    a fatal-signal seal interrupting a record on the main thread cannot
    deadlock on itself)."""

    def __init__(self, flight_dir: str, rank: int = 0,
                 segment_bytes: int = 4 << 20, max_segments: int = 4,
                 beat_secs: float = 1.0, last_k_spans: int = 96) -> None:
        self.dir = flight_dir
        self.rank = int(rank)
        self.segment_bytes = int(segment_bytes)
        self.max_segments = max(1, int(max_segments))
        self.beat_secs = float(beat_secs)
        self.last_k_spans = int(last_k_spans)
        self._lock = make_rlock("FlightRecorder._lock")
        self._fh: Optional[IO[str]] = None  # guarded-by: _lock
        self._seg_idx = 0  # guarded-by: _lock
        self._seg_bytes = 0  # guarded-by: _lock
        self._seals = 0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        # lock-free beat sampling gate (one float store; a torn read just
        # records one extra beat line)
        self._last_beat_rec = 0.0
        self._last_reports: deque = deque(maxlen=3)  # guarded-by: _lock
        self._log_tail: deque = deque(maxlen=64)  # guarded-by: _lock
        self._last_span_t = 0.0  # guarded-by: _lock
        os.makedirs(self.dir, exist_ok=True)
        self._open_segment(0)

    # ------------------------------------------------------------ segments
    def _seg_path(self, idx: int) -> str:
        return os.path.join(self.dir,
                            "flight_r%d_%04d.jsonl" % (self.rank, idx))

    def header(self) -> dict:
        from paddlebox_tpu.config import flags as _flags
        return {"type": "header", "v": SCHEMA_VERSION, "ts": time.time(),
                "rank": self.rank, "pid": os.getpid(),
                "argv": list(sys.argv), "python": sys.version.split()[0],
                "git_sha": _git_sha(),
                "flags": _flags.all_flags(),
                "env": {k: v for k, v in sorted(os.environ.items())
                        if k.startswith("PBTPU_")
                        or k in ("JAX_PLATFORMS", "XLA_FLAGS")}}

    def _open_segment(self, idx: int) -> None:
        # each segment is self-contained: the header repeats at its top
        # so rotating away segment 0 never loses the run identity. The
        # header is written DIRECTLY (no rotation check): a header
        # larger than segment_bytes must not recurse into rotation
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
            self._seg_idx = idx
            self._fh = open(self._seg_path(idx), "a", encoding="utf-8")
            self._seg_bytes = self._fh.tell()
            drop = self._seg_path(idx - self.max_segments)
            if os.path.exists(drop):
                try:
                    os.unlink(drop)
                except OSError:
                    pass
            try:
                line = json.dumps(self.header(), default=repr) + "\n"
                self._fh.write(line)
                self._fh.flush()
                self._seg_bytes += len(line.encode("utf-8"))
            except (OSError, TypeError, ValueError):
                pass

    def segments(self) -> List[str]:
        with self._lock:
            lo = max(0, self._seg_idx - self.max_segments + 1)
            return [self._seg_path(i)
                    for i in range(lo, self._seg_idx + 1)
                    if os.path.exists(self._seg_path(i))]

    # ------------------------------------------------------------- records
    def record(self, rtype: str, **fields) -> None:
        """Append one flushed JSONL record; rotates segments past the
        byte bound. Never raises — a full disk degrades telemetry, it
        must not fail a training step."""
        rec = {"type": rtype, "v": SCHEMA_VERSION, "ts": time.time(),
               "rank": self.rank}
        rec.update(fields)
        try:
            line = json.dumps(rec, default=repr) + "\n"
        except (TypeError, ValueError):
            return
        with self._lock:
            if self._closed or self._fh is None:
                return
            try:
                self._fh.write(line)
                self._fh.flush()
            except (OSError, ValueError):
                return
            # ENCODED bytes, not characters: the rotation bound is a
            # disk contract and multibyte payloads cost up to 4x len()
            self._seg_bytes += len(line.encode("utf-8"))
            if self._seg_bytes >= self.segment_bytes:
                try:
                    self._open_segment(self._seg_idx + 1)
                except OSError:
                    # dir deleted / disk full at rotation: the black
                    # box degrades closed — it must NEVER crash the
                    # training step it instruments
                    self._closed = True

    def on_report(self, report: dict) -> None:
        """StepReport / cluster_report / cluster_health passthrough —
        the report IS the record (it already carries type/ts/rank)."""
        with self._lock:
            if report.get("type") == "step_report":
                self._last_reports.append(report)
        self.record("report", report=report)
        self._record_span_window()

    def _record_span_window(self) -> None:
        """Spans that ENDED since the last window, compacted — riding the
        report cadence keeps the disk rate bounded by obs_report_every,
        not by span volume."""
        from paddlebox_tpu.obs.tracer import get_tracer
        with self._lock:
            cut = self._last_span_t
            spans = [s for s in get_tracer().all_spans() if s[4] > cut]
            if not spans:
                return
            self._last_span_t = max(s[4] for s in spans)
        spans = spans[-256:]
        self.record("spans", n=len(spans), spans=[
            [name, tid, round(t0, 6), round((t1 - t0) * 1e3, 3),
             ("0x%016x" % (trace & (2**64 - 1))) if trace is not None
             else None]
            for name, tid, _tname, t0, t1, trace in spans])

    def on_log(self, level: str, line: str) -> None:
        with self._lock:
            self._log_tail.append((time.time(), level, line))
        self.record("log", level=level, line=line[:2000])

    def on_beat(self, label: str) -> None:
        """Sampled (>= beat_secs apart): beats are per-step-hot, the
        black box needs liveness evidence, not every step."""
        now = time.monotonic()
        if now - self._last_beat_rec < self.beat_secs:
            return
        self._last_beat_rec = now
        self.record("beat", label=label)

    # --------------------------------------------------------------- seal
    def seal(self, reason: str, exc: Optional[BaseException] = None,
             extra_text: Optional[str] = None) -> Optional[str]:
        """Flush and write the SEALED manifest: reason, exception,
        last-K spans, every thread's stack, last reports, log tail,
        segment list. Returns the manifest path (None past MAX_SEALS or
        on an unwritable dir). Later seals write numbered siblings so a
        watchdog seal can't mask the real crash manifest."""
        from paddlebox_tpu.obs.tracer import get_tracer
        with self._lock:
            if self._seals >= MAX_SEALS:
                return None
            self._seals += 1
            n = self._seals
            last_reports = list(self._last_reports)
            log_tail = [{"ts": t, "level": lv, "line": ln}
                        for t, lv, ln in self._log_tail]
        manifest = {
            "type": "sealed", "v": SCHEMA_VERSION, "ts": time.time(),
            "rank": self.rank, "pid": os.getpid(), "reason": reason,
            "seal_index": n,
            "exception": ("".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__))[-8000:]
                if exc is not None else None),
            "spans": [[name, tid, tname, round(t0, 6),
                       round((t1 - t0) * 1e3, 3),
                       ("0x%016x" % (trace & (2**64 - 1)))
                       if trace is not None else None]
                      for name, tid, tname, t0, t1, trace
                      in get_tracer().last_spans(self.last_k_spans)],
            "threads": _thread_stacks(),
            "last_reports": last_reports,
            "log_tail": log_tail,
            "segments": [os.path.basename(p) for p in self.segments()],
            "header": self.header(),
        }
        try:
            # device-plane snapshot (round 20): compile counts, donation
            # audit, transfer counters, last HBM ledger sample — the
            # postmortem must say whether the dying rank was recompiling
            # or copying its slab
            from paddlebox_tpu.obs import device as _device
            manifest["device"] = _device.snapshot()
        except Exception:  # noqa: BLE001 — sealing must never raise into a crash path
            manifest["device"] = None
        if extra_text:
            manifest["extra_text"] = extra_text[-8000:]
        self.record("sealed", reason=reason, seal_index=n)
        path = os.path.join(
            self.dir, "SEALED_r%d.json" % self.rank if n == 1
            else "SEALED_r%d.%d.json" % (self.rank, n))
        try:
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(manifest, fh, default=repr)
                fh.flush()
                os.fsync(fh.fileno())
        except OSError:
            return None
        return path

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# ------------------------------------------------------------- module API
_ACTIVE: Optional[FlightRecorder] = None
_HOOKS_INSTALLED = False
_FATAL_FH: Optional[IO[str]] = None


def active() -> Optional[FlightRecorder]:
    return _ACTIVE


def set_active(fr: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, fr
    return prev


def on_beat(label: str) -> None:
    fr = _ACTIVE
    if fr is not None:
        fr.on_beat(label)


def seal_active(reason: str, exc: Optional[BaseException] = None,
                extra_text: Optional[str] = None) -> Optional[str]:
    fr = _ACTIVE
    if fr is None:
        return None
    try:
        return fr.seal(reason, exc=exc, extra_text=extra_text)
    except Exception:  # noqa: BLE001 — sealing must never raise into a crash path
        return None


def _excepthook(exc_type, exc, tb):
    seal_active("excepthook:%s" % getattr(exc_type, "__name__", "?"),
                exc=exc)
    _PREV_EXCEPTHOOK(exc_type, exc, tb)


def _thread_excepthook(args):
    # a dead worker thread (stager, conn thread) is evidence, not a
    # process death: record, don't seal — the watchdog seals if the job
    # then wedges on the missing thread
    fr = _ACTIVE
    if fr is not None:
        fr.on_log("ERROR", "uncaught in thread %r: %s" % (
            getattr(args.thread, "name", "?"),
            "".join(traceback.format_exception(
                args.exc_type, args.exc_value, args.exc_traceback))[-2000:]))
    _PREV_THREADHOOK(args)


def _signal_handler(signum, frame):
    name = signal.Signals(signum).name
    seal_active("signal:%s" % name)
    # restore whatever was there and re-deliver so exit semantics
    # (status, core) are exactly the no-recorder ones
    prev = _PREV_SIGNAL.get(signum, signal.SIG_DFL)
    signal.signal(signum, prev if callable(prev) or prev in (
        signal.SIG_DFL, signal.SIG_IGN) else signal.SIG_DFL)
    os.kill(os.getpid(), signum)


_PREV_EXCEPTHOOK = sys.excepthook
_PREV_THREADHOOK = threading.excepthook
_PREV_SIGNAL: dict = {}


def install_crash_hooks(fr: FlightRecorder) -> None:
    """Idempotent: chain sys.excepthook / threading.excepthook, enable
    faulthandler into ``fatal_r<rank>.txt`` (C-level SIGSEGV-class
    stacks), and register Python handlers for SIGABRT/SIGTERM that seal
    then re-deliver. Hooks read the ACTIVE recorder at fire time, so a
    recorder swap needs no re-install. Signal handlers only land when
    called from the main thread (signal.signal's own constraint)."""
    global _HOOKS_INSTALLED, _PREV_EXCEPTHOOK, _PREV_THREADHOOK, _FATAL_FH
    try:
        import faulthandler
        fatal_path = os.path.join(fr.dir, "fatal_r%d.txt" % fr.rank)
        fh = open(fatal_path, "w", encoding="utf-8")
        faulthandler.enable(file=fh, all_threads=True)
        prev, _FATAL_FH = _FATAL_FH, fh  # keep the fd alive for the C handler
        if prev is not None:
            try:
                prev.close()
            except OSError:
                pass
    except (OSError, RuntimeError):
        pass
    if _HOOKS_INSTALLED:
        return
    _PREV_EXCEPTHOOK = sys.excepthook
    sys.excepthook = _excepthook
    _PREV_THREADHOOK = threading.excepthook
    threading.excepthook = _thread_excepthook
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGABRT, signal.SIGTERM):
            try:
                _PREV_SIGNAL[sig] = signal.signal(sig, _signal_handler)
            except (OSError, ValueError):
                pass
    _HOOKS_INSTALLED = True


def ensure_from_flags(rank: int = 0) -> Optional[FlightRecorder]:
    """Flag-configured recorder (obs_flight_dir '' = off). Called by
    make_step_reporter — every runner and serving server goes through
    it. A changed dir swaps the recorder (tests set per-tmp dirs); an
    empty flag closes and clears the active one, so the autouse flag
    restore in tests self-heals the module state."""
    global _ACTIVE
    from paddlebox_tpu.config import flags
    d = str(flags.get_flag("obs_flight_dir")).strip()
    if not d:
        if _ACTIVE is not None:
            _ACTIVE.close()
            _ACTIVE = None
        return None
    # same dir AND same rank reuses; a later caller that knows the real
    # rank (the sharded runners resolve it after fleet init) must not be
    # stuck with a stale rank-0 recorder writing the wrong artifacts
    if (_ACTIVE is not None and _ACTIVE.dir == d
            and _ACTIVE.rank == int(rank)):
        return _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()
        _ACTIVE = None
    try:
        fr = FlightRecorder(
            d, rank=rank,
            segment_bytes=int(flags.get_flag("obs_flight_segment_bytes")),
            max_segments=int(flags.get_flag("obs_flight_segments")))
    except OSError as e:
        # an unwritable/full flight dir degrades telemetry — it must
        # never kill the trainer/server construction it instruments
        from paddlebox_tpu.obs import log as obs_log
        obs_log.warning("flight recorder disabled: dir unusable",
                        dir=d, error=repr(e)[:200])
        return None
    install_crash_hooks(fr)
    _ACTIVE = fr
    return fr
