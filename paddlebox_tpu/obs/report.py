"""StepReport: the per-cadence structured telemetry record + sinks.

Every `obs_report_every` steps the trainer assembles ONE structured
record — stage-timer deltas, StatRegistry counter deltas, gauges,
histogram bucket deltas with percentiles, examples/sec, whatever extras
the runner attaches (streaming AUC at pass end) — and emits it through a
pluggable MetricsSink (JSONL file, stderr, in-memory list for tests, or
nothing: the last report is always retained for the watchdog dump and
cluster aggregation regardless of sink).

Deltas, not cumulatives: a report describes its WINDOW, so rate math and
cross-rank comparison need no history, and a merged cluster view can
min/median/max the windows directly (obs/aggregate.py).
"""

from __future__ import annotations

import copy
import json
import sys
import time
from typing import Callable, Dict, List, Optional

from paddlebox_tpu.utils.channel import poll_depth_gauges
from paddlebox_tpu.utils.stats import (StatRegistry, hist_percentile)

SCHEMA_VERSION = 1


class MetricsSink:
    """Pluggable report destination."""

    def emit(self, record: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullSink(MetricsSink):
    def emit(self, record: dict) -> None:
        pass


class ListSink(MetricsSink):
    """Retains records in memory (tests, probes)."""

    def __init__(self) -> None:
        self.records: List[dict] = []

    def emit(self, record: dict) -> None:
        self.records.append(record)


class JsonlSink(MetricsSink):
    """One JSON object per line, appended + flushed per emit — the
    machine-consumable export (the abacus/monitor dump role)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._fh = open(path, "a", encoding="utf-8")

    def emit(self, record: dict) -> None:
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class StderrSink(MetricsSink):
    def emit(self, record: dict) -> None:
        sys.stderr.write(json.dumps(record) + "\n")


def make_sink(spec: str) -> MetricsSink:
    """'' → NullSink (assemble + retain only), 'stderr' → StderrSink,
    anything else → JsonlSink(path)."""
    spec = (spec or "").strip()
    if not spec:
        return NullSink()
    if spec == "stderr":
        return StderrSink()
    return JsonlSink(spec)


class StepReporter:
    """Assembles StepReports at a step cadence from the process-global
    StatRegistry + the caller's stage timers.

    Thread contract: note_examples/maybe_report come from ONE driver
    thread at a time — the pass driver in trainers (the thread that owns
    the timers), or any pool/conn thread in the serving plane provided
    the caller serializes (ServingServer holds its _report_lock around
    note+report); peek() may be called from the watchdog thread (it
    only reads last_report).
    """

    def __init__(self, rank: int = 0, every: Optional[int] = None,
                 sink: Optional[MetricsSink] = None,
                 timers: Optional[Dict] = None,
                 aggregator=None,
                 clock: Callable[[], float] = time.perf_counter) -> None:
        if every is None or sink is None:
            from paddlebox_tpu.config import flags
            if every is None:
                every = int(flags.get_flag("obs_report_every"))
            if sink is None:
                sink = make_sink(str(flags.get_flag("obs_report_path")))
        self.rank = int(rank)
        self.every = int(every)
        self.sink = sink
        self.timers = timers or {}
        self.aggregator = aggregator
        self._clock = clock
        self._registry = StatRegistry.instance()
        self._prev_counters: Dict[str, int] = {}
        self._prev_hists: Dict[str, List[int]] = {}
        self._prev_timers: Dict[str, tuple] = {}
        self._examples = 0
        self._last_step = 0
        self._last_t = clock()
        self.last_report: Optional[dict] = None

    # ------------------------------------------------------------ cadence
    def note_examples(self, n: int) -> None:
        self._examples += int(n)

    def due(self, step: int) -> bool:
        return self.every > 0 and (step - self._last_step) >= self.every

    def maybe_report(self, step: int, extra: Optional[dict] = None,
                     force: bool = False) -> Optional[dict]:
        """Assemble + emit when the cadence is due (or force=True at pass
        boundaries). Reporting disabled (every<=0) stays disabled even
        under force — off means off, zero assembly cost."""
        if self.every <= 0:
            return None
        if not force and not self.due(step):
            return None
        return self._report(step, extra)

    def peek(self) -> Optional[dict]:
        """DEEP COPY of the last assembled report (watchdog dump + HTTP
        exporter surface); never assembles. A copy, not the internal
        dict: consumers hold and mutate what they get (the exporter
        hands it to json in another thread, the watchdog stashes it),
        and a by-reference return would let any of them corrupt
        reporter state."""
        rep = self.last_report
        return copy.deepcopy(rep) if rep is not None else None

    # ----------------------------------------------------------- assembly
    def _report(self, step: int, extra: Optional[dict]) -> dict:
        now = self._clock()
        interval = max(now - self._last_t, 1e-9)
        poll_depth_gauges()  # sample named-channel depths into gauges
        # watchdog-beat age as a gauge: the cluster health plane reads
        # it per rank (a rank that reports but stopped beating is wedged
        # between cadences — freshness alone can't see that)
        from paddlebox_tpu.obs import watchdog as _wd
        w = _wd.active()
        if w is not None:
            self._registry.set_gauge(
                "beat_age_s", max(0.0, time.monotonic() - w._beat[0]))
        # device plane (round 20): sample the HBM live-buffer ledger at
        # report cadence (owner-bucketed gauges + leak detector);
        # near-free no-op when no jit entry point is instrumented in
        # this process (serving replicas stay jax-free)
        from paddlebox_tpu.obs import device as _device
        _device.on_report()
        snap = self._registry.snapshot_all()

        stats_delta = {}
        for k, v in snap["counters"].items():
            d = v - self._prev_counters.get(k, 0)
            if d:
                stats_delta[k] = d
        hists = {}
        for k, counts in snap["hists"].items():
            prev = self._prev_hists.get(k)
            delta = ([c - p for c, p in zip(counts, prev)] if prev
                     else list(counts))
            n = sum(delta)
            if n <= 0:
                continue
            hists[k] = {
                "count": n,
                "counts": delta,
                "p50": round(hist_percentile(delta, 0.50), 3),
                "p90": round(hist_percentile(delta, 0.90), 3),
                "p99": round(hist_percentile(delta, 0.99), 3),
            }
        timers = {}
        for name, t in self.timers.items():
            ms, calls = t.elapsed_ms(), t.count
            pms, pcalls = self._prev_timers.get(name, (0.0, 0))
            if ms - pms > 1e-6 or calls != pcalls:
                timers[name] = {"ms": round(ms - pms, 3),
                                "calls": calls - pcalls}
            self._prev_timers[name] = (ms, calls)

        record = {
            "type": "step_report",
            "v": SCHEMA_VERSION,
            "ts": time.time(),
            "rank": self.rank,
            "step": int(step),
            "interval_s": round(interval, 6),
            "examples": self._examples,
            "examples_per_sec": round(self._examples / interval, 2),
            "timers": timers,
            "stats": stats_delta,
            "gauges": {k: round(v, 6) for k, v in snap["gauges"].items()},
            "hists": hists,
        }
        if extra:
            record.update(extra)

        self._prev_counters = snap["counters"]
        self._prev_hists = snap["hists"]
        self._examples = 0
        self._last_step = int(step)
        self._last_t = now
        self.last_report = record
        self.sink.emit(record)
        # durable tier: the flight recorder keeps the report (and the
        # span window that produced it) on disk across a crash
        from paddlebox_tpu.obs import flight as _flight
        fr = _flight.active()
        if fr is not None:
            fr.on_report(record)
        if self.aggregator is not None:
            self.aggregator.publish(record)
        return record

    def close(self) -> None:
        self.sink.close()
        if self.aggregator is not None:
            self.aggregator.close()
