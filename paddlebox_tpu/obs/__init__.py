"""obs: the runtime telemetry plane (round 10).

Unifies the reproduction's observability tiers the way the reference's
platform/monitor.h + timer discipline + chrometracing profiler did
(SURVEY.md §5.1), always-on cheap:

  * span tracer  — per-thread ring of named spans; chrome-tracing JSON
                   export loadable in Perfetto without jax.profiler
                   (obs/tracer.py)
  * StepReport   — per-cadence structured record (timer/stat deltas,
                   gauges, histogram percentiles, examples/sec) through a
                   pluggable MetricsSink (obs/report.py)
  * aggregation  — non-zero ranks piggyback their reports to rank 0 over
                   the existing mesh/store plane; rank 0 emits a merged
                   per-rank min/median/max view (obs/aggregate.py)
  * watchdog     — heartbeat thread dumping spans + per-thread stacks +
                   the last StepReport on silence (obs/watchdog.py)
  * log          — rank-prefixed structured lines replacing bare print()
                   in library code (obs/log.py; boxlint BX501 enforces)
  * flight       — always-on bounded on-disk black box per rank with
                   crash SEALING (excepthook / fatal signal / watchdog
                   fire → durable manifest of spans+stacks+reports);
                   the postmortem artifact a SIGKILL'd rank leaves
                   behind (obs/flight.py, round 14)
  * health       — rank 0 folds report freshness, beat age, error-line
                   rate, queue depths and serving SLO burn into a
                   per-rank health score published as cluster_health
                   each aggregation cadence — the elastic-fleet trigger
                   signal (obs/health.py, round 14)
  * trace ids    — 64-bit per-step/per-request ids carried across the
                   p2p mesh and the serving RPC boundary; spans record
                   them, tools/trace_stitch.py merges per-rank chrome
                   traces into one cluster timeline with cross-rank
                   flow events (obs/tracer.py, round 14)
  * exporter     — per-rank HTTP ops endpoint (flag obs_http_port,
                   port +rank): /metrics Prometheus exposition,
                   /report, /health, /stacks, /flight, /quality,
                   /device — the live READ surface over every tier
                   above, answered from defensive snapshots only
                   (obs/exporter.py, round 18)
  * device       — the XLA/device tier (obs/device.py, round 20):
                   instrument_jit wraps every jit entry point (boxlint
                   BX901 enforces) with exact compile counts/wall time,
                   one-time cost/memory-analysis snapshots, a
                   steady-state recompile sentinel, and a donation
                   audit (donated-buffer pointer reuse); the runners'
                   staging/write-back paths account H2D/D2H transfer
                   bytes and the live-buffer ledger buckets
                   jax.live_arrays() by owner at report cadence with a
                   monotonic-growth leak detector — all through the
                   StatRegistry, so reports/metrics/flight/health carry
                   it unchanged

Import surface is deliberately jax-free: every hot-path hook (span,
beat) must stay importable and near-free on any host — the serving
plane (serving/, round 12) runs this whole stack in jax-free replica
processes (per-pull latency histograms, QPS windows, cache-rate extras
ride the same StepReport/sink/aggregation machinery unchanged).
"""

from paddlebox_tpu.obs import device  # noqa: F401
from paddlebox_tpu.obs import exporter  # noqa: F401
from paddlebox_tpu.obs import flight  # noqa: F401
from paddlebox_tpu.obs import log  # noqa: F401
from paddlebox_tpu.obs.device import (account_d2h, account_h2d,  # noqa: F401
                                      instrument_jit)
from paddlebox_tpu.obs.aggregate import (ClusterAggregator,  # noqa: F401
                                         MeshObsTransport, StoreObsTransport,
                                         make_transport,
                                         merge_cluster_reports)
from paddlebox_tpu.obs.flight import FlightRecorder  # noqa: F401
from paddlebox_tpu.obs.health import HealthMonitor  # noqa: F401
from paddlebox_tpu.obs.report import (JsonlSink, ListSink,  # noqa: F401
                                      MetricsSink, NullSink, StderrSink,
                                      StepReporter, make_sink)
from paddlebox_tpu.obs.tracer import (SpanTracer, current_trace,  # noqa: F401
                                      get_tracer, next_trace_id, span,
                                      step_trace_id, trace_ctx)
from paddlebox_tpu.obs.tracer import \
    configure_from_flags as _tracer_configure
from paddlebox_tpu.obs.watchdog import StallWatchdog  # noqa: F401
from paddlebox_tpu.obs.watchdog import beat  # noqa: F401
from paddlebox_tpu.obs.watchdog import ensure_from_flags as _wd_ensure


def make_step_reporter(rank: int = 0, timers=None, aggregator=None,
                       **kwargs) -> StepReporter:
    """Flag-configured reporter + tracer sync + (flag-gated) watchdog +
    (flag-gated) flight recorder — the one call every trainer makes at
    construction."""
    _tracer_configure()
    flight.ensure_from_flags(rank=rank)
    reporter = StepReporter(rank=rank, timers=timers,
                            aggregator=aggregator, **kwargs)
    _wd_ensure(tracer=get_tracer(), report_fn=reporter.peek)
    # live ops endpoint (round 18, flag-gated): /report answers this
    # reporter's peek, /health reaches the health plane through
    # reporter.aggregator — one bind per runner/replica construction
    exp = exporter.ensure_from_flags(rank=rank)
    if exp is not None:
        exp.bind(reporter=reporter)
    return reporter


def obs_rank_world(mesh=None, fleet=None):
    """(rank, world) in the TRANSPORT rank space — mesh rank == fleet
    worker index, the space both piggyback planes address their "rank 0"
    in. Never jax.process_index(): a job is free to map fleet ranks onto
    jax processes differently (MeshComm.positions_of exists for exactly
    that), and a mismatched aggregator would drain nothing while the
    real rank 0 self-publishes into an inbox nobody reads."""
    if mesh is not None:
        return int(mesh.rank), int(mesh.world)
    if fleet is not None and getattr(fleet, "initialized", False):
        return int(fleet.worker_index()), int(fleet.worker_num())
    return 0, 1


def make_cluster_aggregator(mesh=None, fleet=None, rank: int = 0,
                            world: int = 1):
    """The ONE multi-process aggregator wiring both sharded runners use:
    transport from the job's existing plane (p2p mesh, else fleet
    store), rank 0 emitting merged cluster reports — and the derived
    cluster_health records (obs/health.py) — through the flag-configured
    sink. None when no piggyback plane exists."""
    transport = make_transport(mesh=mesh, fleet=fleet)
    if transport is None:
        return None
    from paddlebox_tpu.config import flags
    sink = (make_sink(str(flags.get_flag("obs_report_path")))
            if rank == 0 else None)
    health = (HealthMonitor(
        world, drift_warn=float(flags.get_flag("data_quality_warn")))
        if rank == 0 else None)
    return ClusterAggregator(transport, rank, world, sink=sink,
                             health=health)


def export_chrome_trace(path=None, rank: int = 0) -> dict:
    """Dump the span rings as chrome-tracing JSON (Perfetto-loadable)."""
    return get_tracer().export_chrome(path=path, pid=rank)
