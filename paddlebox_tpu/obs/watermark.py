"""Feed-to-serve watermark plane (round 20): freshness lineage + tiers.

The repo's headline claim is seconds-level feed-to-serve freshness, but
until this round it was only ever PROBED (the round-19 drop-to-servable
number, the round-21 staleness leg). This module is the shared
vocabulary that turns it into a continuously measured, alarmed
invariant:

  * ``data/streaming.py`` stamps each micro-pass window's source-file
    mtime span (``born_min_ts``/``born_ts``);
  * ``train/streaming_runner.py`` passes the span into
    ``TouchedRowJournal.publish`` → a ``KIND_WATERMARK`` record lands
    in the same fsync as the window's rows
    (utils/journal_format.py:pack_watermark);
  * ``serving/refresh.py``'s JournalDeltaSource tracks the newest
    APPLIED ``born_max`` per journal dir; the low-water-mark across
    dirs is the view stack's watermark (``applied_watermark``);
  * ``serving/server.py`` stamps every pull response with it
    (codec ``wm`` field) and both server and client feed
    ``observe_freshness`` — so ``now - watermark`` is sampled at pull
    cadence, not probe cadence, and the histogram's p50/p99 mean
    "freshness as traffic saw it".

Unit note: the shared histogram buckets are powers of two starting at
1 (utils/stats.py HIST_BOUNDS) — sub-second freshness in SECONDS would
collapse into the first bucket, so the histogram observes MILLISECONDS
(``freshness_e2e_ms``, 1 ms..2^25 ms ≈ 9.3 h) and the derived gauges
republish seconds under the names the dashboards pin
(``freshness_e2e_secs`` / ``_p50`` / ``_p99``).

Degrade contract: everything here is telemetry — never raises into the
serving or training path; ``obs_watermark=false`` turns the whole
plane off (the pairwise overhead bench's control arm).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

from paddlebox_tpu.config import flags
from paddlebox_tpu.utils.stats import (StatRegistry, gauge_get, gauge_set,
                                       hist_observe, hist_percentile,
                                       stat_get)

#: the one end-to-end freshness histogram (milliseconds — see unit note)
FRESHNESS_HIST = "freshness_e2e_ms"


def enabled() -> bool:
    """Watermark plane master switch (flag ``obs_watermark``)."""
    return bool(flags.get_flag("obs_watermark"))


def observe_freshness(watermark_ts: Optional[float],
                      now: Optional[float] = None) -> Optional[float]:
    """One end-to-end freshness sample from a watermark-stamped pull:
    ``now - watermark_ts`` seconds, observed into ``freshness_e2e_ms``
    and republished as the ``freshness_e2e_secs``/``_p50``/``_p99``
    gauges (process-cumulative percentiles; the serving report window
    derives per-window ones from histogram deltas). Returns the sample,
    or None when there is no watermark yet (cold journal)."""
    if not watermark_ts or watermark_ts <= 0.0:
        return None
    if now is None:
        now = time.time()
    fresh = max(0.0, float(now) - float(watermark_ts))
    hist_observe(FRESHNESS_HIST, fresh * 1e3)
    gauge_set("freshness_e2e_secs", fresh)
    counts = StatRegistry.instance().hist_counts(FRESHNESS_HIST)
    gauge_set("freshness_e2e_secs_p50",
              hist_percentile(counts, 0.50) / 1e3)
    gauge_set("freshness_e2e_secs_p99",
              hist_percentile(counts, 0.99) / 1e3)
    return fresh


def freshness_burn(counts_delta: Sequence[int]) -> Optional[float]:
    """SLO burn for one report window: p99 of the window's freshness
    histogram DELTA divided by ``freshness_slo_secs``. > 1 means served
    vectors are staler than the promise. None when the SLO is disabled
    or the window saw no stamped pulls (no data is not a burn)."""
    slo = float(flags.get_flag("freshness_slo_secs"))
    if slo <= 0.0 or not counts_delta or sum(counts_delta) <= 0:
        return None
    return (hist_percentile(list(counts_delta), 0.99) / 1e3) / slo


def tier_hit_burn(hit_rate: float) -> Optional[float]:
    """Tier-hit burn: ``tier_hit_rate_warn / hit_rate`` — > 1 when the
    resident (host-RAM) hit rate fell below the warn floor, i.e. the
    SSD tier is thrashing. None when disabled."""
    warn = float(flags.get_flag("tier_hit_rate_warn"))
    if warn <= 0.0:
        return None
    return warn / max(float(hit_rate), 1e-9)


#: the tiered-store hit ladder, fastest tier first: counter name →
#: ladder label. HBM residency is the device feed slab (whole working
#: set by construction), host-RAM is the store's resident index,
#: SSD-promote is a tier fault-in, miss creates the row.
TIER_LADDER_COUNTERS = (
    ("sparse_keys_resident_hit", "host_ram_hit"),
    ("sparse_keys_faulted_in", "ssd_promote"),
    ("sparse_keys_prefetch_faulted", "ssd_prefetch"),
    ("sparse_keys_created", "miss_created"),
)


def tier_ladder() -> Dict[str, float]:
    """Snapshot of the cumulative tier hit ladder (this process) as
    counts plus per-rung fractions of all ladder traffic — the
    cluster-report / probe rendering of the tiered-store telemetry."""
    counts = {label: float(stat_get(name))
              for name, label in TIER_LADDER_COUNTERS}
    total = sum(counts.values())
    out: Dict[str, float] = dict(counts)
    for label, c in counts.items():
        out[label + "_frac"] = round(c / total, 4) if total else 0.0
    out["total"] = total
    out["tier_hit_rate"] = float(gauge_get("tier_hit_rate"))
    promote = StatRegistry.instance().hist_counts("ssd_promote_us")
    out["ssd_promote_p99_us"] = (hist_percentile(promote, 0.99)
                                 if promote else 0.0)
    return out


def freshness_snapshot() -> Dict[str, float]:
    """The freshness ladder as the cluster report / probe renders it:
    last sample + cumulative p50/p99 (seconds) and the streaming-side
    lag gauges, all from this process's registry."""
    return {
        "freshness_e2e_secs": float(gauge_get("freshness_e2e_secs")),
        "freshness_e2e_secs_p50": float(
            gauge_get("freshness_e2e_secs_p50")),
        "freshness_e2e_secs_p99": float(
            gauge_get("freshness_e2e_secs_p99")),
        "streaming_ingest_lag_secs": float(
            gauge_get("streaming_ingest_lag_secs")),
        "streaming_publish_lag_secs": float(
            gauge_get("streaming_publish_lag_secs")),
        "serving_watermark_age_secs": float(
            gauge_get("serving_watermark_age_secs")),
    }
