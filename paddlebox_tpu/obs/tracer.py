"""Span tracer: lock-free per-thread ring buffers → chrome-tracing JSON.

The cheap always-on tier of the reference's tracing ladder (SURVEY.md
§5.1): platform::RecordEvent spans feeding chrometracing_logger. Here a
span is ONE perf_counter pair appended to the calling thread's private
ring (no lock, no allocation beyond a tuple), so instrumenting every hot
path costs ~1us/span and the last `capacity` spans per thread are always
available — to the watchdog's stall dump, and to export_chrome() which
emits valid chrome-tracing JSON loadable in Perfetto WITHOUT jax.profiler
(works on the CPU-fallback container; when a real jax trace is running,
utils/profiler.trace installs TraceAnnotation so the same spans also land
in the XPlane).

Round 14 adds CROSS-PLANE trace ids: a span optionally carries a 64-bit
trace id (thread-local "current trace" context, set per step by the
runners and per request by the serving client), the id travels in mesh
frame headers / serving request dicts, and receiver-side spans record
the SENDER's id — which is what lets tools/trace_stitch.py merge
per-rank chrome traces into one cluster timeline with ph:s/f flow
events across ranks. Exported traces carry a wall-clock origin in their
metadata so the stitcher can place every rank on one absolute axis.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple
from paddlebox_tpu.utils.lockwatch import make_rlock

# process-relative clock origin: chrome ts fields are µs since this epoch.
# _EPOCH_UNIX is the SAME instant on the wall clock (taken back-to-back)
# — the anchor trace_stitch uses to align per-rank traces on one axis.
_EPOCH = time.perf_counter()
_EPOCH_UNIX = time.time()

# jax.profiler.TraceAnnotation factory while a real trace is running
# (installed/removed by utils/profiler.trace) — None = spans are ring-only
_JAX_ANNOTATE = None


def set_jax_annotation(factory) -> None:
    global _JAX_ANNOTATE
    _JAX_ANNOTATE = factory


# ------------------------------------------------------------- trace ids
# Thread-local "current trace": spans recorded while a trace id is set
# carry it into the ring (and from there into the chrome export's args),
# so one request/step can be followed across every span it touches.
_TRACE_CTX = threading.local()
# client-side request ids: salted counter — correlated by equality,
# never decoded. The 15-bit salt mixes the pid with random bytes: a pid
# alone collides under modern pid_max (4M >> 2^15, two processes equal
# mod 32768 would mint identical sequences), the random mix makes a
# cross-process collision 2^-15 per pair instead of systematic.
_NEXT_REQ = itertools.count(1)
_REQ_SALT = ((os.getpid() ^ (os.getpid() >> 15)
              ^ int.from_bytes(os.urandom(2), "little")) & 0x7FFF)


def step_trace_id(rank: int, step: int) -> int:
    """Deterministic 64-bit per-step id: rank in the high 16 bits, step
    counter below — collision-free across ranks because each sender only
    ever mints ids in its own rank-space."""
    return ((int(rank) & 0xFFFF) << 48) | (int(step) & 0xFFFFFFFFFFFF)


def next_trace_id() -> int:
    """Per-request id for planes without a step counter (serving client
    pulls): process-salted monotonic counter, high bit set so the id
    space never collides with step_trace_id's rank<<48 layout."""
    return ((1 << 63) | (_REQ_SALT << 48)
            | (next(_NEXT_REQ) & 0xFFFFFFFFFFFF))


def current_trace() -> Optional[int]:
    return getattr(_TRACE_CTX, "id", None)


def set_trace(trace: Optional[int]) -> Optional[int]:
    """Set this thread's current trace id; returns the previous one."""
    prev = getattr(_TRACE_CTX, "id", None)
    _TRACE_CTX.id = trace
    return prev


class trace_ctx:
    """``with trace_ctx(tid): ...`` — spans inside carry ``tid``.
    Restores the previous id on exit (nesting-safe)."""

    __slots__ = ("_id", "_prev")

    def __init__(self, trace: Optional[int]) -> None:
        self._id = trace

    def __enter__(self):
        self._prev = set_trace(self._id)
        return self._id

    def __exit__(self, *exc):
        set_trace(self._prev)
        return False


class _ThreadRing:
    """One thread's span ring. Only its owner thread writes; readers
    (export, watchdog dump) take a best-effort snapshot — a torn slot
    under concurrent wrap is an acceptable trade for zero locking on the
    record path."""

    __slots__ = ("buf", "idx", "cap", "tid", "tname", "owner")

    def __init__(self, cap: int, tid: int, tname: str, owner) -> None:
        self.buf: List[Optional[Tuple[str, float, float,
                                      Optional[int]]]] = [None] * cap
        self.idx = 0
        self.cap = cap
        self.tid = tid
        self.tname = tname
        self.owner = owner      # weakref to the owning thread

    def record(self, name: str, t0: float, t1: float,
               trace: Optional[int] = None) -> None:
        i = self.idx
        self.buf[i % self.cap] = (name, t0, t1, trace)
        self.idx = i + 1

    def spans(self) -> List[Tuple[str, float, float, Optional[int]]]:
        """Oldest-first snapshot of the live slots."""
        i, cap = self.idx, self.cap
        if i <= cap:
            out = self.buf[:i]
        else:
            cut = i % cap
            out = self.buf[cut:] + self.buf[:cut]
        return [s for s in out if s is not None]


class _NullSpan:
    """Reusable no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("_tr", "name", "t0", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str) -> None:
        self._tr = tracer
        self.name = name

    def __enter__(self):
        ann = _JAX_ANNOTATE
        if ann is not None:
            self._ann = ann(self.name)
            self._ann.__enter__()
        else:
            self._ann = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tr._ring().record(self.name, self.t0, t1,
                                getattr(_TRACE_CTX, "id", None))
        if self._ann is not None:
            self._ann.__exit__(*exc)
        return False


class SpanTracer:
    """Registry of per-thread rings + chrome-trace export."""

    # dead threads' rings retained (newest-first) so a trace exported
    # after a pass still carries its finished stager/producer threads'
    # spans; older ones are pruned at the next thread registration —
    # a job running thousands of passes (one short-lived thread each)
    # must not accumulate dead 4096-slot rings forever
    MAX_DEAD_RINGS = 32

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = int(capacity)
        self.enabled = True
        self._rings: List[_ThreadRing] = []   # guarded-by: _reg_lock
        # RLock, not Lock: the flight recorder's fatal-signal seal path
        # reads last_spans() from the signal handler, which may interrupt
        # this very thread mid-all_spans() — a plain lock would deadlock
        # the dying process instead of sealing and re-delivering
        self._reg_lock = make_rlock("SpanTracer._reg_lock")
        self._local = threading.local()

    def _ring(self) -> _ThreadRing:
        r = getattr(self._local, "ring", None)
        if r is None:
            t = threading.current_thread()
            r = _ThreadRing(self.capacity, t.ident or 0, t.name,
                            weakref.ref(t))
            self._local.ring = r
            with self._reg_lock:
                # registration is rare (once per thread): keep the
                # newest MAX_DEAD_RINGS dead-thread rings, prune older
                dead = [x for x in self._rings
                        if (th := x.owner()) is None or not th.is_alive()]
                if len(dead) > self.MAX_DEAD_RINGS:
                    drop = {id(x) for x in dead[:-self.MAX_DEAD_RINGS]}
                    self._rings = [x for x in self._rings
                                   if id(x) not in drop]
                self._rings.append(r)
        return r

    def span(self, name: str):
        """Context manager timing one named region on this thread. The
        disabled path is one attribute read + one identity return."""
        if not self.enabled:
            return _NULL
        return _Span(self, name)

    def record_span(self, name: str, t0: float, t1: float,
                    trace: Optional[int] = None) -> None:
        """Post-hoc span from perf_counter stamps the caller already
        took (sites that time a region anyway record it span-free).
        An explicit ``trace`` (receiver-side spans tagging the SENDER's
        id) wins over this thread's current trace context."""
        if self.enabled:
            if trace is None:
                trace = getattr(_TRACE_CTX, "id", None)
            self._ring().record(name, t0, t1, trace)

    def clear(self) -> None:
        with self._reg_lock:
            self._rings = []
        # each thread lazily re-registers a fresh ring (its old one is
        # unreachable from the registry, so export never sees it again);
        # this thread's cache is dropped eagerly
        self._local = threading.local()

    # ------------------------------------------------------------- readers
    def all_spans(self) -> List[Tuple[str, int, str, float, float,
                                      Optional[int]]]:
        """(name, tid, thread_name, t0, t1, trace) across every thread,
        t0-sorted; trace is None for spans recorded outside a trace
        context."""
        with self._reg_lock:
            rings = list(self._rings)
        out = []
        for r in rings:
            for name, t0, t1, trace in r.spans():
                out.append((name, r.tid, r.tname, t0, t1, trace))
        out.sort(key=lambda s: s[3])
        return out

    def last_spans(self, k: int = 64) -> List[Tuple[str, int, str, float,
                                                    float, Optional[int]]]:
        return self.all_spans()[-k:]

    def export_chrome(self, path: Optional[str] = None, pid: int = 0,
                      meta: Optional[Dict] = None) -> dict:
        """Chrome-tracing JSON (the chrometracing_logger role): complete
        ("X") events in µs since process epoch plus thread-name metadata,
        loadable in Perfetto / chrome://tracing. Returns the document;
        writes it to `path` when given."""
        events = []
        seen_tids = set()
        for name, tid, tname, t0, t1, trace in self.all_spans():
            if tid not in seen_tids:
                seen_tids.add(tid)
                events.append({"ph": "M", "name": "thread_name", "pid": pid,
                               "tid": tid, "args": {"name": tname}})
            ev = {
                "ph": "X", "cat": "obs", "name": name, "pid": pid,
                "tid": tid,
                "ts": round((t0 - _EPOCH) * 1e6, 3),
                "dur": round((t1 - t0) * 1e6, 3),
            }
            if trace is not None:
                # hex STRING, not int: 64-bit ids exceed the 2^53 range
                # json numbers survive in every consumer
                ev["args"] = {"trace": "0x%016x" % (trace & (2**64 - 1))}
            events.append(ev)
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               # wall-clock instant of ts=0 on THIS process — the anchor
               # tools/trace_stitch.py aligns per-rank traces with
               "metadata": {"rank": pid,
                            "clock_origin_unix_s": _EPOCH_UNIX}}
        if meta:
            doc["metadata"].update(dict(meta))
        if path:
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
        return doc


# ---------------------------------------------------------------- module API
_TRACER = SpanTracer()


def get_tracer() -> SpanTracer:
    return _TRACER


def span(name: str):
    """``with obs.span("h2d_stage"): ...`` — the one-liner every hot path
    uses. Near-free when tracing is disabled."""
    if not _TRACER.enabled:
        return _NULL
    return _Span(_TRACER, name)


def record_span(name: str, t0: float, t1: float,
                trace: Optional[int] = None) -> None:
    _TRACER.record_span(name, t0, t1, trace)


def configure_from_flags() -> None:
    """Sync the module tracer with the obs_trace / obs_trace_capacity
    flags (called by the trainers at construction; safe to call often)."""
    from paddlebox_tpu.config import flags
    _TRACER.enabled = bool(flags.get_flag("obs_trace"))
    cap = int(flags.get_flag("obs_trace_capacity"))
    if cap > 0 and cap != _TRACER.capacity:
        _TRACER.capacity = cap
        _TRACER.clear()
