"""Span tracer: lock-free per-thread ring buffers → chrome-tracing JSON.

The cheap always-on tier of the reference's tracing ladder (SURVEY.md
§5.1): platform::RecordEvent spans feeding chrometracing_logger. Here a
span is ONE perf_counter pair appended to the calling thread's private
ring (no lock, no allocation beyond a tuple), so instrumenting every hot
path costs ~1us/span and the last `capacity` spans per thread are always
available — to the watchdog's stall dump, and to export_chrome() which
emits valid chrome-tracing JSON loadable in Perfetto WITHOUT jax.profiler
(works on the CPU-fallback container; when a real jax trace is running,
utils/profiler.trace installs TraceAnnotation so the same spans also land
in the XPlane).
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

# process-relative clock origin: chrome ts fields are µs since this epoch
_EPOCH = time.perf_counter()

# jax.profiler.TraceAnnotation factory while a real trace is running
# (installed/removed by utils/profiler.trace) — None = spans are ring-only
_JAX_ANNOTATE = None


def set_jax_annotation(factory) -> None:
    global _JAX_ANNOTATE
    _JAX_ANNOTATE = factory


class _ThreadRing:
    """One thread's span ring. Only its owner thread writes; readers
    (export, watchdog dump) take a best-effort snapshot — a torn slot
    under concurrent wrap is an acceptable trade for zero locking on the
    record path."""

    __slots__ = ("buf", "idx", "cap", "tid", "tname", "owner")

    def __init__(self, cap: int, tid: int, tname: str, owner) -> None:
        self.buf: List[Optional[Tuple[str, float, float]]] = [None] * cap
        self.idx = 0
        self.cap = cap
        self.tid = tid
        self.tname = tname
        self.owner = owner      # weakref to the owning thread

    def record(self, name: str, t0: float, t1: float) -> None:
        i = self.idx
        self.buf[i % self.cap] = (name, t0, t1)
        self.idx = i + 1

    def spans(self) -> List[Tuple[str, float, float]]:
        """Oldest-first snapshot of the live slots."""
        i, cap = self.idx, self.cap
        if i <= cap:
            out = self.buf[:i]
        else:
            cut = i % cap
            out = self.buf[cut:] + self.buf[:cut]
        return [s for s in out if s is not None]


class _NullSpan:
    """Reusable no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("_tr", "name", "t0", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str) -> None:
        self._tr = tracer
        self.name = name

    def __enter__(self):
        ann = _JAX_ANNOTATE
        if ann is not None:
            self._ann = ann(self.name)
            self._ann.__enter__()
        else:
            self._ann = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tr._ring().record(self.name, self.t0, t1)
        if self._ann is not None:
            self._ann.__exit__(*exc)
        return False


class SpanTracer:
    """Registry of per-thread rings + chrome-trace export."""

    # dead threads' rings retained (newest-first) so a trace exported
    # after a pass still carries its finished stager/producer threads'
    # spans; older ones are pruned at the next thread registration —
    # a job running thousands of passes (one short-lived thread each)
    # must not accumulate dead 4096-slot rings forever
    MAX_DEAD_RINGS = 32

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = int(capacity)
        self.enabled = True
        self._rings: List[_ThreadRing] = []   # guarded-by: _reg_lock
        self._reg_lock = threading.Lock()
        self._local = threading.local()

    def _ring(self) -> _ThreadRing:
        r = getattr(self._local, "ring", None)
        if r is None:
            t = threading.current_thread()
            r = _ThreadRing(self.capacity, t.ident or 0, t.name,
                            weakref.ref(t))
            self._local.ring = r
            with self._reg_lock:
                # registration is rare (once per thread): keep the
                # newest MAX_DEAD_RINGS dead-thread rings, prune older
                dead = [x for x in self._rings
                        if (th := x.owner()) is None or not th.is_alive()]
                if len(dead) > self.MAX_DEAD_RINGS:
                    drop = {id(x) for x in dead[:-self.MAX_DEAD_RINGS]}
                    self._rings = [x for x in self._rings
                                   if id(x) not in drop]
                self._rings.append(r)
        return r

    def span(self, name: str):
        """Context manager timing one named region on this thread. The
        disabled path is one attribute read + one identity return."""
        if not self.enabled:
            return _NULL
        return _Span(self, name)

    def record_span(self, name: str, t0: float, t1: float) -> None:
        """Post-hoc span from perf_counter stamps the caller already
        took (sites that time a region anyway record it span-free)."""
        if self.enabled:
            self._ring().record(name, t0, t1)

    def clear(self) -> None:
        with self._reg_lock:
            self._rings = []
        # each thread lazily re-registers a fresh ring (its old one is
        # unreachable from the registry, so export never sees it again);
        # this thread's cache is dropped eagerly
        self._local = threading.local()

    # ------------------------------------------------------------- readers
    def all_spans(self) -> List[Tuple[str, int, str, float, float]]:
        """(name, tid, thread_name, t0, t1) across every thread, t0-sorted."""
        with self._reg_lock:
            rings = list(self._rings)
        out = []
        for r in rings:
            for name, t0, t1 in r.spans():
                out.append((name, r.tid, r.tname, t0, t1))
        out.sort(key=lambda s: s[3])
        return out

    def last_spans(self, k: int = 64) -> List[Tuple[str, int, str, float, float]]:
        return self.all_spans()[-k:]

    def export_chrome(self, path: Optional[str] = None, pid: int = 0,
                      meta: Optional[Dict] = None) -> dict:
        """Chrome-tracing JSON (the chrometracing_logger role): complete
        ("X") events in µs since process epoch plus thread-name metadata,
        loadable in Perfetto / chrome://tracing. Returns the document;
        writes it to `path` when given."""
        events = []
        seen_tids = set()
        for name, tid, tname, t0, t1 in self.all_spans():
            if tid not in seen_tids:
                seen_tids.add(tid)
                events.append({"ph": "M", "name": "thread_name", "pid": pid,
                               "tid": tid, "args": {"name": tname}})
            events.append({
                "ph": "X", "cat": "obs", "name": name, "pid": pid,
                "tid": tid,
                "ts": round((t0 - _EPOCH) * 1e6, 3),
                "dur": round((t1 - t0) * 1e6, 3),
            })
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if meta:
            doc["metadata"] = dict(meta)
        if path:
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
        return doc


# ---------------------------------------------------------------- module API
_TRACER = SpanTracer()


def get_tracer() -> SpanTracer:
    return _TRACER


def span(name: str):
    """``with obs.span("h2d_stage"): ...`` — the one-liner every hot path
    uses. Near-free when tracing is disabled."""
    if not _TRACER.enabled:
        return _NULL
    return _Span(_TRACER, name)


def record_span(name: str, t0: float, t1: float) -> None:
    _TRACER.record_span(name, t0, t1)


def configure_from_flags() -> None:
    """Sync the module tracer with the obs_trace / obs_trace_capacity
    flags (called by the trainers at construction; safe to call often)."""
    from paddlebox_tpu.config import flags
    _TRACER.enabled = bool(flags.get_flag("obs_trace"))
    cap = int(flags.get_flag("obs_trace_capacity"))
    if cap > 0 and cap != _TRACER.capacity:
        _TRACER.capacity = cap
        _TRACER.clear()
