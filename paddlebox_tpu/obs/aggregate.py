"""Cluster-wide telemetry aggregation: per-rank StepReports → rank-0 view.

Every report cadence, non-zero ranks ship their StepReport to rank 0
PIGGYBACKED on a plane the job already runs — the p2p socket mesh (a
one-way "obs" frame to the same FramedServer the exchanges use, but
over a DEDICATED short-timeout connection so a telemetry stall can
never brick the lockstep exchange clients; fleet/mesh_comm.py send_obs)
or, when the job runs the store host plane, fire-and-forget KV writes
on the TcpStore. Neither is a collective: a slow rank delays nothing,
rank 0 merges whatever snapshots have arrived and marks the rest stale.

The merged cluster report carries per-rank min/median/max (plus the
per-rank values) for every numeric window metric — which is exactly the
view that makes hostplane imbalance and straggler ranks visible — and
sums histogram bucket counts across ranks before computing percentiles
(fixed shared bounds make that sound; utils/stats.HIST_BOUNDS).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from paddlebox_tpu.obs.report import SCHEMA_VERSION, MetricsSink, NullSink
from paddlebox_tpu.utils.stats import hist_percentile


class StoreObsTransport:
    """Fleet-store piggyback: rank r overwrites ONE key per rank
    (`<ns>/obs/<rank>`) with its latest report; rank 0 polls them
    non-blockingly at its own cadence. Overwrite-in-place keeps the store
    footprint O(world) forever — no per-step key growth, no barriers."""

    def __init__(self, store, namespace: str, rank: int, world: int) -> None:
        self._store = store
        self._ns = namespace.rstrip("/")
        self.rank = int(rank)
        self.world = int(world)
        self._last_seq: Dict[int, Tuple[str, int]] = {}
        self._seq = 0
        # per-transport epoch: a rank restarted by elastic recovery
        # builds a FRESH transport whose seq restarts at 0 — without the
        # epoch in the frame head, rank 0 would discard its reports as
        # stale forever and the rank would read as a permanent straggler
        self._epoch = uuid.uuid4().hex[:12]

    def _key(self, rank: int) -> str:
        return "%s/%d" % (self._ns, rank)

    def publish(self, payload: bytes) -> None:
        self._seq += 1
        framed = (json.dumps([self._epoch, self._seq]).encode()
                  + b"\n" + payload)
        self._store.set(self._key(self.rank), framed)

    def drain(self) -> List[bytes]:
        out = []
        for r in range(self.world):
            if r == self.rank:
                continue
            raw = self._store.get(self._key(r))
            if raw is None:
                continue
            head, _, payload = bytes(raw).partition(b"\n")
            epoch, seq = json.loads(head)
            last = self._last_seq.get(r)
            if (last is not None and last[0] == epoch
                    and int(seq) <= last[1]):
                continue            # already merged this window
            self._last_seq[r] = (str(epoch), int(seq))
            out.append(payload)
        return out


class MeshObsTransport:
    """P2P-mesh piggyback: one fire-and-forget framed call to rank 0's
    FramedServer over MeshComm.send_obs's DEDICATED short-timeout obs
    connection — deliberately NOT the exchange clients, so a telemetry
    timeout bricks only the (re-dialable) obs connection, never the
    lockstep data plane."""

    def __init__(self, mesh) -> None:
        self._mesh = mesh
        self.rank = int(mesh.rank)
        self.world = int(mesh.world)

    def publish(self, payload: bytes) -> None:
        self._mesh.send_obs(payload, to_rank=0)

    def drain(self) -> List[bytes]:
        return self._mesh.drain_obs()


def make_transport(mesh=None, fleet=None):
    """The piggyback plane for this job: the p2p mesh when it is up,
    else the fleet store, else None (single-rank / no control plane)."""
    if mesh is not None:
        return MeshObsTransport(mesh)
    if fleet is not None and getattr(fleet, "initialized", False):
        client = fleet.store_client()
        if client is not None and fleet.worker_num() > 1:
            return StoreObsTransport(client, fleet.obs_namespace(),
                                     fleet.worker_index(),
                                     fleet.worker_num())
    return None


def merge_cluster_reports(reports: List[dict]) -> dict:
    """Rank-0 merge of one window's per-rank StepReports: per-metric
    min/median/max + per_rank values over stats/gauges/timer-ms/
    examples_per_sec; histogram counts sum elementwise before the
    percentile math."""
    per_metric: Dict[str, Dict[int, float]] = {}
    hist_sums: Dict[str, List[int]] = {}
    ranks = []
    step = 0
    for rec in reports:
        r = int(rec.get("rank", 0))
        ranks.append(r)
        step = max(step, int(rec.get("step", 0)))
        per_metric.setdefault("examples_per_sec", {})[r] = float(
            rec.get("examples_per_sec", 0.0))
        for k, v in (rec.get("stats") or {}).items():
            per_metric.setdefault("stats." + k, {})[r] = float(v)
        for k, v in (rec.get("gauges") or {}).items():
            per_metric.setdefault("gauges." + k, {})[r] = float(v)
        for k, v in (rec.get("timers") or {}).items():
            per_metric.setdefault("timers.%s.ms" % k, {})[r] = float(
                v.get("ms", 0.0))
        for k, h in (rec.get("hists") or {}).items():
            counts = h.get("counts") or []
            cur = hist_sums.get(k)
            if cur is None:
                hist_sums[k] = list(counts)
            else:
                for i, c in enumerate(counts):
                    if i < len(cur):
                        cur[i] += c
                    else:
                        cur.append(c)
    metrics = {}
    for k, by_rank in sorted(per_metric.items()):
        vals = sorted(by_rank.values())
        n = len(vals)
        med = (vals[n // 2] if n % 2 else
               0.5 * (vals[n // 2 - 1] + vals[n // 2]))
        metrics[k] = {"min": vals[0], "med": round(med, 3),
                      "max": vals[-1],
                      "per_rank": {str(r): by_rank[r] for r in sorted(by_rank)}}
    hists = {}
    for k, counts in sorted(hist_sums.items()):
        hists[k] = {"count": sum(counts),
                    "p50": round(hist_percentile(counts, 0.50), 3),
                    "p90": round(hist_percentile(counts, 0.90), 3),
                    "p99": round(hist_percentile(counts, 0.99), 3)}
    out = {"type": "cluster_report", "v": SCHEMA_VERSION, "step": step,
           "ranks": sorted(set(ranks)), "metrics": metrics, "hists": hists}
    # tagged quality plane (round 18): pass_end reports ship each
    # rank's sum-mergeable quality state — sum the bucket tables and
    # compute the CLUSTER-wide tagged auc/copc/ctr (per-rank AUCs do
    # not average; their tables sum, exactly the reference's allreduce)
    qstates = [rec["quality_state"] for rec in reports
               if rec.get("quality_state")]
    if qstates:
        from paddlebox_tpu.metrics.quality import merged_report
        q = merged_report(qstates)
        if q is not None:
            out["quality"] = q
    return out


class ClusterAggregator:
    """Per-rank façade the StepReporter publishes through.

    Non-zero ranks: every publish ships the report to rank 0 (best
    effort; a transport failure degrades to a one-line warning, never
    fails the step). Rank 0: stashes its own report, drains peers'
    latest, emits ONE merged cluster record through its sink — and,
    when a HealthMonitor is attached (obs/health.py), the derived
    ``cluster_health`` record right behind it. Only snapshots that
    ARRIVED since the previous merge are merged — a wedged rank drops
    out of the metrics (listed in stale_ranks) instead of having its
    last-ever window re-merged as current forever.

    Failure policy (round 14): consecutive publish failures back off
    EXPONENTIALLY instead of disabling forever — a transient NIC blip
    or a peer restart must not kill cluster telemetry for the job
    lifetime. The backoff is denominated in SKIPPED PUBLISHES (1, 2,
    4, ... capped at BACKOFF_SKIP_CAP) with a BACKOFF_CAP_S wall-clock
    ceiling, whichever expires first: publishes happen at report
    cadence, and every skipped publish is a window rank 0 reads as
    stale — so the re-probe latency must be bounded in WINDOWS (the
    unit the health plane's stale-death threshold counts in), not just
    in seconds. Any success resets everything; a transient blip
    therefore costs a few stale (→ transiently degraded/unhealthy)
    windows and recovers, never the rest of the job.
    """

    #: consecutive failures before backoff starts
    MAX_PUBLISH_FAILURES = 3
    #: max publishes skipped per backoff round (bounds stale windows)
    BACKOFF_SKIP_CAP = 16
    #: wall-clock ceiling on one backoff round (slow-cadence jobs)
    BACKOFF_CAP_S = 60.0

    def __init__(self, transport, rank: int, world: int,
                 sink: Optional[MetricsSink] = None,
                 health=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.transport = transport
        self.rank = int(rank)
        self.world = int(world)
        self.sink = sink or NullSink()
        self.health = health
        self._clock = clock
        self._window: Dict[int, dict] = {}   # rank -> report THIS window
        self.last_cluster_report: Optional[dict] = None
        self.last_cluster_health: Optional[dict] = None
        self._failures = 0
        self._skip_remaining = 0
        self._backoff_until = 0.0

    def publish(self, report: dict) -> Optional[dict]:
        if (self._skip_remaining > 0
                and self._clock() < self._backoff_until):
            self._skip_remaining -= 1
            return None             # backing off; re-probe after the skips
        try:
            if self.rank != 0:
                self.transport.publish(json.dumps(report).encode())
                self._failures = 0
                self._skip_remaining = 0
                self._backoff_until = 0.0
                return None
            self._window[0] = report
            merged = self.collect_and_emit()
            self._failures = 0
            self._skip_remaining = 0
            self._backoff_until = 0.0
            return merged
        except Exception as e:  # noqa: BLE001 — telemetry must not kill a step
            self._failures += 1
            skips = 0
            if self._failures >= self.MAX_PUBLISH_FAILURES:
                # stop paying the publish cost every cadence, but KEEP
                # re-probing: skipped-publish count doubles per failure
                # past the threshold, capped — a transient blip costs a
                # bounded number of stale windows, never the job
                skips = min(
                    self.BACKOFF_SKIP_CAP,
                    2 ** (self._failures - self.MAX_PUBLISH_FAILURES))
                self._skip_remaining = skips
                self._backoff_until = self._clock() + self.BACKOFF_CAP_S
            from paddlebox_tpu.obs import log as obs_log
            obs_log.warning(
                "cluster telemetry publish failed%s" % (
                    " — skipping next %d publish(es)" % skips if skips
                    else ""), error=repr(e)[:200],
                failures=self._failures)
            return None

    def collect_and_emit(self) -> dict:
        for payload in self.transport.drain():
            try:
                rec = json.loads(payload.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            self._window[int(rec.get("rank", -1))] = rec
        # merge ONLY this window's arrivals: a rank that published once
        # and then wedged must drop out of the metrics and read as
        # stale, not have its old window merged as current forever (the
        # straggler diagnostic)
        merged = merge_cluster_reports(list(self._window.values()))
        merged["stale_ranks"] = sorted(
            set(range(self.world)) - set(self._window))
        self._window = {}
        self.last_cluster_report = merged
        self.sink.emit(merged)
        from paddlebox_tpu.obs import flight as _flight
        fr = _flight.active()
        if fr is not None:
            fr.on_report(merged)
        if self.health is not None:
            hrec = self.health.update(merged)
            self.last_cluster_health = hrec
            self.sink.emit(hrec)
            if fr is not None:
                fr.on_report(hrec)
        return merged

    def close(self) -> None:
        self.sink.close()
