"""Per-rank HTTP ops endpoint: the live READ surface of the obs plane.

Everything the telemetry tiers assemble (PR 5 reports/aggregation, PR 9
flight/health, this round's quality/drift planes) was push-only and
file-bound — an operator could not look at a live rank without tailing
JSONL. This module serves it over stdlib ``http.server`` on
``obs_http_port`` (+rank, so every rank of a localhost cluster — and
every serving replica, which carries its replica index as its rank —
gets its own port from ONE flag; 0 = off):

  ``/metrics``  Prometheus text exposition (version 0.0.4) of the
                StatRegistry counters, gauges, fixed-bucket histograms
                (cumulative ``_bucket{le=...}`` series + p50/p90/p99
                gauges) and the quality plane's per-tag auc/copc/ctr
  ``/report``   latest StepReport (rank 0 adds its latest merged
                cluster report)
  ``/health``   rank-0 cluster health record with per-rank scores
                (non-zero ranks answer their own liveness)
  ``/stacks``   every thread's stack, plain text (the watchdog dump,
                on demand)
  ``/flight``   flight-recorder segment list + tail of the black box
  ``/quality``  quality + drift plane snapshot (full detail; /metrics
                carries the headline series)
  ``/device``   device-plane snapshot (round 20): per-jit compile
                counts + wall time, cost/memory analyses, donation
                audit, transfer counters, live-buffer ledger

Scrape-safety is the design contract: every handler answers from
DEFENSIVE SNAPSHOTS — the StatRegistry's snapshot_all (one short
registry lock, the same hold every StepReport assembly takes), the
reporter's deep-copied ``peek()``, the aggregator's last-merged record,
the quality plane's short internal lock — and never touches a training
lock, so a scrape storm can slow scrapes, never the step loop (the
dial-outside-lock discipline of the aggregator, applied to reads).

A port already in use WARNS AND DISABLES the endpoint (telemetry must
never kill the trainer it instruments — same degrade contract as the
flight recorder). Import surface stays jax-free.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from paddlebox_tpu.utils.lockwatch import make_lock

SCHEMA_VERSION = 1

#: Prometheus text exposition content type
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# fleet-health provider (round 21): whoever runs a MultiBoxFleet in
# this process registers a zero-arg callable returning the fleet-wide
# serving record (QPS, p50/p99); /health merges it defensively — the
# health endpoint must answer even when the fleet is mid-teardown
_fleet_health_lock = make_lock("exporter._fleet_health_lock")
_fleet_health_provider = None  # guarded-by: _fleet_health_lock


def set_fleet_health_provider(provider) -> None:
    """Register (or clear, with None) the serving-fleet health section
    of /health. One provider per process — last registration wins."""
    global _fleet_health_provider
    with _fleet_health_lock:
        _fleet_health_provider = provider


def _fleet_health_section() -> Optional[dict]:
    with _fleet_health_lock:
        provider = _fleet_health_provider
    if provider is None:
        return None
    try:
        return provider()
    except Exception as e:
        return {"type": "serving_fleet", "error": repr(e)}

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "pbtpu_" + _NAME_RE.sub("_", str(name))


def render_prometheus(snap: dict, rank: int,
                      quality: Optional[dict] = None,
                      drift: Optional[dict] = None) -> str:
    """StatRegistry snapshot_all + quality/drift snapshots → Prometheus
    text exposition. Pure function (tests pin the format)."""
    from paddlebox_tpu.utils.stats import HIST_BOUNDS, hist_percentile
    lines = []
    lines.append("# pbtpu ops exporter v%d rank=%d ts=%.3f"
                 % (SCHEMA_VERSION, rank, time.time()))
    # ONE TYPE line per metric family, ever: the quality/drift planes
    # also publish plain gauges of the same names (quality_auc,
    # data_drift_score — the health plane reads those), and a second
    # "# TYPE" for a family is a hard parse error to a real Prometheus
    # scraper, not a cosmetic dupe
    typed = set()

    def typ(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append("# TYPE %s %s" % (name, kind))

    # families the quality/drift sections below render (richer: tagged
    # series / window detail) — the plain StatRegistry gauges of the
    # same names are skipped so each family appears exactly once,
    # contiguously (interleaved families are a parse error too)
    owned = set()
    if quality:
        owned |= {"quality_auc", "quality_copc"}
    if drift and drift.get("last"):
        owned |= {"data_drift_score", "data_dropped_slots"}
    for k in sorted(snap.get("counters") or {}):
        n = _prom_name(k)
        typ(n, "counter")
        lines.append("%s %d" % (n, int(snap["counters"][k])))
    for k in sorted(snap.get("gauges") or {}):
        if k in owned:
            continue
        n = _prom_name(k)
        typ(n, "gauge")
        lines.append("%s %.9g" % (n, float(snap["gauges"][k])))
    for k in sorted(snap.get("hists") or {}):
        counts = snap["hists"][k]
        n = _prom_name(k)
        typ(n, "histogram")
        cum = 0
        for i, c in enumerate(counts):
            cum += int(c)
            le = ("+Inf" if i >= len(HIST_BOUNDS)
                  else "%g" % HIST_BOUNDS[i])
            lines.append('%s_bucket{le="%s"} %d' % (n, le, cum))
        lines.append("%s_count %d" % (n, cum))
        for q, tag in ((0.50, "p50"), (0.90, "p90"), (0.99, "p99")):
            typ("%s_%s" % (n, tag), "gauge")
            lines.append("%s_%s %.9g" % (n, tag,
                                         hist_percentile(counts, q)))
    if quality:
        for metric in ("auc", "copc", "actual_ctr", "predicted_ctr",
                       "size"):
            n = "pbtpu_quality_" + metric
            first = True
            for tag in sorted(quality.get("tags") or {}):
                v = quality["tags"][tag].get(metric)
                if v is None:
                    continue
                if first:
                    typ(n, "gauge")
                    first = False
                lines.append('%s{tag="%s"} %.9g'
                             % (n, _NAME_RE.sub("_", tag), float(v)))
        slots = quality.get("slots") or {}
        if slots:
            for metric in ("actual_ctr", "predicted_ctr", "copc", "n"):
                n = "pbtpu_slot_" + metric
                typ(n, "gauge")
                for s in sorted(slots, key=int):
                    lines.append('%s{slot="%s"} %.9g'
                                 % (n, s, float(slots[s][metric])))
    if drift and drift.get("last"):
        last = drift["last"]
        d = last.get("drift") or {}
        for name, v in (("pbtpu_data_drift_score", d.get("score")),
                        ("pbtpu_data_dropped_slots",
                         len(d.get("dropped_slots") or ())),
                        ("pbtpu_data_window_recs", last.get("n_recs")),
                        ("pbtpu_data_label_rate", last.get("label_rate"))):
            if v is None:
                continue
            typ(name, "gauge")
            lines.append("%s %.9g" % (name, float(v)))
    return "\n".join(lines) + "\n"


class ObsExporter:
    """One rank's ops endpoint. Construction BINDS the port (raises
    OSError on conflict — ensure_from_flags turns that into the
    warn-and-disable degrade); serve threads are daemons."""

    def __init__(self, port: int, rank: int = 0,
                 host: str = "127.0.0.1") -> None:
        self.rank = int(rank)
        self.host = host
        self._reporter = None  # guarded-by: _lock
        self._lock = make_lock("ObsExporter._lock")
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            # a scrape must never land access-log noise on the job's
            # stderr (and a broken scraper must never raise into it)
            def log_message(self, fmt, *args):  # noqa: D401
                pass

            def do_GET(self):  # noqa: N802 — http.server contract
                try:
                    exporter._route(self)
                except (BrokenPipeError, ConnectionResetError):
                    pass
                except Exception as e:  # noqa: BLE001 — degrade, never kill
                    try:
                        exporter._send(self, 500, "text/plain",
                                       ("exporter error: %r" % e).encode())
                    except OSError:
                        pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_port)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="pbtpu-obs-http")
        self._thread.start()

    # ------------------------------------------------------------- binding
    def bind(self, reporter=None) -> "ObsExporter":
        """Attach the live StepReporter (make_step_reporter calls this;
        the aggregator — and through it the health plane — is reached
        via reporter.aggregator)."""
        with self._lock:
            if reporter is not None:
                self._reporter = reporter
        return self

    # ------------------------------------------------------------ handlers
    @staticmethod
    def _send(handler, code: int, ctype: str, body: bytes) -> None:
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _send_json(self, handler, obj, code: int = 200) -> None:
        body = json.dumps(obj, default=repr).encode("utf-8")
        self._send(handler, code, "application/json", body)

    def _route(self, handler) -> None:
        path = handler.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            return self._metrics(handler)
        if path == "/report":
            return self._report(handler)
        if path == "/health":
            return self._health(handler)
        if path == "/stacks":
            return self._stacks(handler)
        if path == "/flight":
            return self._flight(handler)
        if path == "/quality":
            return self._quality(handler)
        if path == "/device":
            return self._device(handler)
        if path == "/":
            return self._send_json(handler, {
                "rank": self.rank, "v": SCHEMA_VERSION,
                "endpoints": ["/metrics", "/report", "/health",
                              "/stacks", "/flight", "/quality",
                              "/device"]})
        self._send_json(handler, {"error": "unknown path %s" % path},
                        code=404)

    def _metrics(self, handler) -> None:
        from paddlebox_tpu.metrics import drift as _drift
        from paddlebox_tpu.metrics import quality as _quality
        from paddlebox_tpu.utils.stats import StatRegistry
        snap = StatRegistry.instance().snapshot_all()
        q = _quality.active()
        dm = _drift.active()
        text = render_prometheus(
            snap, self.rank,
            quality=q.report() if q is not None else None,
            drift=dm.snapshot() if dm is not None else None)
        self._send(handler, 200, PROM_CONTENT_TYPE, text.encode("utf-8"))

    def _report(self, handler) -> None:
        with self._lock:
            rep = self._reporter
        out = {"rank": self.rank,
               "report": rep.peek() if rep is not None else None}
        agg = getattr(rep, "aggregator", None)
        if agg is not None and agg.last_cluster_report is not None:
            out["cluster_report"] = agg.last_cluster_report
        self._send_json(handler, out)

    def _health(self, handler) -> None:
        with self._lock:
            rep = self._reporter
        agg = getattr(rep, "aggregator", None)
        health = getattr(agg, "health", None) if agg is not None else None
        fleet = _fleet_health_section()
        if health is not None and health.last_health is not None:
            record = health.last_health
            if fleet is not None:
                record = dict(record)
                record["serving_fleet"] = fleet
            return self._send_json(handler, record)
        # non-rank-0 (or single-rank): answer own liveness so every
        # rank's endpoint is curl-able with the same verb
        last = rep.peek() if rep is not None else None
        record = {
            "type": "rank_liveness", "v": SCHEMA_VERSION,
            "rank": self.rank, "ts": time.time(),
            "last_report_step": (last or {}).get("step"),
            "last_report_ts": (last or {}).get("ts"),
            "note": "per-rank view; the merged cluster_health record "
                    "lives on rank 0's endpoint"}
        if fleet is not None:
            record["serving_fleet"] = fleet
        self._send_json(handler, record)

    def _stacks(self, handler) -> None:
        from paddlebox_tpu.obs.flight import _thread_stacks
        lines = []
        for name, stack in sorted(_thread_stacks().items()):
            lines.append("== %s ==" % name)
            lines.extend(stack)
            lines.append("")
        self._send(handler, 200, "text/plain; charset=utf-8",
                   ("\n".join(lines) + "\n").encode("utf-8"))

    def _flight(self, handler, tail_lines: int = 64) -> None:
        from paddlebox_tpu.obs import flight as _flight
        fr = _flight.active()
        if fr is None:
            return self._send_json(handler, {"active": False})
        segs = fr.segments()
        tail = []
        if segs:
            try:
                with open(segs[-1], "r", encoding="utf-8",
                          errors="replace") as fh:
                    tail = fh.readlines()[-tail_lines:]
            except OSError:
                tail = []
        self._send_json(handler, {
            "active": True, "dir": fr.dir, "rank": fr.rank,
            "segments": segs,
            "tail": [ln.rstrip("\n") for ln in tail]})

    def _device(self, handler) -> None:
        """Device-plane snapshot (round 20): per-entry-point compile
        counts/wall time, cost/memory analyses, donation audit,
        transfer counters and the last live-buffer ledger sample —
        obs/device.py's snapshot() is already a defensive copy."""
        from paddlebox_tpu.obs import device as _device
        out = _device.snapshot()
        out["rank"] = self.rank
        self._send_json(handler, out)

    def _quality(self, handler) -> None:
        from paddlebox_tpu.metrics import drift as _drift
        from paddlebox_tpu.metrics import quality as _quality
        q = _quality.active()
        dm = _drift.active()
        self._send_json(handler, {
            "rank": self.rank,
            "quality": q.report() if q is not None else None,
            "drift": dm.snapshot() if dm is not None else None})

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


# ------------------------------------------------------------- module API
_ACTIVE: Optional[ObsExporter] = None


def active() -> Optional[ObsExporter]:
    return _ACTIVE


def set_active(e: Optional[ObsExporter]) -> Optional[ObsExporter]:
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, e
    return prev


def ensure_from_flags(rank: int = 0) -> Optional[ObsExporter]:
    """Flag-configured endpoint (obs_http_port 0 = off; the bound port
    is flag + rank so one flag serves a whole localhost cluster and a
    replica fleet). Same port+rank reuses; flag 0 closes and clears
    (test self-healing, flight-recorder discipline). A port in use
    WARNS AND DISABLES — never raises into runner construction."""
    global _ACTIVE
    from paddlebox_tpu.config import flags
    base = int(flags.get_flag("obs_http_port"))
    if base <= 0:
        if _ACTIVE is not None:
            _ACTIVE.close()
            _ACTIVE = None
        return None
    port = base + int(rank)
    if (_ACTIVE is not None and _ACTIVE.port == port
            and _ACTIVE.rank == int(rank)):
        return _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.close()
        _ACTIVE = None
    try:
        exp = ObsExporter(port, rank=rank)
    except OSError as e:
        from paddlebox_tpu.obs import log as obs_log
        obs_log.warning("obs http exporter disabled: port unusable",
                        port=port, rank=rank, error=repr(e)[:200])
        return None
    _ACTIVE = exp
    return exp
