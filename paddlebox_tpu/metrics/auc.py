"""Streaming CTR metrics: bucketed AUC, WuAUC, MAE/RMSE, ctr, bucket error.

Numeric-parity re-implementation of the reference's BasicAucCalculator
(paddle/fluid/framework/fleet/metrics.{h,cc}): double-precision pos/neg bucket
tables (metrics.h:150), trapezoid accumulation from the top bucket down
(metrics.cc:273-343), bucket error with kRelativeErrorBound=0.05 /
kMaxSpan=0.01 (metrics.cc:345-380), and the user-weighted WuAUC over
(uid, pred, label) records (metrics.cc:472-556). Batch adds are vectorized
with numpy instead of the reference's per-element CUDA/CPU loops; cross-node
reduction is a pluggable allreduce callable instead of MPI/Gloo.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np
from paddlebox_tpu.utils.lockwatch import make_lock

# allreduce_fn(np.ndarray) -> np.ndarray summed across workers
AllreduceFn = Callable[[np.ndarray], np.ndarray]

_RELATIVE_ERROR_BOUND = 0.05  # kRelativeErrorBound
_MAX_SPAN = 0.01              # kMaxSpan


def trapezoid_auc(table: np.ndarray):
    """Trapezoid accumulation from the top bucket down over a [2, T]
    neg/pos bucket table (metrics.cc:273-343): returns ``(auc, fp, tp)``
    with auc = -0.5 for one-class/empty tables (the reference's
    degenerate convention). The ONE implementation shared by
    BasicAucCalculator.compute and the tagged quality plane
    (metrics/quality.py) — their bit-parity is by construction, not by
    duplicated code."""
    neg_rev = table[0][::-1]
    pos_rev = table[1][::-1]
    fp_cum = np.cumsum(neg_rev)
    tp_cum = np.cumsum(pos_rev)
    tp_prev = tp_cum - pos_rev
    area = float(np.sum(neg_rev * (tp_prev + tp_cum) / 2.0))
    fp = float(fp_cum[-1]) if fp_cum.size else 0.0
    tp = float(tp_cum[-1]) if tp_cum.size else 0.0
    if fp < 1e-3 or tp < 1e-3:
        return -0.5, fp, tp     # all nonclick or all click
    return area / (fp * tp), fp, tp


class BasicAucCalculator:
    """Bucketed streaming AUC with box semantics.

    add_* methods accept numpy arrays and are thread-safe (one lock, like the
    reference's _table_mutex). compute() optionally allreduces tables across
    workers first (metrics.cc:273-297).
    """

    def __init__(self, table_size: int = 1 << 20,
                 mode_collect_in_device: bool = False) -> None:
        """mode_collect_in_device (metrics.h:776): the trainer accumulates
        the [2, table_size] bucket table ON DEVICE inside the jitted step
        and merges it here once per pass via add_bucket_stats — no
        per-step pred D2H. Off: per-batch host adds (add_data)."""
        self._mode_collect_in_device = mode_collect_in_device
        self._lock = make_lock("BasicAucCalculator._lock")
        self._table_size = 0
        self.init(table_size)

    @property
    def mode_collect_in_device(self) -> bool:
        return self._mode_collect_in_device

    @property
    def table_size(self) -> int:
        return self._table_size

    # ------------------------------------------------------------------ init
    def init(self, table_size: int, max_batch_size: int = 0) -> None:
        self._table_size = int(table_size)
        self._max_batch_size = int(max_batch_size)
        self.reset()

    def reset(self) -> None:
        # _table[0] = negatives per bucket, _table[1] = positives per bucket
        self._table = np.zeros((2, self._table_size), dtype=np.float64)
        self._local_abserr = 0.0
        self._local_sqrerr = 0.0
        self._local_pred = 0.0
        self._local_label = 0.0
        self._local_total_num = 0.0
        self._auc = 0.0
        self._mae = 0.0
        self._rmse = 0.0
        self._actual_ctr = 0.0
        self._predicted_ctr = 0.0
        self._actual_value = 0.0
        self._predicted_value = 0.0
        self._bucket_error = 0.0
        self._size = 0.0
        self.reset_records()
        self.reset_nan_inf()

    def reset_records(self) -> None:
        # parallel chunk lists so uids stay uint64 (float64 would collide
        # 64-bit hash uids above 2**53)
        self._wuauc_uids: List[np.ndarray] = []
        self._wuauc_labels: List[np.ndarray] = []
        self._wuauc_preds: List[np.ndarray] = []
        self._user_cnt = 0.0
        self._uauc = 0.0
        self._wuauc = 0.0

    def reset_nan_inf(self) -> None:
        self._nan_cnt = 0.0
        self._inf_cnt = 0.0
        self._nan_total = 0.0
        self._nan_inf_rate = 0.0

    # ------------------------------------------------------------------- add
    def add_data(self, pred, label, mask=None, sample_scale=None) -> None:
        """Vectorized equivalent of add_(mask_|sample_)data (metrics.cc).

        pred in [0,1]; label in {0,1}; optional mask selects rows; optional
        sample_scale weights the positive-bucket increment (metrics.cc:49-63).
        """
        pred = np.asarray(pred, dtype=np.float64).reshape(-1)
        label = np.asarray(label).reshape(-1).astype(np.int64)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1).astype(bool)
            pred, label = pred[keep], label[keep]
            if sample_scale is not None:
                sample_scale = np.asarray(sample_scale).reshape(-1)[keep]
        if pred.size == 0:
            return
        if pred.min() < 0.0 or pred.max() > 1.0:
            raise ValueError("pred must lie in [0, 1]")
        if not np.all((label == 0) | (label == 1)):
            raise ValueError("label must be 0 or 1")

        pos = np.minimum((pred * self._table_size).astype(np.int64),
                         self._table_size - 1)
        with self._lock:
            if sample_scale is None:
                np.add.at(self._table[0], pos[label == 0], 1.0)
                np.add.at(self._table[1], pos[label == 1], 1.0)
            else:
                scale = np.asarray(sample_scale, dtype=np.float64).reshape(-1)
                np.add.at(self._table[0], pos[label == 0], 1.0)
                np.add.at(self._table[1], pos[label == 1], scale[label == 1])
            self._local_abserr += float(np.abs(pred - label).sum())
            self._local_sqrerr += float(((pred - label) ** 2).sum())
            self._local_pred += float(pred.sum())
            self._local_label += float(label.sum())
            self._local_total_num += float(pred.size)

    def add_float_data(self, pred, label, mask=None) -> None:
        """Continuous-label variant (add_unlock_data_with_float_label):
        only error accumulators, no AUC buckets."""
        pred = np.asarray(pred, dtype=np.float64).reshape(-1)
        label = np.asarray(label, dtype=np.float64).reshape(-1)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1).astype(bool)
            pred, label = pred[keep], label[keep]
        with self._lock:
            self._local_abserr += float(np.abs(pred - label).sum())
            self._local_sqrerr += float(((pred - label) ** 2).sum())
            self._local_pred += float(pred.sum())
            self._local_label += float(label.sum())
            self._local_total_num += float(pred.size)

    def add_bucket_stats(self, table: np.ndarray, abserr: float,
                         sqrerr: float, pred_sum: float, label_sum: float,
                         n: float) -> None:
        """Merge a device-accumulated bucket table + scalar accumulators
        (the mode_collect_in_device ingest path: the jitted step built
        table[0]=neg counts, table[1]=pos counts by bucketing preds
        on-device — metrics.h:776 / metrics.cc add-data kernels — and this
        merges ONE pass's psum'd result instead of per-step adds)."""
        table = np.asarray(table, dtype=np.float64)
        if table.shape != (2, self._table_size):
            raise ValueError(f"bucket table shape {table.shape} != "
                             f"(2, {self._table_size})")
        with self._lock:
            self._table += table
            self._local_abserr += float(abserr)
            self._local_sqrerr += float(sqrerr)
            self._local_pred += float(pred_sum)
            self._local_label += float(label_sum)
            self._local_total_num += float(n)

    def add_nan_inf_data(self, pred) -> None:
        pred = np.asarray(pred, dtype=np.float64).reshape(-1)
        with self._lock:
            self._nan_cnt += float(np.isnan(pred).sum())
            self._inf_cnt += float(np.isinf(pred).sum())
            self._nan_total += float(pred.size)

    def add_uid_data(self, pred, label, uid, mask=None) -> None:
        pred = np.asarray(pred, dtype=np.float64).reshape(-1)
        label = np.asarray(label).reshape(-1).astype(np.int64)
        uid = np.asarray(uid).reshape(-1).astype(np.uint64)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1).astype(bool)
            pred, label, uid = pred[keep], label[keep], uid[keep]
        self.add_data(pred, label)
        with self._lock:
            self._wuauc_uids.append(uid)
            self._wuauc_labels.append(label)
            self._wuauc_preds.append(pred)

    # --------------------------------------------------------------- compute
    def compute(self, allreduce: Optional[AllreduceFn] = None) -> None:
        """metrics.cc:273-343 with pluggable cross-worker reduction.

        Snapshot under the lock, reduce + run the trapezoid OUTSIDE it,
        write results back under the lock: ``allreduce`` is a cross-worker
        collective (seconds under skew) and the bucket math is O(table) —
        holding ``_lock`` across either stalls every concurrent
        ``add_data`` on the training path (the round-18 quality-plane
        hand-review finding; boxlint BX601 pins the class now)."""
        with self._lock:
            table = self._table.copy()
            local = np.array(
                [self._local_abserr, self._local_sqrerr, self._local_pred],
                dtype=np.float64)
        if allreduce is not None:
            table = allreduce(table)
            local = allreduce(local)

        # trapezoid from the top bucket down (shared helper)
        auc, fp, tp = trapezoid_auc(table)
        bucket_error = self._calculate_bucket_error(table[0], table[1])
        total = fp + tp
        with self._lock:
            self._auc = auc
            if total > 0:
                self._mae = float(local[0]) / total
                self._rmse = math.sqrt(float(local[1]) / total)
                self._predicted_ctr = float(local[2]) / total
                self._actual_ctr = tp / total
            self._size = total
            self._bucket_error = bucket_error

    def _calculate_bucket_error(self, neg_table: np.ndarray,
                                pos_table: np.ndarray) -> float:
        """metrics.cc:345-380, sequential by construction (windowed scan).

        Sparse walk: only non-empty buckets change the sums; empty buckets
        matter solely through the span-reset cascade on ``last_ctr``, which we
        advance arithmetically between non-empty buckets. Matches the dense
        scan exactly (see _calculate_bucket_error_dense + parity test).
        """
        n = self._table_size
        nz = np.nonzero((neg_table != 0) | (pos_table != 0))[0]
        if nz.size == 0:
            return 0.0
        last_ctr = -1.0
        impression_sum = 0.0
        ctr_sum = 0.0
        click_sum = 0.0
        error_sum = 0.0
        error_count = 0.0
        prev = -1  # previous processed bucket index
        for i in nz.tolist():
            # replay the span-reset cascade over the empty run (prev, i):
            # an empty bucket j resets sums iff ctr_j - last_ctr > span.
            j = prev + 1
            while j < i:
                # smallest j' >= j with j'/n - last_ctr > span
                cand = int((last_ctr + _MAX_SPAN) * n)
                cand = max(cand, j)
                while cand < i and cand / n - last_ctr <= _MAX_SPAN:
                    cand += 1
                if cand >= i:
                    break
                last_ctr = cand / n
                impression_sum = ctr_sum = click_sum = 0.0
                j = cand + 1
            click = float(pos_table[i])
            show = float(neg_table[i] + pos_table[i])
            ctr = i / n
            if abs(ctr - last_ctr) > _MAX_SPAN:
                last_ctr = ctr
                impression_sum = 0.0
                ctr_sum = 0.0
                click_sum = 0.0
            impression_sum += show
            ctr_sum += ctr * show
            click_sum += click
            prev = i
            if impression_sum <= 0:
                continue
            adjust_ctr = ctr_sum / impression_sum
            if adjust_ctr <= 0:
                continue
            relative_error = math.sqrt((1 - adjust_ctr) / (adjust_ctr * impression_sum))
            if relative_error < _RELATIVE_ERROR_BOUND:
                actual_ctr = click_sum / impression_sum
                relative_ctr_error = abs(actual_ctr / adjust_ctr - 1)
                error_sum += relative_ctr_error * impression_sum
                error_count += impression_sum
                last_ctr = -1.0
        return error_sum / error_count if error_count > 0 else 0.0

    def _calculate_bucket_error_dense(self, neg_table: np.ndarray,
                                      pos_table: np.ndarray) -> float:
        """Literal transcription of metrics.cc:345-380 (oracle for tests)."""
        last_ctr = -1.0
        impression_sum = 0.0
        ctr_sum = 0.0
        click_sum = 0.0
        error_sum = 0.0
        error_count = 0.0
        n = self._table_size
        for i in range(n):
            click = float(pos_table[i])
            show = float(neg_table[i] + pos_table[i])
            ctr = i / n
            if abs(ctr - last_ctr) > _MAX_SPAN:
                last_ctr = ctr
                impression_sum = 0.0
                ctr_sum = 0.0
                click_sum = 0.0
            impression_sum += show
            ctr_sum += ctr * show
            click_sum += click
            if impression_sum <= 0:
                continue
            adjust_ctr = ctr_sum / impression_sum
            if adjust_ctr <= 0:
                continue
            relative_error = math.sqrt((1 - adjust_ctr) / (adjust_ctr * impression_sum))
            if relative_error < _RELATIVE_ERROR_BOUND:
                actual_ctr = click_sum / impression_sum
                relative_ctr_error = abs(actual_ctr / adjust_ctr - 1)
                error_sum += relative_ctr_error * impression_sum
                error_count += impression_sum
                last_ctr = -1.0
        return error_sum / error_count if error_count > 0 else 0.0

    def compute_wuauc(self) -> None:
        """metrics.cc:472-556: per-user AUC, mean (uauc) and ins-weighted (wuauc)."""
        with self._lock:
            if not self._wuauc_uids:
                return
            uids = np.concatenate(self._wuauc_uids)          # uint64, lossless
            labels = np.concatenate(self._wuauc_labels).astype(np.int64)
            preds = np.concatenate(self._wuauc_preds).astype(np.float64)
            # sort: uid desc, pred desc, label asc (metrics.cc:473-485);
            # np.lexsort keys are last-key-primary and ascending, so negate
            # pred and flip the uid sort by sorting ascending then reversing
            # per-uid is wrong — instead sort (uid asc, pred desc, label asc)
            # and rely on grouping (group order doesn't affect the sums).
            order = np.lexsort((labels, -preds, uids))
            uids, labels, preds = uids[order], labels[order], preds[order]
            self._user_cnt = 0.0
            self._uauc = 0.0
            self._wuauc = 0.0
            self._size = 0.0
            boundaries = np.nonzero(np.diff(uids))[0] + 1
            for lab, prd in zip(np.split(labels, boundaries),
                                np.split(preds, boundaries)):
                tp, fp, auc = self._single_user_auc(lab, prd)
                if auc != -1:
                    ins_num = tp + fp
                    self._user_cnt += 1
                    self._size += ins_num
                    self._uauc += auc
                    self._wuauc += auc * ins_num
            if self._user_cnt > 0:
                self._uauc /= self._user_cnt
            if self._size > 0:
                self._wuauc /= self._size

    @staticmethod
    def _single_user_auc(labels: np.ndarray, preds: np.ndarray):
        """metrics.cc:520-556 — ties grouped by equal pred."""
        change = np.nonzero(np.diff(preds))[0] + 1
        tp = fp = 0.0
        area = 0.0
        for grp_lab in np.split(labels, change):
            newtp = tp + float((grp_lab == 1).sum())
            newfp = fp + float((grp_lab != 1).sum())
            area += (newfp - fp) * (tp + newtp) / 2.0
            tp, fp = newtp, newfp
        if tp > 0 and fp > 0:
            return tp, fp, area / (fp * tp + 1e-9)
        return tp, fp, -1

    def compute_nan_inf(self, allreduce: Optional[AllreduceFn] = None) -> None:
        """computeNanInfMsg (metrics.cc:621+). Same snapshot / reduce-
        outside / write-back discipline as compute(): the collective must
        not run under the add-path lock."""
        with self._lock:
            v = np.array([self._nan_cnt, self._inf_cnt, self._nan_total],
                         np.float64)
        if allreduce is not None:
            v = allreduce(v)
        nan_cnt, inf_cnt, total = float(v[0]), float(v[1]), float(v[2])
        with self._lock:
            self._nan_inf_rate = (nan_cnt + inf_cnt) / total if total else 0.0

    def compute_continue_msg(self, allreduce: Optional[AllreduceFn] = None) -> None:
        """computeContinueMsg (metrics.cc:569+): continuous-label error stats
        normalized by the record count instead of AUC-table mass."""
        with self._lock:
            v = np.array([self._local_abserr, self._local_sqrerr,
                          self._local_pred, self._local_label,
                          self._local_total_num], np.float64)
        if allreduce is not None:
            v = allreduce(v)  # collective outside the add-path lock
        total = float(v[4])
        with self._lock:
            if total > 0:
                self._mae = float(v[0]) / total
                self._rmse = math.sqrt(float(v[1]) / total)
                self._predicted_value = float(v[2]) / total
                self._actual_value = float(v[3]) / total
            self._size = total

    # ------------------------------------------------------------- accessors
    @property
    def table_size(self) -> int:
        return self._table_size

    def auc(self) -> float:
        return self._auc

    def mae(self) -> float:
        return self._mae

    def rmse(self) -> float:
        return self._rmse

    def actual_ctr(self) -> float:
        return self._actual_ctr

    def predicted_ctr(self) -> float:
        return self._predicted_ctr

    def bucket_error(self) -> float:
        return self._bucket_error

    def size(self) -> float:
        return self._size

    def uauc(self) -> float:
        return self._uauc

    def wuauc(self) -> float:
        return self._wuauc

    def user_cnt(self) -> float:
        return self._user_cnt

    def nan_inf_rate(self) -> float:
        return self._nan_inf_rate

    def actual_value(self) -> float:
        return self._actual_value

    def predicted_value(self) -> float:
        return self._predicted_value


class MetricMsg:
    """One named metric bound to (label, pred[, mask, uid]) tensor names and a
    training phase — analog of Metric::MetricMsg (metrics.h:327-568)."""

    def __init__(self, label_var: str, pred_var: str, name: str,
                 metric_phase: int = -1, table_size: int = 1 << 20,
                 mask_var: str = "", uid_var: str = "",
                 sample_scale_var: str = "", kind: str = "auc",
                 mode_collect_in_device: bool = False) -> None:
        self.name = name
        self.label_var = label_var
        self.pred_var = pred_var
        self.mask_var = mask_var
        self.uid_var = uid_var
        self.sample_scale_var = sample_scale_var
        self.metric_phase = metric_phase
        self.kind = kind
        self.calculator = BasicAucCalculator(table_size,
                                             mode_collect_in_device)

    def add_from(self, tensors: Dict[str, np.ndarray]) -> None:
        pred = tensors[self.pred_var]
        label = tensors[self.label_var]
        mask = tensors.get(self.mask_var) if self.mask_var else None
        if self.kind == "wuauc" and self.uid_var:
            self.calculator.add_uid_data(pred, label, tensors[self.uid_var], mask)
        elif self.kind == "nan_inf":
            self.calculator.add_nan_inf_data(pred)
        elif self.kind == "continue":
            self.calculator.add_float_data(pred, label, mask)
        elif self.sample_scale_var:
            self.calculator.add_data(pred, label, mask,
                                     tensors.get(self.sample_scale_var))
        else:
            self.calculator.add_data(pred, label, mask)

    def get_msg(self, allreduce: Optional[AllreduceFn] = None) -> Dict[str, float]:
        """AUC/MAE/RMSE/ctrs bundle, like get_metric_msg (box_helper_py.cc:115)."""
        c = self.calculator
        if self.kind == "wuauc":
            c.compute_wuauc()
            return {"user_cnt": c.user_cnt(), "size": c.size(),
                    "uauc": c.uauc(), "wuauc": c.wuauc()}
        if self.kind == "nan_inf":
            c.compute_nan_inf(allreduce)
            return {"nan_inf_rate": c.nan_inf_rate()}
        if self.kind == "continue":
            c.compute_continue_msg(allreduce)
            return {"mae": c.mae(), "rmse": c.rmse(), "size": c.size(),
                    "actual_value": c.actual_value(),
                    "predicted_value": c.predicted_value()}
        c.compute(allreduce)
        return {
            "auc": c.auc(), "bucket_error": c.bucket_error(), "mae": c.mae(),
            "rmse": c.rmse(), "actual_ctr": c.actual_ctr(),
            "predicted_ctr": c.predicted_ctr(), "size": c.size(),
        }


def parse_cmatch_rank(x: np.ndarray):
    """Decode the packed cmatch_rank var: high 32 bits = cmatch, low 8 =
    rank (metrics.h:271-279; the encode side is the packer's
    (cmatch<<32)|(rank&0xff))."""
    x = np.asarray(x, np.uint64)
    return ((x >> np.uint64(32)).astype(np.int64),
            (x & np.uint64(0xFF)).astype(np.int64))


def _parse_group(cmatch_rank_group: str, ignore_rank: bool):
    """'222_1,223_2' → [(222,1),(223,2)]; with ignore_rank, bare cmatch
    entries '222,223' are accepted (CmatchRankMetricMsg ctor,
    metrics.h:413-443). Comma or space separated."""
    pairs = []
    for tok in cmatch_rank_group.replace(",", " ").split():
        if ignore_rank and "_" not in tok:
            pairs.append((int(tok), 0))
            continue
        parts = tok.split("_")
        if len(parts) != 2:
            raise ValueError(f"illegal cmatch_rank spec: {tok!r}")
        pairs.append((int(parts[0]), int(parts[1])))
    return pairs


class CmatchRankMetricMsg(MetricMsg):
    """AUC over the instances whose (cmatch, rank) matches the configured
    group — CmatchRankMetricMsg / CmatchRankMaskMetricMsg
    (metrics.h:413-491,534-…); ignore_rank compares cmatch only
    (CmatchAUC)."""

    def __init__(self, label_var: str, pred_var: str, name: str,
                 cmatch_rank_group: str, cmatch_rank_var: str = "cmatch_rank",
                 ignore_rank: bool = False, metric_phase: int = -1,
                 table_size: int = 1 << 20, mask_var: str = "") -> None:
        super().__init__(label_var, pred_var, name, metric_phase,
                         table_size, mask_var=mask_var)
        self.cmatch_rank_var = cmatch_rank_var
        self.ignore_rank = ignore_rank
        self.pairs = _parse_group(cmatch_rank_group, ignore_rank)

    def _match_mask(self, tensors: Dict[str, np.ndarray]) -> np.ndarray:
        cmatch, rank = parse_cmatch_rank(tensors[self.cmatch_rank_var])
        sel = np.zeros(cmatch.shape, bool)
        for cm, rk in self.pairs:
            if self.ignore_rank:
                sel |= cmatch == cm
            else:
                sel |= (cmatch == cm) & (rank == rk)
        return sel

    def add_from(self, tensors: Dict[str, np.ndarray]) -> None:
        sel = self._match_mask(tensors)
        if self.mask_var:
            sel = sel & (np.asarray(tensors[self.mask_var]) != 0)
        if not sel.any():
            return
        self.calculator.add_data(np.asarray(tensors[self.pred_var])[sel],
                                 np.asarray(tensors[self.label_var])[sel])


class MultiTaskMetricMsg(MetricMsg):
    """One AUC fed from a DIFFERENT pred var per matched (cmatch, rank)
    pair (MultiTaskMetricMsg, metrics.h:327-410): instance i matching
    pairs[j] contributes pred_list[j][i]."""

    def __init__(self, label_var: str, pred_var_list, name: str,
                 cmatch_rank_group: str, cmatch_rank_var: str = "cmatch_rank",
                 metric_phase: int = -1, table_size: int = 1 << 20,
                 mask_var: str = "") -> None:
        preds = (pred_var_list.split() if isinstance(pred_var_list, str)
                 else list(pred_var_list))
        super().__init__(label_var, preds[0], name, metric_phase,
                         table_size, mask_var=mask_var)
        self.pred_list = preds
        self.cmatch_rank_var = cmatch_rank_var
        self.pairs = _parse_group(cmatch_rank_group, ignore_rank=False)
        if len(self.pairs) != len(self.pred_list):
            raise ValueError(
                "cmatch_rank group size %d != pred list size %d"
                % (len(self.pairs), len(self.pred_list)))

    def add_from(self, tensors: Dict[str, np.ndarray]) -> None:
        cmatch, rank = parse_cmatch_rank(tensors[self.cmatch_rank_var])
        label = np.asarray(tensors[self.label_var])
        base = (np.asarray(tensors[self.mask_var]) != 0 if self.mask_var
                else np.ones(label.shape, bool))
        taken = np.zeros(label.shape, bool)  # first matching pair wins
        for (cm, rk), pv in zip(self.pairs, self.pred_list):
            sel = (cmatch == cm) & (rank == rk) & base & ~taken
            taken |= sel
            if sel.any():
                self.calculator.add_data(np.asarray(tensors[pv])[sel],
                                         label[sel])


class MetricRegistry:
    """Name → MetricMsg with phase filtering; analog of the metric registry in
    BoxWrapper (box_wrapper.h:758-781) with phase filter (join/update)."""

    def __init__(self) -> None:
        self._metrics: Dict[str, MetricMsg] = {}
        self.phase = -1  # -1 = all phases

    def init_metric(self, name: str, label_var: str, pred_var: str,
                    metric_phase: int = -1, table_size: int = 1 << 20,
                    **kwargs) -> MetricMsg:
        msg = MetricMsg(label_var, pred_var, name, metric_phase, table_size,
                        **kwargs)
        self._metrics[name] = msg
        return msg

    def init_cmatch_rank_metric(self, name: str, label_var: str,
                                pred_var: str, cmatch_rank_group: str,
                                cmatch_rank_var: str = "cmatch_rank",
                                ignore_rank: bool = False,
                                metric_phase: int = -1,
                                table_size: int = 1 << 20,
                                mask_var: str = "") -> MetricMsg:
        """CmatchRank / CmatchRankMask AUC (metrics.h:413-491,534-…)."""
        msg = CmatchRankMetricMsg(
            label_var, pred_var, name, cmatch_rank_group, cmatch_rank_var,
            ignore_rank, metric_phase, table_size, mask_var)
        self._metrics[name] = msg
        return msg

    def init_multi_task_metric(self, name: str, label_var: str,
                               pred_var_list, cmatch_rank_group: str,
                               cmatch_rank_var: str = "cmatch_rank",
                               metric_phase: int = -1,
                               table_size: int = 1 << 20,
                               mask_var: str = "") -> MetricMsg:
        """Per-pair pred selection AUC (MultiTaskMetricMsg,
        metrics.h:327-410)."""
        msg = MultiTaskMetricMsg(
            label_var, pred_var_list, name, cmatch_rank_group,
            cmatch_rank_var, metric_phase, table_size, mask_var)
        self._metrics[name] = msg
        return msg

    def metric_names(self) -> List[str]:
        return list(self._metrics)

    def messages(self) -> List["MetricMsg"]:
        """All registered MetricMsg objects (public iteration surface)."""
        return list(self._metrics.values())

    def get(self, name: str) -> MetricMsg:
        return self._metrics[name]

    def add_batch(self, tensors: Dict[str, np.ndarray]) -> None:
        for m in self._metrics.values():
            if m.metric_phase in (-1, self.phase) or self.phase == -1:
                m.add_from(tensors)

    def get_metric_msg(self, name: str,
                       allreduce: Optional[AllreduceFn] = None) -> Dict[str, float]:
        return self._metrics[name].get_msg(allreduce)

    def flip_phase(self) -> None:
        self.phase = 1 - self.phase if self.phase in (0, 1) else self.phase
