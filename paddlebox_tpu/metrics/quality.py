"""Tagged quality metrics: per-tag masked AUC, COPC, actual/predicted CTR.

MetricMsg-parity port of the reference's tagged multi-task metric family
(paddle/fluid/framework/fleet/metrics.{h,cc}: CmatchRank/MultiTask
MetricMsg + the COPC and ctr fields of get_metric_msg): every tag owns a
``[2, table_size]`` float64 pos/neg bucket table — EXACTLY the
BasicAucCalculator layout, with the same bucketing arithmetic
(``min(int(pred*T), T-1)``, metrics.cc add-data kernels) — plus the five
scalar accumulators (abserr, sqrerr, pred_sum, click_sum, n). Everything
is SUM-MERGEABLE: two ranks' states merge by elementwise addition, which
is how the cluster plane composes a fleet-wide quality report for free
(obs/aggregate.py merges ``quality_state`` extras shipped at pass_end
through the existing piggyback transport; the same table sum the
reference runs as an MPI allreduce in Metric::calculate).

The metrics this plane computes per tag (and per slot, fed from the
batch's slot columns):

  * auc            — trapezoid over the bucket table, BasicAucCalculator
                     parity (degenerate one-class windows read -0.5,
                     metrics.cc:273-343's convention)
  * copc           — Click Over Predicted Click = sum(label)/sum(pred),
                     THE production calibration alarm (a healthy
                     calibrated CTR model holds copc ~ 1.0; a blown-up
                     tower or broken feature drives it off fast)
  * actual_ctr / predicted_ctr, mae, rmse, size — the get_metric_msg
                     bundle

Import surface is numpy+stdlib only (the obs exporter serves these from
jax-free processes).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np
from paddlebox_tpu.utils.lockwatch import make_lock

STATE_VERSION = 1

#: tag used for the unmasked all-instances stream
ALL_TAG = "all"


def table_auc(table: np.ndarray) -> float:
    """Trapezoid AUC from a [2, T] pos/neg bucket table — delegates to
    THE trapezoid (metrics/auc.trapezoid_auc, the exact float64 op
    sequence of BasicAucCalculator.compute), so the tagged plane is
    bit-identical to the untagged one by construction. Returns -0.5 for
    one-class/empty tables."""
    from paddlebox_tpu.metrics.auc import trapezoid_auc
    return trapezoid_auc(np.asarray(table, np.float64))[0]


class TaggedQuality:
    """The tagged quality plane of one rank.

    Thread contract: add_* / report / state are lock-serialized (the
    trainer driver feeds adds; the HTTP exporter may call report() from
    a handler thread — readers hold the lock only for snapshot COPIES
    and run the AUC math outside it, so a scrape storm can never stall
    the add path, and nothing here touches any training lock).
    """

    #: scalar accumulator layout per tag
    _S_ABSERR, _S_SQRERR, _S_PRED, _S_CLICK, _S_N = range(5)

    def __init__(self, table_size: Optional[int] = None) -> None:
        if table_size is None:
            from paddlebox_tpu.config import flags
            table_size = int(flags.get_flag("quality_table_size"))
        self.table_size = int(table_size)
        self._lock = make_lock("TaggedQuality._lock")
        self._tables: Dict[str, np.ndarray] = {}    # guarded-by: _lock
        self._scalars: Dict[str, np.ndarray] = {}   # guarded-by: _lock
        # per-slot ctr accumulators, grown on demand: [n_slots] each
        self._slot_click = np.zeros(0, np.float64)  # guarded-by: _lock
        self._slot_pred = np.zeros(0, np.float64)   # guarded-by: _lock
        self._slot_n = np.zeros(0, np.float64)      # guarded-by: _lock

    # ------------------------------------------------------------ helpers
    def _tag_state_locked(self, tag: str):  # boxlint: disable=BX401 — caller holds _lock (the *_locked contract)
        tab = self._tables.get(tag)
        if tab is None:
            tab = np.zeros((2, self.table_size), np.float64)
            self._tables[tag] = tab
            self._scalars[tag] = np.zeros(5, np.float64)
        return tab, self._scalars[tag]

    def _grow_slots_locked(self, n: int) -> None:  # boxlint: disable=BX401 — caller holds _lock (the *_locked contract)
        if n <= self._slot_n.size:
            return
        for name in ("_slot_click", "_slot_pred", "_slot_n"):
            old = getattr(self, name)
            new = np.zeros(n, np.float64)
            new[:old.size] = old
            setattr(self, name, new)

    # ---------------------------------------------------------------- add
    def add(self, pred, label, tag: str = ALL_TAG, mask=None) -> None:
        """Masked streaming add into one tag's table (the CmatchRankMask
        add_from role). pred in [0,1], label in {0,1}."""
        pred = np.asarray(pred, np.float64).reshape(-1)
        label = np.asarray(label).reshape(-1).astype(np.int64)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1).astype(bool)
            pred, label = pred[keep], label[keep]
        binary = (label == 0) | (label == 1)
        if not binary.all():
            # non-binary rows (absent multi-task labels, padding codes)
            # are structurally not CTR instances: drop them COUNTED, so
            # tables and scalar accumulators stay consistent
            from paddlebox_tpu.utils.stats import stat_add
            stat_add("quality_rows_nonbinary_label",
                     int((~binary).sum()))
            pred, label = pred[binary], label[binary]
        finite = np.isfinite(pred)
        if not finite.all():
            # NaN/Inf preds (a diverged model — EXACTLY when this plane
            # must keep reporting): NaN passes a <0/>1 range check and
            # its int cast is INT64_MIN, which would IndexError the
            # bucket add and kill the step — drop COUNTED instead (the
            # check_nan_inf flag owns loud divergence handling)
            from paddlebox_tpu.utils.stats import stat_add
            stat_add("quality_rows_nonfinite_pred",
                     int((~finite).sum()))
            pred, label = pred[finite], label[finite]
        if pred.size == 0:
            return
        if pred.min() < 0.0 or pred.max() > 1.0:
            raise ValueError("pred must lie in [0, 1]")
        pos = np.minimum((pred * self.table_size).astype(np.int64),
                         self.table_size - 1)
        neg_at = pos[label == 0]
        pos_at = pos[label == 1]
        s_abs = float(np.abs(pred - label).sum())
        s_sqr = float(((pred - label) ** 2).sum())
        s_pred = float(pred.sum())
        s_click = float(label.sum())
        with self._lock:
            tab, sc = self._tag_state_locked(tag)
            np.add.at(tab[0], neg_at, 1.0)
            np.add.at(tab[1], pos_at, 1.0)
            sc += (s_abs, s_sqr, s_pred, s_click, float(pred.size))

    def add_tagged(self, pred, label, tags, prefix: str = "",
                   mask=None) -> None:
        """One add call for an int tag column (cmatch ids, task ids):
        instances group by their tag value into per-tag tables named
        ``<prefix><tag>``. Zero tags are skipped when a prefix is set —
        the packer's cmatch_rank default is all-zeros, which would mint
        a meaningless 'cmatch:0' stream on every untagged job."""
        tags = np.asarray(tags).reshape(-1).astype(np.int64)
        pred = np.asarray(pred, np.float64).reshape(-1)
        label = np.asarray(label).reshape(-1).astype(np.int64)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1).astype(bool)
            tags, pred, label = tags[keep], pred[keep], label[keep]
        for t in np.unique(tags):
            if prefix and t == 0:
                continue
            sel = tags == t
            self.add(pred[sel], label[sel], tag="%s%d" % (prefix, int(t)))

    def add_slot_batch(self, pred, label, slots, segments, valid,
                       num_slots: int) -> None:
        """Per-slot actual/predicted CTR from ONE packed batch's key
        columns: an instance contributes its (pred, label) once to every
        DISTINCT slot it carries a key in. Vectorized — one np.unique
        over (record, slot) pairs (segments already encode rec*S+slot)."""
        valid = np.asarray(valid).reshape(-1).astype(bool)
        seg = np.asarray(segments).reshape(-1)[valid]
        if seg.size == 0:
            return
        pairs = np.unique(seg.astype(np.int64))
        rec = pairs // num_slots
        slot = pairs % num_slots
        pred = np.asarray(pred, np.float64).reshape(-1)[rec]
        label = np.asarray(label, np.float64).reshape(-1)[rec]
        with self._lock:
            self._grow_slots_locked(num_slots)
            np.add.at(self._slot_click, slot, label)
            np.add.at(self._slot_pred, slot, pred)
            np.add.at(self._slot_n, slot, 1.0)

    def add_bucket_table(self, table, abserr: float, sqrerr: float,
                         pred_sum: float, click_sum: float, n: float,
                         tag: str = ALL_TAG) -> None:
        """Merge a device-accumulated [2, Td] bucket table (the sharded
        runner's mode_collect_in_device pass product). A finer device
        table folds down by summing Td/T-wide bucket groups — the same
        counts at coarser pred resolution."""
        table = np.asarray(table, np.float64)
        td = table.shape[1]
        if td != self.table_size:
            if td % self.table_size:
                raise ValueError(
                    "device table size %d does not fold into quality "
                    "table size %d" % (td, self.table_size))
            table = table.reshape(2, self.table_size,
                                  td // self.table_size).sum(axis=2)
        with self._lock:
            tab, sc = self._tag_state_locked(tag)
            tab += table
            sc += (float(abserr), float(sqrerr), float(pred_sum),
                   float(click_sum), float(n))

    def add_batch(self, tensors: Dict[str, np.ndarray]) -> None:
        """MetricMsg-parity feed from the trainers' tensors dict (the
        _add_metrics shape): the unmasked 'all' stream, per-cmatch tags
        from the packed cmatch_rank high bits, and one 'task:<name>'
        stream per multi-task head.

        Degrade contract: the plane is on by default in every trainer,
        so a head whose output is not a probability (or a non-binary
        label column) must SKIP with one warning + a counted stat, not
        kill the training step (explicit add() calls keep the loud
        ValueError)."""
        pred = tensors.get("pred")
        label = tensors.get("label")
        if pred is None or label is None:
            return
        mask = tensors.get("mask")
        try:
            self.add(pred, label, tag=ALL_TAG, mask=mask)
            cm = tensors.get("cmatch_rank")
            if cm is not None:
                cmatch = (np.asarray(cm, np.uint64)
                          >> np.uint64(32)).astype(np.int64)
                if (cmatch != 0).any():
                    self.add_tagged(pred, label, cmatch, prefix="cmatch:",
                                    mask=mask)
            for k in tensors:
                if not k.startswith("pred_"):
                    continue
                task = k[len("pred_"):]
                tl = tensors.get("label_" + task)
                if tl is not None:
                    self.add(tensors[k], tl, tag="task:" + task, mask=mask)
        except ValueError as e:
            from paddlebox_tpu.utils.stats import stat_add
            if stat_add("quality_batch_skipped") == 1:
                from paddlebox_tpu.obs import log as obs_log
                obs_log.warning(
                    "quality plane skipping non-CTR-shaped batches",
                    error=repr(e)[:200])

    # ------------------------------------------------------------ compute
    def _compute(self, tab: np.ndarray, sc: np.ndarray) -> dict:
        """Pure function of one tag's (table, scalars) — callers pass
        snapshots, so no lock is needed here."""
        n = float(sc[self._S_N])
        click = float(sc[self._S_CLICK])
        pred_sum = float(sc[self._S_PRED])
        out = {
            "auc": round(table_auc(tab), 6),
            "size": n,
            "actual_ctr": round(click / n, 6) if n else 0.0,
            "predicted_ctr": round(pred_sum / n, 6) if n else 0.0,
            # COPC: click over predicted click — calibration in one
            # number (1.0 = calibrated; the health plane alarms on a
            # sustained departure)
            "copc": round(click / pred_sum, 6) if pred_sum > 0 else 0.0,
            "mae": round(float(sc[self._S_ABSERR]) / n, 6) if n else 0.0,
            "rmse": round(math.sqrt(float(sc[self._S_SQRERR]) / n), 6)
            if n else 0.0,
        }
        return out

    def compute(self, tag: str = ALL_TAG) -> dict:
        """One tag's quality bundle; an unseen tag reads as the empty
        stream (size 0, auc -0.5). Snapshot under the lock, math
        outside it (see report)."""
        with self._lock:
            tab = self._tables.get(tag)
            if tab is None:
                tab = np.zeros((2, self.table_size), np.float64)
                sc = np.zeros(5, np.float64)
            else:
                tab = tab.copy()
                sc = self._scalars[tag].copy()
        return self._compute(tab, sc)

    def report(self, max_slots: int = 64) -> dict:
        """{tag: metrics} for every fed tag plus a 'slots' section of
        per-slot actual/predicted CTR + copc (slots capped, dominant
        first by instance count, so the pass_end extra stays bounded).

        Lock discipline: the lock holds only for SNAPSHOT COPIES (a few
        array memcpys); the per-tag trapezoid AUCs compute OUTSIDE it —
        the HTTP exporter calls this from scrape threads, and a scrape
        storm computing cumsums under the add path's lock would stall
        the training step (the exact coupling the exporter forbids)."""
        with self._lock:
            snap = {t: (self._tables[t].copy(), self._scalars[t].copy())
                    for t in self._tables}
            slot_click = self._slot_click.copy()
            slot_pred = self._slot_pred.copy()
            slot_n = self._slot_n.copy()
        tags = {t: self._compute(tab, sc)
                for t, (tab, sc) in sorted(snap.items())}
        slots = {}
        order = np.argsort(-slot_n)[:max_slots]
        for s in order.tolist():
            cnt = float(slot_n[s])
            if cnt <= 0:
                continue
            pred_sum = float(slot_pred[s])
            click = float(slot_click[s])
            slots[str(s)] = {
                "n": cnt,
                "actual_ctr": round(click / cnt, 6),
                "predicted_ctr": round(pred_sum / cnt, 6),
                "copc": round(click / pred_sum, 6)
                if pred_sum > 0 else 0.0,
            }
        out = {"tags": tags}
        if slots:
            out["slots"] = slots
        return out

    def publish_gauges(self) -> None:
        """Headline gauges for the report/health plane: the 'all'
        stream's auc + copc ride every StepReport window (the cluster
        HealthMonitor alarms on a copc outside its calibration band)."""
        from paddlebox_tpu.utils.stats import gauge_set
        m = self.compute(ALL_TAG)
        if m["size"] > 0:
            gauge_set("quality_auc", m["auc"])
            gauge_set("quality_copc", m["copc"])

    # ------------------------------------------------------- state / merge
    def state(self) -> dict:
        """Sum-mergeable JSON-safe snapshot: SPARSE bucket tables (most
        of a window's buckets are empty — nz rows of [idx, neg, pos])
        plus the scalar vector per tag, plus the slot accumulators."""
        with self._lock:
            tags = {}
            for t, tab in self._tables.items():
                nz = np.nonzero((tab[0] != 0) | (tab[1] != 0))[0]
                tags[t] = {
                    "nz": [[int(i), float(tab[0][i]), float(tab[1][i])]
                           for i in nz.tolist()],
                    "s": [float(x) for x in self._scalars[t]],
                }
            return {"v": STATE_VERSION, "table_size": self.table_size,
                    "tags": tags,
                    "slots": [self._slot_click.tolist(),
                              self._slot_pred.tolist(),
                              self._slot_n.tolist()]}

    def merge_state(self, state: dict) -> None:
        """Elementwise-add a peer rank's state() into this plane (the
        allreduce-sum role of Metric::calculate, minus the MPI)."""
        if int(state.get("table_size", self.table_size)) != self.table_size:
            raise ValueError("cannot merge quality states of different "
                             "table sizes (%s vs %d)"
                             % (state.get("table_size"), self.table_size))
        with self._lock:
            for t, st in (state.get("tags") or {}).items():
                tab, sc = self._tag_state_locked(t)
                for i, neg, pos in st.get("nz", ()):
                    tab[0][int(i)] += float(neg)
                    tab[1][int(i)] += float(pos)
                sc += np.asarray(st.get("s", [0.0] * 5), np.float64)
            slots = state.get("slots")
            if slots:
                click, pred, cnt = (np.asarray(a, np.float64)
                                    for a in slots)
                self._grow_slots_locked(click.size)
                self._slot_click[:click.size] += click
                self._slot_pred[:pred.size] += pred
                self._slot_n[:cnt.size] += cnt

    def reset(self) -> None:
        with self._lock:
            self._tables.clear()
            self._scalars.clear()
            self._slot_click = np.zeros(0, np.float64)
            self._slot_pred = np.zeros(0, np.float64)
            self._slot_n = np.zeros(0, np.float64)


def merged_report(states: Sequence[dict],
                  max_slots: int = 64) -> Optional[dict]:
    """The rank-0 merge: sum N ranks' quality states and compute the
    cluster-wide report (obs/aggregate.py calls this on the
    ``quality_state`` extras that arrive piggybacked at pass_end).
    Returns None when no state merges (mismatched sizes, empty input)."""
    merged: Optional[TaggedQuality] = None
    for st in states:
        if not st:
            continue
        try:
            if merged is None:
                merged = TaggedQuality(
                    table_size=int(st.get("table_size", 0)) or 1)
            merged.merge_state(st)
        except (ValueError, TypeError, KeyError):
            continue        # a malformed/mismatched peer degrades, never kills
    return merged.report(max_slots=max_slots) if merged is not None else None


# ------------------------------------------------------------- module API
# The ops exporter serves the LIVE trainer's quality plane without a
# binding dance: the owning runner registers its instance here (last
# writer wins — one trainer per process is the deployed shape).
_ACTIVE: Optional[TaggedQuality] = None


def active() -> Optional[TaggedQuality]:
    return _ACTIVE


def set_active(q: Optional[TaggedQuality]) -> Optional[TaggedQuality]:
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, q
    return prev


def attach_pass_extras(extra: dict, quality: Optional[TaggedQuality],
                       ship_state: bool = False) -> dict:
    """pass_end wiring shared by every runner: the computed quality
    bundle rides the report, multi-process ranks also ship the raw
    sum-mergeable state for the rank-0 merge, the headline gauges land
    BEFORE the report assembles (so this window's record — and the
    health plane merging it — carries them), and the drift monitor's
    window rolls."""
    if quality is not None:
        quality.publish_gauges()
        extra["quality"] = quality.report()
        if ship_state:
            extra["quality_state"] = quality.state()
    from paddlebox_tpu.metrics import drift as _drift
    dq = _drift.roll_gauges()
    if dq is not None:
        extra["data_quality"] = dq
    return extra


def make_from_flags() -> Optional[TaggedQuality]:
    """Flag-gated construction (quality_metrics off → None) + module
    registration — the one call every trainer makes."""
    from paddlebox_tpu.config import flags
    if not bool(flags.get_flag("quality_metrics")):
        return None
    q = TaggedQuality()
    set_active(q)
    return q
