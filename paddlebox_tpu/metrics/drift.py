"""Slot-level data-quality drift monitor over the columnar ingest plane.

The two production failures a CTR fleet is blind to without this tier:
a broken upstream feature pipeline (a slot silently stops arriving, the
model keeps training on zeros and AUC decays a day later) and
miscalibration (the pred distribution walks away from the labels). The
reference's monitor tier watches exactly these (its data_feed slot
statistics + the COPC alarm of the metric tier); here the signals are
computed VECTORIZED from each pass's merged ``ColumnarBlock`` — one
``bincount`` over ``key_slot`` (+ one ``np.unique`` over the
(record, slot) pairs) per block, so the monitor costs microseconds per
million keys and rides the ingest thread that built the block anyway.

Per report window (a window = one observed pass load; ``roll()`` is
called by the runners at pass_end) the monitor derives, per slot:

  * coverage      — fraction of records carrying >=1 key in the slot
  * keys/record   — mean keys per covered record
  * cardinality   — distinct-key estimate from a per-slot linear-count
                    bitmap sketch (fixed 2^11 bits: estimate
                    -B*ln(1-fill), exact when fill is low)

plus the label positive rate and (fed from the trainers' metric path)
a fixed-bin pred histogram. The DRIFT SCORE of a window is the worst
relative departure of any component from the rolling reference (the
mean of the last ``history`` healthy-ish windows), in [0, 1]; slots
whose coverage collapses below 10% of reference are named in
``dropped_slots``. ``roll()`` publishes ``data_drift_score`` /
``data_dropped_slots`` gauges into the StatRegistry — they ride every
StepReport to rank 0, where the cluster HealthMonitor (obs/health.py)
scores a drifting rank unhealthy through the exact plane the elastic
fleet triggers on.

numpy+stdlib only; the module-level hooks are near-free when the flag
is off (one global read).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np
from paddlebox_tpu.utils.lockwatch import make_lock

#: per-slot linear-counting sketch bits (2 KiB of bools per slot seen)
SKETCH_BITS = 2048
#: pred-histogram bins over [0, 1]
PRED_BINS = 32
#: records observed per block: larger blocks are SAMPLED (evenly
#: strided select) so the monitor's cost is CONSTANT per pass instead
#: of proportional to pass size — coverage of a dropped slot reads 0
#: at any sample size, and drift ratios compare windows sampled
#: identically. At 4096 records the whole observe is ~2-4 ms.
SAMPLE_RECS = 4096


def _hash_u64(keys: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized (uint64 wraparound is the hash)."""
    k = keys.astype(np.uint64, copy=True)
    k ^= k >> np.uint64(30)
    k *= np.uint64(0xBF58476D1CE4E5B9)
    k ^= k >> np.uint64(27)
    k *= np.uint64(0x94D049BB133111EB)
    k ^= k >> np.uint64(31)
    return k


class _Window:
    """One report window's raw accumulators (grown to the max slot id)."""

    def __init__(self) -> None:
        self.n_recs = 0
        self.slot_keys = np.zeros(0, np.int64)
        self.slot_recs = np.zeros(0, np.int64)
        self.sketch: Dict[int, np.ndarray] = {}     # slot -> bool[SKETCH_BITS]
        self.label_pos = 0.0
        self.label_n = 0.0
        self.pred_hist = np.zeros(PRED_BINS, np.int64)

    def _grow(self, n: int) -> None:
        if n <= self.slot_keys.size:
            return
        for name in ("slot_keys", "slot_recs"):
            old = getattr(self, name)
            new = np.zeros(n, np.int64)
            new[:old.size] = old
            setattr(self, name, new)

    # ------------------------------------------------------------ summary
    def summary(self) -> dict:
        ns = self.slot_keys.size
        cov = (self.slot_recs / max(self.n_recs, 1)).astype(np.float64)
        kpr = np.where(self.slot_recs > 0,
                       self.slot_keys / np.maximum(self.slot_recs, 1), 0.0)
        card = np.zeros(ns, np.float64)
        for s, bits in self.sketch.items():
            fill = float(bits.sum()) / SKETCH_BITS
            # linear counting; a saturated sketch reports its ceiling
            card[s] = (-SKETCH_BITS * np.log(max(1.0 - fill, 1e-9))
                       if fill < 1.0 else SKETCH_BITS * 20.0)
        ph = self.pred_hist.astype(np.float64)
        tot = ph.sum()
        return {"n_recs": int(self.n_recs),
                "coverage": cov, "keys_per_rec": kpr, "cardinality": card,
                "label_rate": (self.label_pos / self.label_n
                               if self.label_n else 0.0),
                "pred_hist": (ph / tot if tot else ph)}


class SlotDriftMonitor:
    """Thread contract: observe_* may come from ingest/driver threads
    (one lock); roll() from the pass driver; snapshot() from the HTTP
    exporter (reads under the same short lock, no training locks)."""

    def __init__(self, history: int = 4, drift_warn: Optional[float] = None,
                 min_coverage: float = 0.01) -> None:
        if drift_warn is None:
            from paddlebox_tpu.config import flags
            drift_warn = float(flags.get_flag("data_quality_warn"))
        self.drift_warn = float(drift_warn)
        self.history = int(history)
        self.min_coverage = float(min_coverage)
        self._lock = make_lock("SlotDriftMonitor._lock")
        self._cur = _Window()                # guarded-by: _lock
        self._ref: List[dict] = []           # guarded-by: _lock
        self.last_roll: Optional[dict] = None
        self.windows = 0

    # ------------------------------------------------------------- observe
    def observe_block(self, block) -> None:
        """One merged ColumnarBlock (or sub-block) of the ingest plane —
        a single vectorized pass over (a bounded sample of) its columns.
        Blocks past SAMPLE_RECS records are evenly strided down so the
        cost per pass is constant, not pass-size-proportional."""
        n_recs = int(block.n_recs)
        if n_recs == 0:
            return
        if n_recs > SAMPLE_RECS:
            idx = np.linspace(0, n_recs - 1, SAMPLE_RECS).astype(np.int64)
            block = block.select(idx)
            n_recs = SAMPLE_RECS
        key_slot = np.asarray(block.key_slot)
        ns = int(key_slot.max()) + 1 if key_slot.size else 0
        counts = (np.bincount(key_slot, minlength=ns).astype(np.int64)
                  if key_slot.size else np.zeros(0, np.int64))
        # records covered per slot: O(K) presence scatter into a
        # [n_recs, ns] bool plane — NO sort (an np.unique over the
        # (rec, slot) pairs measured ~2x the native parse itself at the
        # probe shape; the whole observe must stay a small fraction of
        # the load it rides)
        if key_slot.size:
            rec = np.repeat(np.arange(n_recs, dtype=np.int64),
                            np.diff(np.asarray(block.rec_offsets)))
            pres = np.zeros(n_recs * ns, bool)
            pres[rec * ns + key_slot] = True
            prec = pres.reshape(n_recs, ns).sum(
                axis=0, dtype=np.int64)
            hashed = _hash_u64(np.asarray(block.keys))
            bit = (hashed % np.uint64(SKETCH_BITS)).astype(np.int64)
            # sketch bits the same way: one O(K) scatter into a flat
            # [ns * SKETCH_BITS] bool plane, OR-merged per slot below
            sk = np.zeros(ns * SKETCH_BITS, bool)
            sk[key_slot.astype(np.int64) * SKETCH_BITS + bit] = True
            sk = sk.reshape(ns, SKETCH_BITS)
        labels = np.asarray(block.labels)
        pos = float((labels != 0).sum())
        with self._lock:
            w = self._cur
            w.n_recs += n_recs
            w.label_pos += pos
            w.label_n += float(labels.size)
            if ns:
                w._grow(ns)
                w.slot_keys[:ns] += counts
                w.slot_recs[:ns] += prec
                for s in np.nonzero(counts)[0].tolist():
                    bits = w.sketch.get(s)
                    if bits is None:
                        w.sketch[s] = sk[s].copy()
                    else:
                        bits |= sk[s]

    def observe_preds(self, pred, mask=None) -> None:
        """Pred-distribution histogram (fed from the trainers' metric
        path — the calibration half of the drift signal)."""
        pred = np.asarray(pred, np.float64).reshape(-1)
        if mask is not None:
            pred = pred[np.asarray(mask).reshape(-1).astype(bool)]
        if pred.size == 0:
            return
        idx = np.clip((pred * PRED_BINS).astype(np.int64), 0,
                      PRED_BINS - 1)
        hist = np.bincount(idx, minlength=PRED_BINS)
        with self._lock:
            self._cur.pred_hist += hist

    # ---------------------------------------------------------------- roll
    def _drift_against(self, cur: dict, ref: dict) -> dict:
        """Worst-component relative departure, each clamped to [0, 1]."""
        ns = max(cur["coverage"].size, ref["coverage"].size)

        def pad(v):
            out = np.zeros(ns, np.float64)
            out[:v.size] = v
            return out

        ccov, rcov = pad(cur["coverage"]), pad(ref["coverage"])
        ckpr, rkpr = pad(cur["keys_per_rec"]), pad(ref["keys_per_rec"])
        ccard, rcard = pad(cur["cardinality"]), pad(ref["cardinality"])
        watch = rcov >= self.min_coverage
        per_slot = np.zeros(ns, np.float64)
        if watch.any():
            cov_drop = np.clip((rcov - ccov) / np.maximum(rcov, 1e-9),
                               0.0, 1.0)
            kpr_drift = np.clip(np.abs(ckpr - rkpr)
                                / np.maximum(rkpr, 1e-9), 0.0, 1.0)
            card_drop = np.clip(1.0 - ccard / np.maximum(rcard, 1e-9),
                                0.0, 1.0)
            per_slot = np.where(
                watch, np.maximum(cov_drop,
                                  np.maximum(kpr_drift, card_drop)), 0.0)
        dropped = np.nonzero(watch & (ccov < 0.1 * rcov))[0].tolist()
        label_drift = float(min(abs(cur["label_rate"] - ref["label_rate"])
                                / max(ref["label_rate"], 1e-9), 1.0))
        pred_drift = 0.0
        if cur["pred_hist"].sum() > 0 and ref["pred_hist"].sum() > 0:
            # total variation distance between the pred distributions
            pred_drift = float(
                0.5 * np.abs(cur["pred_hist"] - ref["pred_hist"]).sum())
        score = float(max(per_slot.max() if ns else 0.0,
                          label_drift, pred_drift))
        worst = int(np.argmax(per_slot)) if ns and per_slot.max() > 0 else -1
        return {"score": round(score, 4),
                "dropped_slots": dropped,
                "worst_slot": worst,
                "label_drift": round(label_drift, 4),
                "pred_drift": round(pred_drift, 4)}

    @staticmethod
    def _ref_mean(refs: List[dict]) -> dict:
        ns = max(r["coverage"].size for r in refs)

        def mean(key):
            acc = np.zeros(ns, np.float64)
            for r in refs:
                v = r[key]
                acc[:v.size] += v
            return acc / len(refs)

        ph = np.zeros(PRED_BINS, np.float64)
        for r in refs:
            ph += r["pred_hist"]
        return {"coverage": mean("coverage"),
                "keys_per_rec": mean("keys_per_rec"),
                "cardinality": mean("cardinality"),
                "label_rate": float(np.mean([r["label_rate"]
                                             for r in refs])),
                "pred_hist": ph / len(refs)}

    def roll(self) -> Optional[dict]:
        """Close the current window: drift vs the rolling reference,
        gauges published, reference advanced. Returns the window's
        quality record (None when nothing was observed — an eval-only
        pass must not dilute the reference)."""
        with self._lock:
            w, self._cur = self._cur, _Window()
            if w.n_recs == 0 and w.pred_hist.sum() == 0:
                return None
            cur = w.summary()
            refs = list(self._ref)
            self.windows += 1
            win_idx = self.windows
        if refs:
            drift = self._drift_against(cur, self._ref_mean(refs))
        else:
            # first window IS the reference — no departure to measure
            drift = {"score": 0.0, "dropped_slots": [], "worst_slot": -1,
                     "label_drift": 0.0, "pred_drift": 0.0}
        rec = {
            "window": win_idx,
            "ts": time.time(),
            "n_recs": cur["n_recs"],
            "n_slots": int(cur["coverage"].size),
            "label_rate": round(cur["label_rate"], 6),
            "drift": drift,
        }
        with self._lock:
            # drifting windows still enter the reference (a persistent
            # upstream change becomes the new normal after `history`
            # windows instead of alarming forever), bounded deque
            self._ref.append(cur)
            if len(self._ref) > self.history:
                self._ref.pop(0)
            self.last_roll = rec
        from paddlebox_tpu.utils.stats import gauge_set
        gauge_set("data_drift_score", drift["score"])
        gauge_set("data_dropped_slots", float(len(drift["dropped_slots"])))
        if drift["score"] >= self.drift_warn:
            from paddlebox_tpu.obs import log as obs_log
            obs_log.warning(
                "data-quality drift past warn threshold",
                score=drift["score"], warn=self.drift_warn,
                dropped_slots=str(drift["dropped_slots"][:8]),
                worst_slot=drift["worst_slot"])
        return rec

    def preview_block(self, block) -> float:
        """Score a candidate block against the rolling reference WITHOUT
        admitting it: no reference advance, no window count, no gauge
        publish. The streaming admission gate calls this on a loaded
        micro-pass window BEFORE begin_pass — a poisoned window is
        refused before it trains, and (unlike roll()) it never enters
        the reference, so a burst of poison can't normalize itself.
        Returns 0.0 until a reference exists (the first admitted
        windows define normal).

        Thread contract: callers own this monitor exclusively (the
        streaming runner's private instance) — the live-window swap
        below would interleave observations from a concurrent
        observe_* feeder."""
        with self._lock:
            saved, self._cur = self._cur, _Window()
        try:
            self.observe_block(block)
            with self._lock:
                cur = self._cur.summary() if self._cur.n_recs else None
                refs = list(self._ref)
        finally:
            with self._lock:
                self._cur = saved
        if cur is None or not refs:
            return 0.0
        return float(self._drift_against(cur, self._ref_mean(refs))["score"])

    def admit_block(self, block) -> None:
        """Fold an ADMITTED window's block into the rolling reference
        (observe + roll, the paired commit of preview_block)."""
        self.observe_block(block)
        self.roll()

    def snapshot(self) -> dict:
        """Exporter surface: the last rolled record + the live window's
        size (defensive copies only)."""
        with self._lock:
            import copy
            return {"windows": self.windows,
                    "live_recs": int(self._cur.n_recs),
                    "last": copy.deepcopy(self.last_roll)}


# ------------------------------------------------------------- module API
_ACTIVE: Optional[SlotDriftMonitor] = None


def active() -> Optional[SlotDriftMonitor]:
    return _ACTIVE


def set_active(m: Optional[SlotDriftMonitor]) -> Optional[SlotDriftMonitor]:
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, m
    return prev


def observe_block(block) -> None:
    """Ingest-plane hook (data/dataset.py calls it once per merged pass
    block): lazily builds the flag-gated monitor on first data. Never
    raises — a monitoring bug must not kill the pass load it rides."""
    try:
        m = _ACTIVE
        if m is None:
            from paddlebox_tpu.config import flags
            if not bool(flags.get_flag("data_quality")):
                return
            m = set_active_new()
        m.observe_block(block)
    except Exception as e:  # noqa: BLE001 — telemetry degrades, never kills
        from paddlebox_tpu.obs import log as obs_log
        obs_log.warning("data-quality observe failed",
                        error=repr(e)[:200])


def observe_preds(pred, mask=None) -> None:
    m = _ACTIVE
    if m is not None:
        m.observe_preds(pred, mask=mask)


def set_active_new() -> SlotDriftMonitor:
    global _ACTIVE
    _ACTIVE = SlotDriftMonitor()
    return _ACTIVE


def roll_gauges() -> Optional[dict]:
    """Pass-end hook for the runners: close the window, publish gauges,
    return the quality record for the pass_end report extra. Never
    raises — same degrade contract as observe_block."""
    try:
        m = _ACTIVE
        return m.roll() if m is not None else None
    except Exception as e:  # noqa: BLE001 — telemetry degrades, never kills
        from paddlebox_tpu.obs import log as obs_log
        obs_log.warning("data-quality roll failed", error=repr(e)[:200])
        return None
