from paddlebox_tpu.metrics.auc import BasicAucCalculator, MetricMsg, MetricRegistry

__all__ = ["BasicAucCalculator", "MetricMsg", "MetricRegistry"]
