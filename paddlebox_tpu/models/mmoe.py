"""MMoE: multi-gate mixture-of-experts multi-task ranking (BASELINE.json
config 4). Experts share the pooled slot embeddings; per-task softmax gates
mix expert outputs into task towers. Expert matmuls are batched with einsum
so XLA maps them onto the MXU as one big contraction."""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init


class MMoE:
    name = "mmoe"

    def __init__(self, spec: ModelSpec, num_experts: int = 4,
                 expert_dim: int = 64,
                 tasks: Tuple[str, ...] = ("ctr", "cvr"),
                 tower: Sequence[int] = (32,)) -> None:
        self.spec = spec
        self.num_experts = num_experts
        self.expert_dim = expert_dim
        self.task_names = tasks
        self.tower = tuple(tower)

    def init(self, rng: jax.Array) -> Dict:
        keys = jax.random.split(rng, 2 + len(self.task_names))
        din = self.spec.total_in
        E, H = self.num_experts, self.expert_dim
        params = {
            "expert_w": (jax.random.normal(keys[0], (E, din, H))
                         * jnp.sqrt(2.0 / din)).astype(jnp.float32),
            "expert_b": jnp.zeros((E, H), jnp.float32),
            "gate_w": (jax.random.normal(keys[1], (len(self.task_names), din, E))
                       * 0.01).astype(jnp.float32),
        }
        for i, t in enumerate(self.task_names):
            params.update(mlp_init(keys[2 + i], [H, *self.tower, 1],
                                   f"tower_{t}"))
        return params

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None) -> Dict[str, jnp.ndarray]:
        x = pooled.reshape(pooled.shape[0], -1)
        if dense is not None:
            x = jnp.concatenate([x, dense], axis=-1)
        experts = jax.nn.relu(
            jnp.einsum("bi,eih->beh", x, params["expert_w"])
            + params["expert_b"])                          # [B, E, H]
        gates = jax.nn.softmax(
            jnp.einsum("bi,tie->bte", x, params["gate_w"]), axis=-1)
        mixed = jnp.einsum("bte,beh->bth", gates, experts)  # [B, T, H]
        out = {}
        for i, t in enumerate(self.task_names):
            out[t] = mlp_apply(params, mixed[:, i], f"tower_{t}")[:, 0]
        return out
