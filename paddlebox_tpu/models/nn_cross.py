"""NN-cross CTR model over the dual-output expand embedding.

The model family pull_box_extended_sparse exists for in the reference
(op: paddle/fluid/operators/pull_box_extended_sparse_op.cc; user API
`fluid.contrib.layers.pull_box_extended_sparse`, contrib/layers/nn.py:1678):
every feature carries a SECOND embedding block (the expand/NN-cross
vector) trained jointly with the base one. The base pooled view feeds the
deep tower; the expand vectors feed an explicit slot-interaction (cross)
branch — here an FM-style second-order term plus a linear projection, the
standard shape of the cross branches those models wire the expand output
into. Both branches' gradients flow back through ONE extended push
(build_push_grads_extended → the shared-g2sum expand adagrad rule,
embedding/optimizers.py).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init


class CtrDnnExpand:
    name = "ctr_dnn_expand"
    task_names = ("ctr",)
    use_expand = True   # trainer contract: pull extended, push expand grads

    def __init__(self, spec: ModelSpec, expand_dim: int,
                 hidden=(64, 32)) -> None:
        if expand_dim <= 0:
            raise ValueError("CtrDnnExpand needs expand_dim > 0")
        self.spec = spec
        self.expand_dim = expand_dim
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Dict:
        dims = [self.spec.total_in, *self.hidden, 1]
        params = mlp_init(rng, dims, "dnn")
        k = jax.random.fold_in(rng, 7)
        S, E = self.spec.num_slots, self.expand_dim
        params["cross"] = {
            "lin_w": 0.01 * jax.random.normal(k, (S * E, 1), jnp.float32),
            "lin_b": jnp.zeros((1,), jnp.float32),
            "fm_scale": jnp.ones((), jnp.float32),
        }
        return params

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None,
              expand: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """pooled: [B, S, slot_dim] base view; expand: [B, S, E] sum-pooled
        expand vectors (REQUIRED — the trainer's extended pull supplies
        it)."""
        if expand is None:
            raise ValueError("CtrDnnExpand.apply needs the expand input")
        x = pooled.reshape(pooled.shape[0], -1)
        if dense is not None:
            x = jnp.concatenate([x, dense], axis=-1)
        deep = mlp_apply(params, x, "dnn")[:, 0]
        # FM-style second order across slots on the expand vectors:
        # 0.5 * Σ_e ((Σ_s v_se)² − Σ_s v_se²) = Σ_{s<s'} <v_s, v_s'>
        s_sum = expand.sum(axis=1)
        s_sq = jnp.square(expand).sum(axis=1)
        fm = 0.5 * (jnp.square(s_sum) - s_sq).sum(axis=-1)
        cr = params["cross"]
        lin = (expand.reshape(expand.shape[0], -1) @ cr["lin_w"])[:, 0] \
            + cr["lin_b"][0]
        return deep + cr["fm_scale"] * fm + lin
