"""CTR-DNN: the canonical slot-embedding → seqpool+CVM → MLP ranking model.

Baseline config 1 of BASELINE.json; structurally the model built by the
reference's test_boxps.py graph (emb via _pull_box_sparse → sum-pool → cvm →
fc stack → sigmoid, python/paddle/fluid/tests/unittests/test_boxps.py:87-103
and ctr_dataset_reader-style examples).

use_data_norm adds the reference CTR models' streaming input normalization
(data_norm_op over the flattened slot features; the "summary" params of
boxps_worker.cc:89-95). The summary state lives in params under
``dn_summary`` but is updated by the trainers via ``update_summary`` (the
running-sums decay rule), NOT by the dense optimizer — its entries are
stop_gradient'ed in apply so optax sees zero grads. No special sync mode is
needed in multi-device training: normalization uses only the RATIOS
batch_sum/batch_size and batch_size/batch_square_sum, which are invariant
under the trainers' pmean dense sync (mean vs the reference's
DenseDataNormal sum differs by the world-size factor on all three
components at once)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init
from paddlebox_tpu.ops.data_norm import (DataNormState, data_norm,
                                         data_norm_summary_update)


class CtrDnn:
    name = "ctr_dnn"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec,
                 hidden: Sequence[int] = (512, 256, 128),
                 use_data_norm: bool = False,
                 dn_slot_dim: int = 0,
                 dn_decay: float = 0.9999999) -> None:
        self.spec = spec
        self.hidden = tuple(hidden)
        self.use_data_norm = use_data_norm
        self.dn_slot_dim = dn_slot_dim
        self.dn_decay = dn_decay

    def init(self, rng: jax.Array) -> Dict:
        dims = [self.spec.total_in, *self.hidden, 1]
        params = mlp_init(rng, dims, "dnn")
        if self.use_data_norm:
            st = DataNormState.init(self.spec.total_in)
            params["dn_summary"] = {"batch_size": st.batch_size,
                                    "batch_sum": st.batch_sum,
                                    "batch_square_sum": st.batch_square_sum}
        return params

    def _dn_state(self, params: Dict) -> DataNormState:
        dn = params["dn_summary"]
        return DataNormState(dn["batch_size"], dn["batch_sum"],
                             dn["batch_square_sum"])

    def _assemble(self, pooled: jnp.ndarray,
                  dense: Optional[jnp.ndarray]) -> jnp.ndarray:
        x = pooled.reshape(pooled.shape[0], -1)
        if dense is not None:
            x = jnp.concatenate([x, dense], axis=-1)
        return x

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        x = self._assemble(pooled, dense)
        if self.use_data_norm:
            state = jax.tree.map(jax.lax.stop_gradient,
                                 self._dn_state(params))
            x = data_norm(x.astype(jnp.float32), state,
                          slot_dim=self.dn_slot_dim).astype(x.dtype)
        return mlp_apply(params, x, "dnn")[:, 0]

    def update_summary(self, params: Dict, pooled: jnp.ndarray,
                       dense: Optional[jnp.ndarray] = None) -> Dict:
        """Accumulate this batch into the running summaries (the trainers
        call this after the optimizer step; summary stats never flow
        through optax)."""
        x = self._assemble(pooled, dense).astype(jnp.float32)
        st = data_norm_summary_update(self._dn_state(params),
                                      x, decay=self.dn_decay,
                                      slot_dim=self.dn_slot_dim)
        return dict(params, dn_summary={"batch_size": st.batch_size,
                                        "batch_sum": st.batch_sum,
                                        "batch_square_sum":
                                            st.batch_square_sum})
