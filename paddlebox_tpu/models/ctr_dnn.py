"""CTR-DNN: the canonical slot-embedding → seqpool+CVM → MLP ranking model.

Baseline config 1 of BASELINE.json; structurally the model built by the
reference's test_boxps.py graph (emb via _pull_box_sparse → sum-pool → cvm →
fc stack → sigmoid, python/paddle/fluid/tests/unittests/test_boxps.py:87-103
and ctr_dataset_reader-style examples)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init


class CtrDnn:
    name = "ctr_dnn"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec,
                 hidden: Sequence[int] = (512, 256, 128)) -> None:
        self.spec = spec
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Dict:
        dims = [self.spec.total_in, *self.hidden, 1]
        return mlp_init(rng, dims, "dnn")

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        x = pooled.reshape(pooled.shape[0], -1)
        if dense is not None:
            x = jnp.concatenate([x, dense], axis=-1)
        return mlp_apply(params, x, "dnn")[:, 0]
