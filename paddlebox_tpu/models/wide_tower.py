"""Mesh-parallel model-zoo entries: wide-tower DeepFM (TP) and
expert-parallel MMoE.

The reference replicates its dense towers on every worker — they are small
(BASELINE.json configs top out at 512-wide). These entries are the
beyond-reference counterpart for towers that do NOT fit replicated: the
deep tower's wide hidden layer column/row-splits over a model-parallel
mesh axis (Megatron split, parallel/tensor_parallel.py), and the MMoE
variant shards its expert blocks over the axis. Both are mesh-aware zoo
entries consumed by parallel.mesh_tower.MeshTowerTrainer, which enforces
the TP autodiff contracts (tp_loss_scale + tp_fix_grads) so a user cannot
silently train on partial gradients.

Contract (differs from the replicated zoo's init/apply):
  host_init(seed)  -> (host_params, sharded) — numpy leaves; sharded is a
                      matching dict of bools (True = leaf stacks [P, ...]
                      and lives shard-local on the axis)
  apply_local(p, pooled, axis) -> [B] logits, called per device inside
                      shard_map with the SHARDED leaves already sliced to
                      this device (leading [P] axis removed)
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.parallel.tensor_parallel import (ep_experts_apply,
                                                    ep_experts_init,
                                                    tp_mlp_apply,
                                                    tp_mlp_init)


class TpDeepFM:
    """DeepFM whose deep tower's first (wide) layer is tensor-parallel.

    FM first/second-order terms are replicated exactly as models/deepfm.py;
    the deep path is ONE Megatron block (total_in → d_wide/P per device →
    d_mid, one psum) followed by a small replicated head. d_wide can be
    4096+ — per-device tower memory is O(d_wide/P)."""

    name = "tp_deepfm"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec, n_shards: int,
                 d_wide: int = 4096, d_mid: int = 128,
                 embedx_dim: int = None) -> None:
        self.spec = spec
        self.n_shards = n_shards
        self.d_wide = d_wide
        self.d_mid = d_mid
        self.embedx_dim = (embedx_dim if embedx_dim is not None
                           else spec.slot_dim - 3)

    def host_init(self, seed: int) -> Tuple[Dict, Dict]:
        rng = np.random.RandomState(seed)
        p = tp_mlp_init(rng, self.n_shards, self.spec.total_in,
                        self.d_wide, self.d_mid)
        p["head_w"] = (0.1 * rng.randn(self.d_mid)).astype(np.float32)
        p["head_b"] = np.zeros((), np.float32)
        p["fm_out_w"] = (0.1 * rng.randn(3)).astype(np.float32)
        p["fm_out_b"] = np.zeros((), np.float32)
        sharded = {k: k in ("w1", "b1", "w2") for k in p}
        return p, sharded

    def apply_local(self, p: Dict, pooled: jnp.ndarray,
                    axis: str) -> jnp.ndarray:
        B = pooled.shape[0]
        D = self.embedx_dim
        first_order = pooled[:, :, 2].sum(axis=1)
        v = pooled[:, :, 3:3 + D]
        sum_v = v.sum(axis=1)
        fm2 = 0.5 * (sum_v * sum_v - (v * v).sum(axis=1)).sum(axis=-1)
        x = pooled.reshape(B, -1)
        mid = jax.nn.relu(tp_mlp_apply(p, x, axis))
        deep = mid @ p["head_w"] + p["head_b"]
        stack = jnp.stack([first_order, fm2, deep], axis=-1)
        return stack @ p["fm_out_w"] + p["fm_out_b"]


class EpMMoE:
    """Expert-parallel MMoE-style CTR tower: n_experts dense expert MLPs
    shard over the mesh axis (each device owns E/P), a replicated softmax
    gate mixes them (dense MMoE gating — every expert sees every
    instance), and a small replicated head reads the mixture. The gate's
    partial-gradient footgun is closed by the trainer's tp_fix_grads."""

    name = "ep_mmoe"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec, n_shards: int, n_experts: int = 8,
                 d_hidden: int = 64, d_out: int = 32) -> None:
        if n_experts % n_shards:
            raise ValueError(f"n_experts {n_experts} not divisible by "
                             f"{n_shards} shards")
        self.spec = spec
        self.n_shards = n_shards
        self.n_experts = n_experts
        self.d_hidden = d_hidden
        self.d_out = d_out

    def host_init(self, seed: int) -> Tuple[Dict, Dict]:
        rng = np.random.RandomState(seed)
        p = ep_experts_init(rng, self.n_experts, self.spec.total_in,
                            self.d_hidden, self.d_out)
        # expert leaves regroup [E, ...] → [P, E/P, ...] so the mesh axis
        # is the leading dim (shard_map slices it off)
        el = self.n_experts // self.n_shards
        for k in ("ew1", "eb1", "ew2", "eb2"):
            p[k] = p[k].reshape((self.n_shards, el) + p[k].shape[1:])
        p["head_w"] = (0.1 * rng.randn(self.d_out)).astype(np.float32)
        p["head_b"] = np.zeros((), np.float32)
        sharded = {k: k in ("ew1", "eb1", "ew2", "eb2") for k in p}
        return p, sharded

    def apply_local(self, p: Dict, pooled: jnp.ndarray,
                    axis: str) -> jnp.ndarray:
        B = pooled.shape[0]
        x = pooled.reshape(B, -1)
        mix = ep_experts_apply(p, x, axis)          # [B, d_out], psum'd
        return mix @ p["head_w"] + p["head_b"]
