"""Join-phase pv ranking model: rank_attention over session peers + MLP.

The model family the reference's rank_attention/batch_fc ops exist for
(operators/rank_attention_op.*, batch_fc_op.*): each ad instance attends over
the other ads in its pv (search session) through a per-(rank, peer-rank)
parameter block, and the attention output joins the pooled slot features in
the ranking MLP. Batches must be packed pv-contiguously with a rank-offset
matrix (data/pv.py).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init
from paddlebox_tpu.ops.rank_attention import rank_attention


class JoinPvDnn:
    name = "join_pv_dnn"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec, max_rank: int = 3,
                 att_dim: int = 64,
                 hidden: Sequence[int] = (512, 256, 128)) -> None:
        self.spec = spec
        self.max_rank = max_rank
        self.att_dim = att_dim
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Dict:
        r_mlp, r_att = jax.random.split(rng)
        F = self.spec.sparse_in
        params = mlp_init(
            r_mlp, [F + self.att_dim + self.spec.dense_dim, *self.hidden, 1],
            "dnn")
        params["rank_param"] = (jax.random.normal(
            r_att, (self.max_rank * self.max_rank * F, self.att_dim))
            * jnp.sqrt(1.0 / F)).astype(jnp.float32)
        return params

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None,
              rank_offset: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        x = pooled.reshape(pooled.shape[0], -1)
        if rank_offset is None:
            # update-phase fallback: no pv context → zero attention
            att = jnp.zeros((x.shape[0], self.att_dim), x.dtype)
        else:
            att, _ = rank_attention(x, rank_offset, params["rank_param"],
                                    self.max_rank)
        feats = [x, att]
        if dense is not None:
            feats.append(dense)
        return mlp_apply(params, jnp.concatenate(feats, axis=-1), "dnn")[:, 0]
