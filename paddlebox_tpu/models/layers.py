"""Functional dense building blocks.

Plain param-pytree functions (no flax dependency in the hot path): params are
dicts of jnp arrays, so pjit sharding rules and the ZeRO-1 partitioner
(parallel/sharding.py) can address every leaf by name. Matmul-heavy by
design — everything lowers onto the MXU.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp


def mlp_init(rng: jax.Array, dims: Sequence[int], name: str = "mlp") -> Dict:
    """He-init MLP params: dims = [in, h1, ..., out]."""
    params = {}
    keys = jax.random.split(rng, len(dims) - 1)
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        params[f"{name}_w{i}"] = (jax.random.normal(keys[i], (din, dout))
                                  * jnp.sqrt(2.0 / din)).astype(jnp.float32)
        params[f"{name}_b{i}"] = jnp.zeros((dout,), jnp.float32)
    return params


def mlp_apply(params: Dict, x: jnp.ndarray, name: str = "mlp",
              act: Callable = jax.nn.relu, final_act: bool = False) -> jnp.ndarray:
    i = 0
    while f"{name}_w{i}" in params:
        x = x @ params[f"{name}_w{i}"] + params[f"{name}_b{i}"]
        if final_act or f"{name}_w{i+1}" in params:
            x = act(x)
        i += 1
    return x


def num_layers(params: Dict, name: str = "mlp") -> int:
    i = 0
    while f"{name}_w{i}" in params:
        i += 1
    return i
