"""Functional dense building blocks.

Plain param-pytree functions (no flax dependency in the hot path): params are
dicts of jnp arrays, so sharding rules and the ZeRO-1 partitioner
(parallel/sharded_trainer.py sharding mode) can address every leaf by name.
Matmul-heavy by design — everything lowers onto the MXU.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import flags


def matmul(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """MXU matmul honoring the `matmul_dtype` flag: bfloat16 inputs with
    float32 accumulation (the MXU's native mode — f32 operands run at half
    rate), or plain float32. Params stay float32 masters either way.

    The flag is read at TRACE time: set it before building the trainer
    (jit caches are not keyed on it, so later changes don't retrace)."""
    if flags.get_flag("matmul_dtype") == "bfloat16":
        return jax.lax.dot_general(
            x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return x @ w


def mlp_init(rng: jax.Array, dims: Sequence[int], name: str = "mlp") -> Dict:
    """He-init MLP params: dims = [in, h1, ..., out]."""
    params = {}
    keys = jax.random.split(rng, len(dims) - 1)
    for i, (din, dout) in enumerate(zip(dims[:-1], dims[1:])):
        params[f"{name}_w{i}"] = (jax.random.normal(keys[i], (din, dout))
                                  * jnp.sqrt(2.0 / din)).astype(jnp.float32)
        params[f"{name}_b{i}"] = jnp.zeros((dout,), jnp.float32)
    return params


def mlp_apply(params: Dict, x: jnp.ndarray, name: str = "mlp",
              act: Callable = jax.nn.relu, final_act: bool = False) -> jnp.ndarray:
    i = 0
    while f"{name}_w{i}" in params:
        x = matmul(x, params[f"{name}_w{i}"]) + params[f"{name}_b{i}"]
        if final_act or f"{name}_w{i+1}" in params:
            x = act(x)
        i += 1
    return x


def num_layers(params: Dict, name: str = "mlp") -> int:
    i = 0
    while f"{name}_w{i}" in params:
        i += 1
    return i
