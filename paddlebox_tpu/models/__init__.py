from paddlebox_tpu.models.layers import mlp_init, mlp_apply
from paddlebox_tpu.models.ctr_dnn import CtrDnn
from paddlebox_tpu.models.deepfm import DeepFM
from paddlebox_tpu.models.wide_deep import WideDeep
from paddlebox_tpu.models.dlrm import DLRM
from paddlebox_tpu.models.mmoe import MMoE
from paddlebox_tpu.models.esmm import ESMM
from paddlebox_tpu.models.join_pv import JoinPvDnn
from paddlebox_tpu.models.nn_cross import CtrDnnExpand
from paddlebox_tpu.models.aux_input import CtrDnnAux
from paddlebox_tpu.models.bst import BstSeqCtr
from paddlebox_tpu.models.wide_tower import EpMMoE, TpDeepFM

MODEL_ZOO = {
    "ctr_dnn": CtrDnn,
    "deepfm": DeepFM,
    "wide_deep": WideDeep,
    "dlrm": DLRM,
    "mmoe": MMoE,
    "esmm": ESMM,
    "join_pv_dnn": JoinPvDnn,
    "ctr_dnn_expand": CtrDnnExpand,
    "ctr_dnn_aux": CtrDnnAux,
    "bst_seq_ctr": BstSeqCtr,
    "tp_deepfm": TpDeepFM,
    "ep_mmoe": EpMMoE,
}

__all__ = ["mlp_init", "mlp_apply", "CtrDnn", "DeepFM", "WideDeep", "DLRM",
           "MMoE", "ESMM", "JoinPvDnn", "CtrDnnExpand",
           "CtrDnnAux", "BstSeqCtr", "TpDeepFM", "EpMMoE",
           "MODEL_ZOO"]
