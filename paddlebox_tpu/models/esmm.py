"""ESMM: entire-space multi-task CTR+CVR model (BASELINE.json config 4).

Two towers over shared embeddings. apply returns logits for 'ctr' and 'cvr';
loss_mode="esmm" makes the trainer compose pCTCVR = pCTR·pCVR and train
BCE(click, pCTR) + BCE(conversion, pCTCVR) over the whole impression space
(train/trainer.py:_multi_task_loss). The batch's labels_cvr field carries
the conversion/pay label (defaults to click when the data has only one
label)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init


class ESMM:
    name = "esmm"
    task_names = ("ctr", "cvr")
    loss_mode = "esmm"

    def __init__(self, spec: ModelSpec,
                 tower: Sequence[int] = (256, 128, 64)) -> None:
        self.spec = spec
        self.tower = tuple(tower)

    def init(self, rng: jax.Array) -> Dict:
        k1, k2 = jax.random.split(rng)
        params = {}
        params.update(mlp_init(k1, [self.spec.total_in, *self.tower, 1], "ctr"))
        params.update(mlp_init(k2, [self.spec.total_in, *self.tower, 1], "cvr"))
        return params

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None) -> Dict[str, jnp.ndarray]:
        x = pooled.reshape(pooled.shape[0], -1)
        if dense is not None:
            x = jnp.concatenate([x, dense], axis=-1)
        return {
            "ctr": mlp_apply(params, x, "ctr")[:, 0],
            "cvr": mlp_apply(params, x, "cvr")[:, 0],
        }
