"""CTR-DNN consuming side-table aux rows through the feed path.

The consumer the round-3 verdict found missing: ReplicaCache / InputTable
were unit-tested inventory with no feed path or model reading them. The
reference wires them as ops in the program — `pull_cache_value`
(pull_box_sparse_op.cc:64-80) gathers cached embedding rows and
`lookup_input` (pull_box_sparse_op.cc:173-208) gathers aux feature rows,
with `InputTableDataFeed` (data_feed.h:2221-2252) translating each
instance's string key to a row offset at feed time.

The TPU-native composition: the feed translates keys → offsets host-side
(BatchPacker input_table/use_cache_idx → the `aux_offset` batch leaf), the
frozen side-table rows ride in `params["aux_rows"]` as a NON-TRAINED leaf
(stop_gradient in apply — the same zero-grad contract as dn_summary, so
the dense optimizer's update on it is a no-op), and the model gathers
`aux_rows[aux_offset]` on device — one fused gather, exactly the
lookup_input/pull_cache_value data flow. BoxTrainer(aux_source=...)
refreshes the rows each pass at a FIXED capacity (static shapes: no
recompile as the table grows)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init


class CtrDnnAux:
    """CtrDnn + an aux-row input gathered from a replicated side table."""

    name = "ctr_dnn_aux"
    task_names = ("ctr",)
    use_aux_input = True

    def __init__(self, spec: ModelSpec, aux_dim: int,
                 aux_capacity: int = 1 << 12,
                 hidden: Sequence[int] = (512, 256, 128)) -> None:
        self.spec = spec
        self.aux_dim = aux_dim
        self.aux_capacity = aux_capacity
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Dict:
        dims = [self.spec.total_in + self.aux_dim, *self.hidden, 1]
        params = mlp_init(rng, dims, "dnn")
        # refreshed from the side table each pass (BoxTrainer aux_source);
        # stop_gradient'ed in apply → the optimizer never moves it
        params["aux_rows"] = jnp.zeros((self.aux_capacity, self.aux_dim),
                                       jnp.float32)
        return params

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None,
              aux_offset: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        x = pooled.reshape(pooled.shape[0], -1)
        if dense is not None:
            x = jnp.concatenate([x, dense], axis=-1)
        if aux_offset is None:
            raise ValueError("CtrDnnAux needs the aux_offset batch leaf — "
                             "feed the dataset an input_table or "
                             "use_cache_idx (BatchPacker)")
        aux = jax.lax.stop_gradient(params["aux_rows"])[aux_offset]
        x = jnp.concatenate([x, aux.astype(x.dtype)], axis=-1)
        return mlp_apply(params, x, "dnn")[:, 0]
