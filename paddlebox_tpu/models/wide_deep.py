"""Wide&Deep: linear (wide) head over pooled slot stats + deep MLP tower.

BASELINE.json config 3 companion; the wide part consumes the CVM + embed_w
columns per slot (the memorization path), the deep part the full pooled
embedding."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init


class WideDeep:
    name = "wide_deep"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec,
                 hidden: Sequence[int] = (256, 128, 64)) -> None:
        self.spec = spec
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Dict:
        k1, k2 = jax.random.split(rng)
        params = mlp_init(k1, [self.spec.total_in, *self.hidden, 1], "deep")
        wide_in = self.spec.num_slots * 3 + self.spec.dense_dim
        params["wide_w"] = (jax.random.normal(k2, (wide_in, 1))
                            * 0.01).astype(jnp.float32)
        params["wide_b"] = jnp.zeros((1,), jnp.float32)
        return params

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        B = pooled.shape[0]
        wide_x = pooled[:, :, :3].reshape(B, -1)
        deep_x = pooled.reshape(B, -1)
        if dense is not None:
            wide_x = jnp.concatenate([wide_x, dense], axis=-1)
            deep_x = jnp.concatenate([deep_x, dense], axis=-1)
        wide = (wide_x @ params["wide_w"] + params["wide_b"])[:, 0]
        deep = mlp_apply(params, deep_x, "deep")[:, 0]
        return wide + deep
