"""DeepFM: factorization machine + deep tower over shared slot embeddings.

BASELINE.json config 2 (DeepFM on Criteo-TB). The FM second-order term uses
the standard (sum^2 - sum-of-squares)/2 identity over per-slot embedx
vectors; first-order comes from the pooled embed_w column. All-matmul —
MXU-friendly."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init


class DeepFM:
    name = "deepfm"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec,
                 hidden: Sequence[int] = (400, 400, 400),
                 embedx_dim: int = None) -> None:
        self.spec = spec
        self.hidden = tuple(hidden)
        # pooled slot layout: [log_show, log_ctr, embed_w, embedx...(D)]
        self.embedx_dim = (embedx_dim if embedx_dim is not None
                           else spec.slot_dim - 3)

    def init(self, rng: jax.Array) -> Dict:
        k1, k2 = jax.random.split(rng)
        params = mlp_init(k1, [self.spec.total_in, *self.hidden, 1], "deep")
        params["fm_out_w"] = (jax.random.normal(k2, (3,)) * 0.1).astype(
            jnp.float32)
        params["fm_out_b"] = jnp.zeros((), jnp.float32)
        return params

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        B = pooled.shape[0]
        D = self.embedx_dim
        first_order = pooled[:, :, 2].sum(axis=1)          # Σ slot embed_w
        v = pooled[:, :, 3:3 + D]                          # [B, S, D]
        sum_v = v.sum(axis=1)
        fm2 = 0.5 * (sum_v * sum_v - (v * v).sum(axis=1)).sum(axis=-1)
        x = pooled.reshape(B, -1)
        if dense is not None:
            x = jnp.concatenate([x, dense], axis=-1)
        deep = mlp_apply(params, x, "deep")[:, 0]
        stack = jnp.stack([first_order, fm2, deep], axis=-1)
        return stack @ params["fm_out_w"] + params["fm_out_b"]
