"""Model protocol shared by the CTR zoo.

Every model is a stateless pair (init, apply):
    init(rng) -> params pytree
    apply(params, pooled, dense) -> logits
        pooled: [B, num_slots, slot_dim] fused seqpool+CVM output
                (slot_dim = 3+embedx_dim with CVM columns)
        dense:  [B, dense_dim] float32 or None
        logits: [B] (single task) or dict[str, [B]] (multi-task)
Multi-task models also expose task_names.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

Logits = Union[jnp.ndarray, Dict[str, jnp.ndarray]]


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Static shape info every model needs at init time."""

    num_slots: int
    slot_dim: int          # per-slot pooled width (3+embedx_dim with CVM)
    dense_dim: int = 0

    @property
    def sparse_in(self) -> int:
        return self.num_slots * self.slot_dim

    @property
    def total_in(self) -> int:
        return self.sparse_in + self.dense_dim
