"""BST-style behavior-sequence CTR model — the long-context consumer.

The reference pools every slot (no attention models; SURVEY §2.8 notes no
sequence parallelism either). This zoo entry treats a user's behavior
history as a first-class SEQUENCE: one designated slot's feasigns keep
their order, embed through the same pass slab, and self-attend
(Behavior Sequence Transformer shape) before joining the pooled-slot CTR
tower. For long histories the sequence axis shards over an `sp` mesh axis
and attention runs as ring attention (flash-style ppermute ring) or
Ulysses (seq→head all_to_all) — the parallel/ring_attention.py primitives,
here consumed by a real trained model (parallel/seq_trainer.py).

Mesh-aware contract (like models/wide_tower.py): host_init(seed) →
(host_params, sharded mask — everything replicated here; sequence
parallelism shards ACTIVATIONS, not params), and per-device apply pieces
called inside shard_map."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init

# NOTE: the ring/ulysses primitives import lazily inside
# seq_feature_local — a top-level import would cycle through
# parallel/__init__ → sharded_trainer → train.trainer → models/__init__
# → this module.


class BstSeqCtr:
    """Pooled slots (seqpool+CVM) + attended behavior sequence → MLP head.

    seq_len: TOTAL history length (padded; must divide the sp mesh size).
    attn: "ring" | "ulysses" — which sequence-parallel primitive runs the
    attention (ulysses needs heads % P == 0)."""

    name = "bst_seq_ctr"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec, seq_len: int, n_shards: int,
                 heads: int = 4, d_head: int = 8, d_seq: int = 16,
                 hidden=(64, 32), attn: str = "ring") -> None:
        if seq_len % n_shards:
            raise ValueError(f"seq_len {seq_len} must divide by the mesh "
                             f"size {n_shards}")
        if attn not in ("ring", "ulysses"):
            raise ValueError(f"attn must be ring|ulysses, got {attn!r}")
        if attn == "ulysses" and heads % n_shards:
            raise ValueError(f"ulysses needs heads {heads} divisible by "
                             f"{n_shards}")
        self.spec = spec
        self.seq_len = seq_len
        self.n_shards = n_shards
        self.heads = heads
        self.d_head = d_head
        self.d_seq = d_seq
        self.hidden = tuple(hidden)
        self.attn = attn

    def host_init(self, seed: int) -> Tuple[Dict, Dict]:
        rng = np.random.RandomState(seed)
        Din = self.spec.slot_dim          # pull row width (3 + embedx)
        H, Dh = self.heads, self.d_head
        s = 0.1

        def mat(*shape):
            return (s * rng.randn(*shape)).astype(np.float32)

        p = {
            "pos_emb": mat(self.seq_len, Din),
            "wq": mat(Din, H * Dh), "wk": mat(Din, H * Dh),
            "wv": mat(Din, H * Dh), "wo": mat(H * Dh, self.d_seq),
            "bo": np.zeros(self.d_seq, np.float32),
        }
        dims = [self.spec.total_in + self.d_seq, *self.hidden, 1]
        rng_j = jax.random.PRNGKey(seed + 7)
        mlp = jax.tree.map(np.asarray, mlp_init(rng_j, dims, "dnn"))
        p.update(mlp)
        # sequence parallelism shards activations, not params
        return p, {k: False for k in p}

    def seq_feature_local(self, p: Dict, emb_chunk: jnp.ndarray,
                          valid_chunk: jnp.ndarray, axis: str
                          ) -> jnp.ndarray:
        """This device's sequence chunk → the psum'd [B, d_seq] feature.

        emb_chunk: [B, T/P, Din] pulled history embeddings (local chunk);
        valid_chunk: [B, T/P] bool. Masked positions attend as zeros and
        are excluded from the mean pool."""
        from paddlebox_tpu.parallel.ring_attention import (
            ring_attention, ulysses_attention)
        B, Tl, Din = emb_chunk.shape
        H, Dh = self.heads, self.d_head
        idx = jax.lax.axis_index(axis)
        pos = jax.lax.dynamic_slice_in_dim(p["pos_emb"], idx * Tl, Tl, 0)
        tok = jnp.where(valid_chunk[..., None],
                        emb_chunk + pos[None], 0.0)
        q = (tok @ p["wq"]).reshape(B, Tl, H, Dh)
        k = (tok @ p["wk"]).reshape(B, Tl, H, Dh)
        v = (tok @ p["wv"]).reshape(B, Tl, H, Dh)
        if self.attn == "ring":
            o = ring_attention(q, k, v, axis, causal=False)
        else:
            o = ulysses_attention(q, k, v, axis, causal=False)
        o = o.reshape(B, Tl, H * Dh) @ p["wo"] + p["bo"]     # [B, Tl, d_seq]
        # masked mean over the FULL sequence: local sums psum over the axis
        w = valid_chunk.astype(jnp.float32)[..., None]
        num = jax.lax.psum((o * w).sum(axis=1), axis)
        den = jax.lax.psum(w.sum(axis=1), axis)
        return num / jnp.maximum(den, 1.0)

    def head_apply(self, p: Dict, pooled: jnp.ndarray,
                   seq_feat: jnp.ndarray) -> jnp.ndarray:
        x = jnp.concatenate([pooled.reshape(pooled.shape[0], -1),
                             seq_feat], axis=-1)
        return mlp_apply(p, x, "dnn")[:, 0]

    # ------------------------------------------------------- dense oracle
    def oracle_logits(self, p: Dict, pooled, emb_seq, seq_valid):
        """Single-device full-sequence reference (tests): identical math,
        no mesh."""
        B, T, Din = emb_seq.shape
        H, Dh = self.heads, self.d_head
        tok = jnp.where(seq_valid[..., None], emb_seq + p["pos_emb"][None],
                        0.0)
        q = (tok @ p["wq"]).reshape(B, T, H, Dh)
        k = (tok @ p["wk"]).reshape(B, T, H, Dh)
        v = (tok @ p["wv"]).reshape(B, T, H, Dh)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (Dh ** 0.5)
        o = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        o = o.reshape(B, T, H * Dh) @ p["wo"] + p["bo"]
        w = seq_valid.astype(jnp.float32)[..., None]
        feat = (o * w).sum(axis=1) / jnp.maximum(w.sum(axis=1), 1.0)
        return self.head_apply(p, pooled, feat)
