"""DLRM (MLPerf-rec shape): bottom MLP on dense features, pairwise dot
feature interactions between dense output and per-slot embedx vectors,
top MLP on [bottom, interactions]."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.layers import mlp_apply, mlp_init


class DLRM:
    name = "dlrm"
    task_names = ("ctr",)

    def __init__(self, spec: ModelSpec,
                 bottom: Sequence[int] = (128, 64),
                 top: Sequence[int] = (256, 128)) -> None:
        self.spec = spec
        self.embedx_dim = spec.slot_dim - 3
        self.bottom = tuple(bottom) + (self.embedx_dim,)
        self.top = tuple(top)

    def init(self, rng: jax.Array) -> Dict:
        k1, k2 = jax.random.split(rng)
        params = {}
        if self.spec.dense_dim:
            params.update(mlp_init(
                k1, [self.spec.dense_dim, *self.bottom], "bot"))
        S = self.spec.num_slots + (1 if self.spec.dense_dim else 0)
        n_inter = S * (S - 1) // 2
        top_in = n_inter + (self.embedx_dim if self.spec.dense_dim else 0)
        params.update(mlp_init(k2, [top_in, *self.top, 1], "top"))
        return params

    def apply(self, params: Dict, pooled: jnp.ndarray,
              dense: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        B = pooled.shape[0]
        feats = pooled[:, :, 3:]                      # [B, S, D]
        if dense is not None and self.spec.dense_dim:
            bot = mlp_apply(params, dense, "bot", final_act=True)  # [B, D]
            feats = jnp.concatenate([feats, bot[:, None, :]], axis=1)
        inter = jnp.einsum("bsd,btd->bst", feats, feats)  # [B, S, S]
        S = feats.shape[1]
        iu, ju = jnp.triu_indices(S, k=1)
        x = inter[:, iu, ju]                          # [B, S(S-1)/2]
        if dense is not None and self.spec.dense_dim:
            x = jnp.concatenate([x, bot], axis=-1)
        return mlp_apply(params, x, "top")[:, 0]
