"""MultiSlot text parser.

Parses the reference's MultiSlot instance format (data_feed.cc
MultiSlotDataFeed/SlotRecordInMemoryDataFeed text path): one instance per
line, slots in feed-config order, each encoded as
    <count> <v_1> ... <v_count>
with uint64 feasigns for sparse slots and floats for dense slots. The slot
named "click" (or the first float slot flagged as label) doubles as the
label. A C++ fast path (native/slot_parser.cc) implements the same contract;
this module is the pure-Python reference implementation and fallback.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional

import numpy as np

from paddlebox_tpu.config.configs import DataFeedConfig
from paddlebox_tpu.data.slot_record import SlotRecord


class MultiSlotParser:
    def __init__(self, feed: DataFeedConfig, label_slot: str = "click") -> None:
        self.feed = feed
        self.label_slot = label_slot
        self._slots = [s for s in feed.slots if s.is_used]
        self._all_slots = list(feed.slots)
        # slot name → task name for per-task label extraction
        self._task_by_slot = {slot: task for task, slot
                              in getattr(feed, "task_label_slots", ())}

    def parse_line(self, line: str) -> Optional[SlotRecord]:
        toks = line.split()
        if not toks:
            return None
        rec = SlotRecord()
        pos = 0
        if getattr(self.feed, "parse_ins_id", False):
            # parse_ins_id_ lines lead with the instance id string
            # (SlotRecordInMemoryDataFeed; feeds InputTable translation
            # and dump-field ins_id columns)
            rec.ins_id = toks[0]
            pos = 1
        u_idx = 0
        f_idx = 0
        try:
            for slot in self._all_slots:
                n = int(toks[pos])
                pos += 1
                vals = toks[pos:pos + n]
                if len(vals) != n:
                    raise ValueError(f"slot {slot.name}: expected {n} values")
                pos += n
                if (not slot.is_used and slot.name != self.label_slot
                        and slot.name not in self._task_by_slot):
                    continue
                if slot.type == "uint64":
                    task = self._task_by_slot.get(slot.name)
                    if task is not None and n >= 1:
                        rec.extra_labels[task] = int(vals[0])
                    if slot.is_used:
                        # an unused label slot must NOT consume a sparse
                        # slot ordinal (packer indexes by used-slot order)
                        rec.uint64_slots[u_idx] = np.array(
                            [int(v) for v in vals], dtype=np.uint64)
                        u_idx += 1
                else:
                    arr = np.array([float(v) for v in vals], dtype=np.float32)
                    if slot.name == self.label_slot and n >= 1:
                        rec.label = int(arr[0])
                    task = self._task_by_slot.get(slot.name)
                    if task is not None and n >= 1:
                        rec.extra_labels[task] = int(arr[0])
                    if slot.is_used:
                        rec.float_slots[f_idx] = arr
                        f_idx += 1
        except (ValueError, IndexError):
            return None  # malformed line dropped, like the reference parser
        return rec

    def parse_file(self, path: str) -> Iterator[SlotRecord]:
        """Stream records from a file. Honors the feed's `pipe_command`
        (SlotPaddleBoxDataFeed's pipe-command load path, data_feed.h:
        2119-2134: each file is piped through a user shell command before
        parsing) and transparently decompresses `.gz` inputs."""
        for line in self._open_lines(path):
            rec = self.parse_line(line)
            if rec is not None:
                yield rec

    def _open_lines(self, path: str) -> Iterator[str]:
        pipe = getattr(self.feed, "pipe_command", "")
        if pipe:
            import shlex
            import subprocess
            src = (open(path, "rb") if not path.endswith(".gz")
                   else None)
            cmd = (pipe if src is not None
                   else "zcat %s | %s" % (shlex.quote(path), pipe))
            proc = subprocess.Popen(
                cmd, shell=True, stdin=src,
                stdout=subprocess.PIPE, text=True)
            try:
                yield from proc.stdout
            finally:
                if src is not None:
                    src.close()
                proc.stdout.close()
                rc = proc.wait()
                # 141/-13 = SIGPIPE from the consumer stopping early (e.g.
                # a peeked record or an aborted load) — not a command error
                if rc not in (0, 141, -13):
                    raise IOError("pipe_command %r failed (rc=%d) on %s"
                                  % (pipe, rc, path))
            return
        if path.endswith(".gz"):
            import gzip
            with gzip.open(path, "rt") as f:
                yield from f
        else:
            with open(path, "r") as f:
                yield from f
