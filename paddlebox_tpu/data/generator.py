"""Synthetic CTR data generator.

Analog of the reference's test data tooling (python/paddle/fluid/tests/
unittests/ctr_dataset_reader.py): emits MultiSlot-format text files with a
learnable click signal so e2e tests/benchmarks can verify AUC lift, not just
plumbing. Each sparse slot draws feasigns from its own hash space; the click
probability depends on a hidden per-feasign weight, so models that learn
embeddings beat AUC 0.5 decisively.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

import numpy as np

from paddlebox_tpu.config.configs import DataFeedConfig, SlotConfig


def default_feed_config(num_slots: int = 8, batch_size: int = 256,
                        max_len: int = 4, dense_dim: int = 0,
                        conversion: bool = False) -> DataFeedConfig:
    slots: List[SlotConfig] = [SlotConfig("click", type="float", dim=1,
                                          is_used=False)]
    if conversion:
        # post-click conversion label for the ESMM cvr head
        slots.append(SlotConfig("label_cvr", type="float", dim=1,
                                is_used=False))
    for i in range(num_slots):
        slots.append(SlotConfig(f"slot_{i}", type="uint64", max_len=max_len))
    if dense_dim:
        slots.append(SlotConfig("dense", type="float", dim=dense_dim))
    return DataFeedConfig(
        slots=tuple(slots), batch_size=batch_size,
        task_label_slots=(("cvr", "label_cvr"),) if conversion else ())


def write_synthetic_ctr_files(
        out_dir: str, num_files: int = 4, lines_per_file: int = 1024,
        num_slots: int = 8, vocab_per_slot: int = 1000, max_len: int = 4,
        dense_dim: int = 0, seed: int = 0,
        conversion: bool = False) -> Tuple[List[str], DataFeedConfig]:
    """Returns (file list, matching DataFeedConfig).

    conversion=True additionally emits a `label_cvr` slot: a post-click
    conversion label with its OWN hidden feasign weights, so an ESMM cvr
    head trained on it is learnable and distinct from the click signal."""
    rng = np.random.RandomState(seed)
    os.makedirs(out_dir, exist_ok=True)
    # hidden per-slot feasign weights define the true click logit
    hidden = [rng.randn(vocab_per_slot) * 0.7 for _ in range(num_slots)]
    hidden_cvr = [rng.randn(vocab_per_slot) * 0.7 for _ in range(num_slots)]
    files = []
    for fi in range(num_files):
        path = os.path.join(out_dir, f"part-{fi:05d}.txt")
        with open(path, "w") as f:
            for _ in range(lines_per_file):
                logit = -0.7
                logit_cvr = 0.3
                toks: List[str] = []
                line_feas = []
                for si in range(num_slots):
                    n = rng.randint(1, max_len + 1)
                    feas = rng.randint(0, vocab_per_slot, n)
                    logit += hidden[si][feas].mean()
                    logit_cvr += hidden_cvr[si][feas].mean()
                    # globally unique feasign = slot_base + local id
                    line_feas.append((n, feas + si * vocab_per_slot))
                p = 1.0 / (1.0 + np.exp(-logit))
                click = int(rng.rand() < p)
                toks.append(f"1 {click}")
                if conversion:
                    p_cvr = 1.0 / (1.0 + np.exp(-logit_cvr))
                    conv = int(click and rng.rand() < p_cvr)
                    toks.append(f"1 {conv}")
                for n, feas in line_feas:
                    toks.append(str(n) + " " + " ".join(str(x) for x in feas))
                if dense_dim:
                    dvals = rng.randn(dense_dim) * 2.0 + 1.0
                    toks.append(str(dense_dim) + " "
                                + " ".join(f"{v:.4f}" for v in dvals))
                f.write(" ".join(toks) + "\n")
        files.append(path)
    feed = default_feed_config(num_slots, max_len=max_len,
                               dense_dim=dense_dim, conversion=conversion)
    return files, feed
