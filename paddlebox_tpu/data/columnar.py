"""Columnar record blocks: the zero-object data path.

The reference keeps per-instance SlotRecord objects pooled in a slab
allocator (SlotObjPool, data_feed.h:305) to dodge allocation churn. The
TPU-native pipeline goes further: the native parser emits whole files as
flat columnar arrays (keys + per-key slot/record ids, labels, dense), and
batches are packed by pure numpy slicing — no per-record Python objects
anywhere on the hot path.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from paddlebox_tpu.config.configs import DataFeedConfig
from paddlebox_tpu.data.packer import PackedBatch
from paddlebox_tpu.utils.stats import stat_add


@dataclasses.dataclass
class ColumnarBlock:
    """A set of records in struct-of-arrays form. Keys of record r live at
    keys[rec_offsets[r]:rec_offsets[r+1]] ordered by slot."""

    keys: np.ndarray        # [K] uint64
    key_slot: np.ndarray    # [K] int32
    labels: np.ndarray      # [N] int32
    rec_offsets: np.ndarray  # [N+1] int64
    dense: Optional[np.ndarray] = None  # [N, dense_dim] float32
    task_labels: Optional[dict] = None  # task → [N] int32

    @property
    def n_recs(self) -> int:
        return self.labels.shape[0]

    @property
    def n_keys(self) -> int:
        return self.keys.shape[0]

    @staticmethod
    def from_key_rec(keys, key_slot, key_rec, labels, dense=None,
                     task_labels=None) -> "ColumnarBlock":
        """From parser output where key_rec[i] is each key's record index
        (keys already grouped by record)."""
        n = labels.shape[0]
        counts = np.bincount(key_rec, minlength=n) if keys.size else \
            np.zeros(n, np.int64)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return ColumnarBlock(keys=keys, key_slot=key_slot, labels=labels,
                             rec_offsets=offsets, dense=dense,
                             task_labels=task_labels)

    def select(self, rec_idx: np.ndarray) -> "ColumnarBlock":
        """Sub-block of the given records, fully vectorized (the
        fancy-index split primitive of the block shuffle and any other
        record-subset consumer). Column arrays are fresh copies."""
        rec_idx = np.asarray(rec_idx, np.int64)
        starts = self.rec_offsets[rec_idx]
        counts = self.rec_offsets[rec_idx + 1] - starts
        flat = np.repeat(starts, counts) + _run_aranges(counts)
        offsets = np.zeros(rec_idx.size + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        dense = None if self.dense is None else self.dense[rec_idx]
        task_labels = None
        if self.task_labels is not None:
            task_labels = {t: c[rec_idx]
                           for t, c in self.task_labels.items()}
        return ColumnarBlock(keys=self.keys[flat],
                             key_slot=self.key_slot[flat],
                             labels=self.labels[rec_idx],
                             rec_offsets=offsets, dense=dense,
                             task_labels=task_labels)

    @staticmethod
    def concat(blocks: Sequence["ColumnarBlock"]) -> "ColumnarBlock":
        blocks = [b for b in blocks if b.n_recs]
        if not blocks:
            return ColumnarBlock(np.empty(0, np.uint64), np.empty(0, np.int32),
                                 np.empty(0, np.int32),
                                 np.zeros(1, np.int64), None)
        keys = np.concatenate([b.keys for b in blocks])
        key_slot = np.concatenate([b.key_slot for b in blocks])
        labels = np.concatenate([b.labels for b in blocks])
        offs = [blocks[0].rec_offsets]
        shift = blocks[0].rec_offsets[-1]
        for b in blocks[1:]:
            offs.append(b.rec_offsets[1:] + shift)
            shift += b.rec_offsets[-1]
        rec_offsets = np.concatenate(offs)
        dense = None
        if blocks[0].dense is not None:
            dense = np.concatenate([b.dense for b in blocks])
        task_labels = None
        if blocks[0].task_labels is not None:
            task_labels = {t: np.concatenate([b.task_labels[t]
                                              for b in blocks])
                           for t in blocks[0].task_labels}
        return ColumnarBlock(keys, key_slot, labels, rec_offsets, dense,
                             task_labels)


def pack_columnar(block: ColumnarBlock, rec_idx: np.ndarray,
                  feed: DataFeedConfig, kcap: int, num_slots: int,
                  max_lens: np.ndarray) -> PackedBatch:
    """Pack selected records into one static-shaped batch, fully vectorized.

    rec_idx: record indices for this batch (≤ batch_size).
    Truncates each (record, slot) run to the slot's max_len and the batch to
    kcap keys, counting drops (packer contract parity).
    """
    B = feed.batch_size
    n = min(rec_idx.shape[0], B)
    rec_idx = rec_idx[:n]
    starts = block.rec_offsets[rec_idx]
    ends = block.rec_offsets[rec_idx + 1]
    counts = (ends - starts).astype(np.int64)
    total = int(counts.sum())

    labels = np.zeros(B, dtype=np.int32)
    labels[:n] = block.labels[rec_idx]
    ins_valid = np.zeros(B, dtype=bool)
    ins_valid[:n] = True
    dense = None
    if block.dense is not None:
        dense = np.zeros((B, block.dense.shape[1]), np.float32)
        dense[:n] = block.dense[rec_idx]
    qvalues = np.zeros(B, dtype=np.float32)
    # presence keyed on the FEED config, not the block: a host whose file
    # shard parsed zero records must emit the same batch schema as its
    # peers (lockstep collectives; record-path packer parity)
    task_names = [t for t, _ in getattr(feed, "task_label_slots", ())]
    task_labels = None
    if task_names:
        task_labels = {}
        block_tl = block.task_labels or {}
        for t in task_names:
            arr = np.zeros(B, dtype=np.int32)
            col = block_tl.get(t)
            arr[:n] = col[rec_idx] if col is not None else labels[:n]
            task_labels[t] = arr

    stat_add("ingest_ins_packed", n)
    keys = np.zeros(kcap, dtype=np.uint64)
    slots = np.zeros(kcap, dtype=np.int32)
    # padding tail pinned to the last segment id: the native parser emits
    # keys per record in used-slot-ordinal order (slot_parser.cc config-order
    # loop), so the whole vector stays nondecreasing and seqpool may declare
    # indices_are_sorted (zero-masked padding leaves the last pool untouched)
    segments = np.full(kcap, B * num_slots - 1, dtype=np.int32)
    valid = np.zeros(kcap, dtype=bool)

    if total:
        # gather each batch record's key run: flat index expansion
        flat = np.repeat(starts, counts) + _run_aranges(counts)
        bkeys = block.keys[flat]
        bslots = block.key_slot[flat]
        brec = np.repeat(np.arange(n, dtype=np.int64), counts)
        # per-(record, slot) ordinal for max_len truncation
        group = brec * num_slots + bslots
        ordinal = _group_cumcount(group)
        keep = ordinal < max_lens[bslots]
        dropped = int((~keep).sum())
        bkeys, bslots, brec = bkeys[keep], bslots[keep], brec[keep]
        w = bkeys.shape[0]
        if w > kcap:
            dropped += w - kcap
            bkeys, bslots, brec = bkeys[:kcap], bslots[:kcap], brec[:kcap]
            w = kcap
        if dropped:
            stat_add("packer_keys_dropped", dropped)
        seg = (brec * num_slots + bslots).astype(np.int32)
        # the sorted-segments contract is load-bearing (seqpool declares
        # indices_are_sorted): built-in parsers emit config order, but a
        # user plugin .so may not — repair with a stable group sort
        if seg.size and (np.diff(seg) < 0).any():
            order = np.argsort(seg, kind="stable")
            bkeys, bslots, seg = bkeys[order], bslots[order], seg[order]
        keys[:w] = bkeys
        slots[:w] = bslots
        segments[:w] = seg
        valid[:w] = True

    return PackedBatch(keys=keys, slots=slots, segments=segments, valid=valid,
                       labels=labels, ins_valid=ins_valid, dense=dense,
                       n_ins=n, qvalues=qvalues,
                       cmatch_rank=np.zeros(B, dtype=np.uint64),
                       task_labels=task_labels)


def _run_aranges(counts: np.ndarray) -> np.ndarray:
    """[0..c0), [0..c1), ... concatenated (vectorized)."""
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, np.int64)
    ends = np.cumsum(counts)
    idx = np.arange(total, dtype=np.int64)
    return idx - np.repeat(ends - counts, counts)


def _group_cumcount(group: np.ndarray) -> np.ndarray:
    """Ordinal of each element within its (already contiguous) group."""
    if group.size == 0:
        return np.empty(0, np.int64)
    change = np.empty(group.size, dtype=bool)
    change[0] = True
    np.not_equal(group[1:], group[:-1], out=change[1:])
    starts = np.nonzero(change)[0]
    idx = np.arange(group.size, dtype=np.int64)
    return idx - np.repeat(starts, np.diff(np.append(starts, group.size)))
