"""Static-shape batch packer.

Analog of MiniBatchGpuPack (paddle/fluid/framework/data_feed.h:519-680 +
data_feed.cu:1210-1388): the reference concatenates a batch's CSR slot values
into pinned buffers, H2Ds them and scatters into per-slot LoD tensors. The
TPU redesign flattens every sparse key of the batch into ONE fixed-capacity
key vector plus a segment id per key (instance*num_slots + slot) — XLA gets
fully static shapes and the model side pools with one segment-sum
(ops/seqpool.py) instead of per-slot LoD tensors.

Capacity overflow policy: keys beyond per-slot max_len are dropped (counted
in stats), mirroring the reference's used-slot truncation behavior.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from paddlebox_tpu.config.configs import DataFeedConfig
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.utils.stats import stat_add


@dataclasses.dataclass
class PackedBatch:
    """One static-shaped device-ready batch."""

    keys: np.ndarray        # [KCAP] uint64, padding = 0
    slots: np.ndarray       # [KCAP] int32 slot index per key
    segments: np.ndarray    # [KCAP] int32 = ins*num_slots + slot
    valid: np.ndarray       # [KCAP] bool
    labels: np.ndarray      # [B] int32 (padded instances = 0)
    ins_valid: np.ndarray   # [B] bool — False for padded instances
    dense: Optional[np.ndarray]  # [B, dense_dim] float32 or None
    n_ins: int              # real instances in the batch
    # join-phase extras
    rank_offset: Optional[np.ndarray] = None  # [B, 2*max_rank+1] int32
    qvalues: Optional[np.ndarray] = None      # [B] float32
    ins_ids: Optional[List[str]] = None       # [n_ins] (dump-field lines)
    # (cmatch << 32) | (rank & 0xff) per instance — the encoded
    # cmatch_rank metric var (metrics.h parse_cmatch_rank)
    cmatch_rank: Optional[np.ndarray] = None  # [B] uint64
    # task name → [B] int32 labels (tasks fall back to `labels`)
    task_labels: Optional[dict] = None
    # per-instance side-table row offset (lookup_input / pull_cache_value
    # consumers; see BatchPacker input_table/use_cache_idx)
    aux_offset: Optional[np.ndarray] = None  # [B] int32

    @property
    def batch_size(self) -> int:
        return self.labels.shape[0]


class BatchPacker:
    def __init__(self, feed: DataFeedConfig, max_rank: int = 3,
                 input_table=None, use_cache_idx: bool = False) -> None:
        """input_table: embedding.side_tables.InputTable — when set, each
        packed instance's ins_id translates to an aux-row offset at pack
        time (the InputTableDataFeed role, data_feed.h:2221-2252: the
        feed, not the model, resolves string keys; misses map to the zero
        row at offset 0). use_cache_idx: carry SlotRecord.cache_idx as
        the offset instead (the pull_cache_value index source,
        GpuReplicaCache box_wrapper.h:62-121). Both emit the SAME
        `aux_offset` batch leaf — on device each is one gather from a
        replicated side table."""
        self.feed = feed
        self.sparse_slots = feed.used_sparse_slots()
        self.dense_slots = feed.used_dense_slots()
        self.num_slots = len(self.sparse_slots)
        self.dense_dim = sum(s.dim for s in self.dense_slots)
        self.batch_size = feed.batch_size
        self.kcap = feed.key_capacity()
        self.max_rank = max_rank
        if input_table is not None and use_cache_idx:
            raise ValueError("input_table and use_cache_idx are exclusive "
                             "aux-offset sources")
        self.input_table = input_table
        self.use_cache_idx = use_cache_idx

    def pack(self, records: Sequence[SlotRecord],
             with_rank_offset: Optional[bool] = None) -> PackedBatch:
        if with_rank_offset is None:
            with_rank_offset = self.feed.rank_offset
        B = self.batch_size
        n = min(len(records), B)
        keys = np.zeros(self.kcap, dtype=np.uint64)
        slots = np.zeros(self.kcap, dtype=np.int32)
        # padding tail pinned to the LAST segment id so the whole segment
        # vector is nondecreasing (CSR write order is instance-major,
        # slot-ascending) — seqpool can then declare indices_are_sorted;
        # padding contributions are zero-masked by `valid` so the last
        # segment's pool is unaffected
        segments = np.full(self.kcap, B * self.num_slots - 1, dtype=np.int32)
        valid = np.zeros(self.kcap, dtype=bool)
        labels = np.zeros(B, dtype=np.int32)
        ins_valid = np.zeros(B, dtype=bool)
        dense = (np.zeros((B, self.dense_dim), dtype=np.float32)
                 if self.dense_dim else None)
        qvalues = np.zeros(B, dtype=np.float32)
        cmatch_rank = np.zeros(B, dtype=np.uint64)
        task_names = [t for t, _ in getattr(self.feed, "task_label_slots",
                                            ())]
        task_labels = ({t: np.zeros(B, dtype=np.int32) for t in task_names}
                       if task_names else None)

        w = 0
        dropped = 0
        for i in range(n):
            rec = records[i]
            labels[i] = rec.label
            ins_valid[i] = True
            qvalues[i] = rec.qvalue
            cmatch_rank[i] = ((np.uint64(rec.cmatch) << np.uint64(32))
                              | np.uint64(rec.rank & 0xFF))
            if task_labels is not None:
                for t in task_names:
                    task_labels[t][i] = rec.extra_labels.get(t, rec.label)
            for si, slot_cfg in enumerate(self.sparse_slots):
                vals = rec.uint64_slots.get(si)
                if vals is None or vals.size == 0:
                    continue
                take = min(vals.size, slot_cfg.max_len, self.kcap - w)
                dropped += vals.size - take
                if take <= 0:
                    continue
                keys[w:w + take] = vals[:take]
                slots[w:w + take] = si
                segments[w:w + take] = i * self.num_slots + si
                valid[w:w + take] = True
                w += take
            if dense is not None:
                off = 0
                for fi, slot_cfg in enumerate(self.dense_slots):
                    vals = rec.float_slots.get(fi)
                    d = slot_cfg.dim
                    if vals is not None:
                        m = min(vals.size, d)
                        dense[i, off:off + m] = vals[:m]
                    off += d
        if dropped:
            stat_add("packer_keys_dropped", dropped)
        stat_add("ingest_ins_packed", n)
        batch = PackedBatch(keys=keys, slots=slots, segments=segments,
                            valid=valid, labels=labels, ins_valid=ins_valid,
                            dense=dense, n_ins=n, qvalues=qvalues,
                            ins_ids=[r.ins_id for r in records[:n]],
                            cmatch_rank=cmatch_rank,
                            task_labels=task_labels)
        if self.input_table is not None:
            aux = np.zeros(B, dtype=np.int32)
            for i in range(n):
                aux[i] = self.input_table.get_index_offset(
                    records[i].ins_id)
            batch.aux_offset = aux
        elif self.use_cache_idx:
            # unlike InputTable, ReplicaCache has NO reserved zero row —
            # index 0 is the first real cached embedding, so a record
            # without an index must fail loudly rather than silently
            # train on another record's cache row
            aux = np.zeros(B, dtype=np.int32)
            for i in range(n):
                ci = records[i].cache_idx
                if ci < 0:
                    raise ValueError(
                        f"use_cache_idx: record {i} (ins_id="
                        f"{records[i].ins_id!r}) has no cache_idx — every "
                        "instance needs a ReplicaCache row index")
                aux[i] = ci
            batch.aux_offset = aux
        if with_rank_offset:
            batch.rank_offset = self._build_rank_offset(records[:n], B)
        return batch

    def _build_rank_offset(self, records: Sequence[SlotRecord],
                           B: int) -> np.ndarray:
        """pv rank matrix with CopyRankOffsetKernel parity
        (data_feed.cu:1319-1369): col 0 = own effective rank (cmatch must be
        a join channel and 0 < rank <= max_rank, else -1); then
        (rank_of_peer, row_of_peer) pairs indexed by the peer's rank, peers
        including the instance itself, grouped by search_id."""
        from paddlebox_tpu.data.pv import _JOIN_CMATCH
        mr = self.max_rank
        out = -np.ones((B, 2 * mr + 1), dtype=np.int32)
        by_pv: dict = {}
        eff = []
        for row, rec in enumerate(records):
            by_pv.setdefault(rec.search_id, []).append(row)
            eff.append(rec.rank if (rec.cmatch in _JOIN_CMATCH
                                    and 0 < rec.rank <= mr) else -1)
        for row, rec in enumerate(records):
            out[row, 0] = eff[row]
            if eff[row] <= 0:
                continue
            for peer in by_pv[rec.search_id]:
                if eff[peer] > 0:
                    m = eff[peer] - 1
                    out[row, 2 * m + 1] = records[peer].rank
                    out[row, 2 * m + 2] = peer
        return out
