from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.data.parser import MultiSlotParser
from paddlebox_tpu.data.packer import PackedBatch, BatchPacker
from paddlebox_tpu.data.columnar import ColumnarBlock
from paddlebox_tpu.data.dataset import BoxDataset
from paddlebox_tpu.data.generator import write_synthetic_ctr_files
from paddlebox_tpu.data.streaming import (DirectoryWatcher, FileLedger,
                                          MicroWindow, SocketFeedServer,
                                          StreamingDataset)

__all__ = [
    "SlotRecord",
    "MultiSlotParser",
    "PackedBatch",
    "BatchPacker",
    "ColumnarBlock",
    "BoxDataset",
    "write_synthetic_ctr_files",
    "DirectoryWatcher",
    "FileLedger",
    "MicroWindow",
    "SocketFeedServer",
    "StreamingDataset",
]
