"""pv (search-session) instance grouping + rank-offset feed.

TPU-native PadBoxSlotDataset::PreprocessInstance (data_set.cc:2646-2686) and
SlotPaddleBoxDataFeed::CopyRankOffset / CopyRankOffsetKernel
(data_feed.cu:1319-1385): join-phase models group the batch's ad instances by
search session (pv) and feed a per-instance rank-offset matrix that tells
rank_attention which peer ads share the pv and where they sit in the batch.

The rank-offset row format consumed by ops/rank_attention.py:
    col 0:      this ad's rank (1..max_rank) or -1 if invalid
    col 2m+1:   rank of the peer with rank m+1 in the same pv (or -1)
    col 2m+2:   batch row of that peer (or -1)
A rank participates only when its cmatch tag is 222/223 and
0 < rank <= max_rank (the join-phase ad channels, data_feed.cu:1331-1335).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecord

_JOIN_CMATCH = (222, 223)


def preprocess_instance(records: Sequence[SlotRecord],
                        merge_by_sid: bool = True) -> List[List[int]]:
    """Group record indices into pv instances (PreprocessInstance,
    data_set.cc:2646): sort by search_id, one pv per distinct search_id
    (or one pv per record when merge_by_sid is False)."""
    order = sorted(range(len(records)), key=lambda i: records[i].search_id)
    if not merge_by_sid:
        return [[i] for i in order]
    pvs: List[List[int]] = []
    last_sid = None
    for i in order:
        sid = records[i].search_id
        if last_sid is None or sid != last_sid:
            pvs.append([i])
            last_sid = sid
        else:
            pvs[-1].append(i)
    return pvs


def build_rank_offset(ranks: np.ndarray, cmatchs: np.ndarray,
                      pv_offsets: np.ndarray, max_rank: int = 3) -> np.ndarray:
    """CopyRankOffsetKernel (data_feed.cu:1319-1369) on host.

    ranks/cmatchs: [N] per-ad (batch order, pvs contiguous);
    pv_offsets: [P+1] CSR offsets of pvs into the ad axis.
    Returns [N, 1+2*max_rank] int32, -1 filled.
    """
    n = int(ranks.shape[0])
    cols = 2 * max_rank + 1
    mat = np.full((n, cols), -1, dtype=np.int32)
    eff = np.where(
        np.isin(cmatchs, _JOIN_CMATCH) & (ranks > 0) & (ranks <= max_rank),
        ranks, -1).astype(np.int32)
    for p in range(len(pv_offsets) - 1):
        lo, hi = int(pv_offsets[p]), int(pv_offsets[p + 1])
        mat[lo:hi, 0] = eff[lo:hi]
        members = [(int(eff[k]), k) for k in range(lo, hi) if eff[k] > 0]
        for j in range(lo, hi):
            if eff[j] <= 0:
                continue
            for fast_rank, k in members:
                m = fast_rank - 1
                mat[j, 2 * m + 1] = ranks[k]
                mat[j, 2 * m + 2] = k
    return mat


def pack_pv_batch(records: Sequence[SlotRecord], pvs: List[List[int]],
                  max_rank: int = 3) -> Tuple[List[int], np.ndarray]:
    """Order a batch's records pv-contiguously and build its rank-offset
    matrix (the join-phase feed path, data_feed.cc:3217-3238).

    Returns (record order, rank_offset [N, 1+2*max_rank])."""
    order: List[int] = []
    pv_offsets = [0]
    for pv in pvs:
        order.extend(pv)
        pv_offsets.append(len(order))
    ranks = np.array([records[i].rank for i in order], np.int32)
    cmatchs = np.array([records[i].cmatch for i in order], np.int32)
    mat = build_rank_offset(ranks, cmatchs,
                            np.asarray(pv_offsets, np.int64), max_rank)
    return order, mat
