"""Custom parser plugins.

Analog of DLManager/CustomParser (paddle/fluid/framework/data_feed.h:
682-780 + `ISlotParser`, h:1963): the reference dlopens user `.so` parsers
selected per file format by the DataFeedDesc. Here a plugin is either

  * a python module file exporting ``make_parser(feed) -> parser`` where
    the parser has ``parse_file(path) -> Iterator[SlotRecord]`` (the
    MultiSlotParser contract), or
  * a native shared object honoring the columnar slot-parser C ABI
    (native/slot_parser.cc), loaded through NativeMultiSlotParser.
"""

from __future__ import annotations

import importlib.util
import os
from typing import Any

from paddlebox_tpu.config.configs import DataFeedConfig


def load_parser_plugin(path: str, feed: DataFeedConfig) -> Any:
    """Load a parser from a plugin file (LoadParserSo analog)."""
    if path.endswith(".py"):
        spec = importlib.util.spec_from_file_location(
            "pbtpu_parser_plugin_%s" % os.path.basename(path)[:-3], path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        if not hasattr(mod, "make_parser"):
            raise AttributeError(
                "parser plugin %s must export make_parser(feed)" % path)
        parser = mod.make_parser(feed)
        if not hasattr(parser, "parse_file"):
            raise AttributeError(
                "plugin parser must provide parse_file(path)")
        return parser
    if path.endswith(".so"):
        from paddlebox_tpu.data.native_parser import NativeMultiSlotParser
        return NativeMultiSlotParser(feed, lib_path=path)
    raise ValueError("parser plugin must be a .py module or native .so: "
                     + path)
