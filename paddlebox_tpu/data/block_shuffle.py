"""Columnar block shuffle: codec + vectorized hash routing (round 17).

The cross-host instance shuffle (data/shuffle.py, the PaddleShuffler
analog) used to move per-record Python objects: every instance paid a
struct-pack serialize loop on the sender and a mirror loop on the
receiver — the one surviving per-record hot path after the zero-object
columnar parse (data/columnar.py). Here the shuffle unit becomes the
whole `ColumnarBlock`:

  * codec    — `serialize_block`/`deserialize_block`: one fixed header +
               the raw column bytes (whole-array `tobytes`/`frombuffer`,
               zero per-record work; receive side is zero-copy read-only
               views over the frame buffer).
  * routing  — `block_shuffle_dests`: the per-record destination hash,
               vectorized over `rec_offsets` with ONE
               `np.bitwise_xor.reduceat` — bit-parity with
               `SlotRecord.shuffle_hash()` (same XOR-of-feasigns mod
               0x7FFFFFFF, label fallback for key-less records), pinned
               by tests against the record oracle.
  * split    — `split_block`: fancy-index split of one parsed block into
               per-destination sub-blocks (`ColumnarBlock.select`).

`records_to_block` is the record-path oracle converter (per-record loop,
NOT a hot path): it reproduces the native parser's column conventions so
parity tests can compare the two shuffle codecs record for record.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence

import numpy as np

from paddlebox_tpu.config.configs import DataFeedConfig
from paddlebox_tpu.data.columnar import ColumnarBlock
from paddlebox_tpu.data.slot_record import SlotRecord

#: frame magic ("PBXB") — sniffed against the record codec's "PBXR" by
#: ShufflerBase._deliver so one transport carries either frame kind
BLOCK_MAGIC = 0x50425842
_VERSION = 1
# magic, version, n_recs, n_keys, dense_dim (-1 = none), n_tasks
_HDR = struct.Struct("<IIqqii")
_HASH_MOD = np.uint64(0x7FFFFFFF)


# ---------------------------------------------------------------------------
# codec: header + raw column bytes
# ---------------------------------------------------------------------------


def serialize_block(block: ColumnarBlock) -> bytes:
    """Header + raw column bytes; no per-record loop anywhere."""
    dense = block.dense
    tasks = sorted(block.task_labels) if block.task_labels else []
    parts: List[bytes] = [_HDR.pack(
        BLOCK_MAGIC, _VERSION, block.n_recs, block.n_keys,
        -1 if dense is None else int(dense.shape[1]), len(tasks))]
    for t in tasks:
        tb = t.encode("utf-8")
        parts.append(struct.pack("<H", len(tb)))
        parts.append(tb)
    parts.append(np.ascontiguousarray(block.labels, np.int32).tobytes())
    parts.append(np.ascontiguousarray(block.rec_offsets, np.int64).tobytes())
    parts.append(np.ascontiguousarray(block.keys, np.uint64).tobytes())
    parts.append(np.ascontiguousarray(block.key_slot, np.int32).tobytes())
    if dense is not None:
        parts.append(np.ascontiguousarray(dense, np.float32).tobytes())
    for t in tasks:
        parts.append(np.ascontiguousarray(block.task_labels[t],
                                          np.int32).tobytes())
    return b"".join(parts)


def deserialize_block(buf: bytes) -> ColumnarBlock:
    """Inverse of serialize_block. Columns are ZERO-COPY read-only views
    over `buf` — every downstream consumer (concat, pack_columnar,
    split_batches) only reads or copies-by-fancy-index."""
    magic, ver, n_recs, n_keys, dense_dim, n_tasks = _HDR.unpack_from(buf, 0)
    if magic != BLOCK_MAGIC:
        raise ValueError("bad block shuffle magic 0x%x" % magic)
    if ver != _VERSION:
        raise ValueError("unsupported block codec version %d" % ver)
    off = _HDR.size
    tasks: List[str] = []
    for _ in range(n_tasks):
        (tlen,) = struct.unpack_from("<H", buf, off)
        off += 2
        tasks.append(buf[off:off + tlen].decode("utf-8"))
        off += tlen

    def arr(dt, count):
        nonlocal off
        a = np.frombuffer(buf, dtype=dt, count=count, offset=off)
        off += a.nbytes
        return a

    labels = arr(np.int32, n_recs)
    rec_offsets = arr(np.int64, n_recs + 1)
    keys = arr(np.uint64, n_keys)
    key_slot = arr(np.int32, n_keys)
    dense = None
    if dense_dim >= 0:
        dense = arr(np.float32, n_recs * dense_dim).reshape(n_recs,
                                                            dense_dim)
    task_labels = None
    if tasks:
        task_labels = {t: arr(np.int32, n_recs) for t in tasks}
    return ColumnarBlock(keys=keys, key_slot=key_slot, labels=labels,
                         rec_offsets=rec_offsets, dense=dense,
                         task_labels=task_labels)


# ---------------------------------------------------------------------------
# routing: vectorized shuffle hash + fancy-index split
# ---------------------------------------------------------------------------


def block_record_hash(block: ColumnarBlock) -> np.ndarray:
    """[N] int64 per-record shuffle hash, bit-parity with
    `SlotRecord.shuffle_hash()`: XOR of the record's feasigns mod
    0x7FFFFFFF; a record with zero keys hashes to its label. ONE
    reduceat over the key column — nonempty records' start offsets are
    exactly the segment boundaries (empty records contribute no keys)."""
    h = block.labels.astype(np.int64)
    if block.n_keys:
        counts = np.diff(block.rec_offsets)
        nz = counts > 0
        starts = block.rec_offsets[:-1][nz]
        xr = np.bitwise_xor.reduceat(block.keys, starts)
        h[nz] = (xr % _HASH_MOD).astype(np.int64)
    return h


def block_shuffle_dests(block: ColumnarBlock, world: int) -> np.ndarray:
    """[N] int64 destination rank per record (general_shuffle_func
    analog, data_set.cc:2420-2436, vectorized)."""
    return block_record_hash(block) % int(world)


def split_block(block: ColumnarBlock, dests: np.ndarray,
                world: int) -> List[Optional[ColumnarBlock]]:
    """Split one block into per-destination sub-blocks by fancy index;
    empty destinations map to None (nothing travels)."""
    out: List[Optional[ColumnarBlock]] = []
    for d in range(world):
        idx = np.nonzero(dests == d)[0]
        out.append(block.select(idx) if idx.size else None)
    return out


# ---------------------------------------------------------------------------
# record-path oracle converter (NOT a hot path)
# ---------------------------------------------------------------------------


def records_to_block(recs: Sequence[SlotRecord],
                     feed: DataFeedConfig) -> ColumnarBlock:
    """SlotRecords → ColumnarBlock with the native parser's column
    conventions (keys per record in used-slot-ordinal order, dense slots
    concatenated in config order and dim-padded, task labels falling
    back to the click label). Per-record Python loop — the parity-test
    oracle and archive-compat converter, never the production parse."""
    sparse = feed.used_sparse_slots()
    dense_slots = feed.used_dense_slots()
    dense_dim = sum(s.dim for s in dense_slots)
    task_names = [t for t, _ in getattr(feed, "task_label_slots", ())]
    n = len(recs)
    labels = np.zeros(n, np.int32)
    offsets = np.zeros(n + 1, np.int64)
    dense = np.zeros((n, dense_dim), np.float32) if dense_dim else None
    tls = {t: np.zeros(n, np.int32) for t in task_names} if task_names \
        else None
    key_parts: List[np.ndarray] = []
    slot_parts: List[np.ndarray] = []
    for i, r in enumerate(recs):
        labels[i] = r.label
        cnt = 0
        for si in range(len(sparse)):
            v = r.uint64_slots.get(si)
            if v is None or v.size == 0:
                continue
            key_parts.append(np.ascontiguousarray(v, np.uint64))
            slot_parts.append(np.full(v.size, si, np.int32))
            cnt += v.size
        offsets[i + 1] = offsets[i] + cnt
        if dense is not None:
            off = 0
            for fi, s in enumerate(dense_slots):
                v = r.float_slots.get(fi)
                if v is not None:
                    m = min(v.size, s.dim)
                    dense[i, off:off + m] = v[:m]
                off += s.dim
        if tls is not None:
            for t in task_names:
                tls[t][i] = r.extra_labels.get(t, r.label)
    keys = (np.concatenate(key_parts) if key_parts
            else np.empty(0, np.uint64))
    key_slot = (np.concatenate(slot_parts) if slot_parts
                else np.empty(0, np.int32))
    return ColumnarBlock(keys=keys, key_slot=key_slot, labels=labels,
                         rec_offsets=offsets, dense=dense, task_labels=tls)


def block_to_records(block: ColumnarBlock,
                     feed: DataFeedConfig) -> List[SlotRecord]:
    """Inverse of records_to_block (per-record loop, NOT a hot path):
    the codec-mix compat converter — a record-path pass receiving block
    frames from a columnar peer degrades to this instead of dying.
    Fields the block codec does not carry (ins_id, qvalue, pv rank/
    cmatch/search_id, cache_idx) come back at their defaults, exactly
    the fields whose consumers force the record path at dataset
    construction anyway."""
    dense_slots = feed.used_dense_slots()
    tasks = sorted(block.task_labels) if block.task_labels else []
    out: List[SlotRecord] = []
    for r in range(block.n_recs):
        lo, hi = int(block.rec_offsets[r]), int(block.rec_offsets[r + 1])
        slots = block.key_slot[lo:hi]
        u64 = {int(s): block.keys[lo:hi][slots == s].copy()
               for s in np.unique(slots)}
        f32 = {}
        if block.dense is not None:
            off = 0
            for fi, s in enumerate(dense_slots):
                f32[fi] = block.dense[r, off:off + s.dim].copy()
                off += s.dim
        extra = {t: int(block.task_labels[t][r]) for t in tasks}
        out.append(SlotRecord(label=int(block.labels[r]), uint64_slots=u64,
                              float_slots=f32, extra_labels=extra))
    return out
