"""Binary archive disk spill for pass data.

Analog of the reference's pass disk-spill path: `PreLoadIntoDisk` /
`DumpIntoDisk` / `LoadIntoDiskedFile` (data_set.cc:2090-2215) writing
`BinaryArchive`-serialized SlotRecord batches (framework/archive.h) to
rotating shard files, so a pass larger than host RAM streams from local
disk. Files are self-describing (block magic + length) and are accepted
transparently by `BoxDataset` read workers in place of text inputs.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Sequence

from paddlebox_tpu.config import flags
from paddlebox_tpu.data.shuffle import deserialize_records, serialize_records
from paddlebox_tpu.data.slot_record import SlotRecord

_BLOCK_MAGIC = 0x50425841  # "PBXA"
_BLOCK_HDR = struct.Struct("<II")  # magic, payload_len


def is_archive(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            head = f.read(4)
    except OSError:
        return False
    return (len(head) == 4
            and struct.unpack("<I", head)[0] == _BLOCK_MAGIC)


class BinaryArchiveWriter:
    """Rotating-shard archive writer (BinaryArchiveWriter,
    data_set.cc:2090; rotation cap mirrors the dump subsystem's 2GB files,
    boxps_trainer.cc:112-163)."""

    def __init__(self, prefix: str, max_bytes: int = 0):
        self.prefix = prefix
        self.max_bytes = max_bytes or flags.get_flag("dump_file_max_bytes")
        self._file = None
        self._file_bytes = 0
        self._file_idx = 0
        self.files: List[str] = []
        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)

    def _rotate(self) -> None:
        if self._file is not None:
            self._file.close()
        path = "%s-%05d.bin" % (self.prefix, self._file_idx)
        self._file_idx += 1
        self._file = open(path, "wb")
        self._file_bytes = 0
        self.files.append(path)

    def write_records(self, recs: Sequence[SlotRecord]) -> None:
        if not recs:
            return
        payload = serialize_records(recs)
        if self._file is None or (
                self._file_bytes
                and self._file_bytes + len(payload) > self.max_bytes):
            self._rotate()
        self._file.write(_BLOCK_HDR.pack(_BLOCK_MAGIC, len(payload)))
        self._file.write(payload)
        self._file_bytes += _BLOCK_HDR.size + len(payload)

    def close(self) -> List[str]:
        if self._file is not None:
            self._file.close()
            self._file = None
        return self.files


def read_archive(path: str) -> Iterator[List[SlotRecord]]:
    """Yield record batches from one archive shard."""
    with open(path, "rb") as f:
        while True:
            hdr = f.read(_BLOCK_HDR.size)
            if not hdr:
                return
            if len(hdr) < _BLOCK_HDR.size:
                raise IOError("truncated archive block header in " + path)
            magic, length = _BLOCK_HDR.unpack(hdr)
            if magic != _BLOCK_MAGIC:
                raise IOError("bad archive magic 0x%x in %s" % (magic, path))
            payload = f.read(length)
            if len(payload) < length:
                raise IOError("truncated archive block in " + path)
            yield deserialize_records(payload)
