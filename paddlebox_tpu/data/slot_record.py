"""Slot-record instance model.

Analog of SlotRecordObject/SlotValues (paddle/fluid/framework/data_feed.h:
97-470): one training instance = label + per-slot uint64 feasign lists +
per-slot float features, stored compactly. The reference pools these in a
slab allocator (SlotObjPool) to dodge malloc churn; in Python the pooling
burden falls on the columnar batch path (records are short-lived and the
C++ parser emits columnar arrays directly), so this class stays a plain
__slots__ struct.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class SlotRecord:
    __slots__ = ("label", "uint64_slots", "float_slots", "ins_id", "rank",
                 "cmatch", "qvalue", "search_id", "extra_labels",
                 "cache_idx")

    def __init__(self, label: int = 0,
                 uint64_slots: Optional[Dict[int, np.ndarray]] = None,
                 float_slots: Optional[Dict[int, np.ndarray]] = None,
                 ins_id: str = "", rank: int = 0, cmatch: int = 0,
                 qvalue: float = 0.0, search_id: int = 0,
                 extra_labels: Optional[Dict[str, int]] = None,
                 cache_idx: int = -1) -> None:
        self.label = label
        # slot index (position in feed config) → values
        self.uint64_slots = uint64_slots or {}
        self.float_slots = float_slots or {}
        self.ins_id = ins_id
        self.rank = rank      # pv join-phase rank position
        self.cmatch = cmatch  # channel-match tag for cmatch-rank metrics
        self.qvalue = qvalue  # PCOC q-value
        self.search_id = search_id  # pv (search-session) grouping key
        # task name → label for multi-task heads (conversion/pay/...);
        # tasks absent here train on the primary click label
        self.extra_labels = extra_labels or {}
        # replica-cache row index for pull_cache_value consumers
        # (GpuReplicaCache, box_wrapper.h:62-121); -1 = none
        self.cache_idx = cache_idx

    def all_keys(self) -> np.ndarray:
        if not self.uint64_slots:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(list(self.uint64_slots.values()))

    def shuffle_hash(self) -> int:
        """Stable hash for cross-host instance shuffle routing
        (general_shuffle_func analog, data_set.cc:2420-2436)."""
        keys = self.all_keys()
        if keys.size == 0:
            return self.label
        # cheap order-independent mix
        return int(np.bitwise_xor.reduce(keys) % np.uint64(0x7FFFFFFF))
