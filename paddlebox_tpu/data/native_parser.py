"""ctypes wrapper over the native MultiSlot parser → ColumnarBlock."""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from paddlebox_tpu.config.configs import DataFeedConfig
from paddlebox_tpu.data.columnar import ColumnarBlock
from paddlebox_tpu.native import get_lib
from paddlebox_tpu.utils.stats import stat_add


class NativeMultiSlotParser:
    """Same format contract as data.parser.MultiSlotParser, columnar output.

    Raises RuntimeError at construction when the native lib is unavailable —
    callers fall back to the Python parser.
    """

    def __init__(self, feed: DataFeedConfig, label_slot: str = "click",
                 lib_path: str = None) -> None:
        if lib_path is not None:
            from paddlebox_tpu.native.build import load_lib
            lib = load_lib(lib_path)
        else:
            lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.feed = feed
        slots = list(feed.slots)
        self._slot_types = np.array(
            [0 if s.type == "uint64" else 1 for s in slots], np.int32)
        self._used = np.array([1 if s.is_used else 0 for s in slots], np.int32)
        self._dense_dims = np.array([s.dim for s in slots], np.int32)
        name_to_idx = {s.name: i for i, s in enumerate(slots)}
        self._label_idx = name_to_idx.get(label_slot, -1)
        # per-task label slot indices (task_label_slots config); needs the
        # extended native entry
        self._task_names = []
        task_idx = []
        for task, slot_name in getattr(feed, "task_label_slots", ()):
            if slot_name not in name_to_idx:
                raise ValueError(f"task label slot {slot_name!r} not in feed")
            self._task_names.append(task)
            task_idx.append(name_to_idx[slot_name])
        self._task_idx = np.asarray(task_idx, np.int32)
        if self._task_names and not hasattr(lib, "psr_parse_file2"):
            raise RuntimeError(
                "native parser lacks psr_parse_file2 (task labels)")

    def parse_file_columnar(self, path: str) -> ColumnarBlock:
        lib = self._lib
        c = ctypes
        if self._task_names:
            handle = lib.psr_parse_file2(
                path.encode(),
                self._slot_types.ctypes.data_as(c.POINTER(c.c_int32)),
                self._used.ctypes.data_as(c.POINTER(c.c_int32)),
                self._dense_dims.ctypes.data_as(c.POINTER(c.c_int32)),
                c.c_int32(self._slot_types.size), c.c_int32(self._label_idx),
                self._task_idx.ctypes.data_as(c.POINTER(c.c_int32)),
                c.c_int32(len(self._task_names)))
        else:
            handle = lib.psr_parse_file(
                path.encode(),
                self._slot_types.ctypes.data_as(c.POINTER(c.c_int32)),
                self._used.ctypes.data_as(c.POINTER(c.c_int32)),
                self._dense_dims.ctypes.data_as(c.POINTER(c.c_int32)),
                c.c_int32(self._slot_types.size), c.c_int32(self._label_idx))
        if not handle:
            raise FileNotFoundError(path)
        try:
            n_keys = lib.psr_n_keys(handle)
            n_recs = lib.psr_n_recs(handle)
            n_bad = lib.psr_n_bad(handle)
            dense_dim = lib.psr_dense_dim(handle)
            if n_bad:
                stat_add("parser_bad_lines", int(n_bad))

            def arr(ptr, n, dt):
                if n == 0 or not ptr:
                    return np.empty(0, dt)
                return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dt,
                                                                     copy=True)

            keys = arr(lib.psr_keys(handle), n_keys, np.uint64)
            key_slot = arr(lib.psr_key_slot(handle), n_keys, np.int32)
            key_rec = arr(lib.psr_key_rec(handle), n_keys, np.int64)
            labels = arr(lib.psr_labels(handle), n_recs, np.int32)
            dense = None
            if dense_dim and n_recs:
                dense = np.ctypeslib.as_array(
                    lib.psr_dense(handle),
                    shape=(n_recs, dense_dim)).astype(np.float32, copy=True)
            task_labels = None
            if self._task_names and n_recs:
                tl = np.ctypeslib.as_array(
                    lib.psr_task_labels(handle),
                    shape=(n_recs, len(self._task_names))).astype(
                        np.int32, copy=True)
                task_labels = {t: tl[:, i]
                               for i, t in enumerate(self._task_names)}
            return ColumnarBlock.from_key_rec(keys, key_slot, key_rec,
                                             labels, dense, task_labels)
        finally:
            lib.psr_free(handle)
