"""Cross-host instance shuffle transport.

TPU-native analog of the reference's pass-load shuffle
(`PadBoxSlotDataset::ShuffleData` / `ReceiveSuffleData`, data_set.cc:
2438-2602, riding `boxps::PaddleShuffler::send_message_callback`,
data_set.cc:2485): while read threads parse a pass's files, every instance
is routed to `hash(ins) % world` (general_shuffle_func, data_set.cc:
2420-2436). Local instances flow straight into the merge channel; remote
ones are serialized into batches and sent point-to-point; received batches
are deserialized into the same merge channel. The pass is complete when
every peer has signalled done (wait_message_done analog).

Three transports share the protocol:
  * `LocalShuffleGroup` — N in-process ranks wired by queues; the
    single-process fake for tests (the PsLocalClient pattern,
    distributed/ps/service/ps_local_client.h).
  * `TcpShuffler` — length-prefixed framed messages over ad-hoc TCP
    sockets between hosts (DCN); the PaddleShuffler analog and the LOUD
    fallback transport (`Fleet.make_shuffler`), exactly like
    `hostplane=store`.
  * `MeshShuffler` — round 17: shuffle frames ride the PERSISTENT p2p
    host-plane mesh (`fleet/mesh_comm.py`, the PR-4 machinery) over
    dedicated per-peer framed connections; frames carry cross-plane
    trace ids.

Two frame codecs share every transport (round 17): the legacy
per-record codec below, and the zero-object COLUMNAR BLOCK codec
(`data/block_shuffle.py` — header + raw column bytes, vectorized hash
routing). `ShufflerBase._deliver` sniffs the frame magic, so the merge
channel receives whatever the sender shuffled; the dataset's merge
worker CONVERTS a codec mix (a rank-local downgrade or a split
`shuffle_block_codec` flag) with a loud warning — degraded rate, never
a dead cluster pass, never a silent conversion.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from paddlebox_tpu.config import flags
from paddlebox_tpu.data.block_shuffle import (BLOCK_MAGIC,
                                              block_shuffle_dests,
                                              deserialize_block,
                                              serialize_block, split_block)
from paddlebox_tpu.data.columnar import ColumnarBlock
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.utils.channel import register_depth_gauge
from paddlebox_tpu.utils.rpc import recv_exact
from paddlebox_tpu.utils.stats import stat_add
from paddlebox_tpu.utils.lockwatch import make_lock

_REC_MAGIC = 0x50425852  # "PBXR"


class ShufflePeerUnreachable(ConnectionError):
    """A shuffle peer could not be dialed within the bounded connect
    timeout (`shuffle_connect_secs`) — named so a dead host fails the
    pass load with the endpoint in the message instead of an anonymous
    OS-default ~2-minute stall (the utils/rpc.py round-9 hygiene
    applied to the shuffle transport)."""

# ---------------------------------------------------------------------------
# SlotRecord binary serialization (BinaryArchive analog, framework/archive.h;
# SlotRecord serialize for shuffle: data_feed.h:2254-2314)
# ---------------------------------------------------------------------------


def serialize_records(recs: Sequence[SlotRecord]) -> bytes:
    """Compact batch codec: header + per-record scalar block + CSR slot data."""
    parts: List[bytes] = [struct.pack("<II", _REC_MAGIC, len(recs))]
    for r in recs:
        ins_id = r.ins_id.encode("utf-8")
        u64_items = sorted(r.uint64_slots.items())
        f32_items = sorted(r.float_slots.items())
        parts.append(struct.pack(
            "<iiHHQfH", r.label, r.rank, r.cmatch & 0xFFFF, len(ins_id),
            r.search_id, r.qvalue, len(u64_items)))
        parts.append(ins_id)
        for slot, vals in u64_items:
            v = np.ascontiguousarray(vals, dtype=np.uint64)
            parts.append(struct.pack("<HI", slot, v.size))
            parts.append(v.tobytes())
        parts.append(struct.pack("<H", len(f32_items)))
        for slot, vals in f32_items:
            v = np.ascontiguousarray(vals, dtype=np.float32)
            parts.append(struct.pack("<HI", slot, v.size))
            parts.append(v.tobytes())
        extra = sorted(r.extra_labels.items())
        parts.append(struct.pack("<H", len(extra)))
        for task, lab in extra:
            tb = task.encode("utf-8")
            parts.append(struct.pack("<Hi", len(tb), int(lab)))
            parts.append(tb)
    return b"".join(parts)


def deserialize_records(buf: bytes) -> List[SlotRecord]:
    magic, n = struct.unpack_from("<II", buf, 0)
    if magic != _REC_MAGIC:
        raise ValueError("bad shuffle record magic 0x%x" % magic)
    off = 8
    out: List[SlotRecord] = []
    for _ in range(n):
        (label, rank, cmatch, id_len, search_id, qvalue,
         n_u64) = struct.unpack_from("<iiHHQfH", buf, off)
        off += struct.calcsize("<iiHHQfH")
        ins_id = buf[off:off + id_len].decode("utf-8")
        off += id_len
        u64_slots: Dict[int, np.ndarray] = {}
        for _ in range(n_u64):
            slot, cnt = struct.unpack_from("<HI", buf, off)
            off += 6
            u64_slots[slot] = np.frombuffer(
                buf, dtype=np.uint64, count=cnt, offset=off).copy()
            off += 8 * cnt
        (n_f32,) = struct.unpack_from("<H", buf, off)
        off += 2
        float_slots: Dict[int, np.ndarray] = {}
        for _ in range(n_f32):
            slot, cnt = struct.unpack_from("<HI", buf, off)
            off += 6
            float_slots[slot] = np.frombuffer(
                buf, dtype=np.float32, count=cnt, offset=off).copy()
            off += 4 * cnt
        (n_extra,) = struct.unpack_from("<H", buf, off)
        off += 2
        extra_labels: Dict[str, int] = {}
        for _ in range(n_extra):
            tlen, lab = struct.unpack_from("<Hi", buf, off)
            off += 6
            task = buf[off:off + tlen].decode("utf-8")
            off += tlen
            extra_labels[task] = lab
        out.append(SlotRecord(label=label, uint64_slots=u64_slots,
                              float_slots=float_slots, ins_id=ins_id,
                              rank=rank, cmatch=cmatch, qvalue=qvalue,
                              search_id=search_id,
                              extra_labels=extra_labels))
    return out


# ---------------------------------------------------------------------------
# Transport base: routing + buffering + done barrier
# ---------------------------------------------------------------------------


class ShufflerBase:
    """Shared scatter/flush logic; subclasses provide _send/_send_done."""

    def __init__(self, rank: int, world: int, batch_records: int = 512):
        self.rank = rank
        self.world = world
        self.batch_records = batch_records
        self._out: List[List[SlotRecord]] = [[] for _ in range(world)]  # guarded-by: _out_lock
        self._out_lock = make_lock("ShufflerBase._out_lock")
        # pass epoch: frames are tagged so a fast peer's next-pass records
        # can't leak into this rank's still-draining current pass
        self.epoch = 0
        # parked items per epoch: SlotRecords (record codec, extended
        # individually) and/or ColumnarBlocks (block codec, appended
        # whole) — _deliver sniffs the frame magic
        self._inbox: Dict[int, List[Union[SlotRecord, ColumnarBlock]]] = {}  # guarded-by: _inbox_lock
        self._inbox_lock = make_lock("ShufflerBase._inbox_lock")
        self._done_from: Dict[int, set] = {}  # guarded-by: _done_cv
        self._done_cv = threading.Condition()
        # parked-inbox depth rides the same sampled gauge machinery as
        # the dataset channels (chan_shuffle_inbox_depth, round 17)
        register_depth_gauge("shuffle_inbox", self)

    def __len__(self) -> int:
        """Parked (not yet drained) shuffle items — the queue-pressure
        view poll_depth_gauges samples at report cadence."""
        with self._inbox_lock:
            return sum(len(v) for v in self._inbox.values())

    # -- subclass transport hooks ------------------------------------------
    def _send(self, dest: int, payload: bytes) -> None:
        raise NotImplementedError

    def _send_done(self, dest: int) -> None:
        raise NotImplementedError

    # -- receive side (called by transport threads) ------------------------
    def _deliver(self, payload: bytes, epoch: int) -> None:
        """Deserialize one data frame into the epoch's inbox. The frame
        magic selects the codec: block frames park as ONE ColumnarBlock
        (zero per-record work), record frames as individual SlotRecords."""
        (magic,) = struct.unpack_from("<I", payload, 0)
        if magic == BLOCK_MAGIC:
            block = deserialize_block(payload)
            with self._inbox_lock:
                self._inbox.setdefault(epoch, []).append(block)
            n = block.n_recs
        else:
            recs = deserialize_records(payload)
            with self._inbox_lock:
                self._inbox.setdefault(epoch, []).extend(recs)
            n = len(recs)
        stat_add("shuffle_ins_received", n)
        stat_add("shuffle_bytes_received", len(payload))

    def _peer_done(self, src: int, epoch: int) -> None:
        with self._done_cv:
            self._done_from.setdefault(epoch, set()).add(src)
            self._done_cv.notify_all()

    def _send_payload(self, dest: int, payload: bytes) -> None:
        """Wire-accounted send (both codecs, every transport)."""
        self._send(dest, payload)
        stat_add("shuffle_batches_sent", 1)
        stat_add("shuffle_bytes_sent", len(payload))

    # -- dataset-facing API -------------------------------------------------
    def scatter(self, recs: Sequence[SlotRecord], channel) -> None:
        """Route records: locals to `channel`, remotes to peer buffers
        (ShuffleData, data_set.cc:2438-2545)."""
        local: List[SlotRecord] = []
        to_send: List[Tuple[int, bytes]] = []
        with self._out_lock:
            for r in recs:
                dest = r.shuffle_hash() % self.world
                if dest == self.rank:
                    local.append(r)
                else:
                    buf = self._out[dest]
                    buf.append(r)
                    if len(buf) >= self.batch_records:
                        to_send.append((dest, serialize_records(buf)))
                        self._out[dest] = []
        for dest, payload in to_send:
            self._send_payload(dest, payload)
        if local:
            channel.put_many(local)
        self._drain_inbox(channel)

    def scatter_block(self, block: ColumnarBlock, channel) -> None:
        """Block-codec twin of scatter (round 17): ONE vectorized hash
        over `rec_offsets` routes every record, a fancy-index split
        yields per-destination sub-blocks, and each remote sub-block
        ships as a single header+raw-columns frame — zero per-record
        Python anywhere. Blocks are file-sized, so there is no
        cross-call batching (`batch_records` applies to the record
        codec only)."""
        dests = block_shuffle_dests(block, self.world)
        subs = split_block(block, dests, self.world)
        for dest, sub in enumerate(subs):
            if dest == self.rank or sub is None or not sub.n_recs:
                continue
            self._send_payload(dest, serialize_block(sub))
        local = subs[self.rank]
        if local is not None and local.n_recs:
            channel.put(local)
        self._drain_inbox(channel)

    def _drain_inbox(self, channel) -> None:
        with self._inbox_lock:
            got = self._inbox.pop(self.epoch, [])
        if got:
            channel.put_many(got)

    def flush(self, channel, timeout: float = 120.0) -> None:
        """Send remainders + done marker, then block until every peer is
        done with THIS epoch and forward everything received for it
        (wait_message_done analog). Frames a fast peer already sent for its
        next pass stay parked under the next epoch."""
        epoch = self.epoch
        with self._out_lock:
            pending = [(d, serialize_records(buf))
                       for d, buf in enumerate(self._out) if buf]
            self._out = [[] for _ in range(self.world)]
        for dest, payload in pending:
            self._send_payload(dest, payload)
        for dest in range(self.world):
            if dest != self.rank:
                self._send_done(dest)
        with self._done_cv:
            ok = self._done_cv.wait_for(
                lambda: len(self._done_from.get(epoch, ()))
                >= self.world - 1, timeout)
            n_done = len(self._done_from.get(epoch, ()))
        if not ok:
            raise TimeoutError(
                "shuffle flush: %d/%d peers done" % (n_done, self.world - 1))
        self._drain_inbox(channel)
        with self._done_cv:
            self._done_from.pop(epoch, None)
        self.epoch = epoch + 1

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# In-process fake: N ranks in one process
# ---------------------------------------------------------------------------


class _InProcShuffler(ShufflerBase):
    def __init__(self, rank: int, world: int, group: "LocalShuffleGroup",
                 batch_records: int = 512):
        super().__init__(rank, world, batch_records)
        self._group = group

    def _send(self, dest: int, payload: bytes) -> None:
        # serialize/deserialize anyway so the codec is exercised
        self._group.members[dest]._deliver(payload, self.epoch)

    def _send_done(self, dest: int) -> None:
        self._group.members[dest]._peer_done(self.rank, self.epoch)


class LocalShuffleGroup:
    """world in-process shuffler endpoints sharing memory — the
    single-process multi-rank fake for deterministic tests."""

    def __init__(self, world: int, batch_records: int = 512):
        self.members = [_InProcShuffler(r, world, self, batch_records)
                        for r in range(world)]

    def __getitem__(self, rank: int) -> _InProcShuffler:
        return self.members[rank]


# ---------------------------------------------------------------------------
# TCP transport (PaddleShuffler analog)
# ---------------------------------------------------------------------------

_MSG_DATA = 0
_MSG_DONE = 1
_HDR = struct.Struct("<IIII")  # type, src_rank, epoch, payload_len


# shared record state (_out/_inbox) is annotated on ShufflerBase; the
# per-destination _dest_locks list guards one socket each, which the
# one-lock-attr guarded-by convention cannot express
class TcpShuffler(ShufflerBase):  # boxlint: disable=BX403
    """Framed point-to-point shuffle over TCP between hosts.

    endpoints[i] = (host, port) of rank i's listener. Connections are
    opened lazily on first send; the listener accepts any number of peer
    connections and demuxes by the src_rank field in each frame.
    """

    def __init__(self, rank: int, world: int,
                 endpoints: Sequence[Tuple[str, int]],
                 batch_records: int = 512):
        super().__init__(rank, world, batch_records)
        self.endpoints = list(endpoints)
        self._conns: Dict[int, socket.socket] = {}
        # per-destination locks: a slow/unreachable peer must not serialize
        # sends to healthy peers
        self._dest_locks = [threading.Lock() for _ in range(world)]
        self._stop = threading.Event()
        host, port = self.endpoints[rank]
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(world)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self._server.getsockname()[1]

    # -- receive path -------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                hdr = recv_exact(conn, _HDR.size)
                if hdr is None:
                    return
                mtype, src, epoch, length = _HDR.unpack(hdr)
                payload = (recv_exact(conn, length) if length
                           else b"")
                if length and payload is None:
                    return
                if mtype == _MSG_DATA:
                    self._deliver(payload, epoch)
                elif mtype == _MSG_DONE:
                    self._peer_done(src, epoch)
        finally:
            conn.close()

    # -- send path ----------------------------------------------------------
    def _send_frame(self, dest: int, mtype: int, payload: bytes) -> None:
        frame = _HDR.pack(mtype, self.rank, self.epoch, len(payload)) + payload
        with self._dest_locks[dest]:
            conn = self._conns.get(dest)
            if conn is None:
                # bounded dial + NODELAY (round-17 hygiene, the same fix
                # PR 4 applied to utils/rpc.py): a dead peer raises the
                # NAMED error within shuffle_connect_secs instead of the
                # OS-default ~2-minute connect stall, and small done/
                # remainder frames don't sit in Nagle's buffer behind a
                # bulk send
                host, port = self.endpoints[dest]
                try:
                    conn = socket.create_connection(
                        (host, port),
                        timeout=float(flags.get_flag(
                            "shuffle_connect_secs")))
                except OSError as e:
                    raise ShufflePeerUnreachable(
                        "shuffle peer %d unreachable at %s:%d: %r"
                        % (dest, host, port, e)) from e
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn.settimeout(None)
                self._conns[dest] = conn
            conn.sendall(frame)

    def _send(self, dest: int, payload: bytes) -> None:
        self._send_frame(dest, _MSG_DATA, payload)

    def _send_done(self, dest: int) -> None:
        self._send_frame(dest, _MSG_DONE, b"")

    def close(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        for s in self._conns.values():
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()


# ---------------------------------------------------------------------------
# p2p mesh transport (round 17): shuffle rides the persistent host plane
# ---------------------------------------------------------------------------


class MeshShuffler(ShufflerBase):
    """Shuffle frames over the PERSISTENT p2p host-plane mesh
    (`fleet/mesh_comm.py`) instead of the ad-hoc TcpShuffler sockets:
    endpoints already rendezvous'd once through the store at mesh
    bring-up, sends ride dedicated per-peer framed connections (never
    the lockstep exchange clients), and every frame carries a
    cross-plane trace id (round 14) so `tools/trace_stitch.py` can draw
    the shuffle's cross-rank hops.

    ONE MeshShuffler per MeshComm (the mesh has a single shuffle-frame
    handler); reuse it across passes — the epoch tag keeps a fast
    peer's next-pass frames parked. `close()` only unregisters the
    handler: the mesh and its connections belong to the fleet."""

    def __init__(self, mesh, batch_records: int = 512):
        super().__init__(int(mesh.rank), int(mesh.world), batch_records)
        self._mesh = mesh
        mesh.set_shuffle_handler(self._on_frame)

    def _on_frame(self, req: dict) -> None:
        """Called from the mesh server's connection threads (and the
        handler-registration drain of frames that arrived earlier)."""
        mtype = int(req["mtype"])
        if mtype == _MSG_DATA:
            self._deliver(req["data"], int(req["epoch"]))
        elif mtype == _MSG_DONE:
            self._peer_done(int(req["from"]), int(req["epoch"]))
        else:
            raise ValueError("unknown shuffle frame type %r" % (mtype,))

    def _send_frame(self, dest: int, mtype: int, payload: bytes) -> None:
        self._mesh.send_shuffle(dest, {"mtype": mtype, "epoch": self.epoch,
                                       "from": self.rank, "data": payload})

    def _send(self, dest: int, payload: bytes) -> None:
        self._send_frame(dest, _MSG_DATA, payload)

    def _send_done(self, dest: int) -> None:
        self._send_frame(dest, _MSG_DONE, b"")

    def close(self) -> None:
        self._mesh.set_shuffle_handler(None)
