"""Streaming ingest: tail a live source into bounded micro-pass windows.

The reference trains in daily drops because its data arrives in daily
drops; this module collapses that cadence. A ``StreamingDataset`` tails
a live source — a watched directory (the deployment shape: upstream
writers land MultiSlot text files) or a socket feed (producers push
lines over TCP; the spooler lands them as files so both modes flow
through the SAME native-parser/block-shuffle plane) — and cuts it into
**micro-pass windows**: bounded batches of complete files that each
become one ordinary BoxDataset, preloadable and trainable exactly like
a day's pass.

Torn/in-progress-file safety (the round-19 fix, pinned by tests):

  * rename convention — writers that follow write-temp-then-rename
    publish atomically; any ``.tmp`` / ``.part`` / ``.inprogress`` /
    ``.open`` suffix or ``.``/``_`` name prefix is skipped outright.
  * size stability — a bare file only counts as sealed after its size
    is unchanged (and nonzero) across ``streaming_stable_polls``
    consecutive watcher polls, so an in-place appender's torn tail is
    never parsed mid-write.
  * consumed-file ledger — every file that entered a committed window
    is recorded (atomic JSON replace, riding the journal/checkpoint
    dir) and skipped on re-scan, so a restarted tailer resumes without
    double-consuming. Commit happens at the micro-pass BOUNDARY (after
    the window trained or was refused), so a crash mid-window re-reads
    at-least-once — the journal sweep on restart keeps that sound.

No jax imports here: window formation runs on the ingest thread while
the previous window trains.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from paddlebox_tpu.config import flags
from paddlebox_tpu.data.dataset import BoxDataset
from paddlebox_tpu.utils.stats import gauge_set, stat_add

#: writer-convention suffixes that mark a file as still being written
IN_PROGRESS_SUFFIXES = (".tmp", ".part", ".inprogress", ".open")


def _is_in_progress_name(name: str) -> bool:
    """Rename-convention check: temp-suffixed or hidden names are a
    writer's scratch space, never ingested."""
    if name.startswith(".") or name.startswith("_"):
        return True
    return any(name.endswith(s) for s in IN_PROGRESS_SUFFIXES)


def _count_lines(path: str) -> int:
    """Instance count of a MultiSlot text file = its line count; a
    buffered byte scan (no decode) keeps window formation cheap."""
    n = 0
    last = b"\n"
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            n += chunk.count(b"\n")
            last = chunk[-1:]
    if last != b"\n":
        n += 1  # unterminated final line still parses as one instance
    return n


class FileLedger:
    """Consumed-file ledger: which source files already entered a
    committed micro-pass window. Persisted as one JSON doc, replaced
    atomically (write temp + fsync + os.replace) so a crash never
    leaves a torn ledger — the restart worst case is re-consuming the
    windows since the last commit, never skipping unconsumed data.

    Keyed by basename: the watch dir is the namespace (upstream
    rotation moves files in, never renames within), and basenames keep
    the ledger valid when the model/journal root is re-mounted at a
    different path than the watch dir."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._files: Dict[str, int] = {}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
            self._files = {str(k): int(v)
                           for k, v in doc.get("files", {}).items()}
        except (OSError, ValueError):
            self._files = {}

    def consumed(self, path: str) -> bool:
        return os.path.basename(path) in self._files

    def record(self, paths: Sequence[str]) -> None:
        """In-memory mark only — pair with flush(). Split out so a
        caller can take its lock around the dict update and keep the
        fsync'd file write outside it."""
        for p in paths:
            try:
                size = os.path.getsize(p)
            except OSError:
                size = -1
            self._files[os.path.basename(p)] = size

    def flush(self) -> None:
        """Persist the ledger (write temp + fsync + atomic replace).
        Single-writer contract: only one thread records/flushes (the
        micro-pass boundary); concurrent readers stay safe because a
        crash mid-flush leaves the previous complete doc in place."""
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"version": 1, "files": self._files}, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)

    def mark(self, paths: Sequence[str]) -> None:
        if not paths:
            return
        self.record(paths)
        self.flush()

    def __len__(self) -> int:
        return len(self._files)


class DirectoryWatcher:
    """Poll a directory for complete, unconsumed data files.

    Each ``poll()`` re-lists the dir and returns the files that became
    ready since the last call (deterministic mtime-then-name order).
    Ready = not temp-named, not ledger-consumed, nonzero size unchanged
    across ``stable_polls`` consecutive polls. Returned files are
    remembered in-process so one watcher never yields a file twice;
    cross-restart dedup is the ledger's job."""

    def __init__(self, source_dir: str, ledger: Optional[FileLedger] = None,
                 stable_polls: Optional[int] = None) -> None:
        self.source_dir = source_dir
        self.ledger = ledger
        self.stable_polls = int(
            stable_polls if stable_polls is not None
            else flags.get_flag("streaming_stable_polls"))
        self._sizes: Dict[str, Tuple[int, int]] = {}  # name -> (size, stable)
        self._yielded: set = set()

    def poll(self) -> List[str]:
        try:
            names = os.listdir(self.source_dir)
        except OSError:
            return []
        ready: List[Tuple[float, str, str]] = []
        for name in sorted(names):
            if _is_in_progress_name(name) or name in self._yielded:
                continue
            path = os.path.join(self.source_dir, name)
            if self.ledger is not None and self.ledger.consumed(path):
                self._yielded.add(name)
                continue
            try:
                st = os.stat(path)
            except OSError:
                continue  # vanished between listdir and stat
            if not os.path.isfile(path) or st.st_size == 0:
                continue
            size, stable = self._sizes.get(name, (-1, 0))
            stable = stable + 1 if st.st_size == size else 1
            self._sizes[name] = (st.st_size, stable)
            if stable >= self.stable_polls:
                ready.append((st.st_mtime, name, path))
        out = []
        for _, name, path in sorted(ready):
            self._yielded.add(name)
            self._sizes.pop(name, None)
            out.append(path)
        if out:
            stat_add("streaming_files_discovered", len(out))
        return out


class SocketFeedServer:
    """Socket-feed mode: a TCP listener that spools pushed MultiSlot
    text lines into the watched directory.

    Producers connect and stream newline-terminated lines (the same
    bytes a file drop would hold). The spooler writes them to a
    ``spool-*.txt.tmp`` scratch file and RENAMES it into place every
    ``spool_lines`` lines and on connection close — the exact
    write-temp-then-rename convention the DirectoryWatcher trusts, so
    socket ingest reuses the whole file-based micro-pass plane instead
    of growing a second parser path."""

    def __init__(self, spool_dir: str, port: int = 0,
                 spool_lines: int = 2048, host: str = "127.0.0.1") -> None:
        os.makedirs(spool_dir, exist_ok=True)
        self.spool_dir = spool_dir
        self.spool_lines = int(spool_lines)
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)
        self.port = self._srv.getsockname()[1]
        self._seq_lock = threading.Lock()
        self._seq = 0                       # guarded-by: _seq_lock
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []  # accept-thread only (+ close() after stop)
        self._accept = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="stream-accept")
        self._accept.start()

    def _next_spool(self) -> str:
        with self._seq_lock:
            self._seq += 1
            return os.path.join(self.spool_dir,
                                "spool-%08d.txt" % self._seq)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._conn_loop, args=(conn,),
                                 daemon=True, name="stream-spool")
            t.start()
            self._threads.append(t)

    def _conn_loop(self, conn: socket.socket) -> None:
        try:
            buf = b""
            lines: List[bytes] = []
            conn.settimeout(0.5)
            while not self._stop.is_set():
                try:
                    chunk = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not chunk:
                    break
                buf += chunk
                *full, buf = buf.split(b"\n")
                lines.extend(full)
                if len(lines) >= self.spool_lines:
                    self._seal(lines[:self.spool_lines])
                    lines = lines[self.spool_lines:]
            if buf:
                lines.append(buf)  # producer closed without newline
            self._seal(lines)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _seal(self, lines: List[bytes]) -> None:
        lines = [ln for ln in lines if ln.strip()]
        if not lines:
            return
        path = self._next_spool()
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(b"\n".join(lines) + b"\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        stat_add("streaming_spool_files", 1)
        stat_add("streaming_spool_lines", len(lines))

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self._accept.join(timeout=5.0)
        for t in self._threads:
            t.join(timeout=5.0)


class MicroWindow:
    """One micro-pass worth of source files, wrapped as a BoxDataset.

    ``born_ts`` (newest source-file mtime, wall clock) is the ingest
    timestamp the freshness gauges measure from: ingest-to-train lag is
    train start minus born_ts; ingest-to-serve freshness is the serving
    swap minus born_ts. ``born_min_ts`` (oldest source-file mtime) is
    the other end of the span: the pair rides the journal's watermark
    record (round 20) so the serving plane knows the freshness of what
    it just applied, not only that something arrived."""

    def __init__(self, index: int, files: List[str], instances: int,
                 dataset: BoxDataset) -> None:
        self.index = index
        self.files = list(files)
        self.instances = int(instances)
        self.dataset = dataset
        self.born_ts = max((os.path.getmtime(f) for f in files),
                           default=time.time())
        self.born_min_ts = min((os.path.getmtime(f) for f in files),
                               default=self.born_ts)
        self.formed_ts = time.time()


class StreamingDataset:
    """Tail a live source into a sequence of micro-pass windows.

    ``next_window(deadline=...)`` blocks (polling at
    ``streaming_poll_secs``) until enough complete files accumulate to
    fill ``streaming_micro_pass_instances`` instances, then returns a
    MicroWindow whose BoxDataset rides the same native parser and
    (optional) block-shuffle mesh plane as a batch pass. A partial
    window is flushed when ``flush_after`` seconds pass with data
    pending but below the bound — freshness beats fullness on a slow
    stream. Windows are committed (ledger-marked) by the runner at the
    micro-pass boundary via ``commit_window``.

    Thread contract: next_window runs on ONE ingest/driver thread;
    commit_window on the train driver. The ledger write is the only
    shared mutation and both callers serialize through ``_lock``.
    """

    def __init__(self, feed, source_dir: str,
                 ledger_dir: Optional[str] = None,
                 read_threads: int = 2, shuffler=None,
                 micro_pass_instances: Optional[int] = None,
                 flush_after: Optional[float] = None,
                 socket_port: Optional[int] = None,
                 dataset_kwargs: Optional[dict] = None) -> None:
        self.feed = feed
        self.source_dir = source_dir
        self.read_threads = int(read_threads)
        self.shuffler = shuffler
        self.micro_pass_instances = int(
            micro_pass_instances if micro_pass_instances is not None
            else flags.get_flag("streaming_micro_pass_instances"))
        self.poll_secs = float(flags.get_flag("streaming_poll_secs"))
        # partial-window flush: default a handful of poll intervals —
        # long enough to coalesce a burst, short enough that a trickle
        # source still trains within seconds
        self.flush_after = (float(flush_after) if flush_after is not None
                            else 10.0 * self.poll_secs)
        self._dataset_kwargs = dict(dataset_kwargs or {})
        os.makedirs(source_dir, exist_ok=True)
        self.ledger = FileLedger(os.path.join(
            ledger_dir or source_dir, "_streaming", "consumed.json"))
        self.watcher = DirectoryWatcher(source_dir, self.ledger)
        self.server: Optional[SocketFeedServer] = None
        if socket_port is not None:
            self.server = SocketFeedServer(source_dir, port=socket_port)
        self._lock = threading.Lock()
        self._pending: List[Tuple[str, int]] = []  # (path, lines)
        self._pending_since: Optional[float] = None
        self._windows = 0
        self._stop = threading.Event()

    # ------------------------------------------------------------- windows
    def _pending_instances(self) -> int:
        return sum(n for _, n in self._pending)

    def _cut_window(self) -> MicroWindow:
        """Take pending files up to the instance bound into one window."""
        files: List[str] = []
        instances = 0
        while self._pending:
            path, n = self._pending[0]
            if files and instances + n > self.micro_pass_instances:
                break
            files.append(path)
            instances += n
            self._pending.pop(0)
        self._pending_since = time.time() if self._pending else None
        ds = BoxDataset(self.feed, read_threads=self.read_threads,
                        shuffler=self.shuffler, **self._dataset_kwargs)
        ds.set_filelist(files)
        win = MicroWindow(self._windows, files, instances, ds)
        self._windows += 1
        gauge_set("streaming_window_instances", float(instances))
        stat_add("streaming_windows_formed")
        return win

    def next_window(self, deadline: Optional[float] = None
                    ) -> Optional[MicroWindow]:
        """Block until a window is ready; None on deadline/stop.

        deadline is an absolute time.time() bound — the runner passes
        now + streaming_idle_timeout_secs to drain finite drops."""
        while not self._stop.is_set():
            for path in self.watcher.poll():
                try:
                    n = _count_lines(path)
                except OSError:
                    continue  # vanished mid-count: next poll re-lists
                if n == 0:
                    continue
                if not self._pending:
                    self._pending_since = time.time()
                self._pending.append((path, n))
            if self._pending:
                full = self._pending_instances() >= self.micro_pass_instances
                aged = (self._pending_since is not None
                        and time.time() - self._pending_since
                        >= self.flush_after)
                if full or aged:
                    return self._cut_window()
            if deadline is not None and time.time() >= deadline:
                return None
            self._stop.wait(self.poll_secs)
        return None

    def commit_window(self, window: MicroWindow) -> None:
        """Micro-pass boundary: record the window's files as consumed so
        a restart never double-trains them. Called AFTER the window
        trained (or was refused — a refused window is dropped, not
        retried: the gate exists to keep a poisoned drop out). The
        in-memory mark happens under the lock (the watcher reads it);
        the fsync'd file write happens OUTSIDE it — only this (train
        driver) thread writes, and a torn flush just re-consumes the
        last windows on restart."""
        if window.files:
            with self._lock:
                self.ledger.record(window.files)
            self.ledger.flush()
        stat_add("streaming_windows_committed")

    # -------------------------------------------------------------- control
    def stop(self) -> None:
        """Unblock next_window and stop the socket spooler."""
        self._stop.set()
        if self.server is not None:
            self.server.close()

    def resume(self) -> None:
        """Clear a prior stop() so a fresh runner.run() can tail again
        (drain-and-resume cadence); a closed socket spooler stays
        closed — re-create the StreamingDataset for a new feed port."""
        self._stop.clear()

    def close(self) -> None:
        self.stop()

    @property
    def socket_port(self) -> Optional[int]:
        return self.server.port if self.server is not None else None
