"""Pass-scoped in-memory dataset with threaded load pipeline.

TPU-native PadBoxSlotDataset (paddle/fluid/framework/data_set.h:438-566,
data_set.cc:2217-2817): a pass's files are read by N threads into a channel,
optionally shuffled across hosts (data/shuffle.py transport), merged while
registering every feasign with the table's feed-pass agent (MergeInsKeys →
AddKeys, data_set.cc:2291-2347), then split into equalized per-worker batch
ranges for training (PrepareTrain, data_set.cc:2775-2817).

The preload/wait split mirrors BoxHelper::PreLoadIntoMemory/WaitFeedPassDone
(box_wrapper.h:1131-1172) so pass N+1 loads while pass N trains.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import DataFeedConfig
from paddlebox_tpu.data.columnar import ColumnarBlock
from paddlebox_tpu.data.packer import BatchPacker, PackedBatch
from paddlebox_tpu.data.parser import MultiSlotParser
from paddlebox_tpu.data.slot_record import SlotRecord
from paddlebox_tpu.obs.tracer import span as obs_span
from paddlebox_tpu.utils.channel import Channel, ChannelClosed
from paddlebox_tpu.utils.stats import stat_add
from paddlebox_tpu.utils.timer import Timer

# add_keys_fn(keys: np.ndarray) registers pass keys (PSAgent AddKeys analog)
AddKeysFn = Callable[[np.ndarray], None]


# its only Lock guards method-local state (the read-worker file cursor,
# a local in load_into_memory); cross-thread hand-off rides the Channel,
# which carries its own guarded-by contract
class BoxDataset:  # boxlint: disable=BX403
    def __init__(self, feed: DataFeedConfig, read_threads: int = 4,
                 parser: Optional[MultiSlotParser] = None,
                 shuffler=None, columnar: Optional[bool] = None,
                 input_table=None, use_cache_idx: bool = False) -> None:
        """input_table / use_cache_idx: aux-row offset sources wired
        through the packer (the InputTableDataFeed / pull_cache_value
        feed roles — see BatchPacker); they force the record path since
        offsets translate per SlotRecord."""
        self.feed = feed
        self.read_threads = read_threads
        self.parser = parser or MultiSlotParser(feed)
        self.packer = BatchPacker(feed, input_table=input_table,
                                  use_cache_idx=use_cache_idx)
        self.shuffler = shuffler  # cross-host instance shuffle transport
        self._files: List[str] = []
        self._records: List[SlotRecord] = []
        self._preload_threads: List[threading.Thread] = []
        self._merge_thread: Optional[threading.Thread] = None
        self._channel: Optional[Channel] = None
        self._add_keys_fn: Optional[AddKeysFn] = None
        self._load_error: Optional[BaseException] = None
        self.timers = {n: Timer() for n in ("read", "merge", "shuffle")}
        # columnar fast path: native C++ parser → struct-of-arrays blocks,
        # numpy-only batch packing (no per-record Python objects). Default:
        # on whenever the native lib builds — round 17: a cross-host
        # shuffler no longer forces the record path (blocks ride the
        # shuffle whole via data/block_shuffle.py's codec + vectorized
        # hash routing; flag shuffle_block_codec=False restores the
        # legacy per-record codec, which does need SlotRecords).
        # task-label config errors fail loudly on EVERY host (the native
        # parser would raise only where the lib builds; the record path
        # would silently substitute the click label)
        slot_names = {s.name for s in feed.slots}
        for task, slot_name in getattr(feed, "task_label_slots", ()):
            if slot_name not in slot_names:
                raise ValueError(
                    f"task_label_slots: slot {slot_name!r} (task {task!r}) "
                    f"not in the feed config")
        self._native_parser = None
        if columnar is None:
            # an explicitly-passed custom parser (e.g. a dlopen plugin)
            # translates per record — the built-in native columnar parse
            # would silently ignore it
            columnar = parser is None
        if columnar and feed.rank_offset:
            # pv rank-offset matrices are built from per-record pv fields
            # (search_id/rank/cmatch) which the columnar blocks don't carry
            columnar = False
        if columnar and (input_table is not None or use_cache_idx
                         or getattr(feed, "parse_ins_id", False)):
            # aux offsets and ins_id-prefixed lines translate per
            # SlotRecord; the native columnar parser reads plain lines
            columnar = False
        # per-task label feeds ride the columnar path too: the extended
        # native entry (psr_parse_file2) emits task-label columns; the
        # NativeMultiSlotParser constructor raises if the lib lacks it,
        # which downgrades to the record path below
        if columnar:
            try:
                from paddlebox_tpu.data.native_parser import \
                    NativeMultiSlotParser
                self._native_parser = NativeMultiSlotParser(feed)
            except (RuntimeError, ImportError):
                self._native_parser = None
        self.columnar = self._native_parser is not None
        self._load_columnar = self.columnar  # per-load effective mode
        self._disk_writer = None    # BinaryArchiveWriter when spilling
        self.disk_files: List[str] = []
        self._block = None          # merged ColumnarBlock
        self._perm: Optional[np.ndarray] = None  # shuffle permutation

    # ------------------------------------------------------------ file list
    def set_filelist(self, files: Sequence[str]) -> None:
        self._files = list(files)

    def my_shard_files(self, rank: int, world: int) -> List[str]:
        """Per-rank file split (data_set.cc:1961-1973)."""
        return [f for i, f in enumerate(self._files) if i % world == rank]

    # ----------------------------------------------------------- load paths
    def load_into_memory(self, add_keys_fn: Optional[AddKeysFn] = None) -> None:
        self.preload_into_memory(add_keys_fn)
        self.wait_preload_done()

    def preload_into_memory(self,
                            add_keys_fn: Optional[AddKeysFn] = None) -> None:
        """Spawn read+merge threads; returns immediately
        (PreLoadIntoMemory, data_set.cc:2217-2261)."""
        if self._preload_threads:
            raise RuntimeError("preload already running")
        self._records = []
        self._block = None
        self._perm = None
        self._add_keys_fn = add_keys_fn
        self._load_error = None
        self._channel = Channel(capacity=64, name="dataset_blocks")
        files = list(self._files)
        from paddlebox_tpu.data.archive import is_archive, read_archive
        # per-load state is captured in locals so a failed later call can't
        # flip an in-flight load's mode mid-pass
        disk_writer = self._disk_writer
        # archive inputs and disk spill stream SlotRecords, not columnar
        # blocks — downgrade this load to the record path when either is in
        # play (the archive codec round-trips full records). The eager sniff
        # sweep only runs when columnar is actually a candidate; the record
        # path sniffs lazily per file inside the read workers.
        if self.columnar and disk_writer is None:
            use_columnar = not any(is_archive(f) for f in files)
            if (use_columnar and self.shuffler is not None
                    and not flags.get_flag("shuffle_block_codec")):
                # the legacy per-record shuffle codec (the block codec's
                # bit-parity oracle) moves SlotRecords — this load runs
                # the record path so the oracle stays exercisable
                use_columnar = False
        else:
            use_columnar = False
        self._load_columnar = use_columnar
        lock = threading.Lock()
        cursor = {"i": 0}

        def read_worker():
            t = self.timers["read"]
            try:
                while True:
                    with lock:
                        if cursor["i"] >= len(files):
                            return
                        path = files[cursor["i"]]
                        cursor["i"] += 1
                    t.start()
                    if use_columnar:
                        with obs_span("ingest_parse"):
                            block = self._native_parser.parse_file_columnar(
                                path)
                        stat_add("ingest_ins_parsed", block.n_recs)
                        stat_add("ingest_keys_parsed", block.n_keys)
                        self._put_block(block)
                    elif is_archive(path):
                        for recs in read_archive(path):
                            self._put_records(recs)
                    else:
                        batch: List[SlotRecord] = []
                        for rec in self.parser.parse_file(path):
                            batch.append(rec)
                            if len(batch) >= 512:
                                self._put_records(batch)
                                batch = []
                        if batch:
                            self._put_records(batch)
                    t.pause()
            except BaseException as e:  # surfaced in wait_preload_done
                self._load_error = e

        def merge_worker():
            """MergeInsKeys (data_set.cc:2291-2347): drain channel, register
            keys with the feed-pass agent, append to the pass memory.
            A codec mix — a peer shuffling the OTHER frame kind into this
            pass because a rank-local downgrade diverged the modes (an
            archive file in that rank's shard, a host whose native lib
            didn't build) or the shuffle_block_codec flag was split —
            CONVERTS here with a loud warning instead of failing: one
            stray shard must not kill a cluster pass load (round-17
            review), but the degraded rate must never be silent."""
            t = self.timers["merge"]
            blocks = []
            mixed_warned = [False]

            def warn_mix(kind: str) -> None:
                if mixed_warned[0]:
                    return
                mixed_warned[0] = True
                from paddlebox_tpu.obs import log as obs_log
                obs_log.warning(
                    "shuffle codec mix: " + kind + " — a peer runs the "
                    "other ingest mode (archive shard? native lib "
                    "missing? split shuffle_block_codec flag?); "
                    "converting at the merge, throughput degraded")

            try:
                while True:
                    try:
                        items = self._channel.get_many(256)
                    except ChannelClosed:
                        break
                    t.start()
                    stray = [it for it in items
                             if isinstance(it, ColumnarBlock)
                             is not use_columnar]
                    if stray:
                        items = [it for it in items
                                 if isinstance(it, ColumnarBlock)
                                 is use_columnar]
                    if use_columnar:
                        if stray:
                            warn_mix("record frames in a columnar pass")
                            from paddlebox_tpu.data.block_shuffle import \
                                records_to_block
                            items = items + [records_to_block(stray,
                                                              self.feed)]
                            stat_add("ingest_codec_mix_converted",
                                     len(stray))
                        with obs_span("ingest_merge"):
                            for block in items:
                                if (self._add_keys_fn is not None
                                        and block.n_keys):
                                    self._add_keys_fn(block.keys)
                                blocks.append(block)
                                stat_add("dataset_ins_merged", block.n_recs)
                        t.pause()
                        continue
                    recs = items
                    if stray:
                        warn_mix("columnar block frames in a "
                                 "record-path pass")
                        from paddlebox_tpu.data.block_shuffle import \
                            block_to_records
                        for b in stray:
                            recs = recs + block_to_records(b, self.feed)
                            stat_add("ingest_codec_mix_converted",
                                     b.n_recs)
                    if disk_writer is not None:
                        # disk spill: keys are registered when the archives
                        # are loaded back, not at dump time (PreLoadIntoDisk,
                        # data_set.cc:2090-2215)
                        disk_writer.write_records(recs)
                        stat_add("dataset_ins_spilled", len(recs))
                    else:
                        with obs_span("ingest_merge"):
                            if self._add_keys_fn is not None:
                                keys = [r.all_keys() for r in recs]
                                keys = [k for k in keys if k.size]
                                if keys:
                                    self._add_keys_fn(np.concatenate(keys))
                            self._records.extend(recs)
                        stat_add("dataset_ins_merged", len(recs))
                    t.pause()
                if use_columnar:
                    self._block = ColumnarBlock.concat(blocks)
                    if self._block.n_recs:
                        # slot-level data-quality monitor (round 18,
                        # flag data_quality): one vectorized pass over
                        # the merged block's columns on this merge
                        # thread — the runners roll the window at
                        # pass_end (metrics/drift.py)
                        from paddlebox_tpu.metrics import drift as _drift
                        with obs_span("ingest_quality"):
                            _drift.observe_block(self._block)
                return
            except BaseException as e:
                self._load_error = e
                # keep draining so blocked readers can finish instead of
                # deadlocking on the bounded channel; error surfaces in
                # wait_preload_done
                try:
                    while True:
                        self._channel.get_many(256)
                except ChannelClosed:
                    pass

        readers = [threading.Thread(target=read_worker, daemon=True)
                   for _ in range(max(1, self.read_threads))]
        for th in readers:
            th.start()
        self._preload_threads = readers
        self._merge_thread = threading.Thread(target=merge_worker, daemon=True)
        self._merge_thread.start()

    def _put_records(self, recs: List[SlotRecord]) -> None:
        """Route through cross-host shuffle when configured
        (ShuffleData, data_set.cc:2438-2545)."""
        stat_add("ingest_ins_parsed", len(recs))
        if self.shuffler is not None and not flags.get_flag(
                "dataset_disable_shuffle"):
            with obs_span("ingest_shuffle"):
                self.shuffler.scatter(recs, self._channel)
        else:
            self._channel.put_many(recs)

    def _put_block(self, block) -> None:
        """Columnar twin of _put_records (round 17): the whole parsed
        block routes through the cross-host shuffle — vectorized hash
        over rec_offsets, fancy-index split, per-destination sub-block
        frames (ShufflerBase.scatter_block) — so shuffled jobs stay
        zero-object end to end."""
        if self.shuffler is not None and not flags.get_flag(
                "dataset_disable_shuffle"):
            with obs_span("ingest_shuffle"):
                self.shuffler.scatter_block(block, self._channel)
        else:
            self._channel.put(block)

    def wait_preload_done(self) -> None:
        """WaitFeedPassDone half: join readers, drain merge
        (data_set.cc:2262)."""
        for th in self._preload_threads:
            th.join()
        flush_error: Optional[BaseException] = None
        try:
            if self.shuffler is not None:
                with obs_span("ingest_shuffle_flush"):
                    self.shuffler.flush(self._channel)
        except BaseException as e:
            # a dead peer must not leave the merge thread blocked on a
            # never-closed channel and the dataset stuck in "preload
            # already running"
            flush_error = e
        finally:
            self._channel.close()
            if self._merge_thread is not None:
                self._merge_thread.join()
            self._preload_threads = []
            self._merge_thread = None
            if self._disk_writer is not None:
                self.disk_files = self._disk_writer.close()
                self._disk_writer = None
        if self._load_error is not None:
            # the load error is the root cause (a dead reader also starves
            # the shuffle); surface it over any secondary flush failure
            raise RuntimeError("dataset load failed") from self._load_error
        if flush_error is not None:
            raise RuntimeError(
                "cross-host shuffle flush failed") from flush_error

    # -------------------------------------------------------------- disk spill
    def preload_into_disk(self, out_prefix: str,
                          max_bytes: int = 0) -> None:
        """Read (+cross-host shuffle) the pass and spill it to rotating
        binary archive shards instead of RAM (PreLoadIntoDisk/DumpIntoDisk,
        data_set.cc:2090-2215). Resulting shard paths land in
        `self.disk_files` after wait_preload_done(); feed them back via
        set_filelist + load_into_memory to train from the spill."""
        from paddlebox_tpu.data.archive import BinaryArchiveWriter
        if self._preload_threads:
            raise RuntimeError("preload already running")
        self._disk_writer = BinaryArchiveWriter(out_prefix, max_bytes)
        self.disk_files = []
        try:
            self.preload_into_memory(None)
        except BaseException:
            self._disk_writer = None
            raise

    def load_into_disk(self, out_prefix: str, max_bytes: int = 0) -> None:
        self.preload_into_disk(out_prefix, max_bytes)
        self.wait_preload_done()

    def slots_shuffle(self, slot_indices: Sequence[int],
                      seed: Optional[int] = None) -> None:
        """Permute the given slots' feasign lists ACROSS records, leaving
        every other slot in place (BoxHelper::SlotsShuffle, box_wrapper.h:
        1174-1198) — the AUC-runner's feature-ablation primitive: retrain/
        re-eval with one slot decorrelated and measure the AUC drop."""
        if self._load_columnar:
            raise RuntimeError("slots_shuffle needs the record path "
                               "(construct the dataset with columnar=False)")
        rng = np.random.RandomState(seed)
        n = len(self._records)
        for si in slot_indices:
            vals = [r.uint64_slots.get(si) for r in self._records]
            perm = rng.permutation(n)
            for r, j in zip(self._records, perm):
                v = vals[j]
                if v is None:
                    r.uint64_slots.pop(si, None)
                else:
                    r.uint64_slots[si] = v

    # -------------------------------------------------------------- train prep
    def local_shuffle(self, seed: Optional[int] = None) -> None:
        if flags.get_flag("dataset_disable_shuffle"):
            # FLAGS_padbox_dataset_disable_shuffle (flags.cc:969): keep load
            # order — deterministic runs / cross-process parity tests
            return
        rng = np.random.RandomState(seed)
        if self._load_columnar:
            if self._block is not None and self._block.n_recs:
                self._perm = rng.permutation(self._block.n_recs)
        else:
            rng.shuffle(self._records)

    @property
    def records(self) -> List[SlotRecord]:
        return self._records

    @property
    def block(self):
        return self._block

    def all_keys(self) -> np.ndarray:
        """Every feasign in the loaded pass (for test-mode feed passes)."""
        if self._load_columnar:
            return (self._block.keys if self._block is not None
                    else np.empty(0, np.uint64))
        if not self._records:
            return np.empty(0, np.uint64)
        return np.concatenate([r.all_keys() for r in self._records])

    def __len__(self) -> int:
        if self._load_columnar:
            return self._block.n_recs if self._block is not None else 0
        return len(self._records)

    def release_memory(self) -> None:
        self._records = []
        self._block = None
        self._perm = None

    def split_batches(self, num_workers: int,
                      equalize: Optional[Callable[[int], int]] = None
                      ) -> List[List[PackedBatch]]:
        """Equalized per-worker batch split (compute_paddlebox_thread_batch,
        data_set.cc:2690-2755): every worker gets the SAME number of batches
        so lockstep collectives never deadlock; short workers wrap around.

        equalize: optional allreduce-max over hosts of the local batch count
        (MPI allreduce analog); receives local count, returns global max.
        """
        bs = self.feed.batch_size
        n = len(self)
        per_worker = (n + num_workers - 1) // num_workers
        local_batches = (per_worker + bs - 1) // bs if n else 0
        target = equalize(local_batches) if equalize else local_batches
        if self._load_columnar:
            return self._split_batches_columnar(num_workers, per_worker,
                                                target)
        out: List[List[PackedBatch]] = []
        for w in range(num_workers):
            lo = w * per_worker
            hi = min(lo + per_worker, n)
            recs = self._records[lo:hi]
            batches: List[PackedBatch] = []
            for b in range(target):
                chunk = recs[b * bs:(b + 1) * bs]
                if not chunk and recs:
                    # wrap around to equalize step counts
                    chunk = recs[:bs]
                if not chunk:
                    chunk = self._records[:bs]
                batches.append(self.packer.pack(chunk))
            out.append(batches)
        return out

    def _split_batches_columnar(self, num_workers: int, per_worker: int,
                                target: int) -> List[List[PackedBatch]]:
        from paddlebox_tpu.data.columnar import pack_columnar
        bs = self.feed.batch_size
        n = len(self)
        perm = (self._perm if self._perm is not None
                else np.arange(n, dtype=np.int64))
        sparse_slots = self.feed.used_sparse_slots()
        max_lens = np.array([s.max_len for s in sparse_slots], np.int64)
        kcap = self.feed.key_capacity()
        num_slots = len(sparse_slots)
        out: List[List[PackedBatch]] = []
        for w in range(num_workers):
            lo = w * per_worker
            hi = min(lo + per_worker, n)
            recs = perm[lo:hi]
            batches: List[PackedBatch] = []
            for b in range(target):
                chunk = recs[b * bs:(b + 1) * bs]
                if chunk.size == 0 and recs.size:
                    chunk = recs[:bs]
                if chunk.size == 0:
                    chunk = perm[:bs]
                batches.append(pack_columnar(self._block, chunk, self.feed,
                                             kcap, num_slots, max_lens))
            out.append(batches)
        return out
