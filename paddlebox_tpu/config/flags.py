"""Process-level flag registry with environment override.

TPU-native analog of the reference's gflags tier (PADDLE_DEFINE_EXPORTED_* in
paddle/fluid/platform/flags.cc; box-cluster flags at flags.cc:946-975). Flags
are declared in code with a typed default and can be overridden by environment
variables named ``PBTPU_<FLAG_NAME>`` (mirroring the ``FLAGS_*`` env convention).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict

_REGISTRY: Dict[str, "_Flag"] = {}
_LOCK = threading.Lock()

_ENV_PREFIX = "PBTPU_"


class _Flag:
    __slots__ = ("name", "default", "value", "help", "parser", "from_env")

    def __init__(self, name: str, default: Any, help: str, parser: Callable[[str], Any]):
        self.name = name
        self.default = default
        self.help = help
        self.parser = parser
        env_name = _ENV_PREFIX + name.upper()
        env = os.environ.get(env_name)
        if env is not None:
            try:
                self.value = parser(env)
            except ValueError as e:
                raise ValueError(
                    f"invalid value {env!r} for flag {name!r} "
                    f"(from env {env_name}): {e}") from e
            self.from_env = True
        else:
            self.value = default
            self.from_env = False


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _parser_for(default: Any) -> Callable[[str], Any]:
    if isinstance(default, bool):
        return _parse_bool
    if isinstance(default, int):
        return int
    if isinstance(default, float):
        return float
    return str


def define_flag(name: str, default: Any, help: str = "") -> None:
    with _LOCK:
        if name in _REGISTRY:
            raise ValueError(f"flag {name!r} already defined")
        _REGISTRY[name] = _Flag(name, default, help, _parser_for(default))


def get_flag(name: str) -> Any:
    return _REGISTRY[name].value


def set_flag(name: str, value: Any) -> None:
    flag = _REGISTRY[name]
    if not isinstance(value, type(flag.default)) and flag.default is not None:
        value = flag.parser(str(value))
    flag.value = value


def all_flags() -> Dict[str, Any]:
    return {k: f.value for k, f in sorted(_REGISTRY.items())}


# ---------------------------------------------------------------------------
# Core flag set (parity with the box-cluster flag block, flags.cc:946-975, plus
# worker flags boxps_worker.cc:41-54, re-expressed for the TPU runtime).
# ---------------------------------------------------------------------------

# Reference flags that are STRUCTURAL NO-OPS here and therefore do not
# exist (deliberate divergences, see ARCHITECTURE.md):
#   enable_pullpush_dedup_keys — dedup is load-bearing in the fused step's
#       merge-then-optimize contract, never optional
#   padbox_record_pool_max_size / padbox_slotrecord_extend_dim — the
#       zero-object columnar path replaces the SlotObjPool; expand dims
#       live in TableConfig.expand_embed_dim
#   padbox_dataset_disable_polling — readers consume a fixed file list,
#       no polling loop exists
#   enable_sparse_push_barrier — the push is part of the fused step; there
#       is no async push stream to barrier on
#   feed-pass/shuffle/merge thread counts — read parallelism is
#       BoxDataset(read_threads=...); key registration and merge ride the
#       channel consumer; per-chunk staging parallelism is stack_threads

define_flag("shuffle_block_codec", True,
            "cross-host instance shuffle rides whole ColumnarBlocks "
            "(round 17, data/block_shuffle.py): header + raw column "
            "bytes per frame (whole-array tobytes/frombuffer), "
            "destination from ONE vectorized hash over rec_offsets "
            "(bit-parity with SlotRecord.shuffle_hash), fancy-index "
            "split into per-destination sub-blocks — zero per-record "
            "Python end to end. Off = the legacy per-record codec (the "
            "parity oracle; forces the record-path load for shuffled "
            "datasets). Keep it identical on every host for line rate: "
            "mixed frame kinds (also from a RANK-LOCAL downgrade — an "
            "archive file in one rank's shard, a host whose native lib "
            "didn't build) CONVERT at the merge worker with a loud "
            "warning — one stray shard degrades throughput, never "
            "kills the cluster pass")
define_flag("shuffle_connect_secs", 20.0,
            "TcpShuffler peer dial timeout in seconds: a dead peer "
            "raises ShufflePeerUnreachable naming the endpoint instead "
            "of the OS-default ~2-minute connect stall (the utils/"
            "rpc.py round-9 hygiene applied to the shuffle transport). "
            "Established-connection sends stay unbounded — the flush "
            "done-barrier timeout bounds the pass")
define_flag("dataset_disable_shuffle", False,
            "disable BOTH the cross-host instance shuffle stage and local "
            "in-memory shuffling (deterministic load-order passes)")
define_flag("auc_runner_mode", False,
            "AUC-runner replay mode (slots-shuffle evaluation)")
define_flag("check_nan_inf", False,
            "default for TrainerConfig.check_nan_inf: after each batch, "
            "check the loss for NaN/Inf and raise (FLAGS_check_nan_inf)")
define_flag("padbox_max_batch_keys", 0,
            "static per-batch key capacity override; 0 = derive from the "
            "feed config (DataFeedConfig.key_capacity)")
define_flag("sparse_table_load_factor", 0.75,
            "native host hash table resize load factor (hashtable.h:211)")
define_flag("dump_file_max_bytes", 2 << 30,
            "rotation size for debug dump files (2GB like dump writers)")
define_flag("chunk_prefetch_depth", 1,
            "single-host trainer: scan chunks staged AHEAD on a producer "
            "thread while the device trains (the shard_batches stager "
            "role; peak extra memory = this many staged chunks); 0 = "
            "stage inline between dispatches")
define_flag("h2d_lean", False,
            "input-bound deployments (slow host->device links): stage "
            "train batches on the LEAN wire — no perm/inv/first_idx/pos "
            "host products. With h2d_uid_wire (default) the sorted [K] "
            "uid vector still ships and the step runs the FAST push "
            "(device-derived maps by searchsorted — no jnp.unique sort); "
            "with it off, ids only ship and the step pays the on-device "
            "unique sort (~+8 ms on the axon chip, the round-5 tier). "
            "Wins when H2D bytes dominate the pass (the 68 MB/s tunnel "
            "regime, BASELINE.md e2e rows)")
define_flag("h2d_uid_wire", True,
            "lean-wire push reunification (round 8): under h2d_lean, ship "
            "the [K] int32 SORTED deduped uid vector next to the ids and "
            "derive perm/inverse/position maps on device (searchsorted + "
            "segment scatter-add + scatter-min) — the fast host-dedup "
            "push at lean-wire byte cost, bit-identical to the host-"
            "staged path. Also switches the sharded runners' push staging "
            "to uid-only (per-destination perm/inv/pos derived on device "
            "from the a2a'd bucket ids). Off = the round-5 ids-only wire "
            "(single-host trainer) / full host product staging (sharded)")
define_flag("wire_delta_ids", False,
            "measured wire experiment: ship the sorted uid vector as "
            "(int32 base, int16 deltas) — 2 bytes/key less H2D, one "
            "device cumsum to decode, pull-row reuse disabled (in-range "
            "padding recode; see pass_table.delta_encode_uids). Raises "
            "when an inter-uid gap exceeds int16 (very sparse pass "
            "shapes). Single-host uid wire only")
define_flag("h2d_stack_chunks", 1,
            "scan chunks whose host-staged batch arrays share ONE device "
            "transfer per leaf (the per-transfer fixed cost — ~250 ms on "
            "the axon tunnel — amortizes over the group; per-chunk views "
            "are device-side slices). 1 = one transfer set per chunk; "
            "peak staged host memory grows with the group")
define_flag("stack_threads", 4,
            "host batch-staging threads per scan chunk (lookup + dedup; "
            "the feed-thread pool role, box_wrapper.h:862); <=1 = serial")
define_flag("stager_threads", 4,
            "sharded-trainer routing threads: per-worker bucketize and "
            "per-destination push dedup fan out on this pool inside the "
            "stager (reference 20/30 reader/merge threads, "
            "flags.cc:966-968); <=1 = serial")
define_flag("stream_depth", 2,
            "sharded-trainer input stream: staged-ahead step queue depth "
            "(peak live routed steps is this + 2: one in the consumer's "
            "hands, one in flight on the stager thread; boxps "
            "device_reader_->Next double-buffer role)")
define_flag("profile_per_op", False,
            "accumulate per-op timing in the train loop (TrainFilesWithProfiler)")
define_flag("push_write", "auto",
            "how the push writes updated rows back into the pass slab: "
            "'scatter' (row scatter, cost ~ touched rows — right for CPU "
            "and small batches), 'rebuild' (pos map + full slab "
            "gather/select, flat cost ~ slab bytes; pos host-staged on "
            "the full wire, device-derived on the uid wire), or 'auto' "
            "(measured rebuild/scatter crossover on accelerators; "
            "scatter on CPU). The round-5 'log' mode was deleted in "
            "round 8 — no measured regime ever selected it; findings "
            "retained in BASELINE.md round 5")
define_flag("push_block_rows", 1024,
            "blocked-scatter tile height for push_write=blocked (round "
            "11): the sorted uid vector is bucketized into contiguous "
            "row blocks of this many slab rows and each touched block is "
            "applied with ONE dynamic_update_slice of a gathered tile "
            "instead of a giant row scatter (push_blocked_write). Must "
            "divide the table's pass_capacity (resolve_push_write "
            "validates). Cost class ~ min(touched_blocks) * block bytes: "
            "small blocks approach scatter's touched-rows cost, large "
            "blocks approach rebuild's slab-bytes cost — bench.py "
            "push_ladder records the crossover")
define_flag("push_blocked_pallas", False,
            "route push_write=blocked's per-block tile placement through "
            "the hand-written Mosaic kernel (pallas_blocked_write: grid "
            "over touched blocks, block ids scalar-prefetched, slab "
            "aliased in place) instead of the XLA fori_loop of "
            "dynamic_update_slices. Off-TPU it runs interpreted — "
            "correct but slow (bench records both tiers)")
define_flag("push_onehot_rows", 0,
            "MXU one-hot matmul accumulation for the first N merged rows "
            "of the uid-wire push (merge_grads_onehot): rows [0, N) merge "
            "as onehot(inv) @ grads on the MXU — cost flat in batch keys "
            "— while the tail keeps the VPU segment scatter-add, whose "
            "cost is flat in duplicates. Wins when a dense short tail of "
            "hot keys absorbs most of the batch's occurrences. f32 "
            "accumulation ORDER differs from "
            "the sorted segment-sum — a measured opt-in, not "
            "bit-parity with the oracle (exact for integer grads). "
            "0 = off (the default, oracle-exact path)")
define_flag("slab_embed_dtype", "float32",
            "DEVICE slab storage precision for the embedding weight "
            "columns (round-11 dtype diet): 'float32' = the classic "
            "homogeneous f32 [capacity, width] slab; 'bfloat16' = one "
            "uint16 slab where embed_w/embedx/expand weights store bf16 "
            "(half the bytes) and the header + ALL optimizer stats "
            "(g2sum/adam moments) store lossless f32 bit-splits — "
            "~2x pass rows per HBM byte at equal optimizer precision "
            "(accessor.ValueLayout.embed_dtype / encode_slab_rows). "
            "Host stores, checkpoints and the push/pull math stay f32; "
            "rows decode at gather and encode at write. Weight updates "
            "round to bf16 at the slab write (AUC-parity gated, "
            "tests/test_push_blocked.py), stats round-trip bit-exactly")
define_flag("flatten_dense_opt", True,
            "wrap the dense optimizer in optax.flatten so the whole dense "
            "update runs as one fused vector op instead of per-parameter "
            "op chains (elementwise optimizers only; exact same numbers)")
define_flag("use_pallas_push", False,
            "route the in-table adagrad row update through the hand-written "
            "Pallas kernel (embedding/pallas_push.py) instead of XLA "
            "(helped the old scatter write path ~2.6 ms/step on v5e; "
            "measured slightly SLOWER under push_write=rebuild — leave "
            "off there, BASELINE.md)")
define_flag("strict_bucket_overflow", False,
            "raise on sharded bucket overflow instead of dropping the "
            "overflowed keys' gradients with a warning (the "
            "PADDLE_ENFORCE discipline, box_wrapper_impl.h:139); the "
            "sharded_bucket_overflow stat counts drops either way")
define_flag("matmul_dtype", "float32",
            "dense matmul operand dtype: bfloat16 (MXU native, f32 "
            "accumulation; wins once the MLP dominates the step) or float32")
define_flag("hostplane", "p2p",
            "multi-process per-step host exchange transport (round 9): "
            "'p2p' = persistent socket mesh (fleet/mesh_comm.py) — "
            "endpoints rendezvous once through the TcpStore, then every "
            "per-step bucket/uid exchange rides direct peer connections "
            "(O(W*P*KB) bytes, true all-to-all; under h2d_uid_wire the "
            "per-destination dedup moves BEFORE the network so only "
            "sorted unique uid vectors travel), with a loud COLLECTIVE "
            "fallback to 'store' when any rank fails to dial its peers; "
            "'store' = the round-5 central TcpStore allgather funnel "
            "(O(W^2*P*KB) through one NIC + 3 counter round-trips per "
            "rank per step). Must be set identically on every rank — a "
            "split setting deadlocks the lockstep exchange")
define_flag("sharding_policy", "key-mod",
            "2-D sparse parallelism policy for the sharded pass table "
            "(round 13, parallel/sharding.py): 'key-mod' = shard by "
            "key % P (the BoxPS split_input_to_shard layout, bit-"
            "identical to the pre-policy path — the parity oracle); "
            "'table-wise' = each table pinned whole to one shard "
            "(table id from the feasign's high bits, see "
            "sharding_table_shift) so a table's sparse traffic flows "
            "only to its owner; '2d-grid' = table-group x row grid "
            "(sharding_grid_rows) with an optional replicated hot-key "
            "tier (sharding_hot_threshold). Must be set identically on "
            "every rank — the p2p rendezvous validates and fails loud "
            "on a split setting")
define_flag("sharding_num_tables", 64,
            "number of logical embedding tables the table-wise/2d-grid "
            "policies route over: table id = "
            "(key >> sharding_table_shift) % this")
define_flag("sharding_table_shift", 48,
            "bit position of the feasign's table/slot field for the "
            "table-wise/2d-grid policies (the reference packs the slot "
            "in the feasign's high bits); 0 = fold the low bits")
define_flag("sharding_grid_rows", 0,
            "row-axis size R of the 2d-grid policy (shard = "
            "table_group * R + key % R); must divide the shard count. "
            "0 = auto (largest divisor of P not above sqrt(P))")
define_flag("sharding_hot_threshold", 0,
            "2d-grid replicated hot tier: keys whose frequency-sketch "
            "estimate reaches this at the pass freeze are REPLICATED "
            "(served from the host mirror, dropped from the p2p uid "
            "wire by senders and re-added by owners) instead of "
            "routed. The sketch must be fed the same frequency "
            "knowledge on every rank (policy.observe is cluster-"
            "deterministic input by contract). 0 = hot tier off")
define_flag("sharding_hot_cap", 1024,
            "max replicated hot keys per shard for the 2d-grid hot "
            "tier — freeze_hot raises beyond it (an unbounded "
            "replicated set defeats the wire saving it exists for)")
define_flag("incremental_pass", True,
            "incremental pass lifecycle (BeginPass/EndPass delta, the "
            "BoxPS keep-rows-resident cadence): begin_pass diffs the new "
            "pass's key set against the rows already resident in the slab "
            "and promotes only NEW keys (device-side permute instead of a "
            "full host rebuild + H2D); end_pass transfers and writes back "
            "only the rows the pass actually touched. Bit-parity with the "
            "full path (tests/test_pass_incremental.py). Memory: the "
            "single-chip slab stays resident in HBM between passes (no "
            "extra copy); the SHARDED table instead keeps a host-DRAM "
            "mirror of each owned shard's slab between passes (~slab "
            "bytes of host RAM — small next to the host store itself, "
            "but not free). Off = rebuild the whole slab every pass (the "
            "pre-round-6 behavior, no residency anywhere)")
define_flag("obs_trace", True,
            "record named spans into the per-thread ring tracer "
            "(obs/tracer.py — the cheap always-on tier of the reference's "
            "tracing ladder, platform::RecordEvent role). ~1us/span; the "
            "ring is what export_chrome_trace and the stall watchdog "
            "dump read. Off = span() returns a shared no-op")
define_flag("obs_trace_capacity", 4096,
            "spans retained PER THREAD in the tracer ring before "
            "wrap-around (fixed memory: capacity * ~100B per thread)")
define_flag("obs_report_every", 20,
            "StepReport cadence in steps (obs/report.py): every N steps "
            "the trainer assembles one structured record — stage timer "
            "deltas, StatRegistry counter deltas, gauges, histogram "
            "percentiles, examples/sec — and emits it through the "
            "configured sink (obs_report_path); in multi-process runs "
            "non-zero ranks also piggyback it to rank 0 for the merged "
            "cluster view. <=0 = reporting off (zero assembly cost)")
define_flag("obs_report_path", "",
            "StepReport sink: '' = assemble + retain only (the watchdog "
            "and cluster aggregation still see reports), 'stderr' = one "
            "JSON line per report to stderr, any other value = append-"
            "JSONL file path (rank 0's file also carries the merged "
            "cluster_report records in multi-process runs)")
define_flag("obs_watchdog_secs", 0.0,
            "stall watchdog silence threshold in seconds (obs/"
            "watchdog.py, the native tools/tpu_watchdog.sh successor): "
            "runners beat at step and exchange boundaries; when no beat "
            "arrives within the threshold the watchdog dumps the last-K "
            "spans, every thread's stack, and the last StepReport to "
            "stderr. <=0 = disabled")
define_flag("obs_flight_dir", "",
            "flight-recorder directory (obs/flight.py, round 14): when "
            "set, every rank keeps an always-on bounded on-disk black "
            "box — segment-rotated JSONL of a flags+env+git-sha header, "
            "StepReports, cluster reports/health, span windows at "
            "report cadence, warning/error log lines and sampled beats, "
            "flushed per record so it survives SIGKILL — plus a SEALED "
            "postmortem manifest (last-K spans, every thread's stack, "
            "last reports) written on excepthook, SIGABRT/SIGTERM, or a "
            "watchdog fire. The failure artifact the elastic fleet "
            "(ROADMAP item 5) consumes. '' = off (zero cost)")
define_flag("obs_flight_segment_bytes", 4 << 20,
            "flight-recorder segment rotation size in bytes; total disk "
            "per rank is bounded by this times obs_flight_segments")
define_flag("obs_flight_segments", 4,
            "flight-recorder segments retained per rank (oldest "
            "deleted at rotation; each segment re-writes the run "
            "header so any surviving segment is self-contained)")
define_flag("obs_watchdog_action", "dump",
            "what the watchdog does after dumping: 'dump' = report only "
            "(fires once per silence window), 'raise' = also interrupt "
            "the main thread (KeyboardInterrupt) so a wedged job dies "
            "loudly instead of burning its reservation")
define_flag("serving_cache_rows", 65536,
            "hot-key embedding cache capacity per serving process in "
            "ROWS (serving/cache.py): the hottest rows live in one "
            "resident [rows, dim] f32 array in front of the mmap'd "
            "view stack, with frequency-gated admission and CLOCK "
            "eviction (HierarchicalKV's cache-semantics model). Memory "
            "= rows * dim * 4 bytes + ~100 B/row bookkeeping. 0 = no "
            "cache (every pull probes the mmap store)")
define_flag("serving_cache_admit", 2,
            "admission threshold for the serving hot-key cache: a "
            "missed key enters the cache only after this many misses "
            "within the admission sketch's aging window (TinyLFU-style "
            "scan resistance — a one-shot sweep over cold keys cannot "
            "flush the hot set). 1 = admit on first miss")
define_flag("serving_refresh_secs", 0.5,
            "delta-refresh poll cadence in seconds (serving/refresh."
            "py): the watcher re-discovers completed xbox views "
            "(SaveDelta/SaveBase DONE markers) on this interval and "
            "atomically swaps a freshly-composed view generation in — "
            "the serving-side bound on model staleness is this poll "
            "plus the new views' compile time. <=0 still polls at the "
            "0.05s floor")
define_flag("serving_pull_threads", 4,
            "bounded lookup pool per serving process (serving/server."
            "py): every pull RPC executes on one of these workers "
            "regardless of how many connections are open, so overload "
            "degrades by queueing (visible in the latency histogram) "
            "instead of by thrashing the box")
define_flag("serving_drain_secs", 10.0,
            "graceful-drain bound in seconds: at shutdown a serving "
            "process refuses new pulls and waits up to this long for "
            "in-flight pulls to finish before the transport stops")
define_flag("serving_report_requests", 200,
            "StepReport cadence for the serving plane, in pull "
            "REQUESTS (the serving step unit): every N pulls the "
            "process emits one obs window record — p50/p99 lookup "
            "latency from the serving_lookup_us histogram, keys/s, "
            "request count, cache hit rate — through the standard "
            "obs_report_path sink. <=0 = reporting off")
define_flag("serving_slo_us", 15000.0,
            "serving lookup latency SLO in microseconds (round 14): "
            "every report window each replica publishes gauge "
            "serving_slo_burn = window p99 of serving_lookup_us divided "
            "by this — burn > 1.0 means the replica is out of SLO and "
            "the cluster health plane (obs/health.py) scores it "
            "degraded. Default 15ms sits above the recorded quiet-"
            "container p99 ceiling (BASELINE round 12: 4.6-7.1ms at "
            "b4096 incl first-touch page-in). <=0 disables the gauge")
define_flag("serving_num_shards", 1,
            "serving fleet width in BOXES (round 21): the sharded tier "
            "partitions the key space across this many boxes; each box "
            "filters its views to its own slice (serving/store.py "
            "ShardSpec) and the fleet client routes every pull by the "
            "same policy. 1 = the single-box plane, no filtering")
define_flag("serving_shard_index", -1,
            "which box of the serving fleet THIS process serves "
            "(0..serving_num_shards-1). -1 = unsharded: serve the full "
            "view (single-box mode, probes, tests). MultiBoxFleet sets "
            "this per child via flag overrides")
define_flag("serving_shard_policy", "",
            "sharding policy name for the serving fleet partition "
            "(parallel/sharding.py resolve_sharding_policy): '' = the "
            "flag-configured trainer policy (sharding_policy), so the "
            "serving partition matches training by default; set "
            "explicitly ('key-mod', '2d-grid') to diverge")
define_flag("serving_hot_keys", "",
            "path to a hot-key set file (serving/store.py "
            "write_hot_keys): every box ADDITIONALLY keeps these rows — "
            "the replicated hot tier — so the client may answer a "
            "head-key pull from ANY box instead of converging on the "
            "owner. '' = no replicated tier")
define_flag("serving_journal_dir", "",
            "comma-separated touched-row journal dirs to tail for "
            "journal-fed freshness (round 21, serving/refresh.py "
            "JournalDeltaSource): touched rows land in the served view "
            "one refresh poll after the trainer flushes them, cutting "
            "staleness from the SaveDelta interval to seconds. '' = "
            "refresh from completed xbox views only")
define_flag("ckpt_format", "columnar",
            "sparse batch-model checkpoint format (round 15): 'columnar' "
            "= sparse.xman manifest + N striped binary part files "
            "written by a parallel writer pool (atomic tmp+fsync+rename "
            "per part; the manifest lands only after every part is "
            "durable) and loaded via mmap + a reader pool "
            "(embedding/ckpt_store.py); 'pickle' = the legacy single "
            "sparse.pkl blob. Loaders sniff the format, so either kind "
            "of checkpoint loads regardless of this flag")
define_flag("ckpt_parts", 8,
            "part files per columnar sparse checkpoint (contiguous row "
            "stripes; trimmed so no part is empty). More parts = more "
            "writer/reader parallelism and smaller atomic units; the "
            "manifest pins the exact part list, so stray parts from an "
            "interrupted larger-parts save are ignored")
define_flag("ckpt_io_threads", 0,
            "checkpoint writer/reader pool threads; 0 = one per part "
            "capped at the box's cores (and at 16). The pool writes/"
            "reads disjoint row stripes — np.tofile/memmap copies "
            "release the GIL, so the threads genuinely overlap")
define_flag("ckpt_journal", True,
            "persistent touched-row journal (train/journal.py): every "
            "end-of-pass write-back appends its touched (keys, rows) "
            "delta and the day-cadence lifecycle mutations append "
            "deterministic event records, into segment-rotated binary "
            "files under <batch_model_dir>/_journal/rank<r>. Enables "
            "save_base(mode='touched'/'auto') — day-boundary snapshot "
            "cost proportional to the delta — and the elastic mid-day "
            "rejoin artifact (replay-over-base, ROADMAP item 5). SSD "
            "tier movement is journaled as MOVE records (spill / "
            "fault-in key sets) so touched saves stay exact with the "
            "tier engaged; only server-side PS spills, rotation loss "
            "and external store loads still taint the epoch")
define_flag("ckpt_journal_segment_bytes", 64 << 20,
            "touched-row journal segment rotation size in bytes; each "
            "segment re-writes a self-describing header (flight-"
            "recorder discipline), records are flushed per append so a "
            "SIGKILL leaves a parseable prefix")
define_flag("ckpt_journal_segments", 32,
            "max live journal segments per rank; exceeding the bound "
            "drops the OLDEST segment and marks the epoch incomplete "
            "(touched saves then fall back to full, which re-anchors "
            "and resets) — bounded disk beats unbounded promises")
define_flag("ckpt_xbox_columnar", True,
            "emit xbox serving views (SaveBase/SaveDelta output) "
            "DIRECTLY as the serving columnar file (view.xcol, sorted "
            "keys) instead of embedding.pkl: serving's compile_view_dir "
            "becomes a detect-and-skip no-op on these dirs and "
            "delta-refresh staleness drops by the pickle->columnar "
            "re-encode. Off = the legacy pkl views (readers handle "
            "both, mixed histories compose)")
define_flag("obs_http_port", 0,
            "per-rank live ops HTTP endpoint (obs/exporter.py, round "
            "18): every rank (and every serving replica, whose replica "
            "index is its rank) binds 127.0.0.1:<port + rank> and "
            "serves /metrics (Prometheus text exposition of the "
            "StatRegistry counters/gauges/histograms + quality-plane "
            "auc/copc/ctr), /report (latest StepReport; rank 0 adds "
            "the merged cluster report), /health (rank 0: per-rank "
            "cluster health scores), /stacks (every thread's stack), "
            "/flight (black-box segment list + tail) and /quality — "
            "all answered from defensive snapshots, never a training "
            "lock. A port already in use warns and disables the "
            "endpoint. 0 = off (zero cost)")
define_flag("quality_metrics", True,
            "tagged quality-metric plane (metrics/quality.py, round "
            "18): the trainers stream per-tag masked AUC (the 'all' "
            "stream, per-cmatch tags, per-task heads), COPC (click "
            "over predicted click — the calibration alarm), actual/"
            "predicted CTR per tag AND per slot into sum-mergeable "
            "bucket tables (MetricMsg parity with the reference's "
            "tagged metric family); pass_end reports carry the "
            "computed bundle, multi-process runs ship the raw state "
            "so rank 0 merges a cluster-wide quality report, and the "
            "quality_auc/quality_copc gauges feed the health plane. "
            "Off = no quality adds (zero cost)")
define_flag("quality_table_size", 65536,
            "bucket count of each tagged quality AUC table (the "
            "BasicAucCalculator table_size role; the reference uses "
            "1<<20 — 65536 keeps per-tag memory at 1 MB and the "
            "pass_end state wire compact while holding AUC resolution "
            "to ~1.5e-5 of pred space). Every rank must use the same "
            "value: cluster merge refuses mismatched table sizes")
define_flag("data_quality", True,
            "slot-level data-quality drift monitor (metrics/drift.py, "
            "round 18): the columnar ingest plane accumulates per-slot "
            "coverage, keys/record and a distinct-key sketch per "
            "report window (one bincount over key_slot per block) "
            "plus label/pred histograms; each pass_end rolls the "
            "window against a rolling reference and publishes the "
            "data_drift_score / data_dropped_slots gauges the cluster "
            "HealthMonitor penalizes — a dropped upstream slot or a "
            "calibration blow-up turns the rank unhealthy through the "
            "same plane the elastic fleet triggers on. Off = no "
            "monitoring (zero cost)")
define_flag("data_quality_warn", 0.5,
            "drift-score warn threshold in [0, 1]: a rolled window "
            "whose worst per-slot departure (coverage drop, keys/"
            "record drift, cardinality collapse) or label/pred "
            "distribution drift reaches this logs a warning on the "
            "victim rank, and rank 0's HealthMonitor scores any rank "
            "whose data_drift_score gauge is past it -0.6 — past the "
            "0.5 healthy bar on its own (flag 'data_drift' in the "
            "cluster_health record)")
define_flag("preload_promote", True,
            "overlap the NEXT pass's host-side promote work (key diff + "
            "host-store reads for non-resident keys) with the current "
            "pass's training on the preload thread (the PreLoad/"
            "WaitFeedPassDone tail-hiding role, box_wrapper.h:1131-1172); "
            "only active with incremental_pass and a store that supports "
            "lookup_present")
define_flag("debug_lock_order", False,
            "construct the package's locks through the lockwatch runtime "
            "validator (utils/lockwatch.py): records per-thread "
            "acquisition order in the static BX7xx Class._attr identity "
            "vocabulary, flags AB/BA inversions loudly the first time "
            "both nestings are observed (lockwatch_inversions stat), and "
            "publishes lock_hold_us_<name> histograms through the obs "
            "StatRegistry. Off (default) = plain threading locks, zero "
            "added cost; the concurrency suites run with it on")
define_flag("device_obs", True,
            "device-plane observability (obs/device.py, round 20): "
            "every jit entry point runs through instrument_jit — exact "
            "per-fn compile counts + compile wall time, a one-time "
            "cost/memory-analysis snapshot (flops & bytes-accessed per "
            "example, temp/alias bytes — the step_audit math, live), a "
            "steady-state RECOMPILE SENTINEL (device_recompiles stat + "
            "HealthMonitor penalty), a donation audit (donation_miss "
            "when a donated buffer was copied instead of aliased — the "
            "regime-step mechanism), and the HBM live-buffer ledger "
            "sampled at report cadence. Off = bare jax.jit everywhere "
            "(zero added cost, zero device signals); bench.py's "
            "device_overhead block holds the on-cost at <=2%")
define_flag("device_recompile_warmup", 3,
            "compiles each instrumented fn may accumulate before the "
            "recompile sentinel treats further compiles as steady-state "
            "shape/dtype churn (counted in device_recompiles, logged "
            "loudly once per fn, scored unhealthy by the cluster "
            "HealthMonitor): legitimate multi-signature entry points "
            "(a tail chunk, an eval twin shape) fit inside the "
            "allowance; a mis-staged batch recompiling every step "
            "does not")
define_flag("device_donation_min_bytes", 65536,
            "donation-audit floor: donated buffers smaller than this "
            "are not pointer-checked (XLA legitimately declines to "
            "alias tiny buffers and the alarm exists for slab-scale "
            "copies — the >=4M-row regime step is a ~272MB one)")
define_flag("device_leak_windows", 3,
            "live-buffer leak detector: consecutive ledger samples "
            "(report cadence) of strictly-growing total device bytes "
            "before device_leak_suspect fires (once per sustained "
            "climb, loud warn with the growth)")
define_flag("device_leak_min_bytes", 1 << 20,
            "live-buffer leak detector: minimum total growth across "
            "the monotonic window before it counts — compile-time "
            "constant buffers and small per-pass arrays must not page "
            "an operator")
define_flag("host_store_stripes", 0,
            "shard the host embedding store's hash index into N "
            "stripes (embedding/striped_store.py): keys route by "
            "splitmix64(key) mod N, each stripe owns an independent "
            "inner store (+ rng seeded seed+stripe) so lookups gather "
            "per-stripe in parallel threads and the single global "
            "index stops being the billion-key bottleneck. 0 (default) "
            "= the flat single-index store — bit-compatible with every "
            "existing checkpoint/journal; striped stores draw a "
            "DIFFERENT init stream (per-stripe rngs), so flip it only "
            "on fresh runs or restored-from-checkpoint runs")
# streaming continuous training (data/streaming.py +
# train/streaming_runner.py): the day/pass cadence collapsed into
# bounded micro-passes tailing a live source
define_flag("streaming_micro_pass_instances", 4096,
            "target instances per streaming micro-pass window: the "
            "directory watcher accumulates ready files until their "
            "line count reaches this bound, then hands the window to "
            "the preloader — the unit of training, admission, "
            "micro-checkpointing and journal publish in the streaming "
            "plane (smaller = fresher served vectors, more per-pass "
            "overhead)")
define_flag("streaming_poll_secs", 0.2,
            "streaming source poll interval: how often the directory "
            "watcher re-lists the watched dir (and the socket spooler "
            "checks its seal cadence) while waiting for new data; also "
            "the granularity of the runner's idle wait")
define_flag("streaming_stable_polls", 2,
            "consecutive size-stable watcher polls before a bare "
            "(non temp-suffixed) file counts as sealed and may enter a "
            "micro-pass window — the torn-write guard for writers that "
            "append in place instead of the write-temp-then-rename "
            "convention (.tmp/.part/._* names are always skipped)")
define_flag("streaming_base_every", 8,
            "micro-checkpoint decimation: save_base(mode='auto') every "
            "K admitted micro-passes (journal segments are published "
            "at EVERY micro-pass boundary regardless — serving "
            "freshness rides the journal, durability rides the base "
            "cadence). 0 = no in-run base saves")
define_flag("streaming_admission_max_drift", 0.8,
            "drift-gated admission threshold: a loaded micro-pass "
            "window whose SlotDriftMonitor preview score against the "
            "rolling reference of ADMITTED windows reaches this is "
            "refused before begin_pass — it never trains, never "
            "mutates the store, and never enters the reference. "
            "0 disables the gate")
define_flag("streaming_idle_timeout_secs", 0.0,
            "streaming runner exit condition: stop after this many "
            "seconds with no new complete window from the source "
            "(0 = run until stop() or max_micro_passes) — the bound "
            "bench/test/demo legs use to drain a finite drop")
# feed-to-serve watermark plane (obs/watermark.py, round 20): born-ts
# lineage through train->journal->serving, tier-hit telemetry, and the
# freshness/tier SLO burn gauges HealthMonitor alarms on
define_flag("obs_watermark", True,
            "feed-to-serve watermark plane master switch: when on, the "
            "streaming boundary stamps every journal publish with the "
            "window's born-ts span, the serving plane stamps pull "
            "responses with its applied watermark, and both ends "
            "observe the end-to-end freshness histogram. Off = no "
            "stamps, no freshness samples (the pairwise overhead "
            "bench's control arm); everything else degrades to "
            "pre-round-20 behavior")
define_flag("freshness_slo_secs", 30.0,
            "feed-to-serve freshness SLO: the serving report window's "
            "p99 of (pull time - applied watermark) is divided by this "
            "to form the serving_freshness_burn gauge — burn > 1 means "
            "served vectors are older than the promise and "
            "HealthMonitor flags the rank (freshness_burn, -0.4). "
            "0 disables the burn computation (freshness is still "
            "measured)")
define_flag("tier_hit_rate_warn", 0.05,
            "tiered-store hit-rate floor: when a warm feed-pass "
            "lookup's resident-hit rate (host-RAM hits / keys looked "
            "up) falls BELOW this, tier_hit_burn (= warn_rate / "
            "observed_rate) exceeds 1 and HealthMonitor flags the rank "
            "(tier_hit_low, -0.3) — the SSD tier is thrashing instead "
            "of absorbing the cold tail. Cold stores (first passes) "
            "never burn. 0 disables")
