"""Typed structured configs.

TPU-native analog of the reference's proto tier: DataFeedDesc
(paddle/fluid/framework/data_feed.proto), TrainerDesc + BoxPSWorkerParameter
(framework/trainer_desc.proto:78,121-129), sparse-optimizer hyperparameters
(framework/fleet/heter_ps/optimizer_conf.h:20-45) and CTR accessor thresholds
(distributed/ps/table/ctr_accessor.{h,cc}). Dataclasses instead of protobuf:
they are hashable/static-friendly for jit closure capture.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from paddlebox_tpu.config import flags


@dataclasses.dataclass(frozen=True)
class SparseOptimizerConfig:
    """Hyperparameters of the in-table sparse optimizer.

    Field names and defaults mirror heter_ps/optimizer_conf.h:20-45 so configs
    written against the reference carry over unchanged.
    """

    # embed_w (the 1-d "lr" weight) SGD
    nonclk_coeff: float = 0.1
    clk_coeff: float = 1.0
    min_bound: float = -10.0
    max_bound: float = 10.0
    learning_rate: float = 0.05
    initial_g2sum: float = 3.0
    initial_range: float = 0.0
    beta1_decay_rate: float = 0.9
    beta2_decay_rate: float = 0.999
    ada_epsilon: float = 1e-8
    # embedx (the mf_dim-wide factor vector)
    mf_create_thresholds: float = 10.0
    mf_learning_rate: float = 0.05
    mf_initial_g2sum: float = 3.0
    mf_initial_range: float = 1e-4
    mf_beta1_decay_rate: float = 0.9
    mf_beta2_decay_rate: float = 0.999
    mf_min_bound: float = -10.0
    mf_max_bound: float = 10.0
    mf_ada_epsilon: float = 1e-8
    nodeid_slot: int = 9008
    feature_learning_rate: float = 0.05
    optimizer: str = "adagrad"  # adagrad | adam | adam_shared | naive


@dataclasses.dataclass(frozen=True)
class TableConfig:
    """Sparse embedding table shape + lifecycle policy.

    embedx_dim mirrors BoxPS ``embedx_dim`` (box_wrapper.h:650 GetInsEx arg);
    decay/shrink thresholds mirror CtrCommonAccessor (ctr_accessor.cc:63-79).
    """

    embedx_dim: int = 8                  # factor width (pull returns 1+embedx ... cvm adds 2)
    expand_embed_dim: int = 0            # second table for NN-cross (pull_box_extended_sparse)
    pass_capacity: int = 1 << 20         # max unique keys resident per pass (HBM slab rows)
    value_dtype: str = "float32"
    # accessor lifecycle (ctr_accessor semantics)
    show_click_decay_rate: float = 0.98
    delete_threshold: float = 0.8
    delete_after_unseen_days: float = 30.0
    base_threshold: float = 1.5
    delta_threshold: float = 0.25
    delta_keep_days: float = 16.0
    optimizer: SparseOptimizerConfig = dataclasses.field(
        default_factory=SparseOptimizerConfig)
    # host/SSD tiering
    host_shard_bits: int = 6             # host store sharded into 2**bits locks
    ssd_dir: Optional[str] = None        # spill tier directory; None = DRAM only
    ssd_threshold_mb: float = 0          # spill host values beyond this budget

    def ssd_max_resident_rows(self, row_width: int) -> Optional[int]:
        """DRAM row budget for the pass-cadence limiter
        (CheckNeedLimitMem, box_wrapper.h:627-629); None = no limit.
        Fractional MB budgets are honored (small-scale tests)."""
        if not self.ssd_dir or not self.ssd_threshold_mb:
            return None
        return int(self.ssd_threshold_mb * (1 << 20)) // (row_width * 4)


@dataclasses.dataclass(frozen=True)
class SlotConfig:
    """One feature slot (DataFeedDesc.multi_slot_desc.slots entry)."""

    name: str
    type: str = "uint64"     # uint64 (sparse feasign) | float (dense)
    dim: int = 1             # dense dim for float slots
    is_used: bool = True
    max_len: int = 64        # per-instance value cap used for static batch packing


@dataclasses.dataclass(frozen=True)
class DataFeedConfig:
    """Analog of DataFeedDesc proto (data_feed.proto) + packer capacities."""

    slots: Tuple[SlotConfig, ...] = ()
    batch_size: int = 512
    pipe_command: str = ""               # optional preprocessing pipe, like ref pipe_command
    parser: str = "multislot"            # multislot text | binary archive
    rank_offset: bool = False            # emit pv rank-offset matrix (join phase)
    # per-task label slots for multi-task models: (task_name, slot_name)
    # pairs; tasks not listed fall back to the primary click label
    # (MMoE/ESMM train each head on its own label, metrics.h MultiTask)
    task_label_slots: Tuple[Tuple[str, str], ...] = ()
    # static capacity of flattened sparse keys per batch; 0 = batch*avg heuristic
    batch_key_capacity: int = 0
    # lines start with the instance id string (SlotRecordInMemoryDataFeed
    # parse_ins_id_); the id keys dump-field lines and InputTable aux-row
    # translation (InputTableDataFeed, data_feed.h:2221-2252)
    parse_ins_id: bool = False

    def used_sparse_slots(self) -> List[SlotConfig]:
        return [s for s in self.slots if s.is_used and s.type == "uint64"]

    def used_dense_slots(self) -> List[SlotConfig]:
        return [s for s in self.slots if s.is_used and s.type == "float"]

    def key_capacity(self, batch_size: Optional[int] = None) -> int:
        if self.batch_key_capacity:
            return self.batch_key_capacity
        override = int(flags.get_flag("padbox_max_batch_keys"))
        if override:
            return override
        bs = batch_size or self.batch_size
        per_ins = sum(min(s.max_len, 16) for s in self.used_sparse_slots())
        return max(128, bs * max(per_ins, 1))


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout. Axes follow jax.sharding.Mesh conventions."""

    data: int = 1        # data-parallel axis size ("dp")
    model: int = 1       # table-shard / tensor axis size ("mp")
    pipeline: int = 1    # pipeline stages ("pp")
    axis_names: Tuple[str, ...] = ("data", "model")


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Two-tier, pass-cadenced checkpoints (SaveBase/SaveDelta semantics,
    box_wrapper.cc:1286-1318)."""

    batch_model_dir: str = "ckpt/batch"
    xbox_model_dir: str = "ckpt/xbox"
    save_delta_every_passes: int = 1
    save_base_every_days: int = 1
    async_save: bool = True


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """Analog of TrainerDesc + BoxPSWorkerParameter (trainer_desc.proto:78,121-129)."""

    # TrainerDesc compat (STRUCTURAL NO-OP): the reference's device-worker
    # thread count. Here the mesh defines device concurrency (one shard_map
    # program) and host staging parallelism comes from the stack_threads /
    # stream_depth flags — accepted so TrainerDesc configs carry over,
    # never consulted.
    thread_num: int = 1
    sync_mode: str = "step"              # step | k_step | async | sharding
    sync_weight_step: int = 1            # K in K-step dense sync
    # one flat allreduce ring across ALL devices even on a 2D (node, chip)
    # mesh, instead of the hierarchical RS/psum/AG split (the reference's
    # sync_one_ring_ TrainerDesc knob, boxps_worker.cc SyncParam)
    sync_one_ring: bool = False
    async_mode: bool = False             # host async dense table
    sharding: bool = False               # ZeRO-1 dense param partitioning
    dump_fields: Tuple[str, ...] = ()
    dump_fields_path: str = ""
    dump_thread_num: int = 1
    dense_lr: float = 1e-3
    dense_optimizer: str = "adam"
    # default from the check_nan_inf env flag (FLAGS_check_nan_inf)
    check_nan_inf: bool = dataclasses.field(
        default_factory=lambda: bool(flags.get_flag("check_nan_inf")))
    profile: bool = False
    scan_chunk: int = 8                  # batches fused per device dispatch
                                         # (lax.scan megastep); 1 = off
    # dense-tower compute dtype: "float32" | "bfloat16" (mixed precision —
    # params/optimizer state stay f32, matmuls run bf16 on the MXU; bf16
    # keeps f32's exponent range so CTR losses need no loss scaling)
    compute_dtype: str = "float32"
    # sharded-trainer pull/push all_to_all payload dtype: "float32" |
    # "bfloat16". bf16 halves the ICI bytes of the two value a2as (the
    # walk_to_src/walk_to_dest traffic); the in-table optimizer still
    # merges and updates in f32 (grads upcast after transport). The slab
    # and its state columns are untouched — only the wire format changes.
    a2a_dtype: str = "float32"
    # chunk-synchronous sparse: decouple the sparse and dense batch sizes.
    # The table sees ONE pull + ONE merged push per scan chunk (effective
    # sparse batch = scan_chunk × batch_size; pulls read chunk-start
    # state), while dense adam keeps its exact per-batch cadence inside
    # the chunk. The sparse analog of K-step dense sync / the reference's
    # async-table staleness (boxps_worker.cc:57-366) — a throughput mode
    # for runtimes where per-batch table ops dominate (BASELINE.md axon
    # characterization). scan_chunk=1 (or chunks of 1) is bit-identical
    # to exact mode; chunks whose batches share no keys are bit-identical
    # at any chunk size. Unsupported with expand / data_norm / async
    # dense (construction-time error).
    sparse_chunk_sync: bool = False
