from paddlebox_tpu.config import flags
from paddlebox_tpu.config.configs import (
    TableConfig,
    SparseOptimizerConfig,
    DataFeedConfig,
    TrainerConfig,
    CheckpointConfig,
    MeshConfig,
)

__all__ = [
    "flags",
    "TableConfig",
    "SparseOptimizerConfig",
    "DataFeedConfig",
    "TrainerConfig",
    "CheckpointConfig",
    "MeshConfig",
]
