"""Elastic heartbeat manager.

Skeleton of python/paddle/distributed/fleet/elastic/manager.py (etcd-based
node watch): each rank bumps a store COUNTER on an interval; the watcher
judges staleness by how long a peer's counter has sat unchanged on its OWN
clock — no cross-host timestamp comparison, so clock skew between hosts
cannot fake a death. The BoxPS training path itself is gang-scheduled
(SURVEY.md §5.3 — a rank failure kills the job and recovery is
resume-from-last-SaveBase), so the default callback raises; schedulers
that support scale-in/out can install their own restart hook instead.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from paddlebox_tpu.fleet.store import TcpStoreClient


class DeadRankError(RuntimeError):
    pass


class ElasticManager:
    def __init__(self, client: TcpStoreClient, rank: int, world: int,
                 heartbeat_interval: float = 2.0,
                 stale_after: float = 10.0,
                 on_fault: Optional[Callable[[List[int]], None]] = None):
        self.client = client
        self.rank = rank
        self.world = world
        self.interval = heartbeat_interval
        self.stale_after = stale_after
        self.on_fault = on_fault
        self._stop = threading.Event()
        self._dead: List[int] = []
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True)
        self._watch_thread = threading.Thread(target=self._watch_loop,
                                              daemon=True)

    def start(self) -> None:
        self._beat()
        self._hb_thread.start()
        self._watch_thread.start()

    def _key(self, rank: int) -> str:
        return "elastic/hb/%d" % rank

    def _beat(self) -> None:
        self.client.add(self._key(self.rank), 1)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._beat()
            except (ConnectionError, OSError, RuntimeError):
                return  # store gone; the job is ending

    def _watch_loop(self) -> None:
        # (counter value, local time it last changed) per peer
        seen: Dict[int, Tuple[int, float]] = {}
        start = time.monotonic()
        while not self._stop.wait(self.interval):
            now = time.monotonic()
            dead = []
            for r in range(self.world):
                if r == self.rank:
                    continue
                try:
                    c = self.client.counter(self._key(r))
                except (ConnectionError, OSError, RuntimeError):
                    return
                last = seen.get(r)
                if last is None or c != last[0]:
                    seen[r] = (c, now)
                    continue
                born = start if last[0] == 0 else last[1]
                if now - born > self.stale_after:
                    dead.append(r)
            if dead:
                # flag and notify, but KEEP heartbeating: surviving ranks
                # must not look dead to each other while a restart hook
                # replaces the lost one
                self._dead = dead
                if self.on_fault is not None:
                    self.on_fault(dead)
                return

    @property
    def dead_ranks(self) -> List[int]:
        return list(self._dead)

    def check(self) -> None:
        """Raise if a peer died (call at pass boundaries — the natural
        recovery unit, SURVEY.md §5.3)."""
        if self._dead:
            raise DeadRankError("dead ranks: %s" % self._dead)

    def stop(self) -> None:
        self._stop.set()
