"""TCP key-value store: rendezvous + counters for the fleet control plane.

Role of the GlooWrapper rendezvous store (gloo_wrapper.h:53,169-183 — HDFS
file store or HTTP store) and of the brpc control endpoints: hosts publish
small values (endpoints, counters, metric partials, heartbeats) under string
keys; `add` is the atomic counter primitive barriers are built from.
Transport = the shared framed-RPC stack (utils/rpc.py) with class
resolution disabled entirely (only str/bytes/int travel here).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from paddlebox_tpu.utils.rpc import FramedClient, FramedServer, plain_loads


class KVStoreServer:
    def __init__(self, host: str = "0.0.0.0", port: int = 0) -> None:
        self._kv: Dict[str, bytes] = {}  # guarded-by: _cv
        self._counters: Dict[str, int] = {}  # guarded-by: _cv
        self._cv = threading.Condition()
        self._rpc = FramedServer(self._handle, plain_loads, host, port)

    @property
    def port(self) -> int:
        return self._rpc.port

    # ------------------------------------------------------------- handlers
    def _handle(self, req: dict) -> Any:
        op = req["op"]
        key = req.get("key", "")
        if op == "set":
            with self._cv:
                self._kv[key] = req["value"]
                self._cv.notify_all()
            return True
        if op == "get":
            with self._cv:
                return self._kv.get(key)
        if op == "wait":
            deadline = time.monotonic() + req.get("timeout", 60.0)
            with self._cv:
                while key not in self._kv:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        raise TimeoutError("store wait(%s) timed out" % key)
                return self._kv[key]
        if op == "add":
            with self._cv:
                cur = self._counters.get(key, 0) + int(req.get("amount", 1))
                self._counters[key] = cur
                self._cv.notify_all()
                return cur
        if op == "counter":
            with self._cv:
                return self._counters.get(key, 0)
        if op == "wait_counter_ge":
            target = int(req["target"])
            deadline = time.monotonic() + req.get("timeout", 60.0)
            with self._cv:
                while self._counters.get(key, 0) < target:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        raise TimeoutError(
                            "store wait_counter(%s>=%d) timed out"
                            % (key, target))
                return self._counters[key]
        if op == "delete":
            with self._cv:
                self._kv.pop(key, None)
                self._counters.pop(key, None)
            return True
        if op == "keys":
            with self._cv:
                return sorted(self._kv)
        raise ValueError("unknown store op " + op)

    def stop(self) -> None:
        self._rpc.stop()


class TcpStoreClient:
    def __init__(self, host: str, port: int, timeout: float = 300.0) -> None:
        self._rpc = FramedClient(host, port, plain_loads, timeout)

    def set(self, key: str, value: bytes) -> None:
        self._rpc.call({"op": "set", "key": key, "value": value})

    def get(self, key: str) -> Optional[bytes]:
        return self._rpc.call({"op": "get", "key": key})

    def wait(self, key: str, timeout: float = 60.0) -> bytes:
        return self._rpc.call({"op": "wait", "key": key, "timeout": timeout},
                              op_timeout=timeout)

    def add(self, key: str, amount: int = 1) -> int:
        return self._rpc.call({"op": "add", "key": key, "amount": amount})

    def counter(self, key: str) -> int:
        return self._rpc.call({"op": "counter", "key": key})

    def wait_counter_ge(self, key: str, target: int,
                        timeout: float = 60.0) -> int:
        return self._rpc.call({"op": "wait_counter_ge", "key": key,
                               "target": target, "timeout": timeout},
                              op_timeout=timeout)

    def delete(self, key: str) -> None:
        self._rpc.call({"op": "delete", "key": key})

    def keys(self):
        return self._rpc.call({"op": "keys"})

    def close(self) -> None:
        self._rpc.close()
