"""Heterogenous parallel: CPU workers offload dense compute to an
accelerator service.

TPU-native re-design of HeterWrapper / HeterXpuTrainer / HeterCpuWorker
(paddle/fluid/framework/fleet/heter_wrapper.{h,cc}; trainer.h:184): in the
reference, CPU-bound workers run the data pipeline + sparse PS traffic and
ship the dense forward/backward to a GPU/XPU service over brpc. Here:

  * ``HeterDenseService`` lives on the accelerator host: it owns the dense
    params + optimizer and serves jitted train/eval steps over the shared
    framed RPC (utils/rpc.py, the brpc stand-in). Input per call is the
    batch's pulled embedding view + batch meta; output is the embedding
    cotangent (for the worker's sparse push) + loss + preds. Dense updates
    never leave the service.
  * ``HeterTrainer`` is the CPU-side worker: the Downpour data/sparse
    machinery (pull from the CPU PS, dedup, push raw grads back) with the
    compute step replaced by the RPC call.

The split point is the pulled embedding [K, 3+D] — exactly the tensor the
reference ships between heter workers (heter_wrapper.cc SerializeToReq of
the per-batch vars).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from paddlebox_tpu.config.configs import (DataFeedConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data.dataset import BoxDataset
from paddlebox_tpu.metrics.auc import MetricRegistry
from paddlebox_tpu.ps.worker import Communicator, DownpourTrainer
from paddlebox_tpu.utils.rpc import FramedClient, FramedServer, make_loads
from paddlebox_tpu.utils.lockwatch import make_lock


def _allow(module: str, name: str) -> bool:
    return module.split(".")[0] == "numpy"


_loads = make_loads(_allow)


class HeterDenseService:
    """Accelerator-side dense executor (the HeterXpuTrainer service role)."""

    def __init__(self, model, feed: DataFeedConfig, dense_lr: float = 1e-3,
                 use_cvm: bool = True, seed: int = 0,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm

        self.model = model
        B = feed.batch_size
        S = len(feed.used_sparse_slots())
        self.params = model.init(jax.random.PRNGKey(seed))
        self.opt = optax.adam(dense_lr)
        self.opt_state = self.opt.init(self.params)
        self._lock = make_lock("HeterDenseService._lock")

        def loss_fn(params, emb, batch):
            pooled = fused_seqpool_cvm(emb, batch["segments"],
                                       batch["valid"], B, S, use_cvm)
            logits = model.apply(params, pooled, batch.get("dense"))
            lab = batch["labels"].astype(jnp.float32)
            bce = optax.sigmoid_binary_cross_entropy(logits, lab)
            denom = jnp.maximum(batch["ins_valid"].sum(), 1.0)
            loss = jnp.where(batch["ins_valid"], bce, 0.0).sum() / denom
            return loss, jax.nn.sigmoid(logits)

        def train_step(params, opt_state, emb, batch):
            grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1),
                                         has_aux=True)
            (loss, preds), (dparams, demb) = grad_fn(params, emb, batch)
            updates, opt_state = self.opt.update(dparams, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, demb, loss, preds

        def eval_step(params, emb, batch):
            _, preds = loss_fn(params, emb, batch)
            return preds

        from paddlebox_tpu.obs.device import instrument_jit
        self._train_step = instrument_jit(train_step, "heter_train_step",
                                          donate_argnums=(0, 1),
                                          example_count=B)
        self._eval_step = instrument_jit(eval_step, "heter_eval_step",
                                         example_count=B)
        self._rpc = FramedServer(self._handle, _loads, host, port)

    @property
    def port(self) -> int:
        return self._rpc.port

    def _batch_to_device(self, req: dict) -> Dict[str, Any]:
        import jax.numpy as jnp
        batch = {k: jnp.asarray(v) for k, v in req["batch"].items()}
        return batch

    def _handle(self, req: dict) -> Any:
        import jax.numpy as jnp
        method = req["method"]
        if method == "__stop__":
            self.stop()
            return True
        if method == "train_step":
            batch = self._batch_to_device(req)
            emb = jnp.asarray(req["emb"])
            with self._lock:  # one optimizer stream; workers serialize here
                (self.params, self.opt_state, demb, loss,
                 preds) = self._train_step(self.params, self.opt_state,
                                           emb, batch)
            return (np.asarray(demb), float(loss), np.asarray(preds))
        if method == "eval_step":
            batch = self._batch_to_device(req)
            emb = jnp.asarray(req["emb"])
            with self._lock:
                preds = self._eval_step(self.params, emb, batch)
            return np.asarray(preds)
        raise ValueError(f"unknown heter method {method!r}")

    def stop(self) -> None:
        self._rpc.stop()


class HeterDenseClient:
    def __init__(self, host: str, port: int, timeout: float = 300.0) -> None:
        self._rpc = FramedClient(host, port, _loads, timeout)

    def train_step(self, emb: np.ndarray, batch: Dict[str, np.ndarray]
                   ) -> Tuple[np.ndarray, float, np.ndarray]:
        return self._rpc.call({"method": "train_step", "emb": emb,
                               "batch": batch})

    def eval_step(self, emb: np.ndarray,
                  batch: Dict[str, np.ndarray]) -> np.ndarray:
        return self._rpc.call({"method": "eval_step", "emb": emb,
                               "batch": batch})

    def stop_server(self) -> None:
        try:
            self._rpc.call({"method": "__stop__"})
        except (ConnectionError, OSError):
            pass

    def close(self) -> None:
        self._rpc.close()


class HeterTrainer:
    """CPU-side worker (HeterCpuWorker role): data pipeline + PS sparse
    traffic local, dense step remote."""

    SPARSE_TABLE = DownpourTrainer.SPARSE_TABLE

    def __init__(self, ps_client, heter: HeterDenseClient,
                 table_cfg: TableConfig, feed: DataFeedConfig,
                 seed: int = 0, create_tables: bool = True) -> None:
        from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout

        self.client = ps_client
        self.heter = heter
        self.feed = feed
        self.layout = ValueLayout(table_cfg.embedx_dim,
                                  table_cfg.optimizer.optimizer)
        self.push_layout = PushLayout(self.layout.embedx_dim)
        self.num_slots = len(feed.used_sparse_slots())
        self.metrics = MetricRegistry()
        if create_tables:
            ps_client.create_sparse_table(self.SPARSE_TABLE, table_cfg,
                                          seed=seed)
        self.communicator = Communicator(ps_client, self.SPARSE_TABLE,
                                         self.push_layout.width)
        self._shuffle_rng = np.random.RandomState(seed + 1)

    # ------------------------------------------------------------- batches
    def _pull_view(self, b, create: bool = True
                   ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """FillSparseValue on the CPU worker: PS rows → pull view [K, 3+D]
        (show, click, embed_w, embedx — what pull_sparse emits on-device).
        create=False is the test-mode pull (no server-side inserts)."""
        from paddlebox_tpu.embedding import accessor as acc

        uniq, inv = np.unique(b.keys[b.valid], return_inverse=True)
        rows = self.client.pull_sparse(self.SPARSE_TABLE, uniq,
                                       create=create)
        D = self.layout.embedx_dim
        xw0 = self.layout.embedx_w
        view = np.concatenate([
            rows[:, acc.SHOW:acc.SHOW + 1],
            rows[:, acc.CLICK:acc.CLICK + 1],
            rows[:, acc.EMBED_W:acc.EMBED_W + 1],
            rows[:, xw0:xw0 + D],
        ], axis=1)
        emb = np.zeros((b.keys.shape[0], view.shape[1]), np.float32)
        emb[b.valid] = view[inv]
        batch = {
            "segments": b.segments, "valid": b.valid,
            "ins_valid": b.ins_valid, "labels": b.labels,
        }
        if b.dense is not None:
            batch["dense"] = b.dense
        return emb, batch

    def train_pass(self, dataset: BoxDataset) -> Dict[str, float]:
        from paddlebox_tpu.ops.sparse import build_push_grads

        if len(dataset) == 0:
            dataset.load_into_memory()
        dataset.local_shuffle(self._shuffle_rng.randint(1 << 31))
        losses = []
        for b in dataset.split_batches(num_workers=1)[0]:
            emb, batch = self._pull_view(b)
            demb, loss, preds = self.heter.train_step(emb, batch)
            # push construction runs on the CPU worker with the canonical
            # layout helper (ops/sparse.py)
            clicks = b.labels[b.segments // self.num_slots]
            push_rows = np.asarray(build_push_grads(  # boxlint: BX931 ok (CPU-worker push construction: the jnp helper runs on the host backend and the sparse push needs host rows)
                np.asarray(demb), b.slots, clicks, b.valid))
            self.communicator.push(b.keys[b.valid], push_rows[b.valid])
            losses.append(float(loss))
            if self.metrics.metric_names():
                self.metrics.add_batch({"pred": np.asarray(preds),
                                        "label": b.labels,
                                        "mask": b.ins_valid})
        self.communicator.flush()
        return {"loss": float(np.mean(losses)) if losses else 0.0,
                "batches": len(losses), "instances": len(dataset)}

    def predict_pass(self, dataset: BoxDataset):
        """Test-mode eval: create=False pulls (nothing inserted
        server-side) + the service's eval_step."""
        if len(dataset) == 0:
            dataset.load_into_memory()
        preds_all, labels_all = [], []
        for b in dataset.split_batches(num_workers=1)[0]:
            emb, batch = self._pull_view(b, create=False)
            preds = np.asarray(self.heter.eval_step(emb, batch))
            preds_all.append(preds[b.ins_valid])
            labels_all.append(b.labels[b.ins_valid])
        if not preds_all:
            return np.empty(0, np.float32), np.empty(0, np.int32)
        return np.concatenate(preds_all), np.concatenate(labels_all)

    def close(self) -> None:
        self.communicator.stop()
