"""Env-driven role maker (PaddleCloudRoleMaker pattern,
python/paddle/distributed/fleet/base/role_maker.py): rank/world/endpoints
come from environment variables set by the launcher or the cluster
scheduler. PADDLE_* names are accepted as aliases so reference launch
configs carry over.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple


def _env(*names: str, default: Optional[str] = None) -> Optional[str]:
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


class RoleMaker:
    def __init__(self, rank: Optional[int] = None,
                 world: Optional[int] = None,
                 store_endpoint: Optional[str] = None) -> None:
        self.rank = rank if rank is not None else int(
            _env("PBTPU_TRAINER_ID", "PADDLE_TRAINER_ID", default="0"))
        self.world = world if world is not None else int(
            _env("PBTPU_TRAINERS_NUM", "PADDLE_TRAINERS_NUM", default="1"))
        self.store_endpoint = store_endpoint or _env(
            "PBTPU_STORE_ENDPOINT", "PADDLE_GLOO_HTTP_ENDPOINT")
        if not (0 <= self.rank < self.world):
            raise ValueError("rank %d outside world %d"
                             % (self.rank, self.world))

    def is_first_worker(self) -> bool:
        return self.rank == 0

    def store_addr(self) -> Tuple[str, int]:
        if not self.store_endpoint:
            raise ValueError("no store endpoint configured "
                             "(PBTPU_STORE_ENDPOINT=host:port)")
        host, port = self.store_endpoint.rsplit(":", 1)
        return host, int(port)
