"""Multi-process launcher.

Analog of `python -m paddle.distributed.launch` (python/paddle/distributed/
fleet/launch.py): spawns N worker processes with rank/world/store env vars
set, hosts the rendezvous KV store in the launcher process, forwards the
script's stdout/stderr, and propagates the first non-zero exit code.

    python -m paddlebox_tpu.fleet.launch --nproc 2 train.py --epochs 3
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import uuid
from typing import List

from paddlebox_tpu.fleet.store import KVStoreServer


def launch(nproc: int, cmd: List[str], env_extra=None) -> int:
    server = KVStoreServer(host="127.0.0.1")
    run_id = uuid.uuid4().hex[:12]
    procs = []
    try:
        for rank in range(nproc):
            env = dict(os.environ)
            # no PBTPU_COORDINATOR: workers rendezvous the jax.distributed
            # coordinator through the KV store (fleet.init_distributed),
            # avoiding a pick-then-rebind port race in the launcher
            env.update({
                "PBTPU_TRAINER_ID": str(rank),
                "PBTPU_TRAINERS_NUM": str(nproc),
                "PBTPU_STORE_ENDPOINT": "127.0.0.1:%d" % server.port,
                "PBTPU_RUN_ID": run_id,
            })
            if env_extra:
                env.update(env_extra)
            procs.append(subprocess.Popen([sys.executable] + cmd, env=env))
        rc = 0
        for p in procs:
            p.wait()
            if p.returncode and not rc:
                rc = p.returncode
        return rc
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        server.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="paddlebox_tpu.fleet.launch")
    ap.add_argument("--nproc", type=int, default=1,
                    help="worker processes to spawn")
    ap.add_argument("script", help="training script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    return launch(args.nproc, [args.script] + args.script_args)


if __name__ == "__main__":
    sys.exit(main())
