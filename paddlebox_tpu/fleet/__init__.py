"""Fleet: multi-host control plane.

Analog of the reference's python fleet layer (python/paddle/distributed/
fleet/** and the GlooWrapper C++ rendezvous, framework/fleet/gloo_wrapper.h:
139-244): a TCP key-value store for rendezvous + small host-side
collectives (barrier / all_reduce / all_gather used by metric reduction and
dataset bookkeeping — never the training hot path, which is XLA
collectives over ICI), an env-driven role maker (PaddleCloudRoleMaker
pattern), a process launcher (fleet launch.py), and an elastic heartbeat
manager (fleet/elastic/manager.py skeleton).
"""

from paddlebox_tpu.fleet.store import KVStoreServer, TcpStoreClient
from paddlebox_tpu.fleet.role_maker import RoleMaker
from paddlebox_tpu.fleet.fleet import Fleet, fleet
from paddlebox_tpu.fleet.mesh_comm import MeshComm
from paddlebox_tpu.fleet.elastic import ElasticManager

__all__ = [
    "KVStoreServer",
    "TcpStoreClient",
    "RoleMaker",
    "Fleet",
    "fleet",
    "MeshComm",
    "ElasticManager",
]
