"""P2P host data plane: persistent socket mesh for per-step exchanges.

The reference routes cluster-wide sparse traffic over NCCL p2p at HBM rate
(walk_to_dest/walk_to_src, heter_comm_inl.h:1296-1445). Our multi-process
host plane instead funneled every rank's full outgoing bucket set through
ONE central TcpStore rendezvous on every step (fleet.all_gather):
O(W^2 * P * KB) bytes through a single server's NIC plus 3 counter
round-trips per rank per step — the store is a rendezvous service, not a
data plane.

Here every process runs one FramedServer (the shared utils/rpc.py framed
transport); peer addresses rendezvous ONCE through the TcpStore at init
(MeshComm.rendezvous); afterwards every per-step exchange rides the
persistent direct connections — a true all-to-all where rank r ships each
peer only that peer's slice: O(W * P * KB) direct bytes per step and zero
store round-trips. Sends to the W-1 peers fan out on a dedicated sender
pool while the server's per-connection threads drain incoming parts into
the inbox — the send/recv thread pair that lets the (already
stager-threaded) exchange overlap with device compute.

Exchanges are LOCKSTEP: every rank must call exchange() the same number of
times in the same order (the same contract fleet's store collectives
impose); an internal sequence number pairs send #n with recv #n, so a rank
running one step ahead parks its parts in the peer's inbox rather than
corrupting the current step.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

import numpy as np

from paddlebox_tpu.obs import beat as obs_beat
from paddlebox_tpu.obs.tracer import (current_trace, record_span,
                                      step_trace_id)
from paddlebox_tpu.utils.rpc import FramedClient, FramedServer, plain_loads
from paddlebox_tpu.utils.stats import hist_observe
from paddlebox_tpu.utils.lockwatch import make_lock


class MeshConnectError(ConnectionError):
    """A peer's FramedServer could not be dialed at bring-up: the caller
    (fleet.make_mesh_comm) turns this into the COLLECTIVE store fallback."""


class MeshPolicyMismatch(RuntimeError):
    """Ranks rendezvous'd with DIFFERENT sharding-policy identities
    (round 13): they would route the same key to different owners and
    silently corrupt every exchange product — on either host plane, so
    the caller must die loud, not fall back to the store."""


def resolve_hostplane() -> str:
    """The validated `hostplane` flag value. A typo ('P2P', 'p2p ') would
    otherwise SILENTLY select the slow store funnel — fail loud instead."""
    from paddlebox_tpu.config import flags
    v = str(flags.get_flag("hostplane")).strip().lower()
    if v not in ("p2p", "store"):
        raise ValueError(
            "hostplane flag must be 'p2p' or 'store', got %r" % v)
    return v


def _frame(arr: np.ndarray) -> dict:
    """dtype/shape + raw bytes: ONE copy (tobytes) before the transport's
    pickle — np.save's BytesIO round trip cost two more per part on the
    per-step data plane."""
    arr = np.ascontiguousarray(arr)
    return {"data": arr.tobytes(), "dtype": str(arr.dtype),
            "shape": tuple(arr.shape)}


def _unframe(frame: dict) -> np.ndarray:
    """Zero-copy view over the received buffer (READ-ONLY — consumers
    copy if they need to mutate)."""
    return np.frombuffer(frame["data"], dtype=np.dtype(frame["dtype"])
                         ).reshape(frame["shape"])


class MeshComm:
    """One rank's endpoint of the persistent W-rank socket mesh.

    Lifecycle: construct (binds the server) -> rendezvous(store, ...)
    (publish endpoint + owned mesh positions, gather peers', dial every
    peer) -> exchange(parts) per step -> close(). Thread contract: all
    exchange() calls come from ONE thread (the runners' batch stager);
    the inbox is filled concurrently by the server's connection threads.
    """

    def __init__(self, rank: int, world: int, host: str = "0.0.0.0",
                 op_timeout: float = 300.0) -> None:
        self.rank = int(rank)
        self.world = int(world)
        self._op_timeout = float(op_timeout)
        self._cv = threading.Condition()
        # (seq, from_rank) -> framed part, parked until exchange #seq
        # collects it; bounded by the exchange lockstep (a peer can run at
        # most one exchange ahead before blocking on OUR part)
        self._inbox: Dict[Tuple[int, int], dict] = {}  # guarded-by: _cv
        # one-way telemetry piggyback (obs/aggregate.py): raw payloads
        # parked here by the connection threads, drained by the local
        # reporter at its own cadence — no sequencing, no lockstep
        self._obs_inbox: List[bytes] = []  # guarded-by: _cv
        self._conn_lock = make_lock("MeshComm._conn_lock")
        self._clients: Dict[int, FramedClient] = {}  # guarded-by: _conn_lock
        self._endpoints: Dict[int, Tuple[str, int]] = {}  # guarded-by: _conn_lock
        # telemetry frames ride their OWN short-timeout connection: a
        # transient peer stall during a best-effort obs publish must not
        # mark the shared EXCHANGE client broken (FramedClient never
        # reconnects) and take the data plane down with it
        self._obs_clients: Dict[int, FramedClient] = {}  # guarded-by: _conn_lock
        # shuffle plane (round 17): bulk dataset-shuffle frames ride
        # their OWN per-peer connections too — a file-sized block send
        # must never sit in front of a lockstep exchange part on the
        # shared socket (and a shuffle stall must not brick the data
        # plane's client)
        self._shuf_clients: Dict[int, FramedClient] = {}  # guarded-by: _conn_lock
        self._shuf_handler = None      # guarded-by: _cv
        # frames that arrived before the MeshShuffler registered (a
        # peer's read threads can start scattering the moment ITS
        # dataset preloads); drained through the handler at registration
        self._shuf_pending: List[dict] = []  # guarded-by: _cv
        self._shuf_seq = 0             # trace mint counter  # guarded-by: _cv
        # mesh-device positions each fleet rank owns (gathered at
        # rendezvous); lets the sharded a2a route destination shard d to
        # its owner rank without assuming fleet rank == jax process index
        self.positions_of: Dict[int, List[int]] = {}
        self._seq = 0                  # exchange counter (single caller)
        self.bytes_sent = 0            # wire accounting (single caller)
        self.bytes_recv = 0  # guarded-by: _cv
        self.exchange_ms = 0.0         # cumulative, single caller
        self.exchanges = 0
        self._server = FramedServer(self._on_request, plain_loads, host=host)
        self._send_pool = ThreadPoolExecutor(
            max_workers=max(1, min(self.world - 1, 8)),
            thread_name_prefix="mesh-send")

    @property
    def port(self) -> int:
        return self._server.port

    # ------------------------------------------------------------ recv side
    def _on_request(self, req: dict):
        op = req.get("op")
        if op == "obs":
            with self._cv:
                self._obs_inbox.append(req["data"])
                # bounded drop-oldest: if the local aggregator stops
                # draining (dead sink, wedged driver) peers keep
                # publishing — telemetry must cap at stale-window loss,
                # never unbounded memory
                cap = max(64, 4 * self.world)
                if len(self._obs_inbox) > cap:
                    del self._obs_inbox[:len(self._obs_inbox) - cap]
            return True
        if op == "shuf":
            t0 = time.perf_counter()
            with self._cv:
                h = self._shuf_handler
                if h is None:
                    self._shuf_pending.append(req)
            if h is not None:
                # handler runs OUTSIDE _cv: it takes the shuffler's own
                # locks and never blocks (inbox parking, no channel put)
                h(req)
            trace = req.get("trace")
            record_span("mesh_recv_shuffle", t0, time.perf_counter(),
                        trace=trace if isinstance(trace, int) else None)
            return True
        if op != "part":
            raise ValueError("unknown mesh op %r" % (op,))
        t0 = time.perf_counter()
        key = (int(req["seq"]), int(req["from"]))
        with self._cv:
            self._inbox[key] = req
            self.bytes_recv += len(req["data"])
            self._cv.notify_all()
        # receiver-side span tagged with the SENDER's trace id (round
        # 14): the cross-rank hop trace_stitch.py turns into a ph:s/f
        # flow event — one step followed sender rank -> owner rank.
        # isinstance, not int(): a garbage trace from a skewed peer is
        # a telemetry value and must NEVER fail the lockstep exchange
        # (same armor as serving/codec.decode_trace)
        trace = req.get("trace")
        record_span("mesh_recv_part", t0, time.perf_counter(),
                    trace=trace if isinstance(trace, int) else None)
        return True

    # -------------------------------------------------- telemetry piggyback
    OBS_TIMEOUT = 10.0

    def send_obs(self, payload: bytes, to_rank: int = 0) -> None:
        """One-way telemetry frame to a peer's server over a DEDICATED
        short-timeout connection (dialed lazily from the rendezvous'd
        endpoint, re-dialed after a failure). Kept separate from the
        exchange clients on purpose: a timeout here bricks only the
        telemetry connection, never the lockstep data plane. Raises on
        failure — the caller (ClusterAggregator) treats publish as
        best-effort. Self-sends park directly in the local obs inbox."""
        if to_rank == self.rank:
            with self._cv:
                self._obs_inbox.append(bytes(payload))
            return
        with self._conn_lock:
            c = self._obs_clients.get(to_rank)
            ep = self._endpoints.get(to_rank)
        if c is None:
            if ep is None:
                raise ConnectionError(
                    "mesh rank %d has no endpoint for peer %d"
                    % (self.rank, to_rank))
            # dial OUTSIDE _conn_lock: the exchange send path takes the
            # same lock to look up its clients, and a ~OBS_TIMEOUT
            # connect to a wedged peer must not stall the data plane
            c = FramedClient(ep[0], ep[1], plain_loads,
                             timeout=self.OBS_TIMEOUT)
            with self._conn_lock:
                prev = self._obs_clients.get(to_rank)
                if prev is None:
                    self._obs_clients[to_rank] = c
                else:           # lost a dial race; use the winner
                    c.close()
                    c = prev
        try:
            c.call({"op": "obs", "data": bytes(payload)},
                   op_timeout=self.OBS_TIMEOUT)
        except (OSError, ConnectionError):
            # drop the broken telemetry connection; the next publish
            # re-dials (the exchange clients are untouched)
            with self._conn_lock:
                if self._obs_clients.get(to_rank) is c:
                    del self._obs_clients[to_rank]
            c.close()
            raise

    def drain_obs(self) -> List[bytes]:
        """Pop every parked telemetry payload (rank 0's aggregator)."""
        with self._cv:
            out, self._obs_inbox = self._obs_inbox, []
        return out

    # ------------------------------------------------------- shuffle plane
    def set_shuffle_handler(self, fn) -> None:
        """Install (fn) or remove (None) the MeshShuffler's frame
        handler. ONE handler per mesh — a second registration raises.
        Frames that arrived before registration drain through the new
        handler here, in arrival order.

        Lifecycle contract (round-17 review): shuffler GENERATIONS on
        one mesh are sequential — recreate only after the previous
        generation's flush barrier completed cluster-wide (epoch
        counters restart per shuffler, so a frame straddling two
        generations would desynchronize the done-barrier; a peer still
        mid-pass surfaces as ITS flush timeout). Frames parked at
        UNREGISTER time belong to the dying generation and are dropped
        LOUDLY here rather than silently replayed into the next one."""
        with self._cv:
            if fn is not None and self._shuf_handler is not None:
                raise RuntimeError(
                    "mesh rank %d already has a shuffle handler — one "
                    "MeshShuffler per mesh" % self.rank)
            self._shuf_handler = fn
            pending, self._shuf_pending = self._shuf_pending, []
        if fn is None:
            if pending:
                import logging
                logging.getLogger("paddlebox_tpu").warning(
                    "mesh rank %d: dropping %d shuffle frame(s) parked "
                    "at shuffler close — a peer was still scattering "
                    "into a torn-down shuffle (its flush will fail "
                    "loudly)", self.rank, len(pending))
            return
        for req in pending:
            fn(req)

    def send_shuffle(self, to_rank: int, frame: dict) -> None:
        """One shuffle frame to a peer's server over a DEDICATED
        persistent connection (dialed lazily from the rendezvous'd
        endpoint, re-dialed after a failure) — bulk block frames never
        share a socket with the lockstep exchange. Raises on failure;
        the dataset read worker surfaces it as the pass-load error.
        Frames carry a cross-plane trace id (bits 62+61 namespace the
        shuffle mint apart from both step ids and exchange mints)."""
        with self._conn_lock:
            c = self._shuf_clients.get(to_rank)
            ep = self._endpoints.get(to_rank)
        if c is None:
            if ep is None:
                raise ConnectionError(
                    "mesh rank %d has no endpoint for shuffle peer %d"
                    % (self.rank, to_rank))
            # dial OUTSIDE _conn_lock (the send_obs discipline): a slow
            # connect must not stall exchange-client lookups
            c = FramedClient(ep[0], ep[1], plain_loads,
                             timeout=self._op_timeout)
            with self._conn_lock:
                prev = self._shuf_clients.get(to_rank)
                if prev is None:
                    self._shuf_clients[to_rank] = c
                else:           # lost a dial race; use the winner
                    c.close()
                    c = prev
        trace = current_trace()
        if trace is None:
            with self._cv:
                self._shuf_seq += 1
                seq = self._shuf_seq
            trace = (1 << 62) | (1 << 61) | step_trace_id(self.rank, seq)
        t0 = time.perf_counter()
        try:
            c.call(dict(frame, op="shuf", trace=trace),
                   op_timeout=self._op_timeout)
        except (OSError, ConnectionError):
            # drop the broken shuffle connection; the next frame
            # re-dials (exchange + obs clients untouched)
            with self._conn_lock:
                if self._shuf_clients.get(to_rank) is c:
                    del self._shuf_clients[to_rank]
            c.close()
            raise
        record_span("mesh_send_shuffle", t0, time.perf_counter(),
                    trace=trace)

    # ----------------------------------------------------------- rendezvous
    def rendezvous(self, store, namespace: str, advertise_host: str,
                   positions: Iterable[int] = (),
                   timeout: float = 120.0,
                   policy_id: Optional[str] = None) -> "MeshComm":
        """ONE-TIME endpoint exchange through the KV store (the only step
        the store serves; every per-step exchange afterwards is direct):
        publish "host:port" + this rank's owned mesh positions (+ the
        sharding-policy identity when given — the ownership/routing map
        is policy-produced, so ranks must agree on the policy before the
        first exchange) under namespace/<rank>, wait for all peers',
        validate, dial persistent clients."""
        meta = json.dumps({"ep": "%s:%d" % (advertise_host, self.port),
                           "pos": [int(p) for p in positions],
                           "policy": policy_id})
        store.set("%s/%d" % (namespace, self.rank), meta.encode())
        endpoints: Dict[int, Tuple[str, int]] = {}
        for r in range(self.world):
            raw = store.wait("%s/%d" % (namespace, r), timeout)
            m = json.loads(bytes(raw).decode())
            host, port = m["ep"].rsplit(":", 1)
            endpoints[r] = (host, int(port))
            self.positions_of[r] = [int(p) for p in m["pos"]]
            peer_policy = m.get("policy")
            if policy_id is not None and peer_policy != policy_id:
                raise MeshPolicyMismatch(
                    "sharding-policy mismatch at mesh rendezvous: rank "
                    "%d runs %r, peer %d published %r — set the "
                    "sharding_policy flag identically on every rank"
                    % (self.rank, policy_id, r, peer_policy))
        self.connect(endpoints, timeout)
        return self

    def connect(self, endpoints: Mapping[int, Tuple[str, int]],
                timeout: float = 60.0) -> None:
        """Dial every peer's FramedServer; persistent for the process
        lifetime. Raises MeshConnectError naming the first unreachable
        peer so the caller can fall back loudly."""
        eps = {int(r): (h, int(p)) for r, (h, p) in endpoints.items()}
        with self._conn_lock:
            self._endpoints.update(eps)
            missing = [(r, hp) for r, hp in sorted(eps.items())
                       if r != self.rank and r not in self._clients]
        # dial OUTSIDE _conn_lock (boxlint BX601): bring-up dials W-1
        # peers sequentially — holding the lock across them would freeze
        # every concurrent _client/send_obs lookup for the whole window
        # (and the elastic re-rendezvous path will re-enter here mid-run)
        fresh: Dict[int, FramedClient] = {}
        try:
            for r, (host, port) in missing:
                fresh[r] = FramedClient(
                    host, port, plain_loads, timeout=timeout)
        except OSError as e:
            for c in fresh.values():
                c.close()
            raise MeshConnectError(
                "mesh peer %d unreachable at %s:%d: %r"
                % (r, host, port, e)) from e
        with self._conn_lock:
            for r, c in fresh.items():
                if r in self._clients:  # lost a dial race; use the winner
                    c.close()
                else:
                    self._clients[r] = c

    def rank_of_position(self) -> Dict[int, int]:
        """mesh device position -> owning fleet rank (from rendezvous)."""
        return {p: r for r, ps in self.positions_of.items() for p in ps}

    def _client(self, r: int) -> FramedClient:
        with self._conn_lock:
            c = self._clients.get(r)
        if c is None:
            raise ConnectionError("mesh rank %d has no connection to peer "
                                  "%d (rendezvous incomplete?)"
                                  % (self.rank, r))
        return c

    # -------------------------------------------------------------- exchange
    def exchange(self, parts: Mapping[int, np.ndarray]
                 ) -> Dict[int, np.ndarray]:
        """One lockstep all-to-all: parts[r] ships to rank r over its
        persistent connection (W-1 parallel sends on the sender pool);
        returns {r: array} received from every rank this step. The self
        part passes through by reference — zero copies, zero wire."""
        if set(parts) != set(range(self.world)):
            raise ValueError("exchange needs one part per rank 0..%d, got "
                             "%s" % (self.world - 1, sorted(parts)))
        self._seq += 1
        seq = self._seq
        # cross-plane trace id (round 14): inherit the caller's step
        # trace when one is set on this thread, else mint a rank+seq id
        # — the id rides every part's frame header and the receiver
        # records it, which is what lets trace_stitch.py draw this
        # exchange as flow arrows across the cluster timeline. The mint
        # sets bit 62: the stager thread's seq counts ~1:1 with the
        # consumer's step counter, so an un-namespaced mint would
        # systematically collide with the rank's own step ids and
        # stitch unrelated spans into one flow
        trace = current_trace()
        if trace is None:
            trace = (1 << 62) | step_trace_id(self.rank, seq)
        t0 = time.perf_counter()

        def send_one(r: int) -> int:
            frame = _frame(parts[r])
            self._client(r).call(dict(frame, op="part", seq=seq,
                                      trace=trace,
                                      **{"from": self.rank}),
                                 op_timeout=self._op_timeout)
            return len(frame["data"])

        futs = {r: self._send_pool.submit(send_one, r)
                for r in range(self.world) if r != self.rank}

        def send_failure():
            for fr, f in futs.items():
                if f.done() and f.exception() is not None:
                    return fr, f.exception()
            return None

        packed: Dict[int, dict] = {}
        deadline = time.monotonic() + self._op_timeout
        with self._cv:
            for r in range(self.world):
                if r == self.rank:
                    continue
                key = (seq, r)
                while key not in self._inbox:
                    # a dead peer breaks OUR send within the transport
                    # timeout — surface that promptly (short wait ticks)
                    # instead of masking it as a full op_timeout stall
                    # waiting for a part that can never arrive
                    bad = send_failure()
                    if bad is not None:
                        raise ConnectionError(
                            "mesh exchange #%d: send to rank %d failed: %r"
                            % (seq, bad[0], bad[1])) from bad[1]
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            "mesh exchange #%d: no part from rank %d "
                            "within %.0fs" % (seq, r, self._op_timeout))
                    self._cv.wait(min(0.2, remaining))
                packed[r] = self._inbox.pop(key)
        out: Dict[int, np.ndarray] = {self.rank: np.asarray(parts[self.rank])}
        for r, frame in packed.items():
            out[r] = _unframe(frame)
        for f in futs.values():
            self.bytes_sent += f.result()   # surfaces send errors too
        t1 = time.perf_counter()
        self.exchange_ms += (t1 - t0) * 1e3
        self.exchanges += 1
        record_span("mesh_exchange", t0, t1, trace=trace)
        hist_observe("mesh_exchange_us", (t1 - t0) * 1e6)
        # the exchange is a cluster-progress boundary: a peer that never
        # answers shows up as watchdog silence with this as the last beat
        obs_beat("mesh_exchange")
        return out

    def stats(self) -> Dict[str, float]:
        """Cumulative wire accounting since construction (per-step values
        = these divided by `exchanges`)."""
        with self._cv:
            recv = self.bytes_recv
        return {"exchanges": self.exchanges,
                "bytes_sent": self.bytes_sent, "bytes_recv": recv,
                "exchange_ms": round(self.exchange_ms, 3)}

    # ------------------------------------------------------------- lifecycle
    def close(self) -> None:
        self._send_pool.shutdown(wait=False)
        with self._conn_lock:
            for c in self._clients.values():
                c.close()
            self._clients = {}
            for c in self._obs_clients.values():
                c.close()
            self._obs_clients = {}
            for c in self._shuf_clients.values():
                c.close()
            self._shuf_clients = {}
        self._server.stop()
