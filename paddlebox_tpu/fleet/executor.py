"""FleetExecutor analog: actor-style pipeline of interceptors.

Re-design of paddle/fluid/distributed/fleet_executor/ (FleetExecutor,
Carrier, Interceptor, MessageBus — fleet_executor.cc, carrier.cc,
interceptor.cc, message_bus.cc): interceptors are small actors addressed
by int64 ids that exchange `InterceptorMessage`s; a Carrier runs the
interceptors registered to it on a worker thread per interceptor; the
MessageBus routes messages whose destination lives on another carrier over
TCP (the brpc channel role, via utils/rpc.py). The reference uses this as
the pipeline-by-message inference/training runtime, independent of the
BoxPS path; here it serves the same role for host-side pipelines (the
device-side pipeline lives in parallel/pipeline.py).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from paddlebox_tpu.utils.rpc import FramedClient, FramedServer, plain_loads
from paddlebox_tpu.utils.lockwatch import make_lock

STOP = "__stop__"


@dataclasses.dataclass
class InterceptorMessage:
    src_id: int
    dst_id: int
    message_type: str = "DATA"     # DATA | DATA_IS_READY | STOP ...
    payload: Any = None

    def to_wire(self) -> dict:
        return {"src": self.src_id, "dst": self.dst_id,
                "type": self.message_type, "payload": self.payload}

    @classmethod
    def from_wire(cls, d: dict) -> "InterceptorMessage":
        return cls(d["src"], d["dst"], d["type"], d.get("payload"))


class Interceptor:
    """One actor: a handler invoked per message on its own thread
    (interceptor.cc's RegisterMsgHandle + loop)."""

    def __init__(self, interceptor_id: int,
                 handler: Callable[["Interceptor", InterceptorMessage], None]):
        self.id = interceptor_id
        self.handler = handler
        self.carrier: Optional["Carrier"] = None
        self._inbox: "queue.Queue[Optional[InterceptorMessage]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            msg = self._inbox.get()
            if msg is None or msg.message_type == STOP:
                return
            self.handler(self, msg)

    def enqueue(self, msg: InterceptorMessage) -> None:
        self._inbox.put(msg)

    def send(self, dst_id: int, payload: Any = None,
             message_type: str = "DATA") -> None:
        self.carrier.send(InterceptorMessage(self.id, dst_id,
                                             message_type, payload))

    def stop(self) -> None:
        self._inbox.put(None)
        if self._thread is not None:
            self._thread.join()


class Carrier:
    """Hosts interceptors; routes local messages directly and remote ones
    through the message bus (carrier.cc Send / EnqueueInterceptorMessage)."""

    def __init__(self, carrier_id: int = 0,
                 host: str = "127.0.0.1", port: int = 0):
        self.id = carrier_id
        self._interceptors: Dict[int, Interceptor] = {}
        # interceptor_id → (host, port) for remote destinations
        self._routes: Dict[int, Tuple[str, int]] = {}
        self._clients: Dict[Tuple[str, int], FramedClient] = {}
        self._clients_lock = make_lock("Carrier._clients_lock")
        self._rpc = FramedServer(self._on_remote, plain_loads, host, port)

    @property
    def port(self) -> int:
        return self._rpc.port

    # -------------------------------------------------------------- topology
    def add_interceptor(self, interceptor: Interceptor) -> Interceptor:
        interceptor.carrier = self
        self._interceptors[interceptor.id] = interceptor
        interceptor.start()
        return interceptor

    def register_route(self, interceptor_id: int, host: str,
                       port: int) -> None:
        """MessageBus routing table entry (message_bus.cc Init)."""
        self._routes[interceptor_id] = (host, port)

    # --------------------------------------------------------------- routing
    def send(self, msg: InterceptorMessage) -> None:
        local = self._interceptors.get(msg.dst_id)
        if local is not None:
            local.enqueue(msg)
            return
        ep = self._routes.get(msg.dst_id)
        if ep is None:
            raise KeyError("no route to interceptor %d" % msg.dst_id)
        with self._clients_lock:
            cl = self._clients.get(ep)
        if cl is None:
            # dial OUTSIDE _clients_lock (the mesh_comm send_obs
            # discipline, boxlint BX601): a blackholed peer must stall
            # only this sender for the connect timeout, not every thread
            # routing through the carrier
            fresh = FramedClient(ep[0], ep[1], plain_loads)
            with self._clients_lock:
                cl = self._clients.get(ep)
                if cl is None:
                    cl = self._clients[ep] = fresh
            if cl is not fresh:  # lost a dial race; use the winner
                fresh.close()
        cl.call(msg.to_wire())

    def _on_remote(self, wire: dict) -> bool:
        msg = InterceptorMessage.from_wire(wire)
        local = self._interceptors.get(msg.dst_id)
        if local is None:
            raise KeyError("carrier %d hosts no interceptor %d"
                           % (self.id, msg.dst_id))
        local.enqueue(msg)
        return True

    def stop(self) -> None:
        for it in self._interceptors.values():
            it.stop()
        for cl in self._clients.values():
            cl.close()
        self._rpc.stop()


class FleetExecutor:
    """Top-level runner (fleet_executor.cc): builds a carrier, wires
    interceptors, kicks the sources, waits for the sinks."""

    def __init__(self, carrier: Optional[Carrier] = None):
        self.carrier = carrier or Carrier()
        self._done = threading.Event()
        self.results: List[Any] = []
        self._results_lock = make_lock("FleetExecutor._results_lock")

    def add_sink(self, interceptor_id: int,
                 expect: int) -> Interceptor:
        """A terminal interceptor collecting `expect` payloads."""
        remaining = [expect]

        def handler(it, msg):
            with self._results_lock:
                self.results.append(msg.payload)
                remaining[0] -= 1
                if remaining[0] <= 0:
                    self._done.set()

        return self.carrier.add_interceptor(
            Interceptor(interceptor_id, handler))

    def run(self, source_id: int, payloads: List[Any],
            timeout: float = 60.0) -> List[Any]:
        """Feed payloads to the source interceptor; block until the sink
        collected everything."""
        src = self.carrier._interceptors[source_id]
        for p in payloads:
            src.enqueue(InterceptorMessage(-1, source_id, "DATA", p))
        if not self._done.wait(timeout):
            raise TimeoutError("fleet executor run timed out")
        return list(self.results)
