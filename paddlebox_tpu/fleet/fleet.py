"""Fleet facade: init + host-side collectives over the KV store.

The python-visible surface of fleet.init / fleet.util (python/paddle/
distributed/fleet/fleet.py + GlooWrapper Barrier/AllReduce/AllGather,
gloo_wrapper.h:185-244). These collectives move SMALL host data — metric
partials, instance counts, batch-count equalization — over DCN; training
tensors go through XLA collectives on the mesh, never through here.

Collectives are ordered: every rank must issue the same sequence of calls
(the same contract gloo imposes); a per-instance sequence number namespaces
each round's keys.
"""

from __future__ import annotations

import io
import time
from typing import Callable, Optional

import numpy as np

from paddlebox_tpu.fleet.role_maker import RoleMaker
from paddlebox_tpu.fleet.store import KVStoreServer, TcpStoreClient

_OPS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
}


def _pack(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _unpack(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


class Fleet:
    def __init__(self) -> None:
        self.role: Optional[RoleMaker] = None
        self._client: Optional[TcpStoreClient] = None
        self._server: Optional[KVStoreServer] = None
        self._seq = 0
        # namespaces this lifecycle's keys: counters persist in the store,
        # so a restarted job against the same store must not see run 1's
        # pre-satisfied barriers (the launcher stamps a fresh uuid)
        self._run_id = "0"
        self._mesh = None  # p2p host-plane mesh (make_mesh_comm, cached)
        self._mesh_policy = None  # the policy id the cached mesh validated

    # ----------------------------------------------------------------- init
    def init(self, role: Optional[RoleMaker] = None,
             server: Optional[KVStoreServer] = None,
             client: Optional[TcpStoreClient] = None) -> "Fleet":
        """Single-rank jobs need no store; multi-rank jobs rendezvous at
        role.store_endpoint (rank 0 may host the server in-process by
        passing `server`, the launcher's default is a dedicated store)."""
        import os
        self.role = role or RoleMaker()
        self._run_id = os.environ.get("PBTPU_RUN_ID", "0")
        self._seq = 0
        self._server = server
        if client is not None:
            self._client = client
        elif self.role.world > 1 or self.role.store_endpoint:
            host, port = (("127.0.0.1", server.port) if server is not None
                          else self.role.store_addr())
            self._client = TcpStoreClient(host, port)
        return self

    @property
    def initialized(self) -> bool:
        return self.role is not None

    def worker_index(self) -> int:
        return self.role.rank

    def worker_num(self) -> int:
        return self.role.world

    def is_first_worker(self) -> bool:
        return self.role.is_first_worker()

    def store_client(self):
        """The KV store client (None in single-rank jobs) — public surface
        for planes that piggyback on the store (obs/aggregate.py)."""
        return self._client

    def obs_namespace(self) -> str:
        """Run-scoped key namespace for telemetry piggyback writes."""
        return "%s/obs" % self._run_id

    # ---------------------------------------------------------- collectives
    def barrier_worker(self, timeout: float = 120.0) -> None:
        """All ranks reach this point (GlooWrapper::Barrier)."""
        if self.role.world <= 1:
            return
        self._seq += 1
        key = "%s/barrier/%d" % (self._run_id, self._seq)
        self._client.add(key)
        self._client.wait_counter_ge(key, self.role.world, timeout)
        self._compact_old_counters()

    def all_gather(self, arr: np.ndarray,
                   timeout: float = 120.0) -> list:
        """[rank0_arr, rank1_arr, ...] on every rank
        (GlooWrapper::AllGather)."""
        if self.role.world <= 1:
            return [np.asarray(arr)]
        self._seq += 1
        prefix = "%s/coll/%d" % (self._run_id, self._seq)
        self._client.set("%s/%d" % (prefix, self.role.rank),
                         _pack(np.asarray(arr)))
        out = [
            _unpack(self._client.wait("%s/%d" % (prefix, r), timeout))
            for r in range(self.role.world)
        ]
        # ranks ack having READ the round before anyone deletes its data
        # keys; the ack counter itself outlives its round (a laggard's
        # wait_counter_ge may arrive after rank 0 passes the barrier) and
        # is retired two rounds later by _compact_old_counters
        ack = prefix + "/ack"
        self._client.add(ack)
        self._client.wait_counter_ge(ack, self.role.world, timeout)
        if self.role.rank == 0:
            for r in range(self.role.world):
                self._client.delete("%s/%d" % (prefix, r))
        self._compact_old_counters()
        return out

    def _compact_old_counters(self) -> None:
        """Retire collective counters older than 2 rounds so a long run's
        store stays bounded (they used to accumulate forever). Safety:
        when rank 0 COMPLETES round n, every rank has ADDED in round n,
        hence fully finished round n-1 (per-rank call order is strict),
        hence nothing can ever wait on round n-2's counters again. One
        delete covers both key shapes — at each seq exactly one of
        barrier/coll exists and delete of a missing key is a no-op."""
        if self.role.rank != 0 or self._seq < 3:
            return
        old = self._seq - 2
        self._client.delete("%s/barrier/%d" % (self._run_id, old))
        self._client.delete("%s/coll/%d/ack" % (self._run_id, old))

    def all_reduce(self, arr: np.ndarray, op: str = "sum",
                   timeout: float = 120.0) -> np.ndarray:
        """Elementwise reduce across ranks (GlooWrapper::AllReduce; the
        metric-aggregation path box MPI allreduce serves in the
        reference)."""
        if op not in _OPS:
            raise ValueError("allreduce op must be one of %s" % list(_OPS))
        parts = self.all_gather(np.asarray(arr), timeout)
        return _OPS[op](np.stack(parts))

    def metric_allreduce(self) -> Callable[[np.ndarray], np.ndarray]:
        """Adapter matching MetricRegistry/BasicAucCalculator's
        `allreduce(vec) -> vec` hook."""
        return lambda v: self.all_reduce(np.asarray(v, np.float64), "sum")

    def equalize_batches(self) -> Callable[[int], int]:
        """Adapter for BoxDataset.split_batches(equalize=...): allreduce-max
        of local batch counts (compute_paddlebox_thread_batch_nccl,
        data_set.cc:2690-2755)."""
        return lambda n: int(self.all_reduce(
            np.asarray([n], np.int64), "max")[0])

    def _my_host(self) -> str:
        """This rank's address as peers should dial it: PBTPU_HOST wins;
        otherwise loopback for single-machine clusters (store on
        127.0.0.1), else the hostname — never loopback across machines."""
        import os
        import socket
        host = os.environ.get("PBTPU_HOST")
        if host:
            return host
        store_host = (self.role.store_addr()[0]
                      if self.role.store_endpoint else "127.0.0.1")
        if store_host in ("127.0.0.1", "localhost", "::1"):
            return "127.0.0.1"
        return socket.gethostname()

    def init_distributed(self, timeout: float = 120.0) -> None:
        """Join the multi-process XLA runtime with store-based coordinator
        rendezvous: rank 0 binds a free port itself and publishes the
        address, so there is no pick-then-rebind race. Call after init().
        Falls back to the PBTPU_COORDINATOR env when set."""
        import os

        from paddlebox_tpu.parallel.mesh import init_distributed

        if self.role.world <= 1:
            return
        if os.environ.get("PBTPU_COORDINATOR"):
            init_distributed(world=self.role.world, rank=self.role.rank)
            return
        key = "%s/jax_coordinator" % self._run_id
        if self.role.rank == 0:
            import socket
            with socket.socket() as s:
                s.bind((  # held only within this process: no cross-proc race
                    "0.0.0.0", 0))
                port = s.getsockname()[1]
            coord = "%s:%d" % (self._my_host(), port)
            self._client.set(key, coord.encode())
        else:
            coord = self._client.wait(key, timeout).decode()
        init_distributed(coordinator=coord, world=self.role.world,
                         rank=self.role.rank)

    # ------------------------------------------------------------ transports
    def make_shuffler(self, batch_records: int = 512, host: str = None,
                      timeout: float = 120.0, mesh=None):
        """Build this rank's cross-host shuffle transport. Round 17:
        under `hostplane=p2p` the shuffle rides the PERSISTENT mesh
        (`MeshShuffler` over fleet/mesh_comm.py) — pass `mesh=` (or let
        the fleet's already-rendezvous'd mesh serve; building a sharded
        trainer first rendezvouses it with its owned positions, else
        this call rendezvouses a position-less mesh COLLECTIVELY). When
        the mesh is unavailable (collective bring-up fallback) or
        `hostplane=store`, the ad-hoc `TcpShuffler` is built instead —
        LOUDLY on the fallback path, exactly like the exchange plane's
        store fallback. Endpoint rendezvous rides the KV store either
        way (the PaddleShuffler MPI-discovery analog). Returns None in
        single-rank jobs. Must be called by every rank in the same
        collective order."""
        import logging
        import os

        from paddlebox_tpu.data.shuffle import MeshShuffler, TcpShuffler
        from paddlebox_tpu.fleet.mesh_comm import resolve_hostplane

        if self.role.world <= 1:
            return None
        if resolve_hostplane() == "p2p":
            m = mesh if mesh is not None else self._mesh
            if m is None:
                m = self.make_mesh_comm(positions=(), timeout=timeout)
            if m is not None:
                return MeshShuffler(m, batch_records=batch_records)
            logging.getLogger("paddlebox_tpu").warning(
                "rank %d: p2p mesh unavailable for the instance shuffle "
                "— falling back to the ad-hoc TCP shuffle transport "
                "(collective; every rank reverts together)",
                self.role.rank)
        host = host or self._my_host()
        sh = TcpShuffler(self.role.rank, self.role.world,
                         [(host, 0)] * self.role.world,
                         batch_records=batch_records)
        ep_bytes = ("%s:%d" % (host, sh.port)).encode().ljust(64)
        eps = self.all_gather(np.frombuffer(ep_bytes, np.uint8), timeout)
        endpoints = []
        for e in eps:
            txt = bytes(e).rstrip(b" \x00").decode()
            h, p = txt.rsplit(":", 1)
            endpoints.append((h, int(p)))
        sh.endpoints = endpoints
        return sh

    def make_mesh_comm(self, positions=(), timeout: float = 120.0,
                       policy_id=None):
        """Build (once; cached) this rank's p2p host-plane mesh
        (fleet/mesh_comm.py): endpoints + owned mesh positions rendezvous
        ONE TIME through the KV store, then every per-step exchange rides
        persistent direct connections. Returns None in single-rank jobs
        and on fallback. The fallback is COLLECTIVE and loud: bring-up
        success is all-gathered, and if ANY rank failed to dial its peers
        every rank reverts to the store-allgather host plane together — a
        split decision would deadlock the lockstep exchange. Must be
        called by every rank in the same collective order.

        policy_id (round 13): the sharding policy's identity string
        (ShardingPolicy.describe) — published with the endpoint and
        compared across ranks at rendezvous, so a split sharding_policy
        flag (ranks routing the same key to different owners: silent
        product corruption) dies at bring-up instead. None skips the
        check (policy-agnostic callers like the hostplane probe's raw
        exchange legs)."""
        import logging

        from paddlebox_tpu.fleet.mesh_comm import (MeshComm,
                                                   MeshPolicyMismatch)

        if self.role.world <= 1:
            return None
        if self._mesh is not None:
            have = sorted(self._mesh.positions_of.get(self.role.rank, []))
            if have != sorted(int(p) for p in positions):
                # fail HERE with construction context, not at the first
                # per-step exchange deep inside the stager. A cached
                # POSITION-LESS mesh almost always means make_shuffler
                # auto-rendezvous'd before the sharded trainer ran
                # (round-17 review) — name the fix, not just the state
                hint = (" — a position-less mesh was rendezvous'd "
                        "earlier (make_shuffler's auto bring-up?); "
                        "construct the sharded trainer BEFORE the "
                        "shuffler, or pass its mesh to make_shuffler"
                        if not have else "")
                raise ValueError(
                    "make_mesh_comm: mesh already rendezvous'd for "
                    "positions %s; requested %s%s"
                    % (have, list(positions), hint))
            if policy_id is not None and policy_id != self._mesh_policy:
                # the cached mesh validated a DIFFERENT (or no) policy
                # identity at rendezvous; the cross-rank agreement the
                # rendezvous check provides cannot be retrofitted here
                raise ValueError(
                    "make_mesh_comm: mesh already rendezvous'd under "
                    "policy %r; requested %r — one policy per fleet "
                    "lifetime" % (self._mesh_policy, policy_id))
            return self._mesh
        log = logging.getLogger("paddlebox_tpu")
        self._seq += 1
        ns = "%s/mesh/%d" % (self._run_id, self._seq)
        mesh = MeshComm(self.role.rank, self.role.world)
        ok = 1
        # ANY bring-up failure must still reach the collective ok-flag
        # vote below — an escaping exception here would leave every peer
        # blocked in the all_gather (the split-decision hang the vote
        # exists to prevent) and leak this rank's server socket
        mismatch = None
        try:
            mesh.rendezvous(self._client, ns, self._my_host(),
                            positions, timeout, policy_id=policy_id)
        except MeshPolicyMismatch as e:
            # NOT a fallback case: ranks on different sharding policies
            # would corrupt the store plane just the same — vote first
            # (so no peer hangs in the all_gather), then die loud
            mismatch = e
            ok = 0
        except Exception as e:  # noqa: BLE001 — votes fallback, never splits
            log.warning("hostplane=p2p bring-up FAILED on rank %d: %r",
                        self.role.rank, e)
            ok = 0
        flags = self.all_gather(np.asarray([ok], np.int64), timeout)
        if mismatch is not None:
            mesh.close()
            raise mismatch
        if not all(int(f[0]) for f in flags):
            if ok:
                log.warning(
                    "hostplane=p2p: a peer failed mesh bring-up — ALL "
                    "ranks falling back to the store-allgather host plane "
                    "(per-step exchanges funnel through the central store "
                    "again; fix peer reachability to restore p2p)")
            mesh.close()
            return None
        self._mesh = mesh
        self._mesh_policy = policy_id
        return mesh

    # ------------------------------------------------------------- lifecycle
    def stop(self) -> None:
        if self._mesh is not None:
            self._mesh.close()
            self._mesh = None
            self._mesh_policy = None
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        self.role = None


# module-level singleton, like `from paddle.distributed import fleet`
fleet = Fleet()
