"""In-step ablation of the push_write='log' composition (round 5).

tpu_probe shows the log-mode full step at ~16.3 ms/step — the micro
marginals (write_probe: DUS ~0.1 ms, pull2-pull1 ~+0.3) predict ~12.
This decomposes the REAL log-path push, built from the production
building blocks at bench shapes, inside a donated scan chain (the exact
carry structure the trainer uses):

  pull_plain     rows = slab[ids]                       (r4 baseline read)
  pull_comb      rows = pull_rows_combined(slab,log,src)
  push_nowrite   merged_new_rows only (no log write)
  push_dus       merged_new_rows + DUS at carried cursor (the log write)
  push_rebuild   merged_new_rows + rebuild write         (r4 comparison)

Each variant runs the SAME scan-of-8 structure, donated, D2H-synced.
Usage: timeout 1200 python -u tools/log_ablate.py [platform]
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

from paddlebox_tpu.config.configs import SparseOptimizerConfig
from paddlebox_tpu.embedding.accessor import ValueLayout
from paddlebox_tpu.embedding.optimizers import _merged_new_rows
from paddlebox_tpu.ops.sparse import pull_rows_combined

CAP = 1 << 20
W = 17
K = 131072
PW = 12
CHUNK = 8
LOG_BATCHES = 16
REPS = 4


def timed(name, fn, state, extra=None):
    out = fn(*state)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    # re-make state each rep is impossible after donation: thread it
    st = out
    t0 = time.perf_counter()
    for _ in range(REPS):
        st = fn(*st) if isinstance(st, tuple) else fn(st)
        np.asarray(jax.tree_util.tree_leaves(st)[0].ravel()[:1])
    ms = (time.perf_counter() - t0) / REPS / CHUNK * 1e3
    rec = {"variant": name, "ms_per_step": round(ms, 3)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)
    layout = ValueLayout(8, "adagrad")
    conf = SparseOptimizerConfig(mf_create_thresholds=0.0,
                                 mf_initial_range=1e-3)
    L = LOG_BATCHES * K

    slab = jnp.asarray(rng.rand(CAP, W).astype(np.float32))
    log0 = jnp.zeros((L, W), jnp.float32)
    # host-dedup products like the real stage (85% unique)
    n_u = int(K * 0.85)
    uids_np = np.sort(rng.choice(CAP - 1, n_u, replace=False)).astype(np.int32)
    uids_np = np.concatenate(
        [uids_np, np.arange(K - n_u, dtype=np.int32) + CAP])
    ids_np = uids_np[np.minimum(
        np.sort(rng.randint(0, n_u, K)), n_u - 1)].astype(np.int32)
    perm_np = rng.permutation(K).astype(np.int32)
    inv_np = np.sort(rng.randint(0, n_u, K)).astype(np.int32)
    first_np = rng.randint(0, K, K).astype(np.int32)
    src_np = ids_np.copy()
    src_np[::7] = CAP + rng.randint(0, L, src_np[::7].shape[0])  # ~14% log hits
    stacked = {
        "ids": jnp.asarray(np.broadcast_to(ids_np, (CHUNK, K)).copy()),
        "src": jnp.asarray(np.broadcast_to(src_np, (CHUNK, K)).copy()),
        "uids": jnp.asarray(np.broadcast_to(uids_np, (CHUNK, K)).copy()),
        "perm": jnp.asarray(np.broadcast_to(perm_np, (CHUNK, K)).copy()),
        "inv": jnp.asarray(np.broadcast_to(inv_np, (CHUNK, K)).copy()),
        "first": jnp.asarray(np.broadcast_to(first_np, (CHUNK, K)).copy()),
        "grads": jnp.asarray(rng.rand(CHUNK, K, PW).astype(np.float32)),
    }
    pos_np = np.full(CAP, -1, np.int32)
    pos_np[uids_np[:n_u]] = np.arange(n_u, dtype=np.int32)
    stacked_pos = jnp.asarray(
        np.broadcast_to(pos_np, (CHUNK, CAP)).copy())

    def scan_of(body, with_pos=False):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def run(carry, stk, stkpos):
            def step(c, xs):
                b, bp = xs
                return body(c, b, bp), 0.0
            carry2, _ = lax.scan(step, carry, (stk, stkpos))
            return carry2
        return lambda *c: (run(c[0], stacked, stacked_pos),)

    def mk_state():
        prng = jax.random.PRNGKey(0)
        return ((slab + 0.0, log0 + 0.0, jnp.zeros((), jnp.int32), prng),)

    # --- read variants ------------------------------------------------
    def pull_plain(c, b, bp):
        s, lg, cur, prng = c
        rows = jnp.take(s, jnp.minimum(b["ids"], CAP - 1), axis=0)
        return (s, lax.dynamic_update_slice(lg, rows * 0.999, (cur, 0)),
                (cur + K) % (L - K), prng)

    def pull_comb(c, b, bp):
        s, lg, cur, prng = c
        rows = pull_rows_combined(s, lg, b["src"])
        return (s, lax.dynamic_update_slice(lg, rows * 0.999, (cur, 0)),
                (cur + K) % (L - K), prng)

    timed("pull_plain_plus_dus", scan_of(pull_plain), mk_state())
    timed("pull_comb_plus_dus", scan_of(pull_comb), mk_state())

    # flush-first ordering: the PREVIOUS step's rows DUS into the log
    # BEFORE this step's gather — write-then-read instead of the
    # read-after-write hazard (which forces a log copy)
    def scan_flush(body):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def run(carry, stk, stkpos):
            def step(c, xs):
                b, bp = xs
                return body(c, b, bp), 0.0
            carry2, _ = lax.scan(step, carry, (stk, stkpos))
            return carry2
        return lambda *c: (run(c[0], stacked, stacked_pos),)

    def mk_state_flush():
        prng = jax.random.PRNGKey(0)
        prev = jnp.zeros((K, W), jnp.float32)
        return ((slab + 0.0, log0 + 0.0, prev, jnp.zeros((), jnp.int32),
                 prng),)

    def pull_comb_flush(c, b, bp):
        s, lg, prev, cur, prng = c
        lg = lax.dynamic_update_slice(lg, prev, (cur, 0))
        rows = pull_rows_combined(s, lg, b["src"])
        return (s, lg, rows * 0.999, (cur + K) % (L - K), prng)

    timed("pull_comb_flush_first", scan_flush(pull_comb_flush),
          mk_state_flush())

    def push_flush(c, b, bp):
        s, lg, prev, cur, prng = c
        lg = lax.dynamic_update_slice(lg, prev, (cur, 0))
        prng, sub = jax.random.split(prng)
        rows = pull_rows_combined(s, lg, b["src"])
        new_rows = _merged_new_rows(s, b["uids"], b["perm"], b["inv"],
                                    b["grads"], sub, layout, conf,
                                    pulled_rows=rows, first_idx=b["first"])
        return (s, lg, new_rows, (cur + K) % (L - K), prng)

    timed("push_full_flush_first", scan_flush(push_flush), mk_state_flush())

    # --- push variants (all read via combined pull) -------------------
    def push_common(c, b):
        s, lg, cur, prng = c
        prng, sub = jax.random.split(prng)
        rows = pull_rows_combined(s, lg, b["src"])
        new_rows = _merged_new_rows(s, b["uids"], b["perm"], b["inv"],
                                    b["grads"], sub, layout, conf,
                                    pulled_rows=rows, first_idx=b["first"])
        return s, lg, cur, prng, new_rows

    def push_nowrite(c, b, bp):
        s, lg, cur, prng, new_rows = push_common(c, b)
        # keep new_rows alive via the cursor (scalar) — no log-sized op
        cur = cur + K + (new_rows[0, 0] * 0.0).astype(jnp.int32)
        return (s, lg, cur % (L - K), prng)

    def push_dus(c, b, bp):
        s, lg, cur, prng, new_rows = push_common(c, b)
        lg = lax.dynamic_update_slice(lg, new_rows, (cur, 0))
        return (s, lg, (cur + K) % (L - K), prng)

    def push_rebuild(c, b, bp):
        s, lg, cur, prng, new_rows = push_common(c, b)
        sel = jnp.take(new_rows, jnp.clip(bp, 0, K - 1), axis=0)
        s = jnp.where((bp >= 0)[:, None], sel, s)
        return (s, lg, cur, prng)

    timed("push_nowrite", scan_of(push_nowrite), mk_state())
    timed("push_dus", scan_of(push_dus), mk_state())
    timed("push_rebuild", scan_of(push_rebuild), mk_state())

    # ---- operand-placement matrix (round-5b): what makes the combined
    # pull cost ~5 ms in-scan, and what scales with cap in log mode?
    for cap2 in (CAP, CAP * 4):
        slab2 = jnp.asarray(rng.rand(cap2, W).astype(np.float32))
        tag = {"cap": cap2}
        ids2 = jnp.asarray(
            np.broadcast_to(rng.randint(0, cap2, K).astype(np.int32),
                            (CHUNK, K)).copy())
        src2_np = rng.randint(0, cap2, (CHUNK, K)).astype(np.int32)
        src2_np[:, ::7] = cap2 + rng.randint(0, L, src2_np[:, ::7].shape)
        src2 = jnp.asarray(src2_np)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def dus_only(carry, stk):
            def step(c, b):
                lg, cur = c
                nr2 = jnp.ones((K, W), jnp.float32) * b[0].astype(jnp.float32)
                return (lax.dynamic_update_slice(lg, nr2, (cur, 0)),
                        (cur + K) % (L - K)), 0.0
            c2, _ = lax.scan(step, carry, stk)
            return c2

        timed("m_dus_only_logcarry", lambda *c: (dus_only(c[0], ids2),),
              ((log0 + 0.0, jnp.zeros((), jnp.int32)),), tag)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def gather_carry(carry, stk):
            def step(c, b):
                s, acc = c
                rows = jnp.take(s, jnp.minimum(b, cap2 - 1), axis=0)
                return (s, acc + rows[:1, :1]), 0.0
            c2, _ = lax.scan(step, carry, stk)
            return c2

        timed("m_gather_slabcarry",
              lambda *c: (gather_carry(c[0], ids2),),
              ((slab2 + 0.0, jnp.zeros((1, 1))),), tag)

        @jax.jit
        def gather_inv(acc, stk, s):
            def step(a, b):
                rows = jnp.take(s, jnp.minimum(b, cap2 - 1), axis=0)
                return a + rows[:1, :1], 0.0
            a2, _ = lax.scan(step, acc, stk)
            return a2

        timed("m_gather_slabinv",
              lambda *c: (gather_inv(c[0], ids2, slab2),),
              (jnp.zeros((1, 1)),), tag)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def comb_carry(carry, stk):
            def step(c, b):
                s, lg, cur, acc = c
                rows = pull_rows_combined(s, lg, b)
                lg = lax.dynamic_update_slice(lg, rows * 0.999, (cur, 0))
                return (s, lg, (cur + K) % (L - K), acc + rows[:1, :1]), 0.0
            c2, _ = lax.scan(step, carry, stk)
            return c2

        timed("m_comb_carry_dus",
              lambda *c: (comb_carry(c[0], src2),),
              ((slab2 + 0.0, log0 + 0.0, jnp.zeros((), jnp.int32),
                jnp.zeros((1, 1))),), tag)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def comb_nowrite(carry, stk, s):
            # log carried but NEVER written: is the read the cost, or the
            # read+write combination?
            def step(c, b):
                lg, acc = c
                rows = pull_rows_combined(s, lg, b)
                return (lg, acc + rows[:1, :1]), 0.0
            c2, _ = lax.scan(step, carry, stk)
            return c2

        timed("m_comb_logcarry_nowrite",
              lambda *c: (comb_nowrite(c[0], src2, slab2),),
              ((log0 + 0.0, jnp.zeros((1, 1))),), tag)


if __name__ == "__main__":
    main()
