"""One-window TPU measurement battery (run when the axon tunnel is up).

Stages, each D2H-synced via tools.bench_util.timed_scan_chain (axon's
block_until_ready is a no-op, BASELINE.md):
  1. full fused step at bench shapes (decomposes the bench number)
  2. same step at 4x slab rows (slab-size scaling)
  3. step WITHOUT the sparse push (isolates push cost)
  4. step WITHOUT pull+push (dense fwd/bwd only)
Prints one JSON line per stage; safe to kill any time.

Usage:  timeout 1500 python -u tools/tpu_probe.py [platform]
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")
import jax.numpy as jnp
import numpy as np
import optax

from tools.bench_util import make_ctr_batches, timed_scan_chain

from paddlebox_tpu.config.configs import (SparseOptimizerConfig, TableConfig,
                                          TrainerConfig)
from paddlebox_tpu.data.generator import default_feed_config
from paddlebox_tpu.models.base import ModelSpec
from paddlebox_tpu.models.deepfm import DeepFM
from paddlebox_tpu.train.trainer import BoxTrainer, cast_for_compute

D, NUM_SLOTS, BATCH, MAX_LEN = 8, 32, 1024, 4
CHUNK, REPS = 8, 6


def make_trainer(pass_cap):
    from tools.bench_util import make_bench_trainer
    return make_bench_trainer(pass_cap, batch=BATCH, num_slots=NUM_SLOTS,
                              max_len=MAX_LEN, d=D)


def stage(name, pass_cap, strip=None, push_write=None):
    """strip: None | 'push' | 'sparse' — build a variant step.
    push_write: force a write mode (None = the trainer's auto resolve)."""
    tr, feed = make_trainer(pass_cap)
    if push_write is not None:
        tr._push_write = push_write
    elif strip is not None:
        tr._push_write = "scatter"   # stripped steps don't push; plain dicts
    batches = make_ctr_batches(feed, CHUNK, NUM_SLOTS, MAX_LEN, seed=0)
    tr.table.begin_feed_pass()
    for b in batches:
        tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    tr.table.begin_pass()
    stacked = tr._stack_batches(batches)
    if strip is None:
        scan = tr.fns.scan_steps
    else:
        from paddlebox_tpu.ops.seqpool import fused_seqpool_cvm
        from paddlebox_tpu.ops.sparse import pull_sparse
        from paddlebox_tpu.train.trainer import make_scan
        layout = tr.table.layout
        dense_opt = tr.dense_opt
        model = tr.model
        trash = tr.table.padding_id

        def step(slab, params, opt_state, batch, prng):
            prng, sub = jax.random.split(prng)
            valid = batch["ids"] != trash

            def loss_fn(p, emb):
                pooled = fused_seqpool_cvm(emb, batch["segments"], valid,
                                           BATCH, NUM_SLOTS, use_cvm=True,
                                           sorted_segments=True)
                pj = cast_for_compute(p, jnp.bfloat16)
                logits = model.apply(pj, pooled.astype(jnp.bfloat16), None)
                lab = batch["labels"].astype(jnp.float32)
                bce = optax.sigmoid_binary_cross_entropy(
                    logits.astype(jnp.float32), lab)
                return jnp.where(batch["ins_valid"], bce, 0.0).sum() / BATCH

            if strip == "sparse":
                emb = jnp.zeros((batch["ids"].shape[0], 3 + D), jnp.float32)
            else:
                emb = pull_sparse(slab, batch["ids"], layout)
            loss, (dp, demb) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(params, emb)
            updates, opt_state = dense_opt.update(dp, opt_state, params)
            params = optax.apply_updates(params, updates)
            # no push in either stripped variant; keep a slab dependency
            slab = slab.at[0, 0].add(loss * 0.0)
            return slab, params, opt_state, loss, {"ctr": loss}, prng

        scan = make_scan(step)
    state = (tr.table.slab, tr.params, tr.opt_state, tr.table.next_prng())
    dt = timed_scan_chain(scan, state, stacked, REPS) / CHUNK
    print(json.dumps({"stage": name, "pass_cap": pass_cap,
                      "push_write": tr._push_write if strip is None else None,
                      "ms_per_step": round(dt * 1e3, 3),
                      "examples_per_sec": round(BATCH / dt, 1)}), flush=True)


def chunk_sync_stage():
    """One pull + one merged push per chunk (TrainerConfig.
    sparse_chunk_sync) at bench shapes — the per-runtime fresh-evidence
    row the round-4 verdict asked to keep or delete the mode by."""
    from paddlebox_tpu.config.configs import TrainerConfig
    from tools.bench_util import make_bench_trainer
    tr, feed = make_bench_trainer(
        1 << 20, batch=BATCH, num_slots=NUM_SLOTS, max_len=MAX_LEN, d=D,
        trainer_cfg=TrainerConfig(dense_lr=1e-3, compute_dtype="bfloat16",
                                  sparse_chunk_sync=True,
                                  scan_chunk=CHUNK))
    batches = make_ctr_batches(feed, CHUNK, NUM_SLOTS, MAX_LEN, seed=0)
    tr.table.begin_feed_pass()
    for b in batches:
        tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    tr.table.begin_pass()
    stacked, cpush = tr._stack_batches(batches)
    state = (tr.table.slab, tr.params, tr.opt_state, tr.table.next_prng())

    import time as _time
    for rep in range(REPS + 1):
        if rep == 1:
            np.asarray(losses)
            t0 = _time.perf_counter()
        slab, params, opt, losses, preds, prng = tr.fns.scan_chunk(
            state[0], state[1], state[2], stacked, cpush, state[3])
        state = (slab, params, opt, prng)
    np.asarray(losses)
    dt = (_time.perf_counter() - t0) / REPS / CHUNK
    print(json.dumps({"stage": "full_step_chunk_sync",
                      "pass_cap": 1 << 20,
                      "ms_per_step": round(dt * 1e3, 3),
                      "examples_per_sec": round(BATCH / dt, 1)}),
          flush=True)


if __name__ == "__main__":
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    stage("full_step", 1 << 20)
    # compiler-side audit right after the headline stage so a timeout
    # kills the long tail, not the donation-regression check
    try:
        from tools.step_audit import audit
        print(json.dumps({"stage": "step_audit", **audit()}), flush=True)
    except Exception as e:
        print(json.dumps({"stage": "step_audit", "error": repr(e)[:300]}),
              flush=True)
    stage("full_step_4x_slab", 1 << 22)
    # r4<->r5 write-mode comparison rows in the same window
    stage("full_step_rebuild", 1 << 20, push_write="rebuild")
    stage("full_step_rebuild_4x", 1 << 22, push_write="rebuild")
    stage("no_push", 1 << 20, strip="push")
    # capacity-growth attribution: if this row grows with pass_cap too,
    # the 4x-slab cost lives in pull/dense/scan, not the push write
    stage("no_push_4x", 1 << 22, strip="push")
    stage("dense_only", 1 << 20, strip="sparse")
    # hand-written Pallas in-table adagrad vs the XLA update
    from paddlebox_tpu.config import flags as _flags
    _flags.set_flag("use_pallas_push", True)
    try:
        stage("full_step_pallas_push", 1 << 20)
    except Exception as e:  # pallas may not lower on every backend
        print(json.dumps({"stage": "full_step_pallas_push",
                          "error": repr(e)[:300]}), flush=True)
    finally:
        _flags.set_flag("use_pallas_push", False)
    # the chunk-synchronous sparse mode re-measures on every new runtime
    # window (round-5 hygiene): it targets per-op-floor-dominated
    # runtimes and stays default-off while it loses here (BASELINE.md)
    try:
        chunk_sync_stage()
    except Exception as e:
        print(json.dumps({"stage": "full_step_chunk_sync",
                          "error": repr(e)[:300]}), flush=True)
