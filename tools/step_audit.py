"""Compiled-step audit: XLA cost + memory analysis of the fused train step.

The bench argues from the HBM roofline (BASELINE.md): examples/sec is
bounded by bytes-moved per example. This tool asks the COMPILER what the
step actually moves — flops, bytes accessed, temp allocation — so the
"step is byte-minimal" claim is evidence, not belief:

  * temp size ≈ activations only (the donated slab must NOT appear as a
    second slab-sized temp — donation regressions show up here first);
  * bytes accessed per example vs the analytic ~26 KB/example budget.

Since round 20 the per-example math lives in
paddlebox_tpu/obs/device.py (analyze_compiled) — ONE copy shared with
the always-on device plane, so this offline probe and the production
StepReport/device-endpoint fields can never diverge. The instrumented
scan entry point exposes .lower() unchanged, so the audit runs through
the exact wrapper production dispatches through.

Run on any platform (the HLO structure is platform-independent; byte
counts are the compiler's, so capture per platform):

    JAX_PLATFORMS=cpu python tools/step_audit.py [--json]

--json emits the audit on stdout as one JSON object whose field names
match the device plane's analysis snapshot (flops_per_example,
bytes_accessed_per_example, temp_bytes, arg_bytes, output_bytes,
alias_bytes, temp_includes_slab_copy) — the default output is the same
object, kept for the historical CLI contract.
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def audit(pass_cap: int = 1 << 20, batch: int = 1024, num_slots: int = 32,
          max_len: int = 4, d: int = 8, chunk: int = 8) -> dict:
    import jax

    from paddlebox_tpu.obs.device import analyze_compiled
    from tools.bench_util import make_bench_trainer, make_ctr_batches

    trainer, feed = make_bench_trainer(pass_cap, batch=batch,
                                       num_slots=num_slots, max_len=max_len,
                                       d=d)
    batches = make_ctr_batches(feed, chunk, num_slots, max_len, seed=0)
    trainer.table.begin_feed_pass()
    for b in batches:
        trainer.table.add_keys(b.keys[b.valid])
    trainer.table.end_feed_pass()
    trainer.table.begin_pass()
    stacked = trainer._stack_batches(batches)
    args = (trainer.table.slab, trainer.params, trainer.opt_state, stacked,
            trainer.table.next_prng())

    lowered = trainer.fns.scan_steps.lower(*args)
    compiled = lowered.compile()
    out = {"platform": jax.devices()[0].platform,
           "chunk": chunk, "batch": batch,
           "slab_bytes": int(np.prod(trainer.table.slab.shape)) * 4}
    # cost analysis counts the scan BODY once = one batch of examples,
    # so per-example = / batch (NOT / (chunk*batch)) — normalization
    # contract lives in analyze_compiled's docstring
    out.update(analyze_compiled(compiled, examples=batch,
                                slab_bytes=out["slab_bytes"]))
    # the shared helper also returns raw totals; this CLI's historical
    # surface is the per-example + memory fields, keep the totals too
    return out


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--json", action="store_true",
                    help="emit the audit as one JSON object on stdout "
                         "(field names match the device plane's "
                         "analysis snapshot)")
    ap.add_argument("--pass-cap", type=int, default=1 << 20)
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--chunk", type=int, default=8)
    ns = ap.parse_args()
    result = audit(pass_cap=ns.pass_cap, batch=ns.batch, chunk=ns.chunk)
    print(json.dumps(result))
