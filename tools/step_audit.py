"""Compiled-step audit: XLA cost + memory analysis of the fused train step.

The bench argues from the HBM roofline (BASELINE.md): examples/sec is
bounded by bytes-moved per example. This tool asks the COMPILER what the
step actually moves — flops, bytes accessed, temp allocation — so the
"step is byte-minimal" claim is evidence, not belief:

  * temp size ≈ activations only (the donated slab must NOT appear as a
    second slab-sized temp — donation regressions show up here first);
  * bytes accessed per example vs the analytic ~26 KB/example budget.

Run on any platform (the HLO structure is platform-independent; byte
counts are the compiler's, so capture per platform):

    JAX_PLATFORMS=cpu python tools/step_audit.py
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()


def audit(pass_cap: int = 1 << 20, batch: int = 1024, num_slots: int = 32,
          max_len: int = 4, d: int = 8, chunk: int = 8) -> dict:
    import jax

    from tools.bench_util import make_bench_trainer, make_ctr_batches

    trainer, feed = make_bench_trainer(pass_cap, batch=batch,
                                       num_slots=num_slots, max_len=max_len,
                                       d=d)
    batches = make_ctr_batches(feed, chunk, num_slots, max_len, seed=0)
    trainer.table.begin_feed_pass()
    for b in batches:
        trainer.table.add_keys(b.keys[b.valid])
    trainer.table.end_feed_pass()
    trainer.table.begin_pass()
    stacked = trainer._stack_batches(batches)
    args = (trainer.table.slab, trainer.params, trainer.opt_state, stacked,
            trainer.table.next_prng())

    lowered = trainer.fns.scan_steps.lower(*args)
    compiled = lowered.compile()
    out = {"platform": jax.devices()[0].platform,
           "chunk": chunk, "batch": batch,
           "slab_bytes": int(np.prod(trainer.table.slab.shape)) * 4}
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        if ca:
            # cost analysis counts the scan BODY once = one batch of
            # examples, so per-example = / batch (NOT / (chunk*batch))
            out["flops_per_example"] = round(ca.get("flops", 0.0) / batch)
            out["bytes_accessed_per_example"] = round(
                ca.get("bytes accessed", 0.0) / batch)
    except Exception as e:  # cost analysis is best-effort per backend
        out["cost_analysis_error"] = repr(e)
    try:
        ma = compiled.memory_analysis()
        out["temp_bytes"] = int(getattr(ma, "temp_size_in_bytes", -1))
        out["arg_bytes"] = int(getattr(ma, "argument_size_in_bytes", -1))
        out["output_bytes"] = int(getattr(ma, "output_size_in_bytes", -1))
        out["alias_bytes"] = int(getattr(ma, "alias_size_in_bytes", -1))
        if out["temp_bytes"] >= 0:
            # the donated slab must not re-appear as a temp copy
            out["temp_includes_slab_copy"] = bool(
                out["temp_bytes"] >= out["slab_bytes"])
    except Exception as e:
        out["memory_analysis_error"] = repr(e)
    return out


if __name__ == "__main__":
    print(json.dumps(audit()))
