"""Measure chunk-synchronous sparse mode (sparse_chunk_sync) on the chip
vs the exact per-batch step, at bench shapes.

Usage: timeout 1500 python -u tools/chunk_sync_probe.py [platform] [chunks]
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

from tools.bench_util import make_ctr_batches, timed_scan_chain

BATCH, NUM_SLOTS, MAX_LEN = 1024, 32, 4
PASS_CAP = 1 << 20
REPS = 6


def make_trainer(chunk_sync, scan_chunk):
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data.generator import default_feed_config
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.train.trainer import BoxTrainer

    feed = default_feed_config(num_slots=NUM_SLOTS, batch_size=BATCH,
                               max_len=MAX_LEN)
    table_cfg = TableConfig(
        embedx_dim=8, pass_capacity=PASS_CAP,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    model_spec = ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + 8)
    model = DeepFM(model_spec, hidden=(512, 256, 128))
    dtype = ("float32" if jax.default_backend() == "cpu" else "bfloat16")
    return BoxTrainer(model, table_cfg, feed,
                      TrainerConfig(dense_lr=1e-3, compute_dtype=dtype,
                                    scan_chunk=scan_chunk,
                                    sparse_chunk_sync=chunk_sync),
                      seed=0), feed


def run(chunk_sync, C):
    tr, feed = make_trainer(chunk_sync, C)
    batches = make_ctr_batches(feed, C, NUM_SLOTS, MAX_LEN, seed=0)
    tr.table.begin_feed_pass()
    for b in batches:
        tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    tr.table.begin_pass()
    staged = tr._stack_batches(batches)
    prng = jax.random.PRNGKey(0)
    if chunk_sync:
        stacked, cpush = staged

        def call(slab, params, opt, _stacked, prng):
            return tr.fns.scan_chunk(slab, params, opt, _stacked, cpush,
                                     prng)
        scan, arg = call, stacked
    else:
        scan, arg = tr.fns.scan_steps, staged
    state = (tr.table.slab, tr.params, tr.opt_state, prng)
    dt = timed_scan_chain(scan, state, arg, REPS)
    ms = dt / C * 1e3
    print(json.dumps({"mode": "chunk_sync" if chunk_sync else "exact",
                      "chunk": C, "ms_per_batch": round(ms, 3),
                      "examples_per_sec": round(BATCH / (dt / C), 1)}),
          flush=True)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    chunks = [int(c) for c in (sys.argv[2].split(",")
                               if len(sys.argv) > 2 else ["8", "16"])]
    run(False, 8)
    for C in chunks:
        run(True, C)


if __name__ == "__main__":
    main()
