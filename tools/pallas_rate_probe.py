"""Does Pallas/Mosaic sidestep the axon runtime's per-op costs?

XLA ops measured ~90-130 GB/s streaming + ms-scale floors (BASELINE.md).
If a Pallas kernel streams at real v5e HBM rate (~819 GB/s), the hot
path belongs in a few fused kernels. Measures: pallas copy at 64/256 MB,
pallas gather-rows (the pull shape), and the same in XLA for reference.

Usage: timeout 900 python -u tools/pallas_rate_probe.py [platform]
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

REPS = 5
ITERS = 8


def timed(name, fn, *args, bytes_moved=None):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    ms = (time.perf_counter() - t0) / REPS / ITERS * 1e3
    rec = {"op": name, "ms_per_call": round(ms, 4)}
    if bytes_moved:
        rec["gb_per_s"] = round(bytes_moved / (ms * 1e-3) / 1e9, 1)
    print(json.dumps(rec), flush=True)


def chain(body):
    def run(carry, *args):
        def step(_, c):
            return body(c, *args)
        return lax.fori_loop(0, ITERS, step, carry)
    return jax.jit(run)


def copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 0.999 + 0.001


def pallas_scale(x, block_rows):
    n = x.shape[0]
    return pl.pallas_call(
        copy_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(n // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, x.shape[1]),
                               lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, x.shape[1]), lambda i: (i, 0)),
    )(x)


def gather_kernel(idx_ref, slab_ref, o_ref, *, rows_per_step):
    i = pl.program_id(0)
    def body(j, _):
        r = idx_ref[i * rows_per_step + j]
        o_ref[j, :] = slab_ref[r, :]
        return 0
    lax.fori_loop(0, rows_per_step, body, 0)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)

    for mb, rows in ((64, 1 << 17), (256, 1 << 19)):
        x = jnp.asarray(rng.rand(rows, 128).astype(np.float32))
        f = functools.partial(pallas_scale, block_rows=1024)
        timed(f"pallas_scale_{mb}MB", chain(lambda v: f(v)), x,
              bytes_moved=2 * x.size * 4)
        timed(f"xla_scale_{mb}MB", chain(lambda v: v * 0.999 + 0.001), x,
              bytes_moved=2 * x.size * 4)

    # pallas row gather at pull shapes: 131k rows of 128 lanes from 1M-row
    # table (the slab padded to lane width for a fair kernel)
    CAP, K = 1 << 20, 131072
    slab = jnp.asarray(rng.rand(CAP, 128).astype(np.float32))
    idx = jnp.asarray(np.sort(rng.choice(CAP - 1, K, replace=False))
                      .astype(np.int32))
    RPS = 8

    def pgather(i, s):
        return pl.pallas_call(
            functools.partial(gather_kernel, rows_per_step=RPS),
            out_shape=jax.ShapeDtypeStruct((K, 128), jnp.float32),
            grid=(K // RPS,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((RPS, 128), lambda g: (g, 0)),
            interpret=False,
        )(i, s)

    # both sides consume the FULL gathered result (a slice-of-gather can be
    # folded into a 1-row gather by the simplifier, which would invalidate
    # the comparison)
    try:
        def g(c, i, s):
            return c + jnp.sum(pgather(i, s), keepdims=True)[:1, :1]
        timed("pallas_gather_131k_rows", chain(g), jnp.zeros((1, 1)),
              idx, slab, bytes_moved=2 * K * 128 * 4)
    except Exception as e:
        print(json.dumps({"op": "pallas_gather_131k_rows",
                          "error": str(e)[:300]}), flush=True)

    def xg(c, i, s):
        return c + jnp.sum(jnp.take(s, i, axis=0, mode="clip"),
                           keepdims=True)[:1, :1]
    timed("xla_gather_131k_rows_W128", chain(xg), jnp.zeros((1, 1)),
          idx, slab, bytes_moved=2 * K * 128 * 4)


if __name__ == "__main__":
    main()
