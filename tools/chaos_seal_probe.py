"""Chaos leg: kill a rank mid-pass in a REAL 2-process cluster and
verify the postmortem plane end to end.

The round-14 acceptance scenario: a localhost fleet of `--world`
processes runs a pass-shaped loop — per-step p2p mesh exchanges under
per-step trace ids, StepReports at cadence 1 with rank-0 cluster
aggregation + health, watchdog beats, an ACTIVE flight recorder per
rank. The parent SIGABRTs (or SIGKILLs) rank 1 mid-loop, then asserts:

  * SIGABRT leg: the dead rank left a parseable ``SEALED_r1.json``
    manifest (reason signal:SIGABRT, thread stacks, spans, reports)
    AND its flight segments parse.
  * SIGKILL leg: no seal is possible (the kernel gives no notice) —
    the per-record-flushed flight segments ARE the artifact: they must
    parse line-by-line and carry the header + beats/reports.
  * both legs: rank 0's cluster health plane flags the dead rank
    unhealthy within 2 report cadences of the first post-death merge
    (measured, reported as windows_to_unhealthy).
  * stitch leg: the per-rank chrome traces exported before the kill
    stitch into one timeline with >=1 CROSS-RANK flow event (the mesh
    frame trace ids at work).

Usage:  timeout 300 python -u tools/chaos_seal_probe.py [--world 2]
            [--signals ABRT,KILL] [--steps-before-kill 5]
Prints one JSON line per leg plus {"all_ok": ...}; exits 1 on failure.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NUM_DEVICES = 4


def _positions(rank: int, world: int):
    return [int(p) for p in
            np.array_split(np.arange(NUM_DEVICES), world)[rank]]


def worker() -> None:
    """One rank of the chaos cluster (pure host plane — no jax)."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.fleet.fleet import Fleet
    from paddlebox_tpu.fleet.role_maker import RoleMaker
    import paddlebox_tpu.obs as obs
    from paddlebox_tpu.obs.tracer import step_trace_id, trace_ctx

    run_dir = os.environ["CHAOS_DIR"]
    flags.set_flag("obs_flight_dir", run_dir)
    flags.set_flag("obs_report_every", 1)
    fl = Fleet().init(RoleMaker())
    rank, world = fl.worker_index(), fl.worker_num()
    mesh = fl.make_mesh_comm(_positions(rank, world))
    assert mesh is not None, "p2p mesh bring-up failed in chaos worker"
    # a dead peer must surface as a bounded TimeoutError in the
    # survivor's exchange, not a 300s default stall (probe-local knob)
    mesh._op_timeout = 15.0
    aggregator = obs.make_cluster_aggregator(mesh=mesh, rank=rank,
                                             world=world)
    reporter = obs.make_step_reporter(rank=rank, aggregator=aggregator)
    assert obs.flight.active() is not None, "flight recorder not active"
    trace_path = os.path.join(run_dir, "trace_r%d.json" % rank)
    rng = np.random.RandomState(100 + rank)

    death_step = 0
    windows_to_unhealthy = -1
    for step in range(1, 200):
        try:
            with trace_ctx(step_trace_id(rank, step)):
                mesh.exchange({r: rng.randint(0, 1 << 20, 256)
                               .astype(np.int32) for r in range(world)})
        except (ConnectionError, TimeoutError):
            death_step = step
            break
        reporter.note_examples(256)
        reporter.maybe_report(step)
        # the chrome trace export before the kill is what the stitch
        # leg consumes — atomic rename so a kill mid-write can never
        # leave a truncated json behind
        obs.export_chrome_trace(path=trace_path + ".tmp", rank=rank)
        os.replace(trace_path + ".tmp", trace_path)
        print("STEP %d" % step, flush=True)
        time.sleep(0.05)

    if rank != 0:
        fl.stop()
        return
    # rank 0 outlives the peer: flush the window that may still hold
    # the peer's queued last report, then count merges until the health
    # plane flags it — the "within 2 cadences" acceptance measurement
    step = death_step
    reporter.maybe_report(step, force=True)
    for w in range(1, 11):
        step += 1
        time.sleep(0.05)
        reporter.maybe_report(step, force=True)
        health = aggregator.last_cluster_health
        if health and 1 in health["unhealthy_ranks"]:
            windows_to_unhealthy = w
            break
    obs.export_chrome_trace(path=trace_path, rank=0)
    print("RESULT " + json.dumps({
        "rank": rank, "death_step": death_step,
        "windows_to_unhealthy": windows_to_unhealthy,
        "health": aggregator.last_cluster_health}), flush=True)
    reporter.close()
    fl.stop()


def _parse_jsonl(path: str):
    recs = []
    with open(path, encoding="utf-8") as fh:
        for ln in fh:
            recs.append(json.loads(ln))     # raises on corruption
    return recs


def run_leg(world: int, sig_name: str, steps_before_kill: int,
            run_dir: str, timeout: float = 120.0) -> dict:
    import uuid

    from paddlebox_tpu.fleet.store import KVStoreServer
    from tools.trace_stitch import stitch

    os.makedirs(run_dir, exist_ok=True)
    server = KVStoreServer(host="127.0.0.1")
    procs = []
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    run_id = uuid.uuid4().hex[:8]   # ONE namespace for the whole leg
    try:
        for rank in range(world):
            env = dict(os.environ)
            env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH",
                                                            "")
            env.update({
                "PBTPU_TRAINER_ID": str(rank),
                "PBTPU_TRAINERS_NUM": str(world),
                "PBTPU_STORE_ENDPOINT": "127.0.0.1:%d" % server.port,
                "PBTPU_RUN_ID": run_id,
                "CHAOS_WORKER": "1",
                "CHAOS_DIR": run_dir,
                "JAX_PLATFORMS": "cpu",
            })
            procs.append(subprocess.Popen(
                [sys.executable, "-u", os.path.abspath(__file__)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        victim = procs[1]
        # wait until the victim is mid-pass, then kill it
        deadline = time.monotonic() + timeout
        reached = False
        for line in victim.stdout:
            if line.startswith("STEP"):
                if int(line.split()[1]) >= steps_before_kill:
                    reached = True
                    break
            if time.monotonic() > deadline:
                break
        if not reached:
            raise TimeoutError(
                "victim never reached kill step; stderr tail: "
                + (victim.stderr.read() or "")[-1500:])
        signum = getattr(signal, "SIG" + sig_name)
        victim.send_signal(signum)
        victim.wait(timeout=30)
        rank0_out, rank0_err = procs[0].communicate(timeout=timeout)
        if procs[0].returncode != 0:
            raise RuntimeError("rank 0 failed:\n" + rank0_err[-3000:])
        result = None
        for line in rank0_out.splitlines():
            if line.startswith("RESULT "):
                result = json.loads(line[len("RESULT "):])
        if result is None:
            raise RuntimeError("rank 0 printed no RESULT:\n"
                               + rank0_out[-2000:])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()

    leg = {"signal": sig_name, "world": world,
           "victim_rc": victim.returncode,
           "death_step": result["death_step"],
           "windows_to_unhealthy": result["windows_to_unhealthy"]}

    # --- artifact assertions -------------------------------------------
    sealed_path = os.path.join(run_dir, "SEALED_r1.json")
    if sig_name == "ABRT":
        with open(sealed_path, encoding="utf-8") as fh:
            manifest = json.load(fh)
        assert manifest["reason"] == "signal:SIGABRT", manifest["reason"]
        assert manifest["threads"], "no thread stacks in manifest"
        assert manifest["header"]["flags"], "no flags in manifest header"
        leg["sealed"] = {"reason": manifest["reason"],
                         "n_threads": len(manifest["threads"]),
                         "n_spans": len(manifest["spans"]),
                         "n_reports": len(manifest["last_reports"])}
    else:
        assert not os.path.exists(sealed_path), \
            "SIGKILL cannot seal — a manifest means the leg is fake"
    segs = sorted(p for p in os.listdir(run_dir)
                  if p.startswith("flight_r1_"))
    assert segs, "dead rank left no flight segments"
    recs = []
    for s in segs:
        recs.extend(_parse_jsonl(os.path.join(run_dir, s)))
    types = {r["type"] for r in recs}
    assert "header" in types, types
    assert {"beat", "report"} & types, types
    leg["flight_records_r1"] = len(recs)
    leg["flight_record_types"] = sorted(types)

    # --- health assertion ----------------------------------------------
    assert 0 < result["windows_to_unhealthy"] <= 2, \
        "health flagged dead rank in %r windows (bound 2)" % (
            result["windows_to_unhealthy"],)

    # --- stitch leg -----------------------------------------------------
    docs = []
    for r in range(world):
        p = os.path.join(run_dir, "trace_r%d.json" % r)
        with open(p, encoding="utf-8") as fh:
            docs.append(json.load(fh))
    stitched, summary = stitch(docs)
    json.dumps(stitched)            # loadable end to end
    assert summary["cross_rank_flows"] >= 1, summary
    leg["stitch"] = summary
    return leg


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--signals", default="ABRT,KILL")
    ap.add_argument("--steps-before-kill", type=int, default=5)
    ap.add_argument("--dir", default="")
    args = ap.parse_args()
    import tempfile
    base = args.dir or tempfile.mkdtemp(prefix="pbtpu_chaos_")
    ok = True
    for sig_name in [s.strip().upper() for s in args.signals.split(",")]:
        run_dir = os.path.join(base, "leg_%s" % sig_name)
        try:
            leg = run_leg(args.world, sig_name, args.steps_before_kill,
                          run_dir)
            leg["probe"] = "chaos_seal"
            print(json.dumps(leg), flush=True)
        except Exception as e:  # noqa: BLE001 — keep the ladder going
            ok = False
            print(json.dumps({"probe": "chaos_seal", "signal": sig_name,
                              "error": repr(e)[:500]}), flush=True)
    print(json.dumps({"all_ok": ok, "dir": base}), flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    if os.environ.get("CHAOS_WORKER"):
        worker()
    else:
        main()
