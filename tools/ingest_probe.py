"""Ingest shuffle ladder: record-TCP vs block-TCP vs block-mesh.

Round-17 acceptance probe: REAL multi-process measurement of the
cross-host instance shuffle (the pass-load stage the block codec and the
p2p mesh transport replace), at 2-4 processes on one machine. Each rank
parses its own synthetic file shard and the full parse→shuffle→merge
load runs per tier, all three landing IDENTICAL per-rank content
(asserted via a per-rank digest before anything is timed):

  record-tcp  the legacy per-record codec over the ad-hoc TcpShuffler
              sockets (struct-pack loop per instance, both directions)
  block-tcp   the columnar block codec (header + raw column bytes,
              vectorized hash route + fancy-index split) over the SAME
              TcpShuffler transport — isolates the codec win
  block-mesh  the block codec over the PERSISTENT p2p host-plane mesh
              (fleet/mesh_comm.py, MeshShuffler) — the production tier

Per tier: `runs` timed full loads, MEDIAN wall + records/s landed on
this rank, plus shuffle wire bytes from the shuffle stat counters.
NOTE the tiers are END-TO-END loads: record-tcp includes the Python
record parse (the record path's production reality — SlotRecords are
what that codec moves), the block tiers the native columnar parse. The
CODEC-ONLY ladder (same pre-parsed input both ways) lives in bench.py's
"ingest" block; this probe records the pipeline each config actually
runs.

Usage:  timeout 900 python -u tools/ingest_probe.py [--worlds 2]
            [--lines 4000] [--files 2] [--runs 3]
Prints one JSON line per world plus {"all_ok": ...}; exits 1 on failure.
"""

import hashlib
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

TIERS = ("record-tcp", "block-tcp", "block-mesh")


def _digest(ds) -> str:
    """Per-rank content digest, codec-independent: sorted key multiset +
    sorted labels + instance count."""
    keys = np.sort(ds.all_keys())
    if ds._load_columnar:
        labels = ds.block.labels if ds.block is not None else \
            np.empty(0, np.int32)
    else:
        labels = np.array([r.label for r in ds.records], np.int32)
    h = hashlib.sha1()
    h.update(np.ascontiguousarray(keys, np.uint64).tobytes())
    h.update(np.sort(labels).astype(np.int32).tobytes())
    h.update(str(len(ds)).encode())
    return h.hexdigest()


def worker() -> None:
    import tempfile
    import threading

    from paddlebox_tpu.config import flags
    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.data.shuffle import MeshShuffler
    from paddlebox_tpu.fleet.fleet import Fleet
    from paddlebox_tpu.fleet.role_maker import RoleMaker
    from paddlebox_tpu.utils.stats import stat_get

    lines = int(os.environ["INGEST_LINES"])
    files_per_rank = int(os.environ["INGEST_FILES"])
    runs = int(os.environ["INGEST_RUNS"])
    parity_only = bool(os.environ.get("INGEST_PARITY_ONLY"))
    fl = Fleet().init(RoleMaker())
    rank, world = fl.worker_index(), fl.worker_num()

    out_dir = tempfile.mkdtemp(prefix="pbtpu_ingest_r%d_" % rank)
    files, feed = write_synthetic_ctr_files(
        out_dir, num_files=files_per_rank, lines_per_file=lines,
        num_slots=16, vocab_per_slot=5000, max_len=4, seed=100 + rank)
    feed = type(feed)(slots=feed.slots, batch_size=512)

    # transports: the mesh rendezvouses COLLECTIVELY first, then the
    # TCP endpoints all_gather (same order on every rank). Flags are
    # saved and RESTORED — the probe picks each tier's plane itself and
    # must not leave the process on a plane the operator didn't select
    prev_plane = flags.get_flag("hostplane")
    prev_codec = flags.get_flag("shuffle_block_codec")
    mesh = fl.make_mesh_comm(positions=())
    assert mesh is not None, "p2p mesh bring-up failed in ingest probe"
    mesh_sh = MeshShuffler(mesh)
    flags.set_flag("hostplane", "store")
    try:
        tcp_sh = fl.make_shuffler()
    finally:
        flags.set_flag("hostplane", prev_plane)

    def load(tier: str):
        flags.set_flag("shuffle_block_codec", tier != "record-tcp")
        sh = mesh_sh if tier == "block-mesh" else tcp_sh
        try:
            ds = BoxDataset(feed, read_threads=2, shuffler=sh)
            ds.set_filelist(files)
            ds.load_into_memory()
        finally:
            flags.set_flag("shuffle_block_codec", prev_codec)
        want_columnar = tier != "record-tcp"
        assert ds._load_columnar == want_columnar, tier
        return ds

    out = {}
    digests = {}
    for tier in TIERS:
        ds = load(tier)                      # warm + parity leg
        digests[tier] = _digest(ds)
        if parity_only:
            continue
        walls, rates, wire = [], [], []
        for _ in range(runs):
            fl.barrier_worker()
            b0 = (stat_get("shuffle_bytes_sent")
                  + stat_get("shuffle_bytes_received"))
            t0 = time.perf_counter()
            ds = load(tier)
            dt = time.perf_counter() - t0
            walls.append(dt * 1e3)
            rates.append(len(ds) / dt)
            wire.append(stat_get("shuffle_bytes_sent")
                        + stat_get("shuffle_bytes_received") - b0)
        out[tier] = {
            "load_ms": round(float(np.median(walls)), 1),
            "runs_ms": [round(x, 1) for x in walls],
            "records_per_sec": round(float(np.median(rates)), 0),
            "shuffle_bytes": int(np.median(wire)),
            "instances_landed": len(ds),
        }
    ref = digests[TIERS[0]]
    for tier, dig in digests.items():
        assert dig == ref, ("tier %s content diverged on rank %d"
                            % (tier, rank))
    if parity_only:
        out = {"parity": "ok"}
    print("RESULT " + json.dumps({"rank": rank, "world": world,
                                  "lines": lines, "tiers": out}),
          flush=True)
    mesh_sh.close()
    tcp_sh.close()
    fl.stop()


def run_world(world: int, lines: int, files_per_rank: int, runs: int,
              parity_only: bool = False, timeout: float = 600.0) -> dict:
    """Spawn a `world`-process localhost cluster of probe workers (the
    hostplane_probe subprocess pattern — pure host plane, no jax
    collectives)."""
    import uuid

    from paddlebox_tpu.fleet.store import KVStoreServer
    server = KVStoreServer(host="127.0.0.1")
    run_id = uuid.uuid4().hex[:8]
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
            env.update({
                "PBTPU_TRAINER_ID": str(rank),
                "PBTPU_TRAINERS_NUM": str(world),
                "PBTPU_STORE_ENDPOINT": "127.0.0.1:%d" % server.port,
                "PBTPU_RUN_ID": run_id,
                "INGEST_WORKER": "1",
                "INGEST_LINES": str(lines),
                "INGEST_FILES": str(files_per_rank),
                "INGEST_RUNS": str(runs),
                "JAX_PLATFORMS": "cpu",
            })
            if parity_only:
                env["INGEST_PARITY_ONLY"] = "1"
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        results = {}
        for p in procs:
            sout, serr = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError("ingest probe worker failed:\n"
                                   + serr[-3000:])
            for line in sout.splitlines():
                if line.startswith("RESULT "):
                    r = json.loads(line[len("RESULT "):])
                    results[r["rank"]] = r
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
    if set(results) != set(range(world)):
        raise RuntimeError("missing probe results: got %s" % sorted(results))
    return results[0]


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--worlds", default="2")
    ap.add_argument("--lines", type=int, default=4000)
    ap.add_argument("--files", type=int, default=2)
    ap.add_argument("--runs", type=int, default=3)
    args = ap.parse_args()
    ok = True
    for world in [int(w) for w in args.worlds.split(",")]:
        try:
            r = run_world(world, args.lines, args.files, args.runs)
            tiers = r["tiers"]
            # the acceptance bar: the block codec must beat the record
            # codec on the SAME transport (the codec is the claim; the
            # mesh tier is recorded alongside)
            faster = (tiers["block-tcp"]["records_per_sec"]
                      > tiers["record-tcp"]["records_per_sec"])
            ok = ok and faster
            print(json.dumps({"probe": "ingest", "world": world,
                              "lines": r["lines"], "tiers": tiers,
                              "block_beats_record": faster}), flush=True)
        except Exception as e:  # noqa: BLE001 — keep the ladder going
            ok = False
            print(json.dumps({"probe": "ingest", "world": world,
                              "error": repr(e)[:400]}), flush=True)
    print(json.dumps({"all_ok": ok}), flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    if os.environ.get("INGEST_WORKER"):
        worker()
    else:
        main()
