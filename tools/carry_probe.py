"""Does a scan COPY unchanged pass-through carries on this runtime?

The r5 log-structured step carries the slab through the scan untouched
(only the log mutates). full_step log-mode rows still scale with slab
size (tpu_probe: 16.3 ms @1M rows -> 27.4 @4M) even though every written
buffer is slab-size-independent — hypothesis: the runtime materializes a
copy of the unchanged slab carry each scan iteration. Compare:

  carry_pass   scan carry = (slab, log); body mutates log only
  invariant    scan carry = (log,); slab is a closed-over loop invariant
  carry_used   carry = (slab, log); body also READS slab (gather) — the
               real step's shape

Usage: timeout 900 python -u tools/carry_probe.py [platform] [caps...]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

W = 17
K = 131072
L = 16 * K
ITERS = 8
REPS = 3


def timed(name, fn, state, extra=None):
    try:
        out = fn(*state)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = fn(*state)
            np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        ms = (time.perf_counter() - t0) / REPS / ITERS * 1e3
    except Exception as e:
        print(json.dumps({"op": name, "error": str(e)[:200]}), flush=True)
        return
    rec = {"op": name, "ms_per_iter": round(ms, 4)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def probe(cap, rng):
    tag = {"cap": cap}
    slab = jnp.asarray(rng.rand(cap, W).astype(np.float32))
    log = jnp.asarray(rng.rand(L, W).astype(np.float32))
    nr = jnp.asarray(rng.rand(K, W).astype(np.float32))
    idx = jnp.asarray(rng.randint(0, cap, K).astype(np.int32))

    @jax.jit
    def carry_pass(slab, log, nr):
        def body(c, x):
            s, lg = c
            lg = lax.dynamic_update_slice(lg, nr + x, (0, 0))
            return (s, lg), x
        (s, lg), _ = lax.scan(body, (slab, log),
                              jnp.arange(ITERS, dtype=jnp.float32))
        return lg

    timed("carry_pass", carry_pass, (slab, log, nr), tag)

    @jax.jit
    def invariant(log, nr, slab):
        def body(lg, x):
            lg = lax.dynamic_update_slice(
                lg, nr + x + slab[:1, :1], (0, 0))
            return lg, x
        lg, _ = lax.scan(body, log, jnp.arange(ITERS, dtype=jnp.float32))
        return lg

    timed("invariant", invariant, (log, nr, slab), tag)

    @jax.jit
    def carry_used(slab, log, nr, idx):
        def body(c, x):
            s, lg = c
            rows = jnp.take(s, idx, axis=0)
            lg = lax.dynamic_update_slice(lg, rows + x, (0, 0))
            return (s, lg), x
        (s, lg), _ = lax.scan(body, (slab, log),
                              jnp.arange(ITERS, dtype=jnp.float32))
        return lg

    timed("carry_used", carry_used, (slab, log, nr, idx), tag)

    @jax.jit
    def invariant_used(log, nr, idx, slab):
        def body(lg, x):
            rows = jnp.take(slab, idx, axis=0)
            lg = lax.dynamic_update_slice(lg, rows + x, (0, 0))
            return lg, x
        lg, _ = lax.scan(body, log, jnp.arange(ITERS, dtype=jnp.float32))
        return lg

    timed("invariant_used", invariant_used, (log, nr, idx, slab), tag)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)
    caps = [int(a) for a in sys.argv[2:]] or [1 << 20, 1 << 22]
    for cap in caps:
        probe(cap, rng)


if __name__ == "__main__":
    main()
