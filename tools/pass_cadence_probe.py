"""Pass-cadence regression probe (round-6, same pattern as
tools/staged_regression_probe.py).

Measures the begin_pass/end_pass wall clock of ONE PassTable at a
configurable slab size and working-set overlap ratio, for both the full
lifecycle and the incremental (delta promote + touched-row writeback)
lifecycle, and FAILS LOUDLY on regression vs recorded floors:

  * full_lifecycle_rows_per_sec / delta_lifecycle_rows_per_sec — rows of
    the working set divided by (begin + end) seconds, floors at ~40% of
    the recorded quiet-box rates (low enough to ride out container
    noise, high enough to catch an algorithmic regression — the full
    path re-promoting everything through the delta machinery would blow
    straight through them).
  * delta_speedup_at_overlap — delta (begin+end) must stay faster than
    the full lifecycle at the probed overlap; losing this means the
    incremental path silently degenerated into a rebuild.

Prints one JSON line per stage with ok=true/false; exits 1 if any fails.
Usage: timeout 900 python -u tools/pass_cadence_probe.py [rows] [overlap]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# (recorded quiet-box rate AT THIS PROBE'S OWN WORKLOAD — round-6 first
# run, 2026-08-03, container CPU, 256k rows @ 0.9 overlap, 10% touched:
# full begin+end ≈ 349 ms, delta ≈ 149 ms — floor = ~40% of the recorded
# rate. The container is load-noisy (±30%+); the speedup ratio floor is
# deliberately low so only a real degeneration trips it.)
FLOORS = {
    "full_lifecycle_rows_per_sec": (751e3, 300e3),
    "delta_lifecycle_rows_per_sec": (1.76e6, 700e3),
    "delta_speedup_at_overlap": (2.34, 1.25),
}

failures = []


def report(stage, rate):
    rec, floor = FLOORS[stage]
    ok = rate >= floor
    if not ok:
        failures.append(stage)
    print(json.dumps({"stage": stage, "rate": round(float(rate), 2),
                      "recorded": rec, "floor": floor, "ok": ok}),
          flush=True)


def lifecycle_seconds(rows, overlap, incremental, touched_frac=0.1,
                      passes=6, warm_from=2, seed=0):
    """Mean (begin+end) seconds of the warm passes (the first `warm_from`
    are cold build + jit-bucket compiles and are excluded). Marks a FIXED
    count of touched rows per pass via lookup_ids, like a real pass's
    staging would — fixed so the harness's own mutation never recompiles."""
    import jax.numpy as jnp

    from paddlebox_tpu.config import flags
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.embedding.pass_table import PassTable

    flags.set_flag("incremental_pass", incremental)
    cap = 1
    while cap < rows * 2:
        cap <<= 1
    table = PassTable(TableConfig(
        embedx_dim=8, pass_capacity=cap,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3)), seed=seed)
    rng = np.random.RandomState(seed)
    cur = np.unique(rng.randint(0, 1 << 40, rows).astype(np.uint64))
    times = []
    for p in range(passes):
        t0 = time.perf_counter()
        table.begin_feed_pass()
        table.add_keys(cur)
        table.end_feed_pass()
        table.begin_pass()
        np.asarray(table.slab[0, 0:1])  # sync the promote
        t1 = time.perf_counter()
        # a real pass pulls/pushes a subset: mark it touched and mutate
        # those device rows so end_pass has real delta work to do
        n_touch = max(1, min(int(rows * touched_frac), cur.size))
        sub = cur[rng.choice(cur.size, n_touch, replace=False)]
        ids = table.lookup_ids(sub)
        table.set_slab(table.slab.at[jnp.asarray(ids)].add(0.5))
        np.asarray(table.slab[0, 0:1])  # keep the mutation out of `end`
        t2 = time.perf_counter()
        table.end_pass()
        t3 = time.perf_counter()
        if p >= warm_from:
            times.append((t1 - t0) + (t3 - t2))
        keep = rng.rand(cur.size) < overlap
        fresh = np.unique(rng.randint(
            0, 1 << 40, max(1, int(rows * (1 - overlap)))).astype(np.uint64))
        cur = np.unique(np.concatenate([cur[keep], fresh]))
    table.invalidate_residency()
    return float(np.mean(times))


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 18
    overlap = float(sys.argv[2]) if len(sys.argv) > 2 else 0.9
    from paddlebox_tpu.config import flags
    saved = flags.get_flag("incremental_pass")
    try:
        full_s = lifecycle_seconds(rows, overlap, incremental=False)
        delta_s = lifecycle_seconds(rows, overlap, incremental=True)
    finally:
        flags.set_flag("incremental_pass", saved)
    print(json.dumps({"rows": rows, "overlap": overlap,
                      "full_begin_end_ms": round(full_s * 1e3, 2),
                      "delta_begin_end_ms": round(delta_s * 1e3, 2)}),
          flush=True)
    report("full_lifecycle_rows_per_sec", rows / full_s)
    report("delta_lifecycle_rows_per_sec", rows / delta_s)
    report("delta_speedup_at_overlap", full_s / delta_s)
    if failures:
        print(json.dumps({"failed": failures}), flush=True)
        sys.exit(1)
    print(json.dumps({"all_ok": True}), flush=True)


if __name__ == "__main__":
    main()
