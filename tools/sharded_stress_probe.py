"""Seeded stress-rerun harness for the PR-6 sharded parity flake.

The target: tests/test_push_blocked.py::test_sharded_blocked_matches_
scatter flaked EXACTLY ONCE (2026-08-03, round 11) — 6/780 store
elements off by one in a show-like column (≈0.9 vs 1.9: one occurrence
of one key counted in one run and not the other) — in the only run
where the native .so recompile subprocess was executing concurrently.
10 clean reruns followed; root cause not found. This harness makes the
reproduction attempt MECHANICAL instead of anecdotal:

  * ``--reps N`` seeded stress reruns of the 4-config sharded
    blocked-vs-scatter parity (fresh synthetic data per seed), each rep
    under synthetic co-tenant load: GIL-dropping numpy sort burners
    plus an optional looping g++ compile subprocess (``--recompile``,
    the exact co-tenant the flake run had)
  * ``--tier-flip`` runs a HYPOTHESIS test directly: the same config
    trained once with the native router and once with the numpy
    fallback (what a mid-run recompile window can flip between). The
    two tiers only contract to identical products while no bucket
    overflows — WHICH occurrences drop on overflow is explicitly
    unspecified (sharded_table.bucketize docstring), and a dropped
    occurrence is exactly a show-column off-by-one. A mismatch here
    pins that mechanism; a match kills the hypothesis for this shape.

RESOLUTION (round 12): the race was PINNED — not by this e2e harness
(whose shape manifests it only rarely) but by the concurrent-parity
audit it motivated: rt_bucketize kept its generation-tagged dedup
scratch in the SHARED RouteIndex while the stager pool calls it
concurrently on one index with the GIL dropped; two callers drawing the
same generation read each other's seen-marks and silently mis-route an
occurrence (a direct 4-thread repro mismatched 1379/2400 routings).
Fixed by thread-local scratch (native/route.cc round-12 thread
contract); tests/test_native.py::test_concurrent_bucketize_parity is
the regression pin, and this harness remains the e2e-level guard.

Every line is JSON; a parity mismatch prints the differing element
count / max abs diff / affected columns + the rep's seed, and exits 1.
BASELINE.md round 12 records the accumulated reproduction bound.

Usage:
  timeout 3600 python -u tools/sharded_stress_probe.py \
      [--reps 5] [--seed 13] [--burners 2] [--recompile] [--tier-flip]
"""
import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddlebox_tpu.utils.platform import force_cpu_if_requested

force_cpu_if_requested()

import numpy as np  # noqa: E402

D = 4
NUM_SLOTS = 4


def make_data(seed, workdir):
    from paddlebox_tpu.data import write_synthetic_ctr_files
    files, feed = write_synthetic_ctr_files(
        os.path.join(workdir, f"data_{seed}"), num_files=2,
        lines_per_file=480, num_slots=NUM_SLOTS, vocab_per_slot=120,
        max_len=3, seed=seed)
    return files, type(feed)(slots=feed.slots, batch_size=64)


def train_states(files, feed, mode, uid, seed, force_numpy_route=False):
    """One ShardedBoxTrainer pass at (push_write, uid wire); returns the
    per-shard store state — the flaky test's exact workload shape.
    force_numpy_route drops the batch router to the numpy tier (the
    tier a broken/mid-recompile native load falls back to)."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import BoxDataset
    from paddlebox_tpu.models import CtrDnn
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.parallel import ShardedBoxTrainer
    from paddlebox_tpu.parallel import sharded_table as st

    snapshot = {k: flags.get_flag(k) for k in  # boxlint: disable=BX305
                ("push_write", "push_block_rows", "h2d_uid_wire")}
    real_route = st._route_lib
    flags.set_flag("push_write", mode)
    flags.set_flag("push_block_rows", 128)
    flags.set_flag("h2d_uid_wire", uid)
    if force_numpy_route:
        st._route_lib = lambda: None
    try:
        table_cfg = TableConfig(
            embedx_dim=D, pass_capacity=8 * (1 << 9),
            optimizer=SparseOptimizerConfig(
                mf_create_thresholds=0.0, mf_initial_range=1e-3,
                feature_learning_rate=0.1, mf_learning_rate=0.1))
        model = CtrDnn(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                       hidden=(16,))
        trainer = ShardedBoxTrainer(model, table_cfg, feed,
                                    TrainerConfig(dense_lr=3e-3),
                                    seed=seed)
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files[:1])
        trainer.train_pass(ds)
        states = [s.state_items() for s in trainer.table.stores]
        trainer.close()
        ds.release_memory()
        return states
    finally:
        st._route_lib = real_route
        for k, v in snapshot.items():
            # restoring the snapshot taken above — registry names
            flags.set_flag(k, v)  # boxlint: disable=BX305


def diff_states(a, b):
    """None when bit-identical, else a diagnostic dict."""
    for shard, ((ka, va), (kb, vb)) in enumerate(zip(a, b)):
        oa, ob = np.argsort(ka), np.argsort(kb)
        if not np.array_equal(ka[oa], kb[ob]):
            return {"shard": shard, "kind": "key_set"}
        va, vb = va[oa], vb[ob]
        if va.shape != vb.shape or not np.array_equal(va, vb):
            bad = np.nonzero(va != vb)
            return {
                "shard": shard, "kind": "values",
                "n_bad": int(bad[0].size), "of": int(va.size),
                "max_abs_diff": float(np.abs(va - vb).max()),
                "cols": sorted(set(bad[1].tolist()))[:8],
            }
    return None


class LoadBurners:
    """GIL-dropping co-tenant load: numpy sorts on daemon threads."""

    def __init__(self, n):
        self._stop = threading.Event()
        self._threads = []
        for i in range(n):
            a = np.random.RandomState(i).randint(0, 1 << 40, 1 << 19)

            def burn(arr=a):
                while not self._stop.is_set():
                    np.sort(arr)

            t = threading.Thread(target=burn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)


class RecompileLoop:
    """Loops a real g++ -O3 compile of route.cc into a scratch dir —
    the exact co-tenant process mix of the one observed flake (the
    repo's own .so is never touched)."""

    def __init__(self):
        self._stop = threading.Event()
        self._scratch = tempfile.mkdtemp(prefix="pbx_stress_cc_")
        src = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "paddlebox_tpu", "native",
            "route.cc")
        self._src = shutil.copy(src, self._scratch)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        out = os.path.join(self._scratch, "scratch.so")
        while not self._stop.is_set():
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-o", out, self._src],
                capture_output=True)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=60.0)
        shutil.rmtree(self._scratch, ignore_errors=True)


CONFIGS = (("scatter", False), ("blocked", False),
           ("scatter", True), ("blocked", True))


def run_rep(files, feed, seed):
    """One seeded stress rep: all 4 configs, blocked-vs-scatter parity
    per wire. Returns list of mismatch diagnostics (empty = clean)."""
    states = {}
    for mode, uid in CONFIGS:
        states[(mode, uid)] = train_states(files, feed, mode, uid, seed)
    bad = []
    for uid in (False, True):
        d = diff_states(states[("blocked", uid)], states[("scatter", uid)])
        if d is not None:
            d["wire"] = "uid" if uid else "full"
            bad.append(d)
    return bad


def run_tier_flip(files, feed, seed):
    """Native-vs-numpy router tier at a FIXED config (scatter, full
    wire): the products contract to be identical absent bucket
    overflow. A diff here = the recompile-window tier flip can produce
    exactly the observed off-by-one class."""
    a = train_states(files, feed, "scatter", False, seed,
                     force_numpy_route=False)
    b = train_states(files, feed, "scatter", False, seed,
                     force_numpy_route=True)
    return diff_states(a, b)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--seed", type=int, default=13)
    ap.add_argument("--burners", type=int, default=2)
    ap.add_argument("--recompile", action="store_true")
    ap.add_argument("--tier-flip", action="store_true")
    args = ap.parse_args()

    work = tempfile.mkdtemp(prefix="pbx_stress_")
    failures = 0
    burners = LoadBurners(args.burners) if args.burners else None
    recompile = RecompileLoop() if args.recompile else None
    try:
        if args.tier_flip:
            files, feed = make_data(args.seed, work)
            d = run_tier_flip(files, feed, args.seed)
            print(json.dumps({"stage": "tier_flip", "seed": args.seed,
                              "match": d is None, "diff": d}),
                  flush=True)
            failures += d is not None
        for rep in range(args.reps):
            seed = args.seed + rep
            files, feed = make_data(seed, work)
            t0 = time.perf_counter()
            bad = run_rep(files, feed, seed)
            print(json.dumps({
                "stage": "stress_rep", "rep": rep, "seed": seed,
                "clean": not bad, "diffs": bad,
                "burners": args.burners,
                "recompile": bool(recompile),
                "secs": round(time.perf_counter() - t0, 1)}),
                flush=True)
            failures += len(bad)
    finally:
        if burners:
            burners.stop()
        if recompile:
            recompile.stop()
        shutil.rmtree(work, ignore_errors=True)
    print(json.dumps({"failures": failures}), flush=True)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
