"""Multi-device (virtual-mesh) benchmark of ShardedBoxTrainer + the stager.

The round-3 verdict's item 2/3: BASELINE.md had no multi-device throughput
row on ANY backend — the software overhead of sharding (host routing, push
dedup, device_put, a2a) had never been timed. This tool measures, on the
8-device CPU mesh (or whatever JAX exposes):

  1. stager routing throughput (keys/s) at 1 vs N threads — the
     _step_host_arrays bucketize + push-dedup stage (flag stager_threads);
  2. end-to-end sharded step throughput (ex/s) with the streamed input,
     vs the single-device BoxTrainer on the same process/platform;
  3. per-step cost attribution: host routing, device_put, step dispatch.

Shapes match bench.py (DeepFM 512/256/128, batch 1024/worker, 32 slots,
1M-row pass slab) so the numbers compose with BASELINE.md's tables.
Emits one JSON dict on stdout.

Run: python tools/sharded_bench.py  (forces cpu + 8 virtual devices)
"""

import json
import os
import sys
import time

if __name__ == "__main__":
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"
    # run as a script: sys.path[0] is tools/, the repo root isn't there
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

D = 8
NUM_SLOTS = 32
BATCH = 1024
MAX_LEN = 4
PASS_CAP = 1 << 20
STEPS = 8          # timed steps per segment
WARMUP = 2


def build_sharded():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from tools.bench_util import make_ctr_batches

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data.generator import default_feed_config
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.parallel.mesh import device_mesh_1d
    from paddlebox_tpu.parallel.sharded_trainer import ShardedBoxTrainer

    P = len(jax.devices())
    feed = default_feed_config(num_slots=NUM_SLOTS, batch_size=BATCH,
                               max_len=MAX_LEN)
    # weak scaling: each shard gets the single-device bench's 1M-row slab
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=P * PASS_CAP,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    model = DeepFM(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(512, 256, 128))
    trainer = ShardedBoxTrainer(model, table_cfg, feed,
                                TrainerConfig(dense_lr=1e-3),
                                mesh=device_mesh_1d(P), seed=0)
    # one pass worth of per-worker batches (recycled per timed step)
    n_batches = STEPS + WARMUP
    per_worker = [make_ctr_batches(feed, n_batches, NUM_SLOTS, MAX_LEN,
                                   seed=1000 + w) for w in range(P)]
    trainer.table.begin_feed_pass()
    for batches in per_worker:
        for b in batches:
            trainer.table.add_keys(b.keys[b.valid])
    trainer.table.end_feed_pass()
    return trainer, per_worker, P


def time_stager(trainer, per_worker, threads: int) -> dict:
    """Route STEPS steps with the given pool size; keys/s of the host
    routing + push-dedup stage alone (no device_put)."""
    from paddlebox_tpu.config import flags
    flags.set_flag("stager_threads", threads)
    if trainer._pool is not None:
        trainer._pool.shutdown(wait=True)
        trainer._pool = None
    n_steps = len(per_worker[0])
    keys_per_step = sum(b.keys.size for pw in per_worker for b in (pw[0],))
    for i in range(WARMUP):
        trainer._step_host_arrays(per_worker, i % n_steps)
    t0 = time.perf_counter()
    for i in range(STEPS):
        trainer._step_host_arrays(per_worker, i % n_steps)
    dt = (time.perf_counter() - t0) / STEPS
    return {"threads": threads, "ms_per_step": round(dt * 1e3, 2),
            "keys_per_sec": round(keys_per_step / dt, 0)}


def time_sharded_steps(trainer, per_worker) -> dict:
    """End-to-end streamed step throughput + attribution. D2H-synced: the
    final losses depend on every step's full compute chain."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P_

    sharding = NamedSharding(trainer.mesh, P_(trainer.axis))
    slabs = jax.device_put(trainer.table.build_slabs(), sharding)
    mtab, mstats = trainer.make_metric_state()
    prng = jax.random.PRNGKey(0)
    params, opt_state = trainer.params, trainer.opt_state

    # --- attribution: host routing / device_put / dispatch (serial timing
    # of each stage, no overlap — the stream overlaps them in production)
    t0 = time.perf_counter()
    arrs = trainer._step_host_arrays(per_worker, 0)
    t_route = time.perf_counter() - t0
    t0 = time.perf_counter()
    dev = {k: jax.device_put(v, sharding) for k, v in arrs.items()}
    jax.block_until_ready(dev)
    t_put = time.perf_counter() - t0

    # warmup/compile
    for i in range(WARMUP):
        (slabs, params, opt_state, loss, preds, prng, mtab,
         mstats) = trainer._step(slabs, params, opt_state, dev, prng,
                                 mtab, mstats)
    np.asarray(loss)

    # steady state: the bounded stream overlaps routing with device steps
    losses = []
    t0 = time.perf_counter()
    stream = trainer.shard_batches(
        [pw[:STEPS] for pw in per_worker])
    try:
        for batch in stream:
            (slabs, params, opt_state, loss, preds, prng, mtab,
             mstats) = trainer._step(slabs, params, opt_state, batch,
                                     prng, mtab, mstats)
            losses.append(loss)
    finally:
        stream.close()
    final = np.asarray(jax.numpy.stack(losses))   # real D2H sync
    dt = (time.perf_counter() - t0) / STEPS
    assert np.isfinite(final).all()
    P = trainer.P
    return {"ms_per_step": round(dt * 1e3, 2),
            "examples_per_sec": round(P * BATCH / dt, 0),
            "examples_per_sec_per_device": round(BATCH / dt, 0),
            "route_ms": round(t_route * 1e3, 2),
            "device_put_ms": round(t_put * 1e3, 2),
            "stream_high_water": trainer.stream_high_water}


def time_single_device() -> dict:
    """BoxTrainer on ONE device, same shapes — the scaling denominator.
    CPU keeps f32 compute (bf16 is emulated there), matching bench.py."""
    from tools.bench_util import make_ctr_batches

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data.generator import default_feed_config
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.train.trainer import BoxTrainer

    feed = default_feed_config(num_slots=NUM_SLOTS, batch_size=BATCH,
                               max_len=MAX_LEN)
    table_cfg = TableConfig(
        embedx_dim=D, pass_capacity=PASS_CAP,
        optimizer=SparseOptimizerConfig(mf_create_thresholds=0.0,
                                        mf_initial_range=1e-3))
    model = DeepFM(ModelSpec(num_slots=NUM_SLOTS, slot_dim=3 + D),
                   hidden=(512, 256, 128))
    trainer = BoxTrainer(model, table_cfg, feed,
                         TrainerConfig(dense_lr=1e-3), seed=0)
    batches = make_ctr_batches(feed, STEPS, NUM_SLOTS, MAX_LEN, seed=0)
    trainer.table.begin_feed_pass()
    for b in batches:
        trainer.table.add_keys(b.keys[b.valid])
    trainer.table.end_feed_pass()
    trainer.table.begin_pass()
    stacked = trainer._stack_batches(batches)
    scan = trainer.fns.scan_steps
    state = (trainer.table.slab, trainer.params, trainer.opt_state,
             trainer.table.next_prng())
    from tools.bench_util import timed_scan_chain
    dt = timed_scan_chain(scan, state, stacked, 4, warmup=WARMUP)
    return {"ms_per_step": round(dt * 1e3 / STEPS, 2),
            "examples_per_sec": round(STEPS * BATCH / dt, 0)}


def main():
    trainer, per_worker, P = build_sharded()
    out = {"devices": P, "batch_per_device": BATCH,
           "keys_per_step": sum(b.keys.size for pw in per_worker
                                for b in (pw[0],))}
    out["stager"] = [time_stager(trainer, per_worker, t)
                     for t in (1, 2, 4, 8)]
    out["sharded"] = time_sharded_steps(trainer, per_worker)
    out["single_device"] = time_single_device()
    spd = (out["sharded"]["examples_per_sec"]
           / out["single_device"]["examples_per_sec"])
    out["scaling_vs_1dev"] = round(spd, 3)
    out["scaling_efficiency"] = round(spd / P, 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
