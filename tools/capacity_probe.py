"""Peak sparse params/chip + reference-key-budget step (round-5 item 3).

Measures the unreported half of BASELINE.json's metric:
  1. the largest pass slab that BUILDS AND TRAINS on the chip — walk the
     capacity ladder until allocation/compile fails, reporting ms/step
     and params/chip at each size (params = rows × width incl optimizer
     state; trainable = rows × (1 + embedx_dim));
  2. one step at the reference's per-batch key budget (1800×2048 ≈ 3.69M
     keys — heter_comm.h:348) — the key-throughput shape the closed core
     is sized for.

The slab is created ON DEVICE (jnp.zeros) and the pass key set is only
the bench batches' keys: promotion H2D of a multi-GB slab through the
~68 MB/s tunnel would measure the link, not the chip (BASELINE.md). The
step itself is the production fused step (make_train_step via
make_bench_trainer), write mode from the auto resolve at each capacity.

Usage: timeout 3000 python -u tools/capacity_probe.py [platform] [caps...]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np

from tools.bench_util import (make_bench_trainer, make_ctr_batches,
                              timed_scan_chain)

D, NUM_SLOTS, BATCH, MAX_LEN = 8, 32, 1024, 4
CHUNK, REPS = 8, 3


def fake_begin_pass(tr, cap):
    """Device-side slab creation (no multi-GB H2D through the tunnel)."""
    W = tr.table.layout.width
    tr.table._slab = jnp.zeros((cap, W), jnp.float32)
    tr.table._in_pass = True


def try_cap(cap):
    t0 = time.perf_counter()
    tr, feed = make_bench_trainer(cap, batch=BATCH, num_slots=NUM_SLOTS,
                                  max_len=MAX_LEN, d=D)
    batches = make_ctr_batches(feed, CHUNK, NUM_SLOTS, MAX_LEN, seed=0)
    tr.table.begin_feed_pass()
    for b in batches:
        tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    W = tr.table.layout.width
    fake_begin_pass(tr, cap)
    stacked = tr._stack_batches(batches)
    state = (tr.table.slab, tr.params, tr.opt_state,
             tr.table.next_prng())
    dt = timed_scan_chain(tr.fns.scan_steps, state, stacked,
                          REPS) / CHUNK
    rec = {
        "cap_rows": cap,
        "push_write": tr._push_write,
        "slab_gb": round(cap * W * 4 / 2**30, 2),
        "params_per_chip": cap * W,
        "trainable_params_per_chip": cap * (1 + D),
        "ms_per_step": round(dt * 1e3, 2),
        "examples_per_sec": round(BATCH / dt, 0),
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    print(json.dumps(rec), flush=True)
    return True


def reference_key_budget():
    """One step at ~1800 keys/instance × 2048 instances (heter_comm.h:348):
    batch 2048, 32 slots × max_len 56 ≈ 1792 keys/ins → K ≈ 3.67M."""
    cap = 1 << 23
    tr, feed = make_bench_trainer(cap, batch=2048, num_slots=NUM_SLOTS,
                                  max_len=56, d=D)
    batches = make_ctr_batches(feed, 2, NUM_SLOTS, 56, seed=0)
    tr.table.begin_feed_pass()
    for b in batches:
        tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    fake_begin_pass(tr, cap)
    stacked = tr._stack_batches(batches)
    state = (tr.table.slab, tr.params, tr.opt_state,
             tr.table.next_prng())
    dt = timed_scan_chain(tr.fns.scan_steps, state, stacked,
                          REPS) / 2
    K = feed.key_capacity()
    print(json.dumps({
        "stage": "reference_key_budget",
        "keys_per_batch": K, "batch": 2048, "pass_cap": cap,
        "push_write": tr._push_write,
        "ms_per_step": round(dt * 1e3, 2),
        "keys_per_sec": round(K / dt, 0),
        "examples_per_sec": round(2048 / dt, 0),
    }), flush=True)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    caps = ([int(a) for a in sys.argv[2:]]
            or [1 << 23, 1 << 24, 1 << 25, 1 << 26, 3 << 25, 1 << 27])
    for cap in caps:
        try:
            ok = try_cap(cap)
        except Exception as e:
            print(json.dumps({"cap_rows": cap,
                              "error": repr(e)[:300]}), flush=True)
            ok = False
        if not ok:
            break
    try:
        reference_key_budget()
    except Exception as e:
        print(json.dumps({"stage": "reference_key_budget",
                          "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
