"""Host-plane exchange ladder: store allgather vs p2p a2a vs p2p+uid,
plus the round-13 sharding-POLICY leg.

Round-9 acceptance probe: REAL multi-process measurement of the per-step
cluster bucket exchange (the staging stage the p2p mesh replaces), at 2-4
processes on one machine. Three tiers, all producing bit-identical
per-destination `push_uids` (asserted on the first step):

  store    exchange_outgoing_buckets through the central TcpStore
           (every rank's FULL [n_local, P, KB] set bounces through one
           server: O(W^2*P*KB) bytes + 3 counter round-trips/rank/step)
  p2p      exchange_incoming_p2p over the persistent socket mesh (each
           rank ships each peer only that peer's destination columns:
           O(W*P*KB) direct bytes), dedup after the wire
  p2p_uid  exchange_push_uids_p2p (dedup BEFORE the wire: only sorted
           unique uid vectors travel)

Per tier: `runs` timed drives of `steps` exchanges each, MEDIAN per-step
staging ms reported (container CPU noise otherwise dominates), plus
exchange bytes/step from the hostplane stat counters.

POLICY leg (--policies, round 13): the full route-and-stage path
(bucketize through the policy's native router + the p2p uid exchange)
on a SKEWED-TABLE workload — zipf-ish table sizes with a hot long-tail
key set carrying half of all occurrences — under key-mod, table-wise,
2d-grid, and 2d-grid with the replicated hot tier active. Per policy:
median staging ms + exchange bytes/step from the hostplane counters
(the PR-5 obs stats are the per-policy measurement), p2p-vs-store
product parity per rank, and per-rank received-byte imbalance. The
acceptance bar: the hot-tier leg must cut per-rank exchange bytes vs
key-mod (routing alone conserves total routed ids — only replication
removes bytes from this host plane; see BASELINE.md round 13).

Usage:  timeout 900 python -u tools/hostplane_probe.py [--worlds 2,4]
            [--kb 32768] [--steps 4] [--runs 3] [--policies]
Prints one JSON line per world plus {"all_ok": ...}; exits 1 on failure.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NUM_DEVICES = 8


def _owned_positions(rank: int, world: int):
    return [int(p) for p in np.array_split(np.arange(NUM_DEVICES), world)[rank]]


def stage_tier(kind: str, buckets, positions, num_devices: int,
               shard_cap: int, all_gather=None, mesh=None, pool=None):
    """ONE host-plane staging step (exchange + per-destination uid dedup)
    at ladder tier `kind` -> {dest: push_uids}. The single definition the
    probe worker, the dryrun_multichip hostplane leg, and any future
    parity check share — the three tiers must produce bit-identical
    products, so their composition lives in exactly one place."""
    from paddlebox_tpu.embedding.pass_table import dedup_uids_sorted
    from paddlebox_tpu.parallel.sharded_table import (
        exchange_incoming_p2p, exchange_outgoing_buckets,
        exchange_push_uids_p2p)
    if kind == "store":
        gb = exchange_outgoing_buckets(buckets, positions, num_devices,
                                       all_gather)
        return {d: dedup_uids_sorted(
            np.concatenate([gb[s][d] for s in range(num_devices)]),
            shard_cap) for d in positions}
    if kind == "p2p":
        inc = exchange_incoming_p2p(buckets, positions, num_devices, mesh)
        return {d: dedup_uids_sorted(inc[d].reshape(-1), shard_cap)
                for d in positions}
    if kind == "p2p_uid":
        return exchange_push_uids_p2p(buckets, positions, num_devices,
                                      shard_cap, mesh, pool=pool)
    raise ValueError("unknown hostplane tier %r" % kind)


def _policy_legs(num_devices: int, num_tables: int, shift: int):
    """The measured policy ladder (construction shared by worker and any
    parity caller): hot threshold 2 on the last leg; the hot set is
    observed deterministically pre-freeze so every rank agrees."""
    from paddlebox_tpu.parallel.sharding import (KeyModPolicy,
                                                 TableWisePolicy,
                                                 TwoDGridPolicy)
    return [
        ("key-mod", KeyModPolicy(num_devices)),
        ("table-wise", TableWisePolicy(num_devices, num_tables, shift)),
        ("2d-grid", TwoDGridPolicy(num_devices, num_tables,
                                   rows=2, table_shift=shift)),
        ("2d-grid+hot", TwoDGridPolicy(num_devices, num_tables, rows=2,
                                       table_shift=shift,
                                       hot_threshold=2, hot_cap=4096)),
    ]


def _skewed_world(num_tables: int, shift: int, n_keys: int, n_hot: int):
    """Deterministic skewed-table key universe (same on every rank):
    zipf-ish per-table sizes, table id in the high bits, plus a hot
    long-tail set that will carry half of every batch's occurrences."""
    rng = np.random.RandomState(777)
    w = 1.0 / np.arange(1, num_tables + 1)
    sizes = np.maximum(16, (w / w.sum() * n_keys)).astype(np.int64)
    parts = []
    for t, n in enumerate(sizes):
        low = rng.randint(0, 1 << 30, int(n)).astype(np.uint64)
        parts.append((np.uint64(t) << np.uint64(shift)) | low)
    keys = np.unique(np.concatenate(parts))
    hot = np.sort(rng.choice(keys, n_hot, replace=False))
    return keys, hot


def policy_worker() -> None:
    """One rank of the policy-leg ladder: route (bucketize via the
    policy router) + stage (p2p uid exchange under the policy) a skewed
    batch stream per policy; parity vs the store path; measure ms and
    exchange bytes from the hostplane stat counters."""
    from concurrent.futures import ThreadPoolExecutor

    from paddlebox_tpu.config import flags
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.fleet.fleet import Fleet
    from paddlebox_tpu.fleet.role_maker import RoleMaker
    from paddlebox_tpu.parallel.sharded_table import (ShardedPassTable,
                                                      stage_push_dedup)
    from paddlebox_tpu.utils.stats import StatRegistry

    kb = int(os.environ["HOSTPLANE_KB"])
    steps = int(os.environ["HOSTPLANE_STEPS"])
    runs = int(os.environ["HOSTPLANE_RUNS"])
    parity_only = bool(os.environ.get("HOSTPLANE_PARITY_ONLY"))
    # any bucket overflow would silently change products per policy —
    # fail loud instead of publishing a corrupt ladder
    flags.set_flag("strict_bucket_overflow", True)
    T, SHIFT = 8, 48
    fl = Fleet().init(RoleMaker())
    rank, world = fl.worker_index(), fl.worker_num()
    positions = _owned_positions(rank, world)
    mesh = fl.make_mesh_comm(positions)
    assert mesh is not None, "p2p mesh bring-up failed in probe worker"
    # the zipf-hot regime the 2-D paper targets: a hot set ~kb wide
    # carries 3/4 of every batch's occurrences, so per-(src,dest)
    # uniques are hot-dominated — the shape where replication pays
    keys, hot = _skewed_world(T, SHIFT, n_keys=6 * kb, n_hot=max(256, kb))
    K = 2 * kb                       # occurrences per source per step
    shard_cap = 1 << max(12, (6 * kb).bit_length())
    cfg = TableConfig(embedx_dim=8,
                      pass_capacity=NUM_DEVICES * shard_cap,
                      optimizer=SparseOptimizerConfig())
    pool = ThreadPoolExecutor(4)
    stats = StatRegistry.instance()

    def batch_for(step_i: int, pos_j: int) -> np.ndarray:
        rng = np.random.RandomState(10_000 + rank * 211 + pos_j * 31
                                    + step_i)
        nh = (3 * K) // 4           # hot tail carries 3/4 of the load
        b = np.concatenate([rng.choice(hot, nh),
                            rng.choice(keys, K - nh)]).astype(np.uint64)
        rng.shuffle(b)
        return b

    out = {}
    for name, pol in _policy_legs(NUM_DEVICES, T, SHIFT):
        table = ShardedPassTable(cfg, NUM_DEVICES, kb, policy=pol)
        if getattr(pol, "hot_threshold", 0) > 0:
            # deterministic global frequency knowledge, identical on
            # every rank — the cluster-agreement contract freeze_hot
            # relies on
            for _ in range(pol.hot_threshold):
                pol.observe(hot)
        table.begin_feed_pass()
        table.add_keys(keys)
        table.end_feed_pass()       # freezes the hot tier

        def stage(step_i: int, use_mesh):
            buckets = []
            for j in range(len(positions)):
                b = batch_for(step_i, j)
                valid = np.ones(b.size, bool)
                buckets.append(table.bucketize(b, valid).buckets)
            return stage_push_dedup(
                buckets, positions, NUM_DEVICES, table.shard_cap,
                multiprocess=True, all_gather=fl.all_gather,
                rebuild=False, pool=pool, uid_only=True,
                mesh=use_mesh, policy=pol)

        # parity leg: p2p product vs store product on step 0. The hot
        # leg's p2p product may exceed the store one by EXACTLY the
        # replicated set (owners re-add whole hot sets; the store path
        # ships everything) — anything else is corruption.
        p2p0 = stage(0, mesh)
        store0 = stage(0, None)
        for i, d in enumerate(positions):
            a = p2p0["push_uids"][i]
            b = store0["push_uids"][i]
            real_a = set(a[a < table.shard_cap].tolist())
            real_b = set(b[b < table.shard_cap].tolist())
            h = pol.hot_local_ids(d)
            extra = real_a - real_b
            assert real_b <= real_a, f"{name} dest {d}: p2p lost ids"
            assert not extra or (h is not None and extra <= set(
                h.tolist())), f"{name} dest {d}: non-hot extras {extra}"
        if parity_only:
            continue
        fl.barrier_worker()
        per_ms, per_bytes = [], []
        for r in range(runs):
            fl.barrier_worker()
            b0 = stats.get("hostplane_exchange_bytes")
            t0 = time.perf_counter()
            for s in range(steps):
                stage(1 + r * steps + s, mesh)
            dt = time.perf_counter() - t0
            per_ms.append(dt * 1e3 / steps)
            per_bytes.append(
                (stats.get("hostplane_exchange_bytes") - b0) // steps)
        out[name] = {
            "exchange_ms": round(float(np.median(per_ms)), 2),
            "runs_ms": [round(x, 2) for x in per_ms],
            "exchange_bytes": int(np.median(per_bytes)),
            "hot_replicated": int(sum(
                h.size for h in (pol.hot_local_ids(d)
                                 for d in range(NUM_DEVICES))
                if h is not None)),
        }
    if parity_only:
        out = {"parity": "ok"}
    print("RESULT " + json.dumps({"rank": rank, "world": world, "kb": kb,
                                  "tiers": out}), flush=True)
    pool.shutdown(wait=False)
    fl.stop()


def worker() -> None:
    from concurrent.futures import ThreadPoolExecutor

    from paddlebox_tpu.fleet.fleet import Fleet
    from paddlebox_tpu.fleet.role_maker import RoleMaker
    from paddlebox_tpu.utils.stats import StatRegistry

    kb = int(os.environ["HOSTPLANE_KB"])
    steps = int(os.environ["HOSTPLANE_STEPS"])
    runs = int(os.environ["HOSTPLANE_RUNS"])
    shard_cap = int(os.environ.get("HOSTPLANE_SHARD_CAP", str(1 << 16)))
    fl = Fleet().init(RoleMaker())
    rank, world = fl.worker_index(), fl.worker_num()
    positions = _owned_positions(rank, world)
    mesh = fl.make_mesh_comm(positions)
    assert mesh is not None, "p2p mesh bring-up failed in probe worker"

    rng = np.random.RandomState(1234 + rank)
    buckets = rng.randint(0, shard_cap - 1,
                          (len(positions), NUM_DEVICES, kb)).astype(np.int32)
    # trash-pad a tail like bucketize does
    buckets[:, :, -kb // 8:] = shard_cap - 1
    # the runners hand their stager pool to the pre-wire dedup — match it
    pool = ThreadPoolExecutor(4)

    def tier_fn(kind):
        return lambda: stage_tier(kind, buckets, positions, NUM_DEVICES,
                                  shard_cap, all_gather=fl.all_gather,
                                  mesh=mesh, pool=pool)

    tiers = [(k, tier_fn(k)) for k in ("store", "p2p", "p2p_uid")]
    # parity across the whole ladder before timing anything
    parity_only = bool(os.environ.get("HOSTPLANE_PARITY_ONLY"))
    ref = tiers[0][1]()
    stats = StatRegistry.instance()
    out = {}
    for name, fn in tiers:
        got = fn()
        for d in positions:
            np.testing.assert_array_equal(
                got[d], ref[d], err_msg=f"tier {name} dest {d}")
        if parity_only:
            continue
        fl.barrier_worker()
        per_step, per_bytes = [], []
        for _ in range(runs):
            fl.barrier_worker()
            b0 = stats.get("hostplane_exchange_bytes")
            t0 = time.perf_counter()
            for _ in range(steps):
                fn()
            dt = time.perf_counter() - t0
            per_step.append(dt * 1e3 / steps)
            per_bytes.append(
                (stats.get("hostplane_exchange_bytes") - b0) // steps)
        out[name] = {"exchange_ms": round(float(np.median(per_step)), 2),
                     "runs_ms": [round(x, 2) for x in per_step],
                     "exchange_bytes": int(np.median(per_bytes))}
    if parity_only:
        out = {"parity": "ok"}
    print("RESULT " + json.dumps({"rank": rank, "world": world, "kb": kb,
                                  "tiers": out}), flush=True)
    fl.stop()


def run_world(world: int, kb: int, steps: int, runs: int,
              parity_only: bool = False, timeout: float = 600.0,
              policies: bool = False) -> dict:
    """Spawn a `world`-process localhost cluster of probe workers (the
    test_multihost subprocess pattern — but pure host-plane: no jax
    collectives, so it runs on this CPU container). policies=True runs
    the round-13 policy ladder instead of the transport ladder."""
    import uuid

    from paddlebox_tpu.fleet.store import KVStoreServer
    server = KVStoreServer(host="127.0.0.1")
    run_id = uuid.uuid4().hex[:8]
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
            env.update({
                "PBTPU_TRAINER_ID": str(rank),
                "PBTPU_TRAINERS_NUM": str(world),
                "PBTPU_STORE_ENDPOINT": "127.0.0.1:%d" % server.port,
                "PBTPU_RUN_ID": run_id,
                "HOSTPLANE_WORKER": "1",
                "HOSTPLANE_KB": str(kb),
                "HOSTPLANE_STEPS": str(steps),
                "HOSTPLANE_RUNS": str(runs),
                "JAX_PLATFORMS": "cpu",
            })
            if parity_only:
                env["HOSTPLANE_PARITY_ONLY"] = "1"
            if policies:
                env["HOSTPLANE_POLICIES"] = "1"
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        results = {}
        for p in procs:
            sout, serr = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError("probe worker failed:\n" + serr[-3000:])
            for line in sout.splitlines():
                if line.startswith("RESULT "):
                    r = json.loads(line[len("RESULT "):])
                    results[r["rank"]] = r
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
    if set(results) != set(range(world)):
        raise RuntimeError("missing probe results: got %s" % sorted(results))
    return results[0]


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--worlds", default="2,4")
    ap.add_argument("--kb", type=int, default=32768)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--policies", action="store_true",
                    help="run the round-13 sharding-policy ladder "
                         "(key-mod / table-wise / 2d-grid / +hot) on "
                         "the skewed-table workload")
    args = ap.parse_args()
    ok = True
    for world in [int(w) for w in args.worlds.split(",")]:
        try:
            r = run_world(world, args.kb, args.steps, args.runs,
                          policies=args.policies)
            tiers = r["tiers"]
            if args.policies:
                # acceptance: the replicated hot tier must remove bytes
                # from the wire (pure re-routing conserves them)
                better = (tiers["2d-grid+hot"]["exchange_bytes"]
                          < tiers["key-mod"]["exchange_bytes"])
                ok = ok and better
                print(json.dumps({
                    "probe": "hostplane_policy", "world": world,
                    "kb": r["kb"], "tiers": tiers,
                    "hot_beats_keymod_bytes": better}), flush=True)
                continue
            # the acceptance bar: p2p must beat the store funnel
            faster = (tiers["p2p"]["exchange_ms"] < tiers["store"]["exchange_ms"]
                      or tiers["p2p_uid"]["exchange_ms"]
                      < tiers["store"]["exchange_ms"])
            ok = ok and faster
            print(json.dumps({"probe": "hostplane", "world": world,
                              "kb": r["kb"], "tiers": tiers,
                              "p2p_beats_store": faster}), flush=True)
        except Exception as e:  # noqa: BLE001 — keep the ladder going
            ok = False
            print(json.dumps({"probe": "hostplane", "world": world,
                              "error": repr(e)[:400]}), flush=True)
    print(json.dumps({"all_ok": ok}), flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    if os.environ.get("HOSTPLANE_WORKER"):
        if os.environ.get("HOSTPLANE_POLICIES"):
            policy_worker()
        else:
            worker()
    else:
        main()
