"""Ablate the push's sub-ops INSIDE the real fused step, on the live chip.

tools/tpu_probe.py attributes ~79% of the step to the push; the microbench
(tools/push_microbench.py) can't see fusion context. This rebuilds the
REAL bench trainer with one sub-op surgically stubbed per variant (via
monkeypatching the trainer/optimizer module globals) and times the real
scan megastep — the difference vs `full` is that sub-op's true in-step
cost. Stubs keep all dataflow dependencies (timing valid) but NOT
numerics (losses stay finite; values are wrong — never use for training).

Usage: timeout 1800 python -u tools/push_ablate.py [platform]
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np

from tools.bench_util import (make_bench_trainer, make_ctr_batches,
                              timed_scan_chain)

BATCH = int(os.environ.get("ABLATE_BATCH", "1024"))
NUM_SLOTS, MAX_LEN = 32, 4
PASS_CAP = 1 << 20
CHUNK = max(1, 8192 // BATCH)
REPS = 6


def run_variant(name, patches):
    """patches: list of (module, attr, replacement_factory) applied before
    the trainer (and so the jitted step) is built."""
    import paddlebox_tpu.embedding.optimizers as opt_mod
    import paddlebox_tpu.train.trainer as tr_mod
    saved = []
    try:
        for mod, attr, repl in patches:
            saved.append((mod, attr, getattr(mod, attr)))
            setattr(mod, attr, repl)
        tr, feed = make_bench_trainer(PASS_CAP, batch=BATCH,
                                      num_slots=NUM_SLOTS, max_len=MAX_LEN)
        batches = make_ctr_batches(feed, CHUNK, NUM_SLOTS, MAX_LEN, seed=0)
        tr.table.begin_feed_pass()
        for b in batches:
            tr.table.add_keys(b.keys[b.valid])
        tr.table.end_feed_pass()
        tr.table.begin_pass()
        stacked = tr._stack_batches(batches)
        state = (tr.table.slab, tr.params, tr.opt_state,
                 jax.random.PRNGKey(0))
        dt = timed_scan_chain(tr.fns.scan_steps, state, stacked, REPS)
        ms = dt / CHUNK * 1e3
        print(json.dumps({"variant": name, "ms_per_step": round(ms, 3),
                          "examples_per_sec": round(BATCH / (dt / CHUNK),
                                                    1)}), flush=True)
    finally:
        for mod, attr, orig in saved:
            setattr(mod, attr, orig)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    import paddlebox_tpu.embedding.optimizers as opt_mod
    import paddlebox_tpu.train.trainer as tr_mod
    from paddlebox_tpu.embedding.optimizers import (_dispatch_apply_push,
                                                    rebuild_uids)

    run_variant("full", [])

    # threefry lazy-init randoms -> zeros (keeps prng dataflow dep)
    def no_fresh(prng, row_ids, shape, dtype, maxval, stream=0):
        return jnp.zeros(shape, dtype) + jax.random.key_data(
            prng).astype(dtype).ravel()[:1] * 0
    run_variant("no_fresh_prng",
                [(opt_mod, "_fresh_uniform", no_fresh)])

    orig_push = opt_mod.push_sparse_hostdedup

    def push_noscatter(slab, uids, perm, inv_sorted, grads, prng, layout,
                       conf):
        sorted_grads = jnp.take(grads, perm, axis=0, unique_indices=True)
        merged = jax.ops.segment_sum(sorted_grads, inv_sorted,
                                     num_segments=uids.shape[0],
                                     indices_are_sorted=True)
        rows = jnp.take(slab, uids, axis=0, mode="clip")
        new_rows = _dispatch_apply_push(rows, merged, prng, layout, conf,
                                        row_ids=uids)
        return jax.lax.dynamic_update_slice(slab, new_rows[:8], (0, 0))
    run_variant("no_slab_scatter",
                [(tr_mod, "push_sparse_hostdedup", push_noscatter)])

    def push_norowgather(slab, uids, perm, inv_sorted, grads, prng, layout,
                         conf):
        sorted_grads = jnp.take(grads, perm, axis=0, unique_indices=True)
        merged = jax.ops.segment_sum(sorted_grads, inv_sorted,
                                     num_segments=uids.shape[0],
                                     indices_are_sorted=True)
        rows = (jnp.zeros((uids.shape[0], slab.shape[1]), slab.dtype)
                + uids[:, None].astype(slab.dtype) * 0 + 0.5)
        new_rows = _dispatch_apply_push(rows, merged, prng, layout, conf,
                                        row_ids=uids)
        return slab.at[uids].set(new_rows, mode="drop", unique_indices=True)
    run_variant("no_slab_row_gather",
                [(tr_mod, "push_sparse_hostdedup", push_norowgather)])

    def push_nosegsum(slab, uids, perm, inv_sorted, grads, prng, layout,
                      conf):
        merged = (jnp.take(grads, perm, axis=0, unique_indices=True)
                  + inv_sorted[:, None].astype(grads.dtype) * 0)
        rows = jnp.take(slab, uids, axis=0, mode="clip")
        new_rows = _dispatch_apply_push(rows, merged, prng, layout, conf,
                                        row_ids=uids)
        return slab.at[uids].set(new_rows, mode="drop", unique_indices=True)
    run_variant("no_segment_sum",
                [(tr_mod, "push_sparse_hostdedup", push_nosegsum)])

    def push_nopermgather(slab, uids, perm, inv_sorted, grads, prng, layout,
                          conf):
        merged = jax.ops.segment_sum(
            grads + perm[:, None].astype(grads.dtype) * 0, inv_sorted,
            num_segments=uids.shape[0], indices_are_sorted=True)
        rows = jnp.take(slab, uids, axis=0, mode="clip")
        new_rows = _dispatch_apply_push(rows, merged, prng, layout, conf,
                                        row_ids=uids)
        return slab.at[uids].set(new_rows, mode="drop", unique_indices=True)
    run_variant("no_perm_gather",
                [(tr_mod, "push_sparse_hostdedup", push_nopermgather)])

    def push_noapply(slab, uids, perm, inv_sorted, grads, prng, layout,
                     conf):
        sorted_grads = jnp.take(grads, perm, axis=0, unique_indices=True)
        merged = jax.ops.segment_sum(sorted_grads, inv_sorted,
                                     num_segments=uids.shape[0],
                                     indices_are_sorted=True)
        rows = jnp.take(slab, uids, axis=0, mode="clip")
        pad = slab.shape[1] - merged.shape[1]
        new_rows = rows * 0.999 + jnp.pad(merged, ((0, 0), (0, pad))) * 1e-6
        return slab.at[uids].set(new_rows, mode="drop", unique_indices=True)
    run_variant("no_apply_push",
                [(tr_mod, "push_sparse_hostdedup", push_noapply)])

    def cheap_rebuild(ids, perm, inv, pad_base):
        return (jnp.arange(ids.shape[0], dtype=jnp.int32)
                + ids[:1] * 0 + perm[:1] * 0 + inv[:1] * 0)
    run_variant("no_rebuild_uids",
                [(tr_mod, "rebuild_uids", cheap_rebuild)])


if __name__ == "__main__":
    main()
