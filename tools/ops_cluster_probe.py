"""Live-ops acceptance probe: a REAL 2-process cluster with HTTP
endpoints up, quality/drift planes streaming, and an injected slot drop.

The round-18 acceptance scenario end to end:

  * two localhost worker processes rendezvous through a TcpStore fleet,
    run window-paced report cadences with rank-0 aggregation + health,
    an active quality plane (synthetic calibrated preds) and a slot
    drift monitor observing synthetic 4-slot ColumnarBlocks;
  * every rank binds its ops endpoint at obs_http_port + rank — the
    parent scrapes ``/metrics`` on BOTH ranks (content-type + exposition
    sanity + the quality series present), ``/health`` on rank 0
    (cluster_health with per-rank scores), and measures scrape latency;
  * at window ``--drop-at`` rank 1's blocks LOSE slot 2 (the broken
    upstream feature pipeline): the probe asserts rank 0's health plane
    scores rank 1 below the healthy bar with the ``data_drift`` flag
    within 2 report windows of the injection, while rank 0 stays
    healthy.

Usage:  timeout 300 python -u tools/ops_cluster_probe.py
            [--world 2] [--windows 24] [--drop-at 8] [--port 19750]
Prints one JSON line with the measurements; exits 1 on failure.
"""

import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

WINDOW_SECS = 0.3


def _make_block(rng, n_recs: int, drop_slot=None):
    from paddlebox_tpu.data.columnar import ColumnarBlock
    keys, slots, recs = [], [], []
    for i in range(n_recs):
        for s in range(4):
            if s == drop_slot:
                continue
            k = rng.randint(1, 5000, size=2).astype(np.uint64)
            keys.extend(k.tolist())
            slots.extend([s, s])
            recs.extend([i, i])
    labels = (rng.rand(n_recs) < 0.2).astype(np.int32)
    return ColumnarBlock.from_key_rec(
        np.array(keys, np.uint64), np.array(slots, np.int32),
        np.array(recs, np.int64), labels)


def worker() -> None:
    """One rank: window-paced reports + quality/drift feeds + the ops
    endpoint (bound by make_step_reporter off obs_http_port)."""
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.fleet.fleet import Fleet
    from paddlebox_tpu.fleet.role_maker import RoleMaker
    import paddlebox_tpu.obs as obs
    from paddlebox_tpu.metrics import drift as drift_mod
    from paddlebox_tpu.metrics import quality as quality_mod
    from paddlebox_tpu.metrics.quality import attach_pass_extras

    windows = int(os.environ["OPS_WINDOWS"])
    drop_at = int(os.environ["OPS_DROP_AT"])
    flags.set_flag("obs_report_every", 1)
    flags.set_flag("obs_http_port", int(os.environ["OPS_HTTP_PORT"]))
    fl = Fleet().init(RoleMaker())
    rank, world = fl.worker_index(), fl.worker_num()
    aggregator = obs.make_cluster_aggregator(fleet=fl, rank=rank,
                                             world=world)
    reporter = obs.make_step_reporter(rank=rank, aggregator=aggregator)
    quality = quality_mod.TaggedQuality(table_size=4096)
    quality_mod.set_active(quality)
    monitor = drift_mod.set_active_new()
    rng = np.random.RandomState(7 + rank)

    unhealthy_window = -1
    unhealthy_entry = None
    for w in range(1, windows + 1):
        drop = 2 if (rank == 1 and w >= drop_at) else None
        monitor.observe_block(_make_block(rng, 400, drop_slot=drop))
        pred = rng.rand(2048)
        label = (rng.rand(2048) < pred).astype(np.int64)  # calibrated
        quality.add(pred, label)
        drift_mod.observe_preds(pred)
        reporter.note_examples(2048)
        extra = {"event": "pass_end"}
        attach_pass_extras(extra, quality, ship_state=True)
        reporter.maybe_report(w, force=True, extra=extra)
        if rank == 0:
            health = aggregator.last_cluster_health
            if (unhealthy_window < 0 and health
                    and 1 in health.get("unhealthy_ranks", ())):
                unhealthy_window = w
                unhealthy_entry = health["ranks"].get("1")
                print("UNHEALTHY %d %s" % (w, json.dumps(unhealthy_entry)),
                      flush=True)
        print("WINDOW %d" % w, flush=True)
        time.sleep(WINDOW_SECS)
    if rank == 0:
        print("RESULT " + json.dumps({
            "unhealthy_window": unhealthy_window,
            "unhealthy_entry": unhealthy_entry,
            "health": aggregator.last_cluster_health}), flush=True)
    reporter.close()
    fl.stop()


def _scrape(port: int, path: str, timeout: float = 3.0):
    t0 = time.perf_counter()
    with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=timeout) as r:
        body = r.read().decode("utf-8")
        return (time.perf_counter() - t0, r.status,
                r.headers.get("Content-Type", ""), body)


def run_probe(world: int, windows: int, drop_at: int, port: int) -> dict:
    from paddlebox_tpu.fleet.store import KVStoreServer
    server = KVStoreServer(host="127.0.0.1")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env["PYTHONPATH"] = repo + os.pathsep + env.get(
                "PYTHONPATH", "")
            env.update({
                "PBTPU_TRAINER_ID": str(rank),
                "PBTPU_TRAINERS_NUM": str(world),
                "PBTPU_STORE_ENDPOINT": "127.0.0.1:%d" % server.port,
                "OPS_WORKER": "1",
                "OPS_WINDOWS": str(windows),
                "OPS_DROP_AT": str(drop_at),
                "OPS_HTTP_PORT": str(port),
                "JAX_PLATFORMS": "cpu",
            })
            procs.append(subprocess.Popen(
                [sys.executable, "-u", os.path.abspath(__file__)],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        # wait until rank 0 is a few windows in, then scrape everything
        for line in procs[0].stdout:
            if line.startswith("WINDOW") and int(line.split()[1]) >= 3:
                break
        scrape_lat = []
        metrics_ok = {}
        for rank in range(world):
            lat, status, ctype, body = _scrape(port + rank, "/metrics")
            scrape_lat.append(lat)
            metrics_ok[rank] = (
                status == 200
                and ctype.startswith("text/plain; version=0.0.4")
                and "# TYPE pbtpu_" in body
                and "pbtpu_quality_auc" in body)
        # latency sample on rank 0 (the busiest endpoint)
        for _ in range(20):
            lat, _, _, _ = _scrape(port, "/metrics")
            scrape_lat.append(lat)
        _, _, _, health0 = _scrape(port, "/health")
        # drain rank 0 to completion for the drift measurement
        out_rest, err0 = procs[0].communicate(timeout=180)
        if procs[0].returncode != 0:
            raise RuntimeError("rank 0 failed:\n" + err0[-3000:])
        result = None
        for line in out_rest.splitlines():
            if line.startswith("RESULT "):
                result = json.loads(line[len("RESULT "):])
        if result is None:
            raise RuntimeError("rank 0 printed no RESULT:\n"
                               + out_rest[-2000:])
        procs[1].communicate(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()

    health0 = json.loads(health0)
    assert health0.get("type") == "cluster_health", health0
    assert set(health0.get("ranks", {})) == {str(r)
                                             for r in range(world)}, health0
    assert all(metrics_ok.values()), metrics_ok
    uw = int(result["unhealthy_window"])
    assert uw > 0, "victim never scored unhealthy: %r" % (result,)
    windows_to_unhealthy = uw - drop_at
    assert windows_to_unhealthy <= 2, \
        "unhealthy after %d windows (bound 2)" % windows_to_unhealthy
    victim = result.get("unhealthy_entry") or {}
    assert "data_drift" in (victim.get("flags") or ()), victim
    assert not victim.get("healthy", True), victim
    rank0 = (result["health"] or {}).get("ranks", {}).get("0") or {}
    assert rank0.get("healthy", False), rank0
    lat_us = np.sort(np.array(scrape_lat) * 1e6)
    return {"probe": "ops_cluster", "world": world,
            "windows": windows, "drop_at": drop_at,
            "metrics_ok": {str(k): v for k, v in metrics_ok.items()},
            "windows_to_unhealthy": windows_to_unhealthy,
            "victim_entry": victim,
            "scrape_p50_us": round(float(lat_us[lat_us.size // 2]), 1),
            "scrape_max_us": round(float(lat_us[-1]), 1),
            "all_ok": True}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--windows", type=int, default=24)
    ap.add_argument("--drop-at", type=int, default=8)
    ap.add_argument("--port", type=int, default=19750)
    args = ap.parse_args()
    try:
        out = run_probe(args.world, args.windows, args.drop_at, args.port)
    except Exception as e:  # noqa: BLE001 — one honest failure line
        print(json.dumps({"probe": "ops_cluster",
                          "error": repr(e)[:600]}), flush=True)
        sys.exit(1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if os.environ.get("OPS_WORKER"):
        worker()
    else:
        main()
