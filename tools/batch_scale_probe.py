"""Throughput vs batch size at a FIXED pass (1M-row slab, same keyspace).

The rebuild slab write costs ~slab bytes regardless of touched rows
(BASELINE.md axon characterization), and per-op dispatch floors charge
per batch — so examples/sec should rise steeply with batch size until
streaming costs take over. Measures the REAL trainer step at batch
1024..8192, scatter vs rebuild, one chunk of batches covering the same
~1M-key draw budget per dispatch.

Usage: timeout 1800 python -u tools/batch_scale_probe.py [platform]
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import numpy as np

from paddlebox_tpu.config import flags
from tools.bench_util import (make_bench_trainer, make_ctr_batches,
                              timed_scan_chain)

NUM_SLOTS, MAX_LEN = 32, 4
PASS_CAP = 1 << 20
TOTAL_EXAMPLES = 8192          # one dispatch covers this many examples
REPS = 6


def run(batch, mode):
    flags.set_flag("push_write", mode)
    try:
        n_batches = max(1, TOTAL_EXAMPLES // batch)
        tr, feed = make_bench_trainer(PASS_CAP, batch=batch,
                                      num_slots=NUM_SLOTS, max_len=MAX_LEN)
        batches = make_ctr_batches(feed, n_batches, NUM_SLOTS, MAX_LEN,
                                   seed=0)
        tr.table.begin_feed_pass()
        for b in batches:
            tr.table.add_keys(b.keys[b.valid])
        tr.table.end_feed_pass()
        tr.table.begin_pass()
        stacked = tr._stack_batches(batches)
        state = (tr.table.slab, tr.params, tr.opt_state,
                 jax.random.PRNGKey(0))
        dt = timed_scan_chain(tr.fns.scan_steps, state, stacked, REPS)
        ms_batch = dt / n_batches * 1e3
        eps = batch * n_batches / dt
        print(json.dumps({"batch": batch, "push_write": mode,
                          "ms_per_batch": round(ms_batch, 3),
                          "examples_per_sec": round(eps, 1)}), flush=True)
        # no end_pass: the slab was donated into the timed chain (the live
        # copy is inside timed_scan_chain's final state) — just drop it
    finally:
        flags.set_flag("push_write", "auto")


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    for batch in (1024, 2048, 4096, 8192):
        for mode in ("rebuild", "scatter"):
            run(batch, mode)


if __name__ == "__main__":
    main()
