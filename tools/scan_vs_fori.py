"""Does lax.scan double-buffer big mutated carries where fori_loop
aliases them in place?

Round-5 evidence so far: a DUS write in a fori chain measures ~free
(write_probe), but the unified-buffer log step under lax.scan still
costs ~ buffer bytes per step. If scan copies mutated carries and fori
does not, the megastep loop should be fori with manual ys.

Body per iteration (the log-mode write pattern at bench shapes):
  rows = gather(buf, src)         [K rows]
  buf  = DUS(buf, rows*0.999, (cap+cur, 0))
  acc += rows[0, 0]               (chain + sync point)

Donated jit, state threaded across reps, ONE np.asarray sync of the
small dependent output per rep (same pattern as timed_scan_chain).

Usage: timeout 1200 python -u tools/scan_vs_fori.py [platform] [rows...]
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

W = 17
K = 131072
ITERS = 8
REPS = 4
L = 16 * K


def timed(name, fn, state, extra=None):
    try:
        out = fn(*state)
        np.asarray(out[-1])           # sync on the small acc only
        st = out
        t0 = time.perf_counter()
        for _ in range(REPS):
            st = fn(*st[:-1], st[-1])
            np.asarray(st[-1])
        ms = (time.perf_counter() - t0) / REPS / ITERS * 1e3
    except Exception as e:
        print(json.dumps({"op": name, "error": str(e)[:200]}), flush=True)
        return
    rec = {"op": name, "ms_per_iter": round(ms, 4)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def probe(cap, rng):
    tag = {"cap": cap, "buf_rows": cap + L}
    buf = jnp.asarray(rng.rand(cap + L, W).astype(np.float32))
    src = jnp.asarray(rng.randint(0, cap, K).astype(np.int32))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def scan_step(buf, src, acc):
        def body(c, _):
            b, cur, a = c
            rows = jnp.take(b, src + cur * 0, axis=0)
            b = lax.dynamic_update_slice(
                b, rows * 0.999, (jnp.int32(cap) + cur, 0))
            return (b, (cur + K) % (L - K), a + rows[0, 0]), 0.0
        (b, cur, a), _ = lax.scan(
            body, (buf, jnp.int32(0), acc),
            jnp.arange(ITERS, dtype=jnp.int32))
        return b, src, a

    timed("scan_gather_dus", scan_step, (buf + 0.0, src,
                                         jnp.zeros(())), tag)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def fori_step(buf, src, acc):
        def body(i, c):
            b, cur, a = c
            rows = jnp.take(b, src + cur * 0, axis=0)
            b = lax.dynamic_update_slice(
                b, rows * 0.999, (jnp.int32(cap) + cur, 0))
            return (b, (cur + K) % (L - K), a + rows[0, 0])
        b, cur, a = lax.fori_loop(0, ITERS, body,
                                  (buf, jnp.int32(0), acc))
        return b, src, a

    timed("fori_gather_dus", fori_step, (buf + 0.0, src,
                                         jnp.zeros(())), tag)

    # fori with manual small-ys accumulation (what a megastep needs)
    @functools.partial(jax.jit, donate_argnums=(0,))
    def fori_ys(buf, src, acc):
        losses = jnp.zeros((ITERS,), jnp.float32)

        def body(i, c):
            b, cur, ls, a = c
            rows = jnp.take(b, src + cur * 0, axis=0)
            b = lax.dynamic_update_slice(
                b, rows * 0.999, (jnp.int32(cap) + cur, 0))
            ls = lax.dynamic_update_slice(ls, rows[:1, 0], (i,))
            return (b, (cur + K) % (L - K), ls, a + rows[0, 0])
        b, cur, ls, a = lax.fori_loop(0, ITERS, body,
                                      (buf, jnp.int32(0), losses, acc))
        return b, src, a + ls.sum()

    timed("fori_gather_dus_ys", fori_ys, (buf + 0.0, src,
                                          jnp.zeros(())), tag)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform,
                      "K": K, "log_rows": L, "iters": ITERS}), flush=True)
    rng = np.random.RandomState(0)
    caps = [int(a) for a in sys.argv[2:]] or [1 << 20, 1 << 22]
    for cap in caps:
        probe(cap, rng)


if __name__ == "__main__":
    main()
