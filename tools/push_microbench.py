"""Micro-bench the push sub-ops on the live chip (axon) or CPU.

The TPU probe battery attributes ~79% of the fused step to the push
(tools/tpu_probe.py, BASELINE.md round-4 TPU rows). This decomposes the
push into its five sub-ops — occurrence gather, segment_sum merge, slab
row gather, in-table optimizer elementwise, slab row scatter — and times
each in a dependence-chained fori_loop (axon's block_until_ready returns
early, so every timed region ends in np.asarray of data that depends on
all iterations).

Usage: timeout 900 python -u tools/push_microbench.py [platform]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

CAP = 1 << 20          # slab rows (bench pass_capacity)
W = 17                 # slab value width (bench layout)
K = 131072             # keys/batch at bench shapes (1024 x 32 x 4)
PW = 12                # push row width (4 + D=8)
ITERS = 32
REPS = 5


def timed(name, fn, *args):
    out = fn(*args)                      # compile
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    ms = (time.perf_counter() - t0) / REPS / ITERS * 1e3
    print(json.dumps({"op": name, "ms_per_call": round(ms, 4)}), flush=True)
    return ms


def chain(body):
    """Wrap op so iteration i+1 depends on iteration i's output."""
    def run(carry, *args):
        def step(_, c):
            return body(c, *args)
        return lax.fori_loop(0, ITERS, step, carry)
    return jax.jit(run)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)
    slab = jnp.asarray(rng.rand(CAP, W).astype(np.float32))
    # host-dedup products: sorted unique ids, padded tail out-of-range
    n_uniq = int(K * 0.85)
    uids_np = np.sort(rng.choice(CAP - 1, n_uniq, replace=False)).astype(
        np.int32)
    uids_np = np.concatenate(
        [uids_np, np.arange(K - n_uniq, dtype=np.int32) + CAP])
    uids = jnp.asarray(uids_np)
    perm = jnp.asarray(rng.permutation(K).astype(np.int32))
    inv_sorted = jnp.asarray(
        np.sort(rng.randint(0, n_uniq, K)).astype(np.int32))
    grads = jnp.asarray(rng.rand(K, PW).astype(np.float32))
    rows = jnp.take(slab, uids, axis=0, mode="clip")

    # 1. occurrence gather [K, PW] by perm
    timed("grad_gather_perm",
          chain(lambda g, p: jnp.take(g, p, axis=0,
                                      unique_indices=True) + 1.0),
          grads, perm)

    # 2. segment-sum merge (sorted segments)
    def seg(g, iv):
        return jax.ops.segment_sum(g, iv, num_segments=K,
                                   indices_are_sorted=True)[:K] + 1.0
    timed("segment_sum_sorted", chain(seg), grads, inv_sorted)

    # 3. slab row gather, unsorted-declared vs sorted-declared
    def gath(c, s, u):
        r = jnp.take(s, u, axis=0, mode="clip")
        return c + r[:1, :1]
    timed("slab_gather", chain(gath), jnp.zeros((1, 1)), slab, uids)

    def gath_sorted(c, s, u):
        r = jnp.take(s, u, axis=0, mode="clip", indices_are_sorted=True)
        return c + r[:1, :1]
    timed("slab_gather_sorted", chain(gath_sorted), jnp.zeros((1, 1)),
          slab, uids)

    # 4. elementwise optimizer proxy (rows -> rows, no gather/scatter)
    timed("elementwise_rows",
          chain(lambda r: r * 0.999 + 0.001), rows)

    # 5. slab row scatter variants
    def scat(s, u, r):
        return s.at[u].set(r, mode="drop", unique_indices=True)
    timed("slab_scatter_unique", chain(scat), slab, uids, rows)

    def scat_sorted(s, u, r):
        return s.at[u].set(r, mode="drop", unique_indices=True,
                           indices_are_sorted=True)
    timed("slab_scatter_unique_sorted", chain(scat_sorted), slab, uids, rows)

    def scat_add(s, u, r):
        return s.at[u].add(r, mode="drop", unique_indices=True,
                           indices_are_sorted=True)
    timed("slab_scatter_add_sorted", chain(scat_add), slab, uids, rows)

    # 6. the full hostdedup push as composed in the trainer
    from paddlebox_tpu.config.configs import SparseOptimizerConfig
    from paddlebox_tpu.embedding.layout import ValueLayout
    from paddlebox_tpu.embedding.optimizers import push_sparse_hostdedup
    layout = ValueLayout.build(embedx_dim=8, optimizer="adagrad")
    conf = SparseOptimizerConfig()
    key = jax.random.PRNGKey(0)

    def full(s, u, p, iv, g, k):
        return push_sparse_hostdedup(s, u, p, iv, g, k, layout, conf)
    timed("full_push_hostdedup", chain(full), slab, uids, perm, inv_sorted,
          grads, key)


if __name__ == "__main__":
    main()
