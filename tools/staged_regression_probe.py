"""Staged-path CPU regression probe (round-5 hygiene item).

CPU ex/s rows are load-noise (±12% quiet, 4× under load — BASELINE.md),
so between TPU windows nothing guarded the data/staging path. This
checks the HOST stages in keys(or lines)/s against floor thresholds set
at ~40% of the recorded quiet-box rates — low enough to ride out
container noise, high enough to catch an algorithmic regression (the
r1 python-loop router was 10-25× under these rates).

Round-10 load guard (the PR-4 flake: "rt_lookup floor dips under
concurrent load — rerun alone"): every floor section runs SERIALLY in
this one process and, when a rate lands under its floor, the section is
re-measured alone up to 2 times after a settle pause before it may
fail — a transient co-tenant burst can no longer false-fail the probe,
while a real algorithmic regression (persistently under floor) still
exits 1. A floor still missed after retries consults a CALIBRATION
workload (np.sort, ~100M keys/s idle): if calibration is suppressed the
box provably isn't delivering its quiet rate (loadavg reads 0.0 in this
container even under full load) and the miss records as INCONCLUSIVE
instead of failing. Each JSON line records load1, retries and (on a
miss) calib_vs_quiet so a floor recorded under load is visibly
annotated.
``--stage NAME`` runs one section in full isolation (the rerun-alone
workflow, now built in).

Prints one JSON line per stage with ok=true/false; exits 1 if any fails.
Usage: timeout 900 python -u tools/staged_regression_probe.py [--stage N]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# (recorded quiet-box rate AT THIS PROBE'S OWN WORKLOAD — round-5
# first run, 2026-07-31 — , floor = ~40% of it). The r2-r4 BASELINE.md
# rates used different shapes (32 slots, bigger vocab), so this probe
# records its own reference once and guards against regression from it.
FLOORS = {
    "rt_lookup_keys_per_sec": (51.8e6, 20e6),
    "rt_dedup_keys_per_sec": (47.2e6, 19e6),
    "uid_sort_keys_per_sec": (116e6, 40e6),
    "bucketize_keys_per_sec": (21.1e6, 8e6),
    # round-13: the policy-parameterized router (rt_bucketize_sharded
    # under a non-key-mod ShardingPolicy: vectorized numpy shard_of +
    # the native dedup/bucket loop) at the bucketize section's exact
    # shape — measured ~3% under the key-mod tier isolated (recorded
    # quiet-derived on 2026-08-04: key-mod's 21.1M x 0.95; the same-day
    # loaded box measured 8.4M vs key-mod's concurrent 10.2M); floor =
    # ~35% so this section's wider numpy-premix noise rides out
    "policy_route_keys_per_sec": (20.1e6, 7e6),
    "parse_lines_per_sec": (722e3, 290e3),
    "pack_instances_per_sec": (722e3, 290e3),
    # round-17: the zero-object shuffled ingest path's two new hot
    # stages — the native columnar pass load in keys/s (read+merge at
    # the probe's 16-slot shape) and the block shuffle codec+routing
    # alone (hash + split + serialize/deserialize round trip, world 2).
    # Recorded under the load guard on 2026-08-04 (load1 ~0.6; a fully
    # co-tenant-loaded same-day run measured 11.3M/0.68M — the floors
    # ride under both); floors = ~40% of recorded
    "ingest_parse_keys_per_sec": (27.2e6, 10e6),
    "ingest_shuffle_records_per_sec": (1.53e6, 600e3),
    # round-8: the uid-lean wire END TO END on CPU (host stage + H2D +
    # jitted scan + D2H, small DeepFM shape below) — guards the whole
    # staged path so a wire regression fails loud between tunnel windows.
    # Recorded on a LOADED round-8 container (sibling rows at ~60% of
    # their quiet-box rates the same run); floor = ~40% of it
    "e2e_lean_examples_per_sec": (6.8e3, 2.7e3),
    # round-9: the p2p host-plane bucket a2a, two in-process mesh
    # endpoints over loopback (keys = one rank's n_local*P*KB per step);
    # the multi-process ladder in tools/hostplane_probe.py recorded
    # store=229.6ms vs p2p=36.4ms at the same shape this round
    "p2p_exchange_keys_per_sec": (30.1e6, 12e6),
    # round-11: the uid-wire push kernel (merge + in-table optimize +
    # slab write) at both write strategies, donated 1M-row slab, dup~8
    # batch — guards the blocked-scatter path between tunnel windows.
    # Recorded under the round-10 load guard on 2026-08-03 (CPU tier;
    # scatter leads blocked HERE — the blocked win is a TPU-regime
    # claim, BASELINE.md round 11); floors = ~40% of recorded
    "push_scatter_keys_per_sec": (983e3, 390e3),
    "push_blocked_keys_per_sec": (845e3, 340e3),
    # round-12: the serving plane's in-process lookup path (mmap view
    # stack + native key index, uniform mix incl. 10% misses over a 2M
    # base at batch 8192 — cache off: the algorithmic floor is the
    # store itself; the RPC tiers live in tools/serving_load_probe.py).
    # Recorded under the load guard on 2026-08-03; floor = ~40%
    "serving_lookup_keys_per_sec": (5.0e6, 2e6),
    # round-18: the tagged quality plane's batch add (bucket np.add.at
    # + the 5-scalar accumulator bundle over a 256k pred/label window
    # split across 4 tags — the per-step metric cost the trainers pay
    # with quality_metrics on; ~0.16 ms at batch 2048). Recorded under
    # the load guard on 2026-08-04 (load1 0.02, calib 1.1x quiet);
    # floor = ~40% of recorded
    "quality_add_keys_per_sec": (13.4e6, 5e6),
    # round-15: the columnar checkpoint plane at the store level, BOTH
    # directions (save = snapshot + fsync'd striped writer pool, load =
    # reader-pool mmap ingest + store install), 512k rows x width 17 on
    # the native store. Recorded under the load guard on 2026-08-04 (a
    # 1-core container: the pools overlap I/O waits, not memcpys —
    # BASELINE.md round 15 has the layer-by-layer attribution); floors
    # = ~40% of recorded
    "ckpt_save_keys_per_sec": (4.6e6, 1.8e6),
    "ckpt_load_keys_per_sec": (4.1e6, 1.6e6),
    # round-16: the SSD spill tier at the ckpt section's shape (256k
    # rows x width 17, fully spilled): fault = the lookup-path PEEK
    # (by-file mmap batch read, no residency change), promote = the
    # BeginFeedPass/LoadSSD2Mem fault-in leg alone (spill off the
    # clock). Recorded under the load guard on 2026-08-06; floors =
    # ~40% of recorded
    "ssd_fault_keys_per_sec": (1.0e6, 400e3),
    "ssd_promote_keys_per_sec": (1.1e6, 440e3),
    # round-21: the multi-box fleet pull END TO END over loopback RPC
    # (key-mod partition + per-shard coalescer flight + 2 in-process
    # boxes with shard-filtered stacks + scatter-back) at batch 8192,
    # 10% misses over a 1M base. Recorded under the load guard on
    # 2026-08-07 (load1 0.1); floor = ~40% of recorded
    "fleet_pull_keys_per_sec": (1.13e6, 450e3),
    # round-19 streaming plane (landed after 21): the micro-pass
    # cadence end to end — watcher discovery + admission preview +
    # preload-overlapped training + per-boundary journal publish over
    # pre-dropped files (DeepFM 16-slot shape, 2 windows x 3000
    # instances). Recorded quiet on 2026-08-07 (load1 0.34); floor =
    # ~40% of recorded
    "streaming_examples_per_sec": (1.05e4, 4.2e3),
}

# CEILINGS: lower-is-better stages (latencies). Same load-guard
# machinery as FLOORS — retries keep the BEST (lowest) measure, a
# still-missed bound consults calibration before failing.
CEILINGS = {
    # round-12: in-process serving lookup p99 at the FLOORS shape —
    # recorded µs, ceiling = ~2.5x of it (latency noise on this 1-core
    # container is wider than rate noise)
    "serving_lookup_p99_us": (4.6e3, 12e3),
    # round-18: one /metrics scrape of the live ops endpoint (loopback
    # HTTP + snapshot_all + Prometheus render with a populated registry
    # + quality plane), p99 of 50 scrapes. Recorded under the load
    # guard on 2026-08-04 (load1 0.02, calib 1.1x quiet); ceiling =
    # ~3.5x (stdlib http.server latency noise under co-tenant load is
    # wide)
    "exporter_scrape_p99_us": (5.8e3, 20e3),
    # round-21: the fleet pull p99 at the fleet FLOORS shape (batch
    # 8192 across 2 loopback boxes, coalescer + RPC + mmap lookup on
    # the clock). Recorded under the load guard on 2026-08-07;
    # ceiling = ~2.5x (two RPC hops of stdlib-socket latency noise)
    "fleet_pull_p99_us": (9.5e3, 24e3),
    # round-19: boxlint wall time, full tree (166 files, all 10 passes,
    # cache DISABLED — the honest cold cost the tier-1 gate pays) and
    # the --changed edit-loop mode. Recorded 2026-08-04 quiet: full
    # ~6.0s; changed ~6.0s WORST CASE (a dirty mid-PR tree: the
    # cross-file passes — flags, collectives vocab, the BX6xx/7xx/8xx
    # call graph — must read the full tree regardless, so --changed
    # only sheds the per-file passes; on a clean tree it drops to the
    # ~5s cross-pass floor). The content-hash cache is the real saver:
    # an unchanged re-run replays in ~0.1s, exact. Ceilings leave
    # growth room but pin the invariant that the LINT can never eat
    # the 870s tier-1 budget (even at 60s it is <7% of it).
    "boxlint_full_tree_secs": (6.0, 60.0),
    "boxlint_changed_secs": (6.0, 60.0),
    # round-20: staged H2D bytes per step at the e2e-lean bench shape
    # (batch 256 x 16 slots x max_len 4, uid wire) — DETERMINISTIC
    # (bytes, not time; the obs/device.py transfer ledger counts them),
    # so the ceiling is tight: ~1.5x recorded catches any fat field
    # sneaking into the staged batch (a resurrected full-wire perm/inv
    # pair alone would roughly double it). Recorded quiet 2026-08-04
    # (394,496 B/step: ids+segments+labels+valid+uids at the uid-lean
    # wire); ceiling = ~1.5x
    "device_h2d_bytes_per_step": (394.5e3, 600e3),
    # round-19 streaming plane: drop-to-journal-poll freshness — the
    # time from an atomic file drop to a serving JournalDeltaSource
    # poll returning the window's trained rows (one 3000-instance
    # micro-pass of train time on the clock). Recorded quiet on
    # 2026-08-07 (load1 0.34: 72ms); ceiling leaves room for co-tenant
    # load — the same stage measured <500ms at load1 1.6
    "streaming_freshness_ms": (72.0, 700.0),
    # round-20 watermark plane: drop-to-SERVED freshness — seconds from
    # an atomic file drop until a live ServingServer's pull response
    # carries a watermark past the drop instant (train + boundary
    # journal publish + 50ms tail poll + overlay swap + stamped RPC on
    # the clock; one 3000-instance micro-pass of train time dominates).
    # Recorded quiet on 2026-08-07 (load1 0.45: 1.0s); ceiling leaves
    # the same ~10x co-tenant headroom ratio as streaming_freshness_ms
    "freshness_e2e_secs": (1.0, 10.0),
}

RETRIES = 2          # extra isolated re-measures before a floor may fail
SETTLE_SECS = 2.0    # pause before a retry (let a co-tenant burst pass)

# Calibration workload: np.sort of a fixed 1M-int64 array, measured
# ~100M keys/s on this container truly idle (2026-08-03). os.getloadavg
# reads 0.0 inside this container even under full co-tenant load, so
# the CALIBRATION RATE is the only trustworthy load signal: when a
# floor stays missed after retries but the calibration itself is
# suppressed below CALIB_SUPPRESSED of quiet, the box provably isn't
# delivering its normal rate and the miss is recorded as inconclusive
# (ok, with a loud note) instead of failing — sustained co-tenant load
# (e.g. a tier-1 run in another shell) can outlast any retry budget.
CALIB_RECORDED = 100e6
CALIB_SUPPRESSED = 0.6

#: stages whose measure is DETERMINISTIC (bytes, not time): container
#: load can never be the cause of a miss, so the calibration escape
#: must not excuse one — a blown byte budget fails even on a loaded box
DETERMINISTIC_STAGES = {"device_h2d_bytes_per_step"}

failures = []


def _load1() -> float:
    try:
        return round(os.getloadavg()[0], 2)
    except OSError:
        return -1.0


def _calib_rate() -> float:
    a = np.random.RandomState(123).randint(
        0, 1 << 40, 1 << 20).astype(np.int64)
    return timed_rate(lambda: np.sort(a), a.size, secs=0.5)


def report(stage, rate, remeasure=None):
    """One floor/ceiling check. `remeasure()` re-runs JUST this section
    (nothing else of the probe executing) — the load guard: a
    bound-missing measure is retried alone up to RETRIES times and the
    BEST measure is judged (highest rate for FLOORS, lowest latency for
    CEILINGS); a still-missed bound then consults the calibration
    workload, and only fails when the box is provably delivering its
    quiet rate. The emitted line carries load1/calib/retries as the
    load-guard note for any bound recorded under load."""
    ceiling = stage in CEILINGS
    rec, bound = (CEILINGS if ceiling else FLOORS)[stage]
    better = min if ceiling else max
    missed = (lambda v: v > bound) if ceiling else (lambda v: v < bound)
    retries = 0
    best = rate
    while missed(best) and remeasure is not None and retries < RETRIES:
        time.sleep(SETTLE_SECS)
        retries += 1
        best = better(best, remeasure())
    ok = not missed(best)
    line = {"stage": stage, "rate": round(best, 0), "recorded": rec,
            ("ceiling" if ceiling else "floor"): bound, "ok": ok,
            "load1": _load1(), "retries": retries}
    if not ok:
        if stage in DETERMINISTIC_STAGES:
            # bytes are load-independent — no calibration escape
            failures.append(stage)
        else:
            calib = _calib_rate()
            line["calib_vs_quiet"] = round(calib / CALIB_RECORDED, 3)
            if calib < CALIB_SUPPRESSED * CALIB_RECORDED:
                # the box itself is slow right now: inconclusive, not failed
                line["ok"] = ok = True
                line["note"] = (
                    "%s missed but calibration at %.0f%% of quiet rate — "
                    "load-suppressed, INCONCLUSIVE; rerun alone"
                    % ("ceiling" if ceiling else "floor",
                       100.0 * calib / CALIB_RECORDED))
            else:
                failures.append(stage)
    elif retries:
        line["note"] = ("below floor on first measure, passed on "
                        "isolated rerun — transient container load")
    print(json.dumps(line), flush=True)


def timed_rate(fn, n_items, secs=2.0):
    fn()                                   # warm
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < secs:
        fn()
        reps += 1
    return reps * n_items / (time.perf_counter() - t0)


# --------------------------------------------------------------- sections
# Each section measures + reports its stages and tears its state down
# before returning, so sections never overlap (floor sections run
# serially/isolated; --stage runs exactly one).

def section_native(rng, K):
    from paddlebox_tpu.native.build import (create_route_index,
                                            destroy_route_index, get_lib,
                                            route_lookup)
    if get_lib() is None:
        print(json.dumps({"error": "native lib unavailable"}), flush=True)
        sys.exit(1)
    pass_keys = np.unique(rng.randint(0, 1 << 40, 1 << 20).astype(np.uint64))
    idx = create_route_index([pass_keys])
    probe = rng.choice(pass_keys, K).astype(np.uint64)
    measure = lambda: timed_rate(  # noqa: E731
        lambda: route_lookup(idx, probe, None, 0), K)
    report("rt_lookup_keys_per_sec", measure(), remeasure=measure)
    destroy_route_index(idx)

    from paddlebox_tpu.embedding.pass_table import (dedup_ids,
                                                    dedup_uids_sorted)
    ids = rng.randint(0, 1 << 20, K).astype(np.int32)
    m_dedup = lambda: timed_rate(  # noqa: E731
        lambda: dedup_ids(ids, 1 << 20), K)
    report("rt_dedup_keys_per_sec", m_dedup(), remeasure=m_dedup)
    # the uid-wire host product (np.unique sort — the only staged dedup
    # work on the uid-lean path)
    m_sort = lambda: timed_rate(  # noqa: E731
        lambda: dedup_uids_sorted(ids, 1 << 20), K)
    report("uid_sort_keys_per_sec", m_sort(), remeasure=m_sort)


def section_bucketize(rng, K):
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.parallel.sharded_table import ShardedPassTable
    pass_keys = np.unique(rng.randint(0, 1 << 40, 1 << 20).astype(np.uint64))
    probe = rng.choice(pass_keys, K).astype(np.uint64)
    t = ShardedPassTable(
        TableConfig(embedx_dim=8, pass_capacity=1 << 21,
                    optimizer=SparseOptimizerConfig()),
        num_shards=8, bucket_cap=4 * K // 8)
    t.begin_feed_pass()
    t.add_keys(pass_keys)
    t.end_feed_pass()
    valid = np.ones(K, bool)
    measure = lambda: timed_rate(  # noqa: E731
        lambda: t.bucketize(probe, valid.copy()), K)
    report("bucketize_keys_per_sec", measure(), remeasure=measure)


def section_policy_route(rng, K):
    # --- policy-parameterized router (round 13) ----------------------
    # the same bucketize shape through a NON-key-mod policy, so the
    # rt_bucketize_sharded tier (pre-mixed numpy shard_of + native
    # dedup/bucket loop) is guarded separately from the legacy key-mod
    # fast path — a regression here would silently slow every
    # table-wise/2d-grid deployment's staging
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.parallel.sharded_table import ShardedPassTable
    from paddlebox_tpu.parallel.sharding import TableWisePolicy
    pass_keys = np.unique(rng.randint(0, 1 << 40, 1 << 20).astype(np.uint64))
    probe = rng.choice(pass_keys, K).astype(np.uint64)
    t = ShardedPassTable(
        TableConfig(embedx_dim=8, pass_capacity=1 << 21,
                    optimizer=SparseOptimizerConfig()),
        num_shards=8, bucket_cap=4 * K // 8,
        policy=TableWisePolicy(8, num_tables=64, table_shift=0))
    t.begin_feed_pass()
    t.add_keys(pass_keys)
    t.end_feed_pass()
    valid = np.ones(K, bool)
    measure = lambda: timed_rate(  # noqa: E731
        lambda: t.bucketize(probe, valid.copy()), K)
    report("policy_route_keys_per_sec", measure(), remeasure=measure)


def section_p2p(rng, K):
    # --- p2p host-plane exchange tier (round 9) ----------------------
    # two in-process mesh endpoints over loopback running the per-step
    # bucket a2a (exchange_incoming_p2p) in lockstep — guards the socket
    # mesh data plane between real multi-process runs (the full ladder
    # incl. the store tier lives in tools/hostplane_probe.py)
    from concurrent.futures import ThreadPoolExecutor

    from paddlebox_tpu.fleet.mesh_comm import MeshComm
    from paddlebox_tpu.parallel.sharded_table import exchange_incoming_p2p
    world, P_hp, KB_hp = 2, 8, 8192
    meshes = [MeshComm(r, world) for r in range(world)]
    eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
    pos = {0: [0, 1, 2, 3], 1: [4, 5, 6, 7]}
    for m in meshes:
        m.connect(eps)
        m.positions_of = dict(pos)
    bks = [rng.randint(0, (1 << 16) - 1, (4, P_hp, KB_hp)).astype(np.int32)
           for _ in range(world)]
    hp_pool = ThreadPoolExecutor(1)

    def one_exchange():
        f = hp_pool.submit(exchange_incoming_p2p, bks[1], pos[1], P_hp,
                           meshes[1])
        exchange_incoming_p2p(bks[0], pos[0], P_hp, meshes[0])
        f.result()

    measure = lambda: timed_rate(one_exchange, 4 * P_hp * KB_hp)  # noqa: E731
    report("p2p_exchange_keys_per_sec", measure(), remeasure=measure)
    for m in meshes:
        m.close()
    hp_pool.shutdown(wait=False)


def section_parse(rng, K):
    import tempfile

    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    out = tempfile.mkdtemp()
    files, feed = write_synthetic_ctr_files(
        out, num_files=2, lines_per_file=8000, num_slots=16,
        vocab_per_slot=5000, max_len=4, seed=1)
    feed = type(feed)(slots=feed.slots, batch_size=512)

    def load():
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        n = len(ds)
        ds.release_memory()
        return n

    n_lines = 16000

    def measure():
        load()                              # warm
        t0 = time.perf_counter()
        reps, n = 0, 0
        while time.perf_counter() - t0 < 4.0:
            n = load()
            reps += 1
        dt = time.perf_counter() - t0
        return reps * n_lines / dt, reps * n / dt

    parse_rate, pack_rate = measure()
    report("parse_lines_per_sec", parse_rate,
           remeasure=lambda: measure()[0])
    # load_into_memory covers parse+merge+batch build in this design
    report("pack_instances_per_sec", pack_rate,
           remeasure=lambda: measure()[1])


def section_ingest(rng, K):
    # --- ingest plane (round 17) -------------------------------------
    # the native columnar parse (read+merge, keys/s of the whole pass
    # load) and the block shuffle codec+routing ALONE (vectorized hash
    # over rec_offsets + fancy-index split + header/raw-column
    # serialize/deserialize at world 2, records/s) — guards the two new
    # hot stages of the zero-object shuffled ingest path. The record
    # codec it replaced measured ~25x slower at this shape (BASELINE.md
    # round 17) — an algorithmic regression back toward per-record work
    # lands far under these floors.
    import tempfile

    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    from paddlebox_tpu.data.block_shuffle import (block_shuffle_dests,
                                                  deserialize_block,
                                                  serialize_block,
                                                  split_block)
    out = tempfile.mkdtemp()
    files, feed = write_synthetic_ctr_files(
        out, num_files=2, lines_per_file=6000, num_slots=16,
        vocab_per_slot=5000, max_len=4, seed=2)
    feed = type(feed)(slots=feed.slots, batch_size=512)

    def load():
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        return ds

    ds = load()                              # warm + the codec's input
    if not ds._load_columnar:
        report("ingest_parse_keys_per_sec", 0.0)
        return
    n_keys, n_recs = ds.block.n_keys, len(ds)

    def m_parse():
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < 4.0:
            load()
            reps += 1
        return reps * n_keys / (time.perf_counter() - t0)

    report("ingest_parse_keys_per_sec", m_parse(), remeasure=m_parse)
    block = ds.block

    def codec_once():
        subs = split_block(block, block_shuffle_dests(block, 2), 2)
        n = 0
        for s in subs:
            if s is not None:
                n += deserialize_block(serialize_block(s)).n_recs
        assert n == n_recs

    def m_codec():
        codec_once()                         # warm
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < 3.0:
            codec_once()
            reps += 1
        return reps * n_recs / (time.perf_counter() - t0)

    report("ingest_shuffle_records_per_sec", m_codec(), remeasure=m_codec)


def section_e2e(rng, K):
    # --- uid-lean wire e2e tier (round 8) ----------------------------
    # host stage (lookup + uid sort) + H2D + jitted scan + loss D2H over
    # a small DeepFM shape — the whole staged path the uid wire carries
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.config import flags as _flags
    from paddlebox_tpu.config.configs import TrainerConfig
    from tools.bench_util import make_bench_trainer, make_ctr_batches
    _flags.set_flag("h2d_lean", True)
    try:
        tr, feed = make_bench_trainer(
            1 << 18, batch=256, num_slots=16, max_len=4, d=8,
            trainer_cfg=TrainerConfig(dense_lr=1e-3))
        chunk = 4
        batches = make_ctr_batches(feed, chunk, 16, 4, seed=0)
        tr.table.begin_feed_pass()
        for b in batches:
            tr.table.add_keys(b.keys[b.valid])
        tr.table.end_feed_pass()
        tr.table.begin_pass()
        state = [tr.table.slab, tr.params, tr.opt_state,
                 tr.table.next_prng()]

        def one_chunk():
            stacked = tr._stack_batches(batches)
            slab, params, opt, losses, _p, key = tr.fns.scan_steps(
                state[0], state[1], state[2], stacked, state[3])
            state[:] = slab, params, opt, key
            assert np.isfinite(np.asarray(losses)).all()

        measure = lambda: timed_rate(one_chunk, chunk * 256,  # noqa: E731
                                     secs=4.0)
        report("e2e_lean_examples_per_sec", measure(), remeasure=measure)
        tr.close()
    finally:
        _flags.set_flag("h2d_lean", False)


def section_push(rng, K):
    # --- device push-write kernels (round 11) ------------------------
    # the uid-wire push at both write strategies, donated slab threaded
    # through like the train step: keys/s of the merge+optimize+write
    # kernel alone. Guards the blocked-scatter path between tunnel
    # windows; recorded on THIS container's CPU tier (the TPU ladder
    # lives in BASELINE.md round 11).
    import functools

    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from paddlebox_tpu.config.configs import SparseOptimizerConfig
    from paddlebox_tpu.embedding.accessor import PushLayout, ValueLayout
    from paddlebox_tpu.embedding.optimizers import push_sparse_uidwire
    from paddlebox_tpu.embedding.pass_table import dedup_uids_sorted

    cap = 1 << 20
    layout = ValueLayout(8, "adagrad")
    conf = SparseOptimizerConfig(mf_create_thresholds=0.0,
                                 mf_initial_range=1e-3)
    push = PushLayout(8)
    ids = rng.randint(0, cap // 8, K).astype(np.int32)   # dup ~8: the
    uids = dedup_uids_sorted(ids, cap)                   # uid-wire shape
    grads = rng.rand(K, push.width).astype(np.float32)
    grads[:, push.SHOW] = 1.0
    prng = jax.random.PRNGKey(0)
    uids_j, ids_j, grads_j = (jnp.asarray(uids), jnp.asarray(ids),
                              jnp.asarray(grads))
    for write, stage in (("scatter", "push_scatter_keys_per_sec"),
                         ("blocked", "push_blocked_keys_per_sec")):
        step = jax.jit(functools.partial(push_sparse_uidwire,
                                         layout=layout, conf=conf,
                                         write=write),
                       donate_argnums=(0,))
        state = [jnp.zeros((cap, layout.width), jnp.float32)]

        def one():
            state[0] = jax.block_until_ready(
                step(state[0], uids_j, ids_j, grads_j, prng))

        measure = lambda: timed_rate(one, K, secs=3.0)  # noqa: E731
        report(stage, measure(), remeasure=measure)
        state[0] = None


def section_serving(rng, K):
    # --- serving lookup tier (round 12) ------------------------------
    # the in-process composed-view lookup (mmap stack + native key
    # index) at the serving batch shape, uniform mix + 10% misses,
    # cache OFF — guards the store/stack algorithmic path; the RPC and
    # cache tiers ride tools/serving_load_probe.py. Latency percentile
    # from the same run rides the CEILINGS check.
    import tempfile

    from paddlebox_tpu.serving.store import (MmapViewStack,
                                             write_xbox_columnar)
    n, dim, batch = 1 << 21, 9, 8192
    path = os.path.join(tempfile.mkdtemp(prefix="pbx_srvprobe_"),
                        "base.xcol")
    keys = np.arange(n, dtype=np.uint64) * 16 + np.uint64(3)
    rows = np.ones((n, dim), np.float32)
    write_xbox_columnar(path, keys, rows)
    stack = MmapViewStack.from_files([path])
    probe = (rng.randint(0, n, 8 * batch).astype(np.uint64)
             * np.uint64(16) + np.uint64(3))
    probe[::10] += np.uint64(1)             # 10% misses
    batches = probe.reshape(8, batch)
    state = {"i": 0, "lat": []}

    def one():
        t0 = time.perf_counter()
        stack.lookup(batches[state["i"] % 8])
        state["lat"].append(time.perf_counter() - t0)
        state["i"] += 1

    def measure():
        state["lat"] = []
        rate = timed_rate(one, batch)
        return rate

    def p99_of_last():
        lat = np.sort(np.array(state["lat"]) * 1e6)
        return float(lat[int(0.99 * (lat.size - 1))])

    rate = measure()
    p99 = p99_of_last()
    report("serving_lookup_keys_per_sec", rate, remeasure=measure)
    report("serving_lookup_p99_us", p99,
           remeasure=lambda: (measure(), p99_of_last())[1])
    stack.close()
    os.unlink(path)


def section_fleet(rng, K):
    # --- multi-box serving fleet (round 21) --------------------------
    # the CLIENT-routed pull path end to end over loopback RPC: a
    # 2-box in-process fleet (shard-filtered mmap stacks behind real
    # FramedServers) pulled through the FleetClient — partition by
    # key-mod, per-shard coalescer flight, both boxes answering in
    # parallel, scatter back to caller order. Guards the whole routing
    # + wire + lookup sandwich; the in-process lookup alone is the
    # serving section's floor, and the multi-PROCESS ladder lives in
    # tools/fleet_probe.py (BASELINE.md round 21).
    import tempfile

    from paddlebox_tpu.parallel.sharding import KeyModPolicy
    from paddlebox_tpu.serving.client import FleetClient
    from paddlebox_tpu.serving.refresh import ViewManager
    from paddlebox_tpu.serving.server import ServingServer
    from paddlebox_tpu.serving.store import (MmapViewStack, ShardSpec,
                                             write_xbox_columnar)
    n, dim, batch = 1 << 20, 9, 8192
    path = os.path.join(tempfile.mkdtemp(prefix="pbx_fleetprobe_"),
                        "base.xcol")
    keys = np.arange(n, dtype=np.uint64) * 16 + np.uint64(3)
    write_xbox_columnar(path, keys, np.ones((n, dim), np.float32))
    policy = KeyModPolicy(2)
    servers = [
        ServingServer(manager=ViewManager(MmapViewStack(
            [], shard_spec=ShardSpec(s, policy), extra_files=(path,))),
            watch=False)
        for s in range(2)]
    fc = FleetClient([[("127.0.0.1", s.port)] for s in servers],
                     policy=policy)
    probe = (rng.randint(0, n, 8 * batch).astype(np.uint64)
             * np.uint64(16) + np.uint64(3))
    probe[::10] += np.uint64(1)             # 10% misses
    batches = probe.reshape(8, batch)
    state = {"i": 0, "lat": []}

    def one():
        t0 = time.perf_counter()
        fc.pull(batches[state["i"] % 8])
        state["lat"].append(time.perf_counter() - t0)
        state["i"] += 1

    def measure():
        state["lat"] = []
        return timed_rate(one, batch)

    def p99_of_last():
        lat = np.sort(np.array(state["lat"]) * 1e6)
        return float(lat[int(0.99 * (lat.size - 1))])

    try:
        rate = measure()
        p99 = p99_of_last()
        report("fleet_pull_keys_per_sec", rate, remeasure=measure)
        report("fleet_pull_p99_us", p99,
               remeasure=lambda: (measure(), p99_of_last())[1])
    finally:
        fc.close()
        for s in servers:
            s.drain(timeout=2)
        os.unlink(path)


def section_ckpt(rng, K):
    # --- checkpoint plane (round 15) ---------------------------------
    # the columnar sparse batch tier END TO END at the store level:
    # save = state_items + striped writer pool (fsync'd parts +
    # manifest), load = manifest + reader-pool mmap ingest + store
    # install — guards both directions of the restore path between
    # rounds. 512k rows x width 17 (~36 MB of row bytes), native store
    # when the lib is present (same tier the trainer runs).
    import shutil
    import tempfile

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.embedding.pass_table import PassTable

    R = 1 << 19
    tcfg = TableConfig(embedx_dim=8, pass_capacity=1 << 10,
                       optimizer=SparseOptimizerConfig())
    t = PassTable(tcfg, seed=1)
    keys = rng.permutation(np.arange(1, R + 1, dtype=np.uint64))
    vals = rng.rand(R, t.layout.width).astype(np.float32)
    t.store.assign(keys, vals)
    root = tempfile.mkdtemp(prefix="pbx_ckptprobe_")
    path = os.path.join(root, "probe.xman")
    try:
        def save_rate():
            return timed_rate(lambda: t.save(path), R)

        def load_rate():
            return timed_rate(lambda: t.load(path), R)

        rate_s = save_rate()
        report("ckpt_save_keys_per_sec", rate_s, remeasure=save_rate)
        rate_l = load_rate()
        report("ckpt_load_keys_per_sec", rate_l, remeasure=load_rate)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def section_ssd(rng, K):
    # --- SSD spill tier (round 16) -----------------------------------
    # the host store's third memory tier at the probe's checkpoint
    # shape (256k rows x width 17): (a) promote — batched by-file
    # fault-in of a fully-spilled working set, the leg BeginFeedPass/
    # LoadSSD2Mem and the PromotePrefetcher pay per pass (spill is done
    # off the clock each cycle; only fault_in_keys is timed); (b) cold
    # fault — the lookup-path PEEK over sleeping rows (mmap block read
    # grouped by file, no residency change), the price of touching a
    # tier row without promoting it.
    import shutil
    import tempfile

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.embedding.pass_table import PassTable

    R = 1 << 18
    root = tempfile.mkdtemp(prefix="pbx_ssdprobe_")
    try:
        tcfg = TableConfig(embedx_dim=8, pass_capacity=1 << 10,
                           ssd_dir=root,
                           optimizer=SparseOptimizerConfig())
        t = PassTable(tcfg, seed=1)
        st = t.store
        keys = rng.permutation(np.arange(1, R + 1, dtype=np.uint64))
        vals = rng.rand(R, t.layout.width).astype(np.float32)
        st.assign(keys, vals)
        st.spill_exact(keys)

        def fault_rate():
            # peek: every call re-reads all R rows off the blocks
            return timed_rate(lambda: st.lookup(keys), R)

        def promote_rate():
            st.fault_in_keys(keys)               # warm
            total, reps = 0.0, 0
            while total < 2.0:
                st.spill_exact(keys)             # off the clock
                t0 = time.perf_counter()
                st.fault_in_keys(keys)
                total += time.perf_counter() - t0
                reps += 1
            return reps * R / total

        rate_f = fault_rate()
        report("ssd_fault_keys_per_sec", rate_f, remeasure=fault_rate)
        rate_p = promote_rate()
        report("ssd_promote_keys_per_sec", rate_p,
               remeasure=promote_rate)
    finally:
        shutil.rmtree(root, ignore_errors=True)


def section_quality(rng, K):
    # --- quality + ops endpoint (round 18) ---------------------------
    # (a) TaggedQuality.add at the trainers' feed shape: 256k preds/
    # labels per measure split across 4 tags — bucket np.add.at into
    # the per-tag [2, T] tables + scalar accumulators; (b) one
    # /metrics scrape of a live exporter over a populated registry
    # (the operator-facing read path), p99 of 50 scrapes rides the
    # CEILINGS check.
    import urllib.request

    from paddlebox_tpu.metrics.quality import TaggedQuality
    from paddlebox_tpu.obs.exporter import ObsExporter
    from paddlebox_tpu.utils.stats import (gauge_set, hist_observe,
                                           stat_add)

    n = 1 << 18
    pred = rng.rand(n)
    label = (rng.rand(n) < pred).astype(np.int64)
    tags = rng.randint(0, 4, n)
    q = TaggedQuality(table_size=65536)

    def add_once():
        q.add_tagged(pred, label, tags)

    rate = timed_rate(add_once, n)
    report("quality_add_keys_per_sec", rate,
           remeasure=lambda: timed_rate(add_once, n))

    # a representative registry: a few dozen counters/gauges + two
    # histograms + the quality plane above (exporter reads it via the
    # module registration)
    from paddlebox_tpu.metrics import quality as quality_mod
    quality_mod.set_active(q)
    for i in range(32):
        stat_add("probe_counter_%d" % i, i)
        gauge_set("probe_gauge_%d" % i, i * 0.5)
    for v in rng.randint(1, 1 << 20, 512).tolist():
        hist_observe("probe_hist_us", v)
        hist_observe("probe_hist2_us", v)
    exp = ObsExporter(port=0)           # ephemeral port, direct bind
    url = "http://127.0.0.1:%d/metrics" % exp.port
    state = {"lat": []}

    def scrape_once():
        t0 = time.perf_counter()
        with urllib.request.urlopen(url, timeout=5) as r:
            r.read()
        state["lat"].append(time.perf_counter() - t0)

    def p99():
        state["lat"] = []
        for _ in range(50):
            scrape_once()
        lat = np.sort(np.array(state["lat"]) * 1e6)
        return float(lat[int(0.99 * (lat.size - 1))])

    try:
        report("exporter_scrape_p99_us", p99(), remeasure=p99)
    finally:
        exp.close()
        quality_mod.set_active(None)


def section_boxlint(rng, K):
    # --- boxlint wall time (round 19) --------------------------------
    # The tier-1 gate runs the full 10-pass lint every suite; the three
    # interprocedural concurrency passes (BX6xx/7xx/8xx) added a
    # package-wide call-graph build, and the --changed/--cache satellite
    # exists precisely so lint cost can't creep into the 870s budget
    # unnoticed. CEILINGS entries pin both modes (cache disabled here —
    # cold cost is the honest bound; a cache hit is ~0.1s and exact).
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run_lint(extra):
        t0 = time.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "tools.boxlint", "-q", "--no-cache",
             *extra, "paddlebox_tpu/", "tools/"],
            cwd=root, capture_output=True, text=True, timeout=300)
        dt = time.perf_counter() - t0
        # rc 0 (clean) or 1 (dirty working tree mid-edit) are both
        # valid timings; rc 2 = checker crash, surface it
        assert r.returncode in (0, 1), r.stderr[-500:]
        return dt

    report("boxlint_full_tree_secs", run_lint([]),
           remeasure=lambda: run_lint([]))
    report("boxlint_changed_secs", run_lint(["--changed"]),
           remeasure=lambda: run_lint(["--changed"]))


def section_device(rng, K):
    # --- device plane gates (round 20) -------------------------------
    # The obs/device.py tier watching the XLA layer, gated at the bench
    # config's steady state: ZERO steady-state recompiles (the sentinel
    # that catches mis-staged shape churn), ZERO donation misses (the
    # regime-step slab-copy mechanism — ROADMAP item 1's hypothesis,
    # now a standing alarm), the compiled scan's temp allocation must
    # NOT contain a slab-sized copy (step_audit's historical check,
    # live), and the staged H2D bytes/step ride a ceiling so a wire
    # regression (a fat field sneaking into the staged batch) flags
    # like a rate regression.
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.config.configs import TrainerConfig
    from paddlebox_tpu.obs import device as _device
    from paddlebox_tpu.utils.stats import StatRegistry
    from tools.bench_util import make_bench_trainer, make_ctr_batches

    reg = StatRegistry.instance()
    for k in ("device_recompiles", "donation_miss",
              "device_transfer_bytes_h2d"):
        reg.reset(k)
    _device.monitor().reset()
    tr, feed = make_bench_trainer(
        1 << 18, batch=256, num_slots=16, max_len=4, d=8,
        trainer_cfg=TrainerConfig(dense_lr=1e-3))
    chunk = 4
    batches = make_ctr_batches(feed, chunk, 16, 4, seed=0)
    tr.table.begin_feed_pass()
    for b in batches:
        tr.table.add_keys(b.keys[b.valid])
    tr.table.end_feed_pass()
    tr.table.begin_pass()
    state = [tr.table.slab, tr.params, tr.opt_state, tr.table.next_prng()]
    reg.reset("device_transfer_bytes_h2d")  # staging only, not slab build
    steps = 0
    for _ in range(3):                      # 12 steps: steady state
        stacked = tr._stack_batches(batches)
        slab, params, opt, losses, _p, key = tr.fns.scan_steps(
            state[0], state[1], state[2], stacked, state[3])
        state[:] = slab, params, opt, key
        steps += chunk
    assert np.isfinite(np.asarray(losses)).all()

    for stage, val in (
            ("device_recompiles_steady", reg.get("device_recompiles")),
            ("device_donation_miss_steady", reg.get("donation_miss"))):
        ok = int(val) == 0
        print(json.dumps({"stage": stage, "value": int(val), "bound": 0,
                          "ok": ok, "load1": _load1()}), flush=True)
        if not ok:
            failures.append(stage)

    entry = _device.snapshot()["entries"].get("scan_steps") or {}
    ana = entry.get("analysis") or {}
    flag = ana.get("temp_includes_slab_copy")
    ok = flag is False                      # None = analysis unavailable
    print(json.dumps({"stage": "temp_includes_slab_copy", "value": flag,
                      "ok": ok, "temp_bytes": ana.get("temp_bytes"),
                      "alias_bytes": ana.get("alias_bytes"),
                      "load1": _load1()}), flush=True)
    if not ok:
        failures.append("temp_includes_slab_copy")

    report("device_h2d_bytes_per_step",
           reg.get("device_transfer_bytes_h2d") / max(steps, 1))
    tr.close()


def section_streaming(rng, K):
    # --- streaming micro-pass plane (round 19) -----------------------
    # The continuous-training cadence end to end: watcher discovery +
    # admission preview + preload-overlapped micro-pass training +
    # per-boundary journal publish, sustained ex/s over pre-dropped
    # files (FLOOR), and the drop-to-journal-poll freshness — the
    # seconds from an atomic file drop to a serving JournalDeltaSource
    # poll returning the trained rows (CEILING: lower is better, a rise
    # is a staleness regression).
    import shutil
    import tempfile
    import threading

    from paddlebox_tpu.config import flags
    from paddlebox_tpu.config.configs import (CheckpointConfig,
                                              SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data import (StreamingDataset,
                                    write_synthetic_ctr_files)
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.serving.refresh import JournalDeltaSource
    from paddlebox_tpu.train import CheckpointManager, StreamingRunner
    from paddlebox_tpu.train.trainer import BoxTrainer

    root = tempfile.mkdtemp()
    files, feed = write_synthetic_ctr_files(
        os.path.join(root, "staging"), num_files=4, lines_per_file=1500,
        num_slots=16, vocab_per_slot=5000, max_len=4, seed=3)
    feed = type(feed)(slots=feed.slots, batch_size=512)
    old_poll = flags.get_flag("streaming_poll_secs")
    flags.set_flag("streaming_poll_secs", 0.02)
    trainer = BoxTrainer(
        DeepFM(ModelSpec(num_slots=16, slot_dim=3 + 8), hidden=(256, 128)),
        TableConfig(embedx_dim=8, pass_capacity=1 << 18,
                    optimizer=SparseOptimizerConfig(
                        mf_create_thresholds=0.0, mf_initial_range=1e-3)),
        feed, TrainerConfig(dense_lr=1e-3), seed=0)
    cm = CheckpointManager(
        CheckpointConfig(batch_model_dir=os.path.join(root, "batch"),
                         xbox_model_dir=os.path.join(root, "xbox"),
                         async_save=False),
        trainer.table)
    seq = [0]

    def run_once(n_files=4, max_passes=2, base_every=0):
        seq[0] += 1
        source = os.path.join(root, "src-%d" % seq[0])
        os.makedirs(source)
        for i, f in enumerate(files[:n_files]):
            dst = os.path.join(source, "drop-%04d.txt" % i)
            shutil.copyfile(f, dst + ".tmp")
            os.replace(dst + ".tmp", dst)
        stream = StreamingDataset(feed, source,
                                  micro_pass_instances=2 * 1500)
        # the refusal threshold parked high: a drift refusal would skip
        # a window's instances and corrupt the rate (the preview cost
        # itself stays on the clock)
        runner = StreamingRunner(trainer, stream, cm=cm,
                                 base_every=base_every,
                                 admission_max_drift=10.0)
        return runner.run(max_micro_passes=max_passes, idle_timeout=10.0)

    try:
        run_once()                           # compile + warm

        def m_stream():
            return run_once()["examples_per_sec"]

        report("streaming_examples_per_sec", m_stream(),
               remeasure=m_stream)

        def m_fresh():
            jsrc = JournalDeltaSource([cm.journal.dir])
            jsrc.poll()                      # drain the pre-drop backlog
            hit = {}

            def tail():
                while "ts" not in hit:
                    if jsrc.poll():
                        hit["ts"] = time.time()
                        return
                    time.sleep(0.02)

            t = threading.Thread(target=tail, daemon=True)
            t.start()
            t0 = time.time()
            run_once(n_files=2, max_passes=1)
            t.join(timeout=10.0)
            jsrc.close()
            return ((hit["ts"] - t0) if "ts" in hit else 60.0) * 1e3

        report("streaming_freshness_ms", m_fresh(), remeasure=m_fresh)

        def m_e2e():
            # watermark-plane freshness END TO END (round 20): seconds
            # from an atomic file drop until a live ServingServer's
            # pull response carries a watermark >= the drop instant —
            # i.e. until SERVED vectors provably include the dropped
            # data (train + journal publish + tail poll + overlay
            # swap + stamped RPC all on the clock). One base day is
            # landed off the clock so the server has a view to stack.
            from paddlebox_tpu.serving.client import ServingClient
            from paddlebox_tpu.serving.server import ServingServer
            run_once(n_files=2, max_passes=1, base_every=1)
            old_jdir = flags.get_flag("serving_journal_dir")
            old_ref = flags.get_flag("serving_refresh_secs")
            flags.set_flag("serving_journal_dir", cm.journal.dir)
            flags.set_flag("serving_refresh_secs", 0.05)
            server = cli = None
            pk = np.arange(1, 65, dtype=np.uint64)
            try:
                server = ServingServer(os.path.join(root, "xbox"))
                cli = ServingClient([("127.0.0.1", server.port)])
                t0 = time.time()
                done = {}

                def puller():
                    while "dt" not in done and time.time() - t0 < 30.0:
                        try:
                            cli.pull(pk)
                        except (ConnectionError, RuntimeError):
                            pass
                        if cli.last_watermark >= t0:
                            done["dt"] = time.time() - t0
                            return
                        time.sleep(0.02)

                t = threading.Thread(target=puller, daemon=True)
                t.start()
                run_once(n_files=2, max_passes=1)
                t.join(timeout=35.0)
                return done.get("dt", 60.0)
            finally:
                if cli is not None:
                    cli.close()
                if server is not None:
                    server.drain()
                flags.set_flag("serving_journal_dir", old_jdir)
                flags.set_flag("serving_refresh_secs", old_ref)

        report("freshness_e2e_secs", m_e2e(), remeasure=m_e2e)
    finally:
        flags.set_flag("streaming_poll_secs", old_poll)
        trainer.close()
        shutil.rmtree(root, ignore_errors=True)


SECTIONS = (
    ("native", section_native),
    ("bucketize", section_bucketize),
    ("policy_route", section_policy_route),
    ("p2p", section_p2p),
    ("parse", section_parse),
    ("ingest", section_ingest),
    ("e2e", section_e2e),
    ("push", section_push),
    ("serving", section_serving),
    ("fleet", section_fleet),
    ("ckpt", section_ckpt),
    ("ssd", section_ssd),
    ("quality", section_quality),
    ("boxlint", section_boxlint),
    ("device", section_device),
    ("streaming", section_streaming),
)


def main():
    only = None
    if len(sys.argv) == 3 and sys.argv[1] == "--stage":
        only = sys.argv[2]
        if only not in dict(SECTIONS):
            print(json.dumps({"error": "unknown stage %r; have %s"
                              % (only, [n for n, _ in SECTIONS])}))
            sys.exit(2)
    K = 131072
    for name, fn in SECTIONS:
        if only is not None and name != only:
            continue
        # fresh RNG per section → --stage runs reproduce the full-probe
        # workload of that section exactly
        fn(np.random.RandomState(0), K)

    if failures:
        print(json.dumps({"failed": failures, "load1": _load1()}),
              flush=True)
        sys.exit(1)
    print(json.dumps({"all_ok": True}), flush=True)


if __name__ == "__main__":
    main()
