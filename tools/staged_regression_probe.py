"""Staged-path CPU regression probe (round-5 hygiene item).

CPU ex/s rows are load-noise (±12% quiet, 4× under load — BASELINE.md),
so between TPU windows nothing guarded the data/staging path. This
checks the HOST stages in keys(or lines)/s against floor thresholds set
at ~40% of the recorded quiet-box rates — low enough to ride out
container noise, high enough to catch an algorithmic regression (the
r1 python-loop router was 10-25× under these rates).

Prints one JSON line per stage with ok=true/false; exits 1 if any fails.
Usage: timeout 900 python -u tools/staged_regression_probe.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# (recorded quiet-box rate AT THIS PROBE'S OWN WORKLOAD — round-5
# first run, 2026-07-31 — , floor = ~40% of it). The r2-r4 BASELINE.md
# rates used different shapes (32 slots, bigger vocab), so this probe
# records its own reference once and guards against regression from it.
FLOORS = {
    "rt_lookup_keys_per_sec": (51.8e6, 20e6),
    "rt_dedup_keys_per_sec": (47.2e6, 19e6),
    "bucketize_keys_per_sec": (21.1e6, 8e6),
    "parse_lines_per_sec": (722e3, 290e3),
    "pack_instances_per_sec": (722e3, 290e3),
}

failures = []


def report(stage, rate):
    rec, floor = FLOORS[stage]
    ok = rate >= floor
    if not ok:
        failures.append(stage)
    print(json.dumps({"stage": stage, "rate": round(rate, 0),
                      "recorded": rec, "floor": floor, "ok": ok}),
          flush=True)


def timed_rate(fn, n_items, secs=2.0):
    fn()                                   # warm
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < secs:
        fn()
        reps += 1
    return reps * n_items / (time.perf_counter() - t0)


def main():
    rng = np.random.RandomState(0)
    K = 131072

    # --- native route tier -------------------------------------------
    from paddlebox_tpu.native.build import (create_route_index,
                                            destroy_route_index, get_lib,
                                            route_lookup)
    if get_lib() is None:
        print(json.dumps({"error": "native lib unavailable"}), flush=True)
        sys.exit(1)
    pass_keys = np.unique(rng.randint(0, 1 << 40, 1 << 20).astype(np.uint64))
    idx = create_route_index([pass_keys])
    probe = rng.choice(pass_keys, K).astype(np.uint64)
    report("rt_lookup_keys_per_sec",
           timed_rate(lambda: route_lookup(idx, probe, None, 0), K))
    destroy_route_index(idx)

    from paddlebox_tpu.embedding.pass_table import dedup_ids
    ids = rng.randint(0, 1 << 20, K).astype(np.int32)
    report("rt_dedup_keys_per_sec",
           timed_rate(lambda: dedup_ids(ids, 1 << 20), K))

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.parallel.sharded_table import ShardedPassTable
    t = ShardedPassTable(
        TableConfig(embedx_dim=8, pass_capacity=1 << 21,
                    optimizer=SparseOptimizerConfig()),
        num_shards=8, bucket_cap=4 * K // 8)
    t.begin_feed_pass()
    t.add_keys(pass_keys)
    t.end_feed_pass()
    valid = np.ones(K, bool)
    report("bucketize_keys_per_sec",
           timed_rate(lambda: t.bucketize(probe, valid.copy()), K))

    # --- parse + pack tier -------------------------------------------
    import tempfile

    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    out = tempfile.mkdtemp()
    files, feed = write_synthetic_ctr_files(
        out, num_files=2, lines_per_file=8000, num_slots=16,
        vocab_per_slot=5000, max_len=4, seed=1)
    feed = type(feed)(slots=feed.slots, batch_size=512)

    def load():
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        n = len(ds)
        ds.release_memory()
        return n

    n_lines = 16000
    t0 = time.perf_counter()
    reps = 0
    load()
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 4.0:
        n = load()
        reps += 1
    dt = time.perf_counter() - t0
    report("parse_lines_per_sec", reps * n_lines / dt)
    # load_into_memory covers parse+merge+batch build in this design
    report("pack_instances_per_sec", reps * n / dt)

    if failures:
        print(json.dumps({"failed": failures}), flush=True)
        sys.exit(1)
    print(json.dumps({"all_ok": True}), flush=True)


if __name__ == "__main__":
    main()
