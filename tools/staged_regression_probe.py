"""Staged-path CPU regression probe (round-5 hygiene item).

CPU ex/s rows are load-noise (±12% quiet, 4× under load — BASELINE.md),
so between TPU windows nothing guarded the data/staging path. This
checks the HOST stages in keys(or lines)/s against floor thresholds set
at ~40% of the recorded quiet-box rates — low enough to ride out
container noise, high enough to catch an algorithmic regression (the
r1 python-loop router was 10-25× under these rates).

Prints one JSON line per stage with ok=true/false; exits 1 if any fails.
Usage: timeout 900 python -u tools/staged_regression_probe.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# (recorded quiet-box rate AT THIS PROBE'S OWN WORKLOAD — round-5
# first run, 2026-07-31 — , floor = ~40% of it). The r2-r4 BASELINE.md
# rates used different shapes (32 slots, bigger vocab), so this probe
# records its own reference once and guards against regression from it.
FLOORS = {
    "rt_lookup_keys_per_sec": (51.8e6, 20e6),
    "rt_dedup_keys_per_sec": (47.2e6, 19e6),
    "uid_sort_keys_per_sec": (116e6, 40e6),
    "bucketize_keys_per_sec": (21.1e6, 8e6),
    "parse_lines_per_sec": (722e3, 290e3),
    "pack_instances_per_sec": (722e3, 290e3),
    # round-8: the uid-lean wire END TO END on CPU (host stage + H2D +
    # jitted scan + D2H, small DeepFM shape below) — guards the whole
    # staged path so a wire regression fails loud between tunnel windows.
    # Recorded on a LOADED round-8 container (sibling rows at ~60% of
    # their quiet-box rates the same run); floor = ~40% of it
    "e2e_lean_examples_per_sec": (6.8e3, 2.7e3),
    # round-9: the p2p host-plane bucket a2a, two in-process mesh
    # endpoints over loopback (keys = one rank's n_local*P*KB per step);
    # the multi-process ladder in tools/hostplane_probe.py recorded
    # store=229.6ms vs p2p=36.4ms at the same shape this round
    "p2p_exchange_keys_per_sec": (30.1e6, 12e6),
}

failures = []


def report(stage, rate):
    rec, floor = FLOORS[stage]
    ok = rate >= floor
    if not ok:
        failures.append(stage)
    print(json.dumps({"stage": stage, "rate": round(rate, 0),
                      "recorded": rec, "floor": floor, "ok": ok}),
          flush=True)


def timed_rate(fn, n_items, secs=2.0):
    fn()                                   # warm
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < secs:
        fn()
        reps += 1
    return reps * n_items / (time.perf_counter() - t0)


def main():
    rng = np.random.RandomState(0)
    K = 131072

    # --- native route tier -------------------------------------------
    from paddlebox_tpu.native.build import (create_route_index,
                                            destroy_route_index, get_lib,
                                            route_lookup)
    if get_lib() is None:
        print(json.dumps({"error": "native lib unavailable"}), flush=True)
        sys.exit(1)
    pass_keys = np.unique(rng.randint(0, 1 << 40, 1 << 20).astype(np.uint64))
    idx = create_route_index([pass_keys])
    probe = rng.choice(pass_keys, K).astype(np.uint64)
    report("rt_lookup_keys_per_sec",
           timed_rate(lambda: route_lookup(idx, probe, None, 0), K))
    destroy_route_index(idx)

    from paddlebox_tpu.embedding.pass_table import (dedup_ids,
                                                    dedup_uids_sorted)
    ids = rng.randint(0, 1 << 20, K).astype(np.int32)
    report("rt_dedup_keys_per_sec",
           timed_rate(lambda: dedup_ids(ids, 1 << 20), K))
    # the uid-wire host product (np.unique sort — the only staged dedup
    # work on the uid-lean path)
    report("uid_sort_keys_per_sec",
           timed_rate(lambda: dedup_uids_sorted(ids, 1 << 20), K))

    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig)
    from paddlebox_tpu.parallel.sharded_table import ShardedPassTable
    t = ShardedPassTable(
        TableConfig(embedx_dim=8, pass_capacity=1 << 21,
                    optimizer=SparseOptimizerConfig()),
        num_shards=8, bucket_cap=4 * K // 8)
    t.begin_feed_pass()
    t.add_keys(pass_keys)
    t.end_feed_pass()
    valid = np.ones(K, bool)
    report("bucketize_keys_per_sec",
           timed_rate(lambda: t.bucketize(probe, valid.copy()), K))

    # --- p2p host-plane exchange tier (round 9) ----------------------
    # two in-process mesh endpoints over loopback running the per-step
    # bucket a2a (exchange_incoming_p2p) in lockstep — guards the socket
    # mesh data plane between real multi-process runs (the full ladder
    # incl. the store tier lives in tools/hostplane_probe.py)
    from concurrent.futures import ThreadPoolExecutor

    from paddlebox_tpu.fleet.mesh_comm import MeshComm
    from paddlebox_tpu.parallel.sharded_table import exchange_incoming_p2p
    world, P_hp, KB_hp = 2, 8, 8192
    meshes = [MeshComm(r, world) for r in range(world)]
    eps = {r: ("127.0.0.1", m.port) for r, m in enumerate(meshes)}
    pos = {0: [0, 1, 2, 3], 1: [4, 5, 6, 7]}
    for m in meshes:
        m.connect(eps)
        m.positions_of = dict(pos)
    bks = [rng.randint(0, (1 << 16) - 1, (4, P_hp, KB_hp)).astype(np.int32)
           for _ in range(world)]
    hp_pool = ThreadPoolExecutor(1)

    def one_exchange():
        f = hp_pool.submit(exchange_incoming_p2p, bks[1], pos[1], P_hp,
                           meshes[1])
        exchange_incoming_p2p(bks[0], pos[0], P_hp, meshes[0])
        f.result()

    report("p2p_exchange_keys_per_sec",
           timed_rate(one_exchange, 4 * P_hp * KB_hp))
    for m in meshes:
        m.close()
    hp_pool.shutdown(wait=False)

    # --- parse + pack tier -------------------------------------------
    import tempfile

    from paddlebox_tpu.data import BoxDataset, write_synthetic_ctr_files
    out = tempfile.mkdtemp()
    files, feed = write_synthetic_ctr_files(
        out, num_files=2, lines_per_file=8000, num_slots=16,
        vocab_per_slot=5000, max_len=4, seed=1)
    feed = type(feed)(slots=feed.slots, batch_size=512)

    def load():
        ds = BoxDataset(feed, read_threads=1)
        ds.set_filelist(files)
        ds.load_into_memory()
        n = len(ds)
        ds.release_memory()
        return n

    n_lines = 16000
    t0 = time.perf_counter()
    reps = 0
    load()
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 4.0:
        n = load()
        reps += 1
    dt = time.perf_counter() - t0
    report("parse_lines_per_sec", reps * n_lines / dt)
    # load_into_memory covers parse+merge+batch build in this design
    report("pack_instances_per_sec", reps * n / dt)

    # --- uid-lean wire e2e tier (round 8) ----------------------------
    # host stage (lookup + uid sort) + H2D + jitted scan + loss D2H over
    # a small DeepFM shape — the whole staged path the uid wire carries
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddlebox_tpu.config.configs import TrainerConfig
    from paddlebox_tpu.config import flags as _flags
    from tools.bench_util import make_bench_trainer, make_ctr_batches
    _flags.set_flag("h2d_lean", True)
    try:
        tr, feed = make_bench_trainer(
            1 << 18, batch=256, num_slots=16, max_len=4, d=8,
            trainer_cfg=TrainerConfig(dense_lr=1e-3))
        chunk = 4
        batches = make_ctr_batches(feed, chunk, 16, 4, seed=0)
        tr.table.begin_feed_pass()
        for b in batches:
            tr.table.add_keys(b.keys[b.valid])
        tr.table.end_feed_pass()
        tr.table.begin_pass()
        state = [tr.table.slab, tr.params, tr.opt_state,
                 tr.table.next_prng()]

        def one_chunk():
            stacked = tr._stack_batches(batches)
            slab, params, opt, losses, _p, key = tr.fns.scan_steps(
                state[0], state[1], state[2], stacked, state[3])
            state[:] = slab, params, opt, key
            assert np.isfinite(np.asarray(losses)).all()

        report("e2e_lean_examples_per_sec",
               timed_rate(one_chunk, chunk * 256, secs=4.0))
        tr.close()
    finally:
        _flags.set_flag("h2d_lean", False)

    if failures:
        print(json.dumps({"failed": failures}), flush=True)
        sys.exit(1)
    print(json.dumps({"all_ok": True}), flush=True)


if __name__ == "__main__":
    main()
