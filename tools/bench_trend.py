"""Bench trajectory: cross-round deltas of the headline BENCH rates.

Reads every ``BENCH_r*.json`` in the repo root (the driver-archived
rounds 1-5 and the self-stamped rounds bench.py writes from round 14
on — both use the ``{"n", "parsed"}`` envelope), orders them by round
number, and prints one line per headline metric per consecutive pair:
absolute values, the delta, and a REGRESSION flag when a
higher-is-better rate drops (or ms/step rises) by more than
``--threshold`` (default 10%).

Honesty guards: rounds on different platforms (a TPU round vs a
CPU-fallback round) are never compared — the platform column makes the
tier visible; zero/absent values (failed rounds, pre-round fields)
compare as "n/a" rather than as infinite regressions.

Usage:
    python tools/bench_trend.py [--root PATH] [--threshold 0.10] [--json]

Exit code 1 when any flagged regression exists (CI-pluggable), else 0.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

#: (record key, unit, higher_is_better)
HEADLINES: List[Tuple[str, str, bool]] = [
    ("value", "ex/s", True),
    ("e2e_examples_per_sec", "ex/s", True),
    ("e2e_lean", "ex/s", True),
    ("pass_amortized_examples_per_sec", "ex/s", True),
    ("steady_ms_per_step", "ms", False),
    # round-15 checkpoint plane (store-level columnar save/load; absent
    # pre-round-15 rounds compare as n/a, not as regressions)
    ("ckpt_save_keys_per_sec", "keys/s", True),
    ("ckpt_load_keys_per_sec", "keys/s", True),
    # round-17 ingest plane: the cold-pass parse→shuffle→pack→train
    # headline (absent pre-round-17 rounds compare as n/a)
    ("ingest_cold_pass_examples_per_sec", "ex/s", True),
    # round-16 SSD tier (landed after 17 — absent earlier rounds
    # compare as n/a): the feed-pass promote leg and the lookup-path
    # cold fault over spilled rows
    ("ssd_promote_keys_per_sec", "keys/s", True),
    ("ssd_fault_keys_per_sec", "keys/s", True),
    # round-20 device plane: the compiled step's bytes-accessed per
    # example (Tensor Casting's co-design metric, from the one-time
    # cost-analysis snapshot). LOWER is better — a rise past the
    # threshold is a byte-budget regression and flags exactly like a
    # rate regression (absent pre-round-20 rounds compare as n/a)
    ("device_bytes_accessed_per_example", "B/ex", False),
    # round-21 serving fleet: the multi-box ladder's top-rung
    # client-side pull rate (tools/fleet_probe.py; absent pre-round-21
    # rounds compare as n/a)
    ("fleet_pull_keys_per_sec", "keys/s", True),
    # round-19 streaming plane (landed after 21 — absent earlier rounds
    # compare as n/a): sustained micro-pass rate, and the drop-to-
    # journal-poll freshness where LOWER is better — a rise past the
    # threshold is a staleness regression
    ("streaming_examples_per_sec", "ex/s", True),
    ("streaming_freshness_secs", "s", False),
    # round-20 watermark plane (landed after 21/22 — absent earlier
    # rounds compare as n/a): fleet-wide answered-pull QPS from the
    # fleet probe's top rung, and the TRUE feed-to-serve freshness p99
    # (born-ts -> watermark-stamped pull through a live server) where
    # LOWER is better — a rise is a staleness regression
    ("fleet_qps", "q/s", True),
    ("freshness_e2e_p99_secs", "s", False),
]


def load_rounds(root: str) -> List[Dict[str, Any]]:
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.match(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        rec = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
            else doc
        out.append({"round": int(m.group(1)),
                    "path": os.path.basename(path),
                    "schema_version": doc.get("schema_version", 1),
                    "platform": rec.get("platform", "?"),
                    "record": rec})
    out.sort(key=lambda r: r["round"])
    return out


def _num(rec: dict, key: str) -> Optional[float]:
    v = rec.get(key)
    if isinstance(v, (int, float)) and v > 0:
        return float(v)
    return None


def trend(rounds: List[Dict[str, Any]], threshold: float) -> dict:
    rows = []
    regressions = []
    for prev, cur in zip(rounds, rounds[1:]):
        pr, cr = prev["record"], cur["record"]
        comparable = (prev["platform"] == cur["platform"]
                      and prev["platform"] != "?")
        for key, unit, hib in HEADLINES:
            a, b = _num(pr, key), _num(cr, key)
            row = {"metric": key, "unit": unit,
                   "from_round": prev["round"], "to_round": cur["round"],
                   "platform": cur["platform"],
                   "from": a, "to": b}
            if a is None or b is None or not comparable:
                row["delta_pct"] = None
            else:
                delta = (b - a) / a
                row["delta_pct"] = round(100.0 * delta, 1)
                regressed = ((-delta if hib else delta) > threshold)
                row["regression"] = regressed
                if regressed:
                    regressions.append(row)
            rows.append(row)
    return {"rows": rows, "regressions": regressions}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="print cross-round BENCH deltas for the headline "
                    "rates; flag regressions past the threshold")
    ap.add_argument("--root",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="directory holding the BENCH_r*.json series")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression flag threshold as a fraction "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--json", action="store_true",
                    help="one JSON document instead of the table")
    args = ap.parse_args(argv)

    rounds = load_rounds(args.root)
    if len(rounds) < 2:
        print(json.dumps({"error": "need >=2 BENCH_r*.json rounds",
                          "found": [r["path"] for r in rounds]}))
        return 0
    result = trend(rounds, args.threshold)
    if args.json:
        print(json.dumps({"rounds": [
            {k: r[k] for k in ("round", "path", "platform",
                               "schema_version")} for r in rounds],
            **result}))
    else:
        print("round series: " + " -> ".join(
            "r%d[%s]" % (r["round"], r["platform"]) for r in rounds))
        for row in result["rows"]:
            if row["from"] is None and row["to"] is None:
                continue
            def fmt(v):
                return "%.1f" % v if v is not None else "n/a"
            mark = ("  REGRESSION" if row.get("regression")
                    else "" if row["delta_pct"] is None else "")
            delta = ("%+.1f%%" % row["delta_pct"]
                     if row["delta_pct"] is not None else "  n/a")
            print("r%02d->r%02d  %-34s %10s -> %10s  %8s%s"
                  % (row["from_round"], row["to_round"],
                     "%s (%s)" % (row["metric"], row["unit"]),
                     fmt(row["from"]), fmt(row["to"]), delta, mark))
        if result["regressions"]:
            print("%d regression(s) past %.0f%%"
                  % (len(result["regressions"]), 100 * args.threshold))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
