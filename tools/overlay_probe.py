"""Validate the chunk-overlay push design's cost assumptions on the chip.

push_ablate.py: scatter ops cost ~7-12 ms FIXED on this backend; the
overlay design replaces per-batch scatters with traced-offset
dynamic_update_slice + a blended gather, and one fold scatter per chunk.
Measure each piece at real shapes.

Usage: timeout 900 python -u tools/overlay_probe.py [platform]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

CAP = 1 << 20
K = 131072
W = 17
ITERS = 16
REPS = 5


def timed(name, fn, *args):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    ms = (time.perf_counter() - t0) / REPS / ITERS * 1e3
    print(json.dumps({"op": name, "ms_per_call": round(ms, 4)}), flush=True)
    return ms


def chain(body):
    def run(carry, *args):
        def step(i, c):
            return body(c, i, *args)
        return lax.fori_loop(0, ITERS, step, carry)
    return jax.jit(run)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)
    slab = jnp.asarray(rng.rand(CAP, W).astype(np.float32))
    overlay = jnp.asarray(rng.rand(8 * K, W).astype(np.float32))
    rows = jnp.asarray(rng.rand(K, W).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, CAP - 1, K).astype(np.int32))
    ov_idx = jnp.asarray(
        np.where(rng.rand(K) < 0.3, rng.randint(0, 8 * K, K), -1)
        .astype(np.int32))

    # 1. dynamic_update_slice at a TRACED row offset
    def dus(ov, i, r):
        return lax.dynamic_update_slice(ov, r, (i * K % (7 * K), 0))
    timed("dus_traced_offset_131k_rows", chain(dus), overlay, rows)

    # 2. blended pull: slab gather + overlay gather + select
    def blend(c, i, s, ov, idx, oi):
        base = jnp.take(s, idx, axis=0, mode="clip")
        over = jnp.take(ov, jnp.maximum(oi, 0), axis=0)
        r = jnp.where((oi >= 0)[:, None], over, base)
        return c + r[:1, :1]
    timed("blended_pull_gather_select", chain(blend), jnp.zeros((1, 1)),
          slab, overlay, ids, ov_idx)

    # plain pull for reference
    def plain(c, i, s, idx):
        return c + jnp.take(s, idx, axis=0, mode="clip")[:1, :1]
    timed("plain_pull_gather", chain(plain), jnp.zeros((1, 1)), slab, ids)

    # 3. scatter cost vs index count (fold cadence): 16k / 131k / 700k
    for n in (16384, 131072, 700000):
        u = jnp.asarray(np.sort(rng.choice(CAP - 1, n, replace=False))
                        .astype(np.int32))
        r = jnp.asarray(rng.rand(n, W).astype(np.float32))

        def scat(s, i, uu, rr):
            return s.at[uu].set(rr, mode="drop", unique_indices=True)
        timed(f"fold_scatter_{n}_idx", chain(scat), slab, u, r)

    # 4. gather of final rows from overlay (fold's read side)
    fin = jnp.asarray(rng.randint(0, 8 * K, 700000).astype(np.int32))

    def gfin(c, i, ov, f):
        return c + jnp.take(ov, f, axis=0)[:1, :1]
    timed("fold_gather_700k_from_overlay", chain(gfin), jnp.zeros((1, 1)),
          overlay, fin)


if __name__ == "__main__":
    main()
