"""Shared helpers for bench.py and tools/tpu_probe.py — ONE definition of
the synthetic workload and of the D2H-synced timing loop, so the probe
decomposes exactly the number the bench reports."""

import time
from typing import List

import numpy as np


def make_ctr_batches(feed, n_batches: int, num_slots: int, max_len: int,
                     seed: int = 0) -> List:
    """The bench's synthetic CTR batches: ~(max_len+1)/2 keys per slot per
    instance, globally slot-disambiguated uint64 feasigns, 25% positives."""
    from paddlebox_tpu.data.packer import BatchPacker
    from paddlebox_tpu.data.slot_record import SlotRecord

    rng = np.random.RandomState(seed)
    packer = BatchPacker(feed)
    out = []
    for _ in range(n_batches):
        recs = []
        for _ in range(feed.batch_size):
            slots = {}
            for si in range(num_slots):
                n = rng.randint(1, max_len + 1)
                feas = (rng.randint(0, 1 << 22, n).astype(np.uint64)
                        * np.uint64(num_slots) + np.uint64(si))
                slots[si] = feas
            recs.append(SlotRecord(label=int(rng.rand() < 0.25),
                                   uint64_slots=slots))
        out.append(packer.pack(recs))
    return out


def timed_scan_chain(scan, state, stacked, reps: int, warmup: int = 2):
    """Run `scan(slab, params, opt_state, stacked, prng)` reps times with the
    state threaded through (each call consumes the previous call's outputs)
    and return seconds per call. The sync point is np.asarray of the LAST
    call's losses — data that depends on the whole chain — because axon's
    block_until_ready returns early (BASELINE.md measurement validity)."""
    if warmup < 1:
        raise ValueError("warmup must be >= 1 (the first call compiles)")
    for _ in range(warmup):
        slab, params, opt, losses, _p, key = scan(
            state[0], state[1], state[2], stacked, state[3])
        state = (slab, params, opt, key)
    warm = np.asarray(losses)
    if not np.isfinite(warm).all():
        raise FloatingPointError(f"non-finite warmup losses {warm}")
    t0 = time.perf_counter()
    for _ in range(reps):
        slab, params, opt, losses, _p, key = scan(
            state[0], state[1], state[2], stacked, state[3])
        state = (slab, params, opt, key)
    final = np.asarray(losses)
    dt = (time.perf_counter() - t0) / reps
    if not np.isfinite(final).all():
        raise FloatingPointError(f"non-finite losses {final}")
    return dt


def measure_pass_amortized(trainer, batches, batch_size: int,
                           overlaps=(0.0, 0.9), n_passes: int = 3,
                           workset_rows: int = 1 << 18, seed: int = 123):
    """Honest pass-amortized throughput (round-6 verdict item 2): wall
    clock of the FULL pass lifecycle — begin_feed → build → train →
    end_pass — not just the resident jitted step, for both the full and
    the incremental lifecycle at each working-set overlap ratio. The
    working set is the synthetic batch keys plus `workset_rows` filler
    keys that evolve with ~overlap retention between passes (the filler
    plays the day's long-tail: promoted every pass, never touched by a
    push, exactly the rows the delta lifecycle refuses to move twice).

    Pass 1 of each config is the cold build and is excluded from the
    reported means. Every timed segment ends in a real D2H (np.asarray of
    chain-dependent data) — block_until_ready returns early on axon.

    Returns the nested dict bench.py emits under "pass_amortized"."""
    from paddlebox_tpu.config import flags as _flags

    tab = trainer.table
    scan = trainer.fns.scan_steps
    # earlier measurement phases leave the table mid-pass with a hacked
    # slab; reset to a clean between-passes state
    tab._in_pass = False
    tab._slab = None
    tab._touched = None
    tab.invalidate_residency()

    batch_keys = np.unique(np.concatenate(
        [np.asarray(b.keys[b.valid], np.uint64) for b in batches]))
    ws = min(workset_rows, max(0, tab.capacity - 1 - int(batch_keys.size)
                               - workset_rows // 8))
    examples = len(batches) * batch_size
    saved_flag = _flags.get_flag("incremental_pass")

    def filler_seq(overlap, rng, n):
        cur = np.unique(rng.randint(0, 1 << 40, ws).astype(np.uint64))
        out = [cur]
        for _ in range(n - 1):
            keep = rng.rand(cur.size) < overlap
            fresh = np.unique(rng.randint(
                0, 1 << 40, max(1, int(ws * (1.0 - overlap))))
                .astype(np.uint64))
            cur = np.unique(np.concatenate([cur[keep], fresh]))
            out.append(cur)
        return out

    def one_pass(filler):
        t0 = time.perf_counter()
        tab.begin_feed_pass()
        tab.add_keys(filler)
        for b in batches:
            tab.add_keys(b.keys[b.valid])
        tab.end_feed_pass()
        tab.begin_pass()
        np.asarray(tab.slab[0, 0:1])  # D2H sync: promote really done
        t1 = time.perf_counter()
        stacked = trainer._stack_batches(batches)
        slab, params, opt, losses, _preds, key = scan(
            tab.slab, trainer.params, trainer.opt_state, stacked,
            tab.next_prng())
        np.asarray(losses)  # D2H sync for the whole chunk
        tab.set_slab(slab)
        trainer.params, trainer.opt_state = params, opt
        t2 = time.perf_counter()
        tab.end_pass()
        t3 = time.perf_counter()
        return t1 - t0, t2 - t1, t3 - t2

    out = {"workset_rows": int(ws), "batches_per_pass": len(batches),
           "examples_per_pass": examples}
    try:
        for overlap in overlaps:
            cellpair = {}
            for mode, incremental in (("full", False), ("incremental", True)):
                _flags.set_flag("incremental_pass", incremental)
                tab.invalidate_residency()
                fillers = filler_seq(overlap, np.random.RandomState(seed),
                                     n_passes)
                segs = [one_pass(f) for f in fillers]
                warm = segs[1:] or segs
                build = float(np.mean([s[0] for s in warm]))
                train = float(np.mean([s[1] for s in warm]))
                end = float(np.mean([s[2] for s in warm]))
                cellpair[mode] = {
                    "examples_per_sec": round(
                        examples / (build + train + end), 1),
                    "build_ms": round(build * 1e3, 2),
                    "train_ms": round(train * 1e3, 2),
                    "end_ms": round(end * 1e3, 2),
                }
                # leave no residency behind for the next config
                _flags.set_flag("incremental_pass", False)
                tab.invalidate_residency()
            # true overlap of the FULL registered sets (batch keys repeat
            # every pass, so the \"0%\" config still carries their share)
            a = np.union1d(fillers[-2], batch_keys)
            b = np.union1d(fillers[-1], batch_keys)
            inter = np.intersect1d(a, b, assume_unique=True).size
            cellpair["measured_overlap"] = round(inter / max(1, b.size), 3)
            out["overlap_%d" % round(overlap * 100)] = cellpair
    finally:
        _flags.set_flag("incremental_pass", saved_flag)
    return out


def make_bench_trainer(pass_cap: int = 1 << 20, batch: int = 1024,
                       num_slots: int = 32, max_len: int = 4, d: int = 8,
                       trainer_cfg=None):
    """ONE definition of the bench-shape trainer (DeepFM 512/256/128, bf16
    dense, adagrad in-table) shared by bench.py's decomposing probe
    (tools/tpu_probe.py) and the compiled-step audit (tools/step_audit.py)
    — the audit's flops/bytes describe the benched program only while the
    shapes stay identical. Returns (trainer, feed)."""
    from paddlebox_tpu.config.configs import (SparseOptimizerConfig,
                                              TableConfig, TrainerConfig)
    from paddlebox_tpu.data.generator import default_feed_config
    from paddlebox_tpu.models.base import ModelSpec
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.train.trainer import BoxTrainer

    feed = default_feed_config(num_slots=num_slots, batch_size=batch,
                               max_len=max_len)
    table = TableConfig(embedx_dim=d, pass_capacity=pass_cap,
                        optimizer=SparseOptimizerConfig(
                            mf_create_thresholds=0.0, mf_initial_range=1e-3))
    model = DeepFM(ModelSpec(num_slots=num_slots, slot_dim=3 + d),
                   hidden=(512, 256, 128))
    return BoxTrainer(model, table, feed,
                      trainer_cfg or TrainerConfig(
                          dense_lr=1e-3, compute_dtype="bfloat16"),
                      seed=0), feed
