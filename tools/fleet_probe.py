"""Multi-box serving fleet ladder: QPS vs box count, coalescing RPC
reduction, journal-fed freshness, and the kill-one-replica error budget.

Round-21 acceptance probe: REAL spawned MultiBoxFleet grids (B boxes x
R replicas, every replica its own process mmapping a shard-filtered
view), driven closed-loop from a threaded FleetClient. Four legs:

  ladder    one rung per box count (default 1,2 at R=1): routing parity
            vs the full-view oracle first (bit-exact, or the rung
            fails), then `secs` of concurrency-`threads` pulls.
            Client-side keys/s + server-side p99 from the merged replica
            histograms. Acceptance: QPS grows with box count while p99
            stays in the same regime — the split views are each smaller
            and the boxes scan in parallel.
  coalesce  one B=2 fleet, two clients: coalesce on vs off, same fixed
            pull count at concurrency 8. Per-box RPC counts from the
            fleet request counters; acceptance: on-arm sends measurably
            fewer RPCs for the same answered pulls (ISSUE bar: visible
            reduction at concurrency >= 4).
  journal   the SAME B=2 fleet tails a real TouchedRowJournal; the
            probe appends touched rows and measures seconds until a
            pull returns them bit-exactly — the staleness a SaveDelta
            interval (minutes) used to impose.
  kill      B=2 x R=2 grid; SIGKILL one replica of box 0 mid-traffic;
            error rate over the following pulls must stay within the
            failover budget (<= 10%).

Usage:  timeout 240 python -u tools/fleet_probe.py [--boxes 1,2]
            [--n 200000] [--batch 4096] [--threads 8] [--secs 1.5]
Prints one JSON line {"probe": "fleet", ...}; exits 1 on failure.
Heavy imports stay inside functions: spawn re-imports this file in
every fleet child, which must come up jax-free in milliseconds.
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

EMBEDX = 8
DIM = 1 + EMBEDX          # embed_w + embedx: the served row width
WIDTH = 7 + 1 + EMBEDX    # header + adagrad state + embedx (store row)
HOT_ROWS = 2048


def build_store(root: str, n: int):
    """One xbox day dir + the shared hot-key file; returns the key
    universe, the oracle view path, and the hot-key path."""
    from paddlebox_tpu.serving.store import (write_hot_keys,
                                             write_xbox_columnar)
    rng = np.random.RandomState(99)
    keys = np.unique(rng.randint(1, 1 << 40, n).astype(np.uint64))
    rows = rng.randn(keys.size, DIM).astype(np.float32)
    day = os.path.join(root, "day0")
    os.makedirs(day, exist_ok=True)
    view = os.path.join(day, "view.xcol")
    write_xbox_columnar(view, keys, rows)
    with open(os.path.join(day, "DONE"), "w") as f:
        f.write(str(time.time()))
    hot_path = os.path.join(root, "hot.keys")
    write_hot_keys(hot_path, np.sort(rng.choice(keys, HOT_ROWS,
                                                replace=False)))
    return keys, view, hot_path


def check_parity(fc, oracle, keys, hot) -> None:
    """Bit-exact routing parity on a mixed hit/miss/hot probe — run
    before any timing so a wrong ladder never gets published."""
    rng = np.random.RandomState(7)
    for _ in range(3):
        probe = np.concatenate([
            rng.choice(keys, 300), rng.choice(hot, 40),
            rng.randint(1 << 41, 1 << 42, 20).astype(np.uint64)])
        rng.shuffle(probe)
        a = np.ascontiguousarray(fc.pull(probe)).view(np.uint32)
        b = np.ascontiguousarray(oracle.lookup(probe)).view(np.uint32)
        assert np.array_equal(a, b), "fleet parity vs oracle broke"


def drive(fc, keys, threads: int, secs: float, batch: int):
    """Closed-loop fixed-duration load; (keys_pulled, wall_s, errors)."""
    stop_at = time.perf_counter() + secs
    counts = [0] * threads
    errs = [0] * threads

    def worker(i: int) -> None:
        rng = np.random.RandomState(31 + i)
        while time.perf_counter() < stop_at:
            probe = rng.choice(keys, batch)
            try:
                fc.pull(probe)
                counts[i] += batch
            except (ConnectionError, RuntimeError):
                errs[i] += 1

    t0 = time.perf_counter()
    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return sum(counts), time.perf_counter() - t0, sum(errs)


def drive_fixed(fc, keys, threads: int, pulls: int, batch: int) -> int:
    """Fixed-count load (the coalesce A/B arms must answer the SAME
    number of pulls); returns caller errors."""
    errs = [0] * threads

    def worker(i: int) -> None:
        rng = np.random.RandomState(131 + i)
        for _ in range(pulls):
            try:
                fc.pull(rng.choice(keys, batch))
            except (ConnectionError, RuntimeError):
                errs[i] += 1

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return sum(errs)


def ladder_rung(root: str, keys, view: str, hot_path: str, boxes: int,
                threads: int, secs: float, batch: int) -> dict:
    from paddlebox_tpu.serving.fleet import MultiBoxFleet
    from paddlebox_tpu.serving.store import MmapViewStack, read_hot_keys
    oracle = MmapViewStack([], extra_files=(view,))
    hot = read_hot_keys(hot_path)
    with MultiBoxFleet(root, days=["day0"], boxes=boxes, replicas=1,
                       hot_keys_path=hot_path,
                       start_timeout=120.0) as fleet:
        fc = fleet.client(timeout=10.0)
        try:
            check_parity(fc, oracle, keys, hot)
            drive(fc, keys, threads, 0.3, batch)      # warm the pages
            fc.fleet_stats()
            pulled, wall, errors = drive(fc, keys, threads, secs, batch)
            st = fc.fleet_stats()
        finally:
            fc.close()
    return {"boxes": boxes, "replicas": 1,
            "keys_per_sec": int(pulled / wall),
            # answered-pull rate (round 20): the fleet_qps headline
            # bench_trend tracks — drive() counts keys, so pulls =
            # keys / batch
            "qps": round(pulled / batch / wall, 1),
            "p99_us": st["p99_us"], "p50_us": st["p50_us"],
            "errors": errors, "parity": "ok"}


def service_legs(root: str, keys, view: str, hot_path: str,
                 threads: int, batch: int) -> dict:
    """Coalesce A/B + journal freshness + kill-one-replica, all on one
    B=2 x R=2 grid (one spawn, three measurements)."""
    from paddlebox_tpu.serving.fleet import MultiBoxFleet
    from paddlebox_tpu.serving.store import MmapViewStack
    from paddlebox_tpu.train.journal import TouchedRowJournal
    from paddlebox_tpu.utils import journal_format as jf
    import types

    layout = types.SimpleNamespace(width=WIDTH, embedx_dim=EMBEDX,
                                   optimizer="adagrad")
    j = TouchedRowJournal(os.path.join(root, "_journal"), layout, None)
    oracle = MmapViewStack([], extra_files=(view,))
    out = {}
    with MultiBoxFleet(root, days=["day0"], boxes=2, replicas=2,
                       hot_keys_path=hot_path, journal_dirs=[j.dir],
                       flag_overrides={"serving_refresh_secs": 0.2},
                       start_timeout=120.0) as fleet:
        # --- coalesce A/B: same pull count, RPC delta per arm
        rpcs = {}
        for arm, coalesce in (("on", True), ("off", False)):
            fc = fleet.client(timeout=10.0, coalesce=coalesce)
            try:
                before = fc.fleet_stats()["requests"]
                errs = drive_fixed(fc, keys, threads, 25, batch)
                rpcs[arm] = fc.fleet_stats()["requests"] - before
            finally:
                fc.close()
            assert errs == 0, f"coalesce arm {arm}: {errs} pull errors"
        out["coalesce"] = {
            "threads": threads, "pulls_per_arm": threads * 25,
            "rpcs_on": int(rpcs["on"]), "rpcs_off": int(rpcs["off"]),
            "rpc_reduction": round(rpcs["off"] / max(1, rpcs["on"]), 2),
            "ok": rpcs["on"] < 0.8 * rpcs["off"]}

        # --- journal freshness: append -> poll until served bit-exact
        fc = fleet.client(timeout=10.0)
        try:
            tk = np.sort(np.random.RandomState(3).choice(
                keys, 64, replace=False))
            tv = (np.arange(tk.size * WIDTH, dtype=np.float32)
                  .reshape(tk.size, WIDTH) + 0.5)
            cols = jf.xbox_embed_cols(EMBEDX, "adagrad")
            expect = np.ascontiguousarray(tv[:, cols]).view(np.uint32)
            t0 = time.time()
            j.append_rows(tk, tv)
            landed = None
            while time.time() - t0 < 20.0:
                got = np.ascontiguousarray(fc.pull(tk)).view(np.uint32)
                if np.array_equal(got, expect):
                    landed = time.time() - t0
                    break
                time.sleep(0.05)
            assert landed is not None, "journal rows never reached serving"
            out["journal"] = {"staleness_s": round(landed, 2),
                              "ok": landed < 10.0}

            # --- kill one replica of box 0; failover absorbs it. The
            # oracle is the BASE view, so probe only untouched keys —
            # the fleet (correctly) serves the fresher journal values
            # for tk
            fleet.boxes[0]._procs[0].kill()
            pool = np.setdiff1d(keys, tk)
            errors, total = 0, 40
            rng = np.random.RandomState(11)
            for _ in range(total):
                probe = rng.choice(pool, 256)
                try:
                    a = np.ascontiguousarray(fc.pull(probe)).view(np.uint32)
                    b = np.ascontiguousarray(
                        oracle.lookup(probe)).view(np.uint32)
                    assert np.array_equal(a, b), "post-kill parity broke"
                except (ConnectionError, RuntimeError):
                    errors += 1
            out["kill"] = {"errors": errors, "total": total,
                           "error_rate": round(errors / total, 3),
                           "ok": errors <= total * 0.1}
        finally:
            fc.close()
    j.close()
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--boxes", default="1,2")
    ap.add_argument("--n", type=int, default=200_000)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--secs", type=float, default=1.5)
    args = ap.parse_args()
    ok = True
    result = {"probe": "fleet", "n_keys": args.n, "batch": args.batch,
              "threads": args.threads}
    try:
        with tempfile.TemporaryDirectory(prefix="pbtpu-fleet-probe-") as tmp:
            keys, view, hot_path = build_store(tmp, args.n)
            ladder = []
            for b in [int(x) for x in args.boxes.split(",")]:
                ladder.append(ladder_rung(tmp, keys, view, hot_path, b,
                                          args.threads, args.secs,
                                          args.batch))
            result["ladder"] = ladder
            # acceptance: more boxes must dominate — more keys/s AND
            # p99 no worse (each box scans a smaller view in parallel;
            # in practice p99 roughly halves box-to-box)
            if len(ladder) > 1:
                r = ladder[-1]["keys_per_sec"] / max(
                    1, ladder[0]["keys_per_sec"])
                result["qps_scaling"] = round(r, 2)
                result["qps_scales"] = (
                    r > 1.05
                    and ladder[-1]["p99_us"] <= 1.1 * ladder[0]["p99_us"])
                ok = ok and result["qps_scales"]
            legs = service_legs(tmp, keys, view, hot_path,
                                max(4, args.threads), args.batch)
            result.update(legs)
            ok = ok and legs["coalesce"]["ok"] and legs["journal"]["ok"] \
                and legs["kill"]["ok"]
            ok = ok and all(r["errors"] == 0 for r in ladder)
    except Exception as e:  # noqa: BLE001 — publish the failure, exit 1
        ok = False
        result["error"] = repr(e)[:400]
    result["ok"] = ok
    print(json.dumps(result), flush=True)
    print(json.dumps({"all_ok": ok}), flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
