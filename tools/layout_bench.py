"""Slab-layout experiment matrix on the live chip.

push_microbench.py showed EVERY push sub-op on the [CAP, 17] slab running
~2 orders under HBM roofline, and the XLA audit shows the slab padded
CAP x 24 x 4 bytes (width padded 17->24, i.e. width on SUBLANES and rows
on LANES — row gathers cross lanes). This measures, per candidate width
W in {17, 24, 32, 128} plus flat-1D: raw elementwise bandwidth, K-row
gather, K-row scatter — to pick the layout the pass slab should use.

Usage: timeout 900 python -u tools/layout_bench.py [platform]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

CAP = 1 << 20
K = 131072
ITERS = 16
REPS = 5


def timed(name, fn, *args, bytes_moved=None):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    ms = (time.perf_counter() - t0) / REPS / ITERS * 1e3
    rec = {"op": name, "ms_per_call": round(ms, 4)}
    if bytes_moved:
        rec["gb_per_s"] = round(bytes_moved / (ms * 1e-3) / 1e9, 1)
    print(json.dumps(rec), flush=True)
    return ms


def chain(body):
    def run(carry, *args):
        def step(_, c):
            return body(c, *args)
        return lax.fori_loop(0, ITERS, step, carry)
    return jax.jit(run)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)

    # Raw HBM bandwidth roofline: elementwise on 256 MB flat
    big = jnp.asarray(rng.rand(1 << 26).astype(np.float32))
    timed("roofline_elementwise_256MB",
          chain(lambda x: x * 0.999 + 0.001), big,
          bytes_moved=2 * big.size * 4)

    n_uniq = int(K * 0.85)
    uids_np = np.sort(rng.choice(CAP - 1, n_uniq, replace=False)).astype(
        np.int32)
    uids_np = np.concatenate(
        [uids_np, np.arange(K - n_uniq, dtype=np.int32) + CAP])
    uids = jnp.asarray(uids_np)

    for W in (17, 24, 32, 128):
        slab = jnp.asarray(rng.rand(CAP, W).astype(np.float32))
        rows = jnp.take(slab, uids, axis=0, mode="clip")
        timed(f"elementwise_slab_W{W}",
              chain(lambda s: s * 0.999 + 0.001), slab,
              bytes_moved=2 * CAP * W * 4)

        def gath(c, s, u):
            r = jnp.take(s, u, axis=0, mode="clip")
            return c + r[:1, :1]
        timed(f"gather_K_rows_W{W}", chain(gath), jnp.zeros((1, 1)),
              slab, uids, bytes_moved=2 * K * W * 4)

        def scat(s, u, r):
            return s.at[u].set(r, mode="drop", unique_indices=True)
        timed(f"scatter_K_rows_W{W}", chain(scat), slab, uids, rows,
              bytes_moved=2 * K * W * 4)

    # flat-1D variant: rows expanded to element indices (contiguous runs)
    W = 17
    flat = jnp.asarray(rng.rand(CAP * W).astype(np.float32))
    eidx = (uids[:, None].astype(jnp.int32) * W
            + jnp.arange(W, dtype=jnp.int32)[None, :]).reshape(-1)
    vals = jnp.take(flat, jnp.clip(eidx, 0, CAP * W - 1))

    def gath_flat(c, f, i):
        r = jnp.take(f, jnp.clip(i, 0, CAP * W - 1))
        return c + r[:1]
    timed("gather_flat1d_W17", chain(gath_flat), jnp.zeros((1,)),
          flat, eidx, bytes_moved=2 * K * W * 4)

    def scat_flat(f, i, v):
        return f.at[i].set(v, mode="drop", unique_indices=True)
    timed("scatter_flat1d_W17", chain(scat_flat), flat, eidx, vals,
          bytes_moved=2 * K * W * 4)

    # one-hot matmul gather (MXU path): [K, CAP] @ [CAP, W] is too big, but
    # blocked one-hot over 8k-row tiles of the K side is the classic
    # TPU-friendly trick; measure a single 8k tile to extrapolate.
    KT = 8192
    slab17 = jnp.asarray(rng.rand(CAP, 17).astype(np.float32))
    ut = uids[:KT]

    def gath_onehot(c, s, u):
        oh = jax.nn.one_hot(u // 128, CAP // 128, dtype=jnp.bfloat16)
        # coarse proxy: block-gather via matmul on 128-row superblocks
        r = oh @ s.reshape(CAP // 128, -1).astype(jnp.bfloat16)
        return c + r[:1, :1].astype(jnp.float32)
    timed("gather_onehot_8k_superblock", chain(gath_onehot),
          jnp.zeros((1, 1)), slab17, ut)


if __name__ == "__main__":
    main()
