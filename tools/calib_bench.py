"""Calibrate the chip: fixed per-iteration overhead vs real HBM/MXU rates.

layout_bench.py saw ~4.2 ms/iteration on nearly everything — before
trusting any layout conclusion, measure (a) a chained elementwise across
sizes 4 MB -> 256 MB (slope = bandwidth, intercept = per-iteration
overhead), (b) a bf16 matmul chain for MXU rate, (c) loop overhead with a
trivial scalar body.

Usage: timeout 900 python -u tools/calib_bench.py [platform]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms",
                  sys.argv[1] if len(sys.argv) > 1 else "axon")

import jax.numpy as jnp
import numpy as np
from jax import lax

REPS = 5


def timed(name, fn, iters, *args, bytes_moved=None, flops=None):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    ms = (time.perf_counter() - t0) / REPS / iters * 1e3
    rec = {"op": name, "ms_per_iter": round(ms, 4)}
    if bytes_moved:
        rec["gb_per_s"] = round(bytes_moved / (ms * 1e-3) / 1e9, 1)
    if flops:
        rec["tflop_per_s"] = round(flops / (ms * 1e-3) / 1e12, 2)
    print(json.dumps(rec), flush=True)
    return ms


def chain(body, iters):
    def run(carry, *args):
        def step(_, c):
            return body(c, *args)
        return lax.fori_loop(0, iters, step, carry)
    return jax.jit(run)


def main():
    dev = jax.devices()[0]
    print(json.dumps({"device": str(dev), "platform": dev.platform}),
          flush=True)
    rng = np.random.RandomState(0)

    # (c) trivial body: pure loop overhead
    timed("loop_overhead_scalar", chain(lambda x: x * 1.000001, 256), 256,
          jnp.float32(1.0))

    # (a) elementwise across sizes
    for logn, iters in ((20, 64), (22, 64), (24, 32), (26, 16)):
        x = jnp.asarray(rng.rand(1 << logn).astype(np.float32))
        mb = (1 << logn) * 4 // (1 << 20)
        timed(f"elementwise_{mb}MB", chain(lambda v: v * 0.999 + 0.001,
                                           iters), iters, x,
              bytes_moved=2 * x.size * 4)

    # (b) MXU: bf16 matmul 2048^3 and 4096^3
    for n, iters in ((2048, 32), (4096, 16)):
        a = jnp.asarray(rng.rand(n, n).astype(np.float32)).astype(
            jnp.bfloat16)

        def mm(c, m):
            return (c @ m) * 0.5
        timed(f"matmul_bf16_{n}", chain(mm, iters), iters, a, a,
              flops=2 * n ** 3)

    # same elementwise WITHOUT the loop: single fat op, python-level chain
    x = jnp.asarray(rng.rand(1 << 26).astype(np.float32))
    f = jax.jit(lambda v: v * 0.999 + 0.001)
    y = f(x); np.asarray(y.ravel()[:1])
    t0 = time.perf_counter()
    n = 8
    for _ in range(n):
        y = f(y)
    np.asarray(y.ravel()[:1])
    ms = (time.perf_counter() - t0) / n * 1e3
    print(json.dumps({"op": "elementwise_256MB_noloop",
                      "ms_per_iter": round(ms, 4),
                      "gb_per_s": round(2 * x.size * 4 / (ms * 1e-3) / 1e9,
                                        1)}), flush=True)


if __name__ == "__main__":
    main()
