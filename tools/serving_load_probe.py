"""Serving-plane load generator (round 12, ROADMAP item 3).

Measures the online tier the way a capacity planner needs it measured:

  * batch-size ladder — closed-loop max throughput (keys/s and
    requests/s) per pull batch size, hot and uniform key mixes, through
    the REAL RPC path (server process-local, socket loopback)
  * open-loop QPS sweep — requests are scheduled at a fixed offered
    rate regardless of completions (the arrival process real traffic
    has); p50/p99 latency per offered-rate step shows where queueing
    starts (the knee), which closed-loop probing structurally hides
  * cache ablation — hot mix with the hot-key cache on vs off

The synthetic base is built directly on disk in chunks (no RAM ingest,
same as tools/xbox_store_probe.py) and served via a pre-built
ViewManager handed to ServingServer — the probe measures the serving
plane, not day-training.

Usage: timeout 1800 python -u tools/serving_load_probe.py \
        [n_keys] [dim] [secs_per_point]
Prints one JSON line per measurement; "stage" keys match BASELINE.md's
round-12 table.
"""
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from paddlebox_tpu.serving.store import _XBOX_MAGIC  # noqa: E402

N = int(sys.argv[1]) if len(sys.argv) > 1 else 4_000_000
DIM = int(sys.argv[2]) if len(sys.argv) > 2 else 9
SECS = float(sys.argv[3]) if len(sys.argv) > 3 else 3.0
PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "_serving_probe.store")
CHUNK = 2_000_000
HOT_SET = 1 << 16          # distinct hot keys (cacheable working set)
BATCHES = (256, 4096, 32768)


def build_file():
    """Sorted keys 16*i+3 (misses probeable), rows f32 pattern —
    written in chunks, never resident."""
    t0 = time.perf_counter()
    key_off = (8 + 8 + 8 + 63) // 64 * 64
    row_off = (key_off + N * 8 + 63) // 64 * 64
    with open(PATH, "wb") as f:
        f.write(_XBOX_MAGIC)
        f.write(np.int64(N).tobytes())
        f.write(np.int64(DIM).tobytes())
        for lo in range(0, N, CHUNK):
            n = min(CHUNK, N - lo)
            ks = np.arange(lo, lo + n, dtype=np.uint64) * 16 + np.uint64(3)
            f.seek(key_off + lo * 8)
            ks.tofile(f)
        for lo in range(0, N, CHUNK):
            n = min(CHUNK, N - lo)
            rows = np.ones((n, DIM), np.float32)
            rows[:, 0] = ((np.arange(lo, lo + n, dtype=np.int64)
                           & 0xFFFF).astype(np.float32))
            f.seek(row_off + lo * DIM * 4)
            rows.tofile(f)
    print(json.dumps({"stage": "build_file", "n": N, "dim": DIM,
                      "bytes": os.path.getsize(PATH),
                      "secs": round(time.perf_counter() - t0, 1)}),
          flush=True)


def make_server(cache_rows):
    from paddlebox_tpu.config import flags
    from paddlebox_tpu.serving import ServingServer
    from paddlebox_tpu.serving.cache import HotKeyCache
    from paddlebox_tpu.serving.refresh import ViewManager
    from paddlebox_tpu.serving.store import MmapViewStack

    flags.set_flag("serving_report_requests", 0)     # probe does its own
    stack = MmapViewStack.from_files([PATH])
    cache = (HotKeyCache(cache_rows, DIM, admit=2) if cache_rows
             else None)
    return ServingServer(manager=ViewManager(stack, cache), watch=False)


def key_mix(rng, mix, batch, n_batches):
    if mix == "hot":
        ids = rng.randint(0, min(N, HOT_SET), n_batches * batch)
    else:
        ids = rng.randint(0, N, n_batches * batch)
    keys = ids.astype(np.uint64) * np.uint64(16) + np.uint64(3)
    if mix == "uniform":
        keys[::10] += np.uint64(1)          # 10% misses
    return keys.reshape(n_batches, batch)


def closed_loop(client, batches, secs):
    """One pinned client connection pulling as fast as answers return;
    latency per pull recorded locally (the client-side view)."""
    lat = []
    client.pull(batches[0])                  # warm (page-in + admit)
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < secs:
        s = time.perf_counter()
        client.pull(batches[reps % len(batches)])
        lat.append(time.perf_counter() - s)
        reps += 1
    dt = time.perf_counter() - t0
    lat_us = np.sort(np.array(lat) * 1e6)
    return (reps / dt, reps * batches.shape[1] / dt,
            float(lat_us[int(0.50 * (lat_us.size - 1))]),
            float(lat_us[int(0.99 * (lat_us.size - 1))]))


def open_loop(endpoint, batches, qps, secs):
    """Offered-rate arrivals on a scheduler clock; sender threads so a
    slow answer doesn't gate the next arrival (up to a small pool —
    beyond it the probe records the saturation honestly as p99). Each
    sender owns its OWN connection: a shared FramedClient serializes
    every call on its conn mutex, which would measure the client lock
    instead of the server's bounded pull pool."""
    import threading as _th
    from concurrent.futures import ThreadPoolExecutor

    from paddlebox_tpu.serving import ServingClient
    lat = []
    lock = threading.Lock()
    pool = ThreadPoolExecutor(8)
    tls = _th.local()

    def one(i):
        if not hasattr(tls, "client"):
            tls.client = ServingClient([endpoint])
        s = time.perf_counter()
        tls.client.pull(batches[i % len(batches)])
        with lock:
            lat.append(time.perf_counter() - s)

    warm = [pool.submit(one, i) for i in range(8)]  # conns + pool threads
    for f in warm:
        f.result()
    lat.clear()
    n = max(4, int(qps * secs))
    t0 = time.perf_counter()
    futs = []
    for i in range(n):
        target = t0 + i / qps
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        futs.append(pool.submit(one, i))
    for f in futs:
        f.result()
    pool.shutdown(wait=True)
    achieved = n / (time.perf_counter() - t0)
    lat_us = np.sort(np.array(lat) * 1e6)
    return (achieved,
            float(lat_us[int(0.50 * (lat_us.size - 1))]),
            float(lat_us[int(0.99 * (lat_us.size - 1))]))


def store_matches():
    """Reuse the on-disk base only when its HEADER matches this run's
    n/dim — a size-only check would happily serve a stale larger base
    while labeling every line with the new parameters."""
    if not os.path.exists(PATH):
        return False
    with open(PATH, "rb") as f:
        if f.read(8) != _XBOX_MAGIC:
            return False
        n = int(np.frombuffer(f.read(8), np.int64)[0])
        dim = int(np.frombuffer(f.read(8), np.int64)[0])
    return (n, dim) == (N, DIM) and os.path.getsize(PATH) > N * (8 + DIM * 4)


def main():
    if not store_matches():
        build_file()
    from paddlebox_tpu.serving import ServingClient

    rng = np.random.RandomState(0)
    # ---- batch ladder, both mixes, cache on --------------------------
    server = make_server(cache_rows=1 << 17)
    client = ServingClient([("127.0.0.1", server.port)])
    knee_batches = None
    for batch in BATCHES:
        for mix in ("hot", "uniform"):
            batches = key_mix(rng, mix, batch, 8)
            rps, kps, p50, p99 = closed_loop(client, batches, SECS)
            print(json.dumps({
                "stage": f"closed_{mix}_b{batch}",
                "requests_per_sec": round(rps, 1),
                "keys_per_sec": round(kps, 0),
                "p50_us": round(p50, 0), "p99_us": round(p99, 0)}),
                flush=True)
            if mix == "hot" and batch == 4096:
                knee_batches, knee_rps = batches, rps
    # ---- open-loop QPS sweep at the mid batch ------------------------
    for frac in (0.3, 0.6, 0.9):
        qps = max(1.0, knee_rps * frac)
        achieved, p50, p99 = open_loop(("127.0.0.1", server.port),
                                       knee_batches, qps, SECS)
        print(json.dumps({
            "stage": f"open_hot_b4096_load{int(frac * 100)}",
            "offered_qps": round(qps, 1),
            "achieved_qps": round(achieved, 1),
            "p50_us": round(p50, 0), "p99_us": round(p99, 0)}),
            flush=True)
    st = client.stats()
    print(json.dumps({"stage": "cache_counters",
                      "hit": st["cache_hit"], "miss": st["cache_miss"],
                      "evict": st["cache_evict"]}), flush=True)
    client.close()
    server.drain(timeout=5.0)

    # ---- cache ablation: hot mix, cache off --------------------------
    server = make_server(cache_rows=0)
    client = ServingClient([("127.0.0.1", server.port)])
    batches = key_mix(rng, "hot", 4096, 8)
    rps, kps, p50, p99 = closed_loop(client, batches, SECS)
    print(json.dumps({"stage": "closed_hot_b4096_nocache",
                      "requests_per_sec": round(rps, 1),
                      "keys_per_sec": round(kps, 0),
                      "p50_us": round(p50, 0),
                      "p99_us": round(p99, 0)}), flush=True)
    client.close()
    server.drain(timeout=5.0)

    # ---- ops endpoint scrape under pull load (round 18) ---------------
    # a replica with obs_http_port set binds /metrics at construction
    # (make_step_reporter → exporter.ensure_from_flags); the leg runs
    # the closed loop on a side thread while the parent scrapes, so the
    # number recorded is scrape latency WITH the pull plane busy — the
    # operator's actual experience — plus the pull rate while scraped.
    import urllib.request

    from paddlebox_tpu.config import flags as _flags
    from paddlebox_tpu.obs import exporter as _exporter

    _flags.set_flag("obs_http_port", 19790)
    server = make_server(cache_rows=0)
    exp = _exporter.active()
    if exp is None:
        # the exporter's documented degrade (port 19790 taken by a
        # co-tenant/stale probe): skip the leg loudly, don't crash it
        server.drain(timeout=5.0)
        _flags.set_flag("obs_http_port", 0)
        print(json.dumps({"stage": "scrape_under_pull_load",
                          "skipped": "obs http port 19790 unusable — "
                                     "exporter degraded off"}),
              flush=True)
        return
    client = ServingClient([("127.0.0.1", server.port)])
    pulled = {}

    def drive():
        pulled["res"] = closed_loop(client, batches, SECS)

    th = threading.Thread(target=drive)
    th.start()
    lat, errs = [], 0
    url = "http://127.0.0.1:%d/metrics" % exp.port
    while th.is_alive():
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                ok = (r.status == 200
                      and b"pbtpu_serving_lookup_us_p99" in r.read())
            if not ok:
                errs += 1
        except OSError:
            errs += 1
        lat.append(time.perf_counter() - t0)
        time.sleep(0.02)
    th.join()
    client.close()
    server.drain(timeout=5.0)
    _flags.set_flag("obs_http_port", 0)
    _exporter.ensure_from_flags()       # close + release the port
    slat = np.sort(np.array(lat) * 1e6)
    rps, kps, p50, p99 = pulled["res"]
    print(json.dumps({
        "stage": "scrape_under_pull_load",
        "scrapes": int(slat.size), "scrape_errors": errs,
        "scrape_p50_us": round(float(slat[slat.size // 2]), 0),
        "scrape_p99_us": round(float(slat[int(0.99 * (slat.size - 1))]),
                               0),
        "keys_per_sec_during_scrape": round(kps, 0),
        "pull_p99_us": round(p99, 0)}), flush=True)


if __name__ == "__main__":
    main()
