"""Stitch per-rank chrome traces into ONE Perfetto-loadable cluster
timeline with cross-rank flow events.

Each rank's ``obs.export_chrome_trace`` document is self-relative: ts=0
is that process's import instant. The export metadata carries the
wall-clock anchor (``clock_origin_unix_s``) and the rank, so stitching
is: shift every rank's events onto the earliest rank's axis, set
pid=rank (named via process_name metadata), and draw chrome flow events
(``ph: s/t/f``) through every span set that shares a trace id — the
64-bit ids the runners mint per step, the mesh carries in its frame
headers, and the serving codec carries in its request dicts. A mesh
exchange then renders as an arrow from the sender's ``mesh_exchange``
slice to the owner rank's ``mesh_recv_part`` slice; a serving pull as
client span -> replica span.

Clock caveat: the anchors come from ``time.time()`` per process — exact
enough on one box (the 2-4 process clusters this repro runs); across
machines the stitch inherits NTP skew, which offsets slices but keeps
the flow arrows (they bind by id, not by time).

Usage:
    python tools/trace_stitch.py trace_r0.json trace_r1.json ... \
        [-o cluster_trace.json]

Prints one JSON summary line: ranks, events, flows, cross_rank_flows.
Exits 1 when the inputs produce no cross-rank flow at all (a stitched
timeline without a single correlation usually means trace ids are not
flowing — the failure this tool exists to catch).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple


def _trace_of(ev: dict) -> Optional[str]:
    args = ev.get("args")
    if isinstance(args, dict):
        t = args.get("trace")
        if isinstance(t, str) and t:
            return t
    return None


def stitch(docs: List[dict]) -> Tuple[dict, dict]:
    """Merge chrome-trace documents into one; returns (stitched_doc,
    summary). Rank comes from each doc's metadata (fallback: input
    order); events shift onto the earliest clock origin."""
    anchors = []
    for i, doc in enumerate(docs):
        meta = doc.get("metadata") or {}
        rank = int(meta.get("rank", i))
        origin = meta.get("clock_origin_unix_s")
        anchors.append((rank, float(origin) if origin is not None
                        else None, doc))
    # docs without an anchor (pre-round-14 exports) stay UNSHIFTED on
    # the base axis — treating a missing anchor as unix 0 would shift
    # every anchored rank by decades of microseconds
    present = [o for _, o, _ in anchors if o is not None]
    base = min(present) if present else 0.0
    unanchored = sorted(r for r, o, _ in anchors if o is None)

    events: List[dict] = []
    # trace id -> [(ts_mid, pid, tid)] across every rank
    by_trace: Dict[str, List[Tuple[float, int, int]]] = {}
    for rank, origin, doc in anchors:
        shift_us = ((origin - base) * 1e6 if origin is not None else 0.0)
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "args": {"name": "rank %d" % rank}})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + shift_us, 3)
            events.append(ev)
            if ev.get("ph") != "X":
                continue
            trace = _trace_of(ev)
            if trace is None:
                continue
            # bind point INSIDE the slice (perfetto attaches a flow
            # event to the slice containing its ts on that track)
            mid = float(ev["ts"]) + max(0.0, float(ev.get("dur", 0)) / 2)
            by_trace.setdefault(trace, []).append(
                (mid, rank, int(ev.get("tid", 0))))

    flows = cross = 0
    for trace, sites in sorted(by_trace.items()):
        if len(sites) < 2:
            continue
        sites.sort()
        pids = {pid for _, pid, _ in sites}
        is_cross = len(pids) > 1
        for i, (ts, pid, tid) in enumerate(sites):
            ph = ("s" if i == 0
                  else "f" if i == len(sites) - 1 else "t")
            fev = {"ph": ph, "cat": "trace", "name": "trace",
                   "id": trace, "pid": pid, "tid": tid,
                   "ts": round(ts, 3)}
            if ph == "f":
                fev["bp"] = "e"     # bind to enclosing slice
            events.append(fev)
            flows += 1
        if is_cross:
            cross += 1
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": {"stitched_ranks": sorted(r for r, _, _ in anchors),
                        "clock_origin_unix_s": base}}
    summary = {"ranks": len(anchors), "events": len(events),
               "flow_events": flows, "cross_rank_flows": cross}
    if unanchored:
        summary["unanchored_ranks"] = unanchored
    return doc, summary


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank chrome traces into one "
                    "Perfetto-loadable cluster timeline with "
                    "cross-rank flow events")
    ap.add_argument("traces", nargs="+", metavar="TRACE_JSON",
                    help="per-rank chrome-trace files "
                         "(obs.export_chrome_trace output)")
    ap.add_argument("-o", "--out", default="cluster_trace.json",
                    help="stitched output path (default: "
                         "cluster_trace.json)")
    args = ap.parse_args(argv)
    docs = []
    for p in args.traces:
        with open(p, encoding="utf-8") as fh:
            docs.append(json.load(fh))
    doc, summary = stitch(docs)
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    summary["out"] = args.out
    print(json.dumps(summary))
    return 0 if summary["cross_rank_flows"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
