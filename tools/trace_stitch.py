"""Stitch per-rank chrome traces into ONE Perfetto-loadable cluster
timeline with cross-rank flow events.

Each rank's ``obs.export_chrome_trace`` document is self-relative: ts=0
is that process's import instant. The export metadata carries the
wall-clock anchor (``clock_origin_unix_s``) and the rank, so stitching
is: shift every rank's events onto the earliest rank's axis, set
pid=rank (named via process_name metadata), and draw chrome flow events
(``ph: s/t/f``) through every span set that shares a trace id — the
64-bit ids the runners mint per step, the mesh carries in its frame
headers, and the serving codec carries in its request dicts. A mesh
exchange then renders as an arrow from the sender's ``mesh_exchange``
slice to the owner rank's ``mesh_recv_part`` slice; a serving pull as
client span -> replica span.

Round 20 extends the id plumbing to two more planes (the stitcher
itself is name-agnostic — flows bind by trace id, so these stitch with
no changes here):

  * serving fleet: a FleetClient coalescer mints ONE id per flight —
    the ``fleet_pull_flight`` span, the underlying
    ``serving_pull_client`` span and the replica's ``serving_pull``
    span share it, so a coalesced window (N waiters in, one RPC out)
    reads as one timeline;
  * streaming: the runner sets a per-micro-pass-window trace
    (step_trace_id of rank/window), every boundary span
    (streaming_wait_ingest/feed_pass/publish/micro_checkpoint) carries
    it, and the journal's watermark record forwards it to the serving
    tailer's ``journal_watermark_apply`` marker — ONE stitched
    timeline spans ingest -> train -> journal -> pull.

Clock caveat: the anchors come from ``time.time()`` per process — exact
enough on one box (the 2-4 process clusters this repro runs); across
machines the stitch inherits NTP skew, which offsets slices but keeps
the flow arrows (they bind by id, not by time).

POSTMORTEM mode (round 18): an input that is a DIRECTORY is read as a
flight-recorder dir (obs/flight.py) — the per-rank segment files'
``spans`` records (windows of ended spans the recorder lands at report
cadence, flushed per record so they survive SIGKILL) reconstruct one
chrome-trace document per rank found in the dir. Span stamps in the
segments are raw ``perf_counter`` values; each rank's wall anchor is
estimated from the records' own wall ``ts`` (a spans record is written
moments after its newest span ended, so ``min(record_ts - newest_t1)``
over all records bounds the perf-epoch's wall instant from above,
tightly). Live chrome exports and flight dirs mix freely on one command
line; the exit contract is unchanged.

Usage:
    python tools/trace_stitch.py trace_r0.json trace_r1.json ... \
        [-o cluster_trace.json]
    python tools/trace_stitch.py /path/to/flight_dir \
        [-o cluster_trace.json]        # postmortem, no live export needed

Prints one JSON summary line: ranks, events, flows, cross_rank_flows.
Exits 1 when the inputs produce no cross-rank flow at all (a stitched
timeline without a single correlation usually means trace ids are not
flowing — the failure this tool exists to catch).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple


def _trace_of(ev: dict) -> Optional[str]:
    args = ev.get("args")
    if isinstance(args, dict):
        t = args.get("trace")
        if isinstance(t, str) and t:
            return t
    return None


def docs_from_flight_dir(path: str) -> List[dict]:
    """Flight-recorder dir → one chrome-trace document per rank, built
    from the segments' ``spans`` records (the postmortem path: works on
    whatever a SIGKILL'd fleet left flushed on disk).

    Span t0 stamps are raw perf_counter values, so each doc's
    ``clock_origin_unix_s`` (the wall instant of perf_counter()==0) is
    estimated from the records themselves: a spans record's wall ``ts``
    was taken just AFTER its newest span's t1, so ts - max_t1 >= origin
    and the minimum over records is a tight upper bound (slack = the
    smallest record-write delay, microseconds on one box)."""
    by_rank: Dict[int, List[dict]] = {}
    for seg in sorted(glob.glob(os.path.join(path, "flight_r*_*.jsonl"))):
        with open(seg, encoding="utf-8", errors="replace") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue        # torn tail of a killed rank
                if rec.get("type") == "spans":
                    by_rank.setdefault(int(rec.get("rank", 0)),
                                       []).append(rec)
    docs = []
    for rank in sorted(by_rank):
        recs = by_rank[rank]
        origin = None
        events: List[dict] = []
        seen_tids = set()
        for rec in recs:
            spans = rec.get("spans") or []
            newest_t1 = 0.0
            for name, tid, t0, dur_ms, trace in spans:
                t0 = float(t0)
                dur_ms = float(dur_ms)
                newest_t1 = max(newest_t1, t0 + dur_ms / 1e3)
                if tid not in seen_tids:
                    seen_tids.add(tid)
                    events.append({"ph": "M", "name": "thread_name",
                                   "pid": rank, "tid": int(tid),
                                   "args": {"name": "tid%d" % int(tid)}})
                ev = {"ph": "X", "cat": "obs", "name": name,
                      "pid": rank, "tid": int(tid),
                      "ts": round(t0 * 1e6, 3),
                      "dur": round(dur_ms * 1e3, 3)}
                if trace:
                    ev["args"] = {"trace": trace}
                events.append(ev)
            if spans and "ts" in rec:
                est = float(rec["ts"]) - newest_t1
                origin = est if origin is None else min(origin, est)
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "metadata": {"rank": rank, "postmortem": True}}
        if origin is not None:
            doc["metadata"]["clock_origin_unix_s"] = origin
        docs.append(doc)
    return docs


def stitch(docs: List[dict]) -> Tuple[dict, dict]:
    """Merge chrome-trace documents into one; returns (stitched_doc,
    summary). Rank comes from each doc's metadata (fallback: input
    order); events shift onto the earliest clock origin."""
    anchors = []
    for i, doc in enumerate(docs):
        meta = doc.get("metadata") or {}
        rank = int(meta.get("rank", i))
        origin = meta.get("clock_origin_unix_s")
        anchors.append((rank, float(origin) if origin is not None
                        else None, doc))
    # docs without an anchor (pre-round-14 exports) stay UNSHIFTED on
    # the base axis — treating a missing anchor as unix 0 would shift
    # every anchored rank by decades of microseconds
    present = [o for _, o, _ in anchors if o is not None]
    base = min(present) if present else 0.0
    unanchored = sorted(r for r, o, _ in anchors if o is None)

    events: List[dict] = []
    # trace id -> [(ts_mid, pid, tid)] across every rank
    by_trace: Dict[str, List[Tuple[float, int, int]]] = {}
    for rank, origin, doc in anchors:
        shift_us = ((origin - base) * 1e6 if origin is not None else 0.0)
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "args": {"name": "rank %d" % rank}})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + shift_us, 3)
            events.append(ev)
            if ev.get("ph") != "X":
                continue
            trace = _trace_of(ev)
            if trace is None:
                continue
            # bind point INSIDE the slice (perfetto attaches a flow
            # event to the slice containing its ts on that track)
            mid = float(ev["ts"]) + max(0.0, float(ev.get("dur", 0)) / 2)
            by_trace.setdefault(trace, []).append(
                (mid, rank, int(ev.get("tid", 0))))

    flows = cross = 0
    for trace, sites in sorted(by_trace.items()):
        if len(sites) < 2:
            continue
        sites.sort()
        pids = {pid for _, pid, _ in sites}
        is_cross = len(pids) > 1
        for i, (ts, pid, tid) in enumerate(sites):
            ph = ("s" if i == 0
                  else "f" if i == len(sites) - 1 else "t")
            fev = {"ph": ph, "cat": "trace", "name": "trace",
                   "id": trace, "pid": pid, "tid": tid,
                   "ts": round(ts, 3)}
            if ph == "f":
                fev["bp"] = "e"     # bind to enclosing slice
            events.append(fev)
            flows += 1
        if is_cross:
            cross += 1
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": {"stitched_ranks": sorted(r for r, _, _ in anchors),
                        "clock_origin_unix_s": base}}
    summary = {"ranks": len(anchors), "events": len(events),
               "flow_events": flows, "cross_rank_flows": cross}
    if unanchored:
        summary["unanchored_ranks"] = unanchored
    return doc, summary


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-rank chrome traces into one "
                    "Perfetto-loadable cluster timeline with "
                    "cross-rank flow events")
    ap.add_argument("traces", nargs="+", metavar="TRACE_JSON_OR_DIR",
                    help="per-rank chrome-trace files "
                         "(obs.export_chrome_trace output) and/or "
                         "flight-recorder dirs (postmortem mode: "
                         "per-rank docs rebuilt from the segments' "
                         "spans records)")
    ap.add_argument("-o", "--out", default="cluster_trace.json",
                    help="stitched output path (default: "
                         "cluster_trace.json)")
    args = ap.parse_args(argv)
    docs = []
    for p in args.traces:
        if os.path.isdir(p):
            found = docs_from_flight_dir(p)
            if not found:
                print(json.dumps({"error": "no spans records under "
                                           "flight dir %s" % p}))
                return 2
            docs.extend(found)
            continue
        with open(p, encoding="utf-8") as fh:
            docs.append(json.load(fh))
    doc, summary = stitch(docs)
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    summary["out"] = args.out
    print(json.dumps(summary))
    return 0 if summary["cross_rank_flows"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
