"""Pass 8 — interprocedural lock-order deadlock graph (BX7xx).

Classic AB/BA detection on static lock identities: every time code
holding lock A acquires lock B — directly (nested ``with``) or through
any chain of package calls — the graph gains edge A->B. A cycle means
two threads entering the cycle at different nodes can each hold the lock
the other needs: the textbook deadlock the reference avoided by a fixed
C++ lock hierarchy around the shared hash table (BoxPS's one
thread-per-GPU discipline), and the shape our six threaded planes (mesh,
ingest, serving, obs, journal, flight) can now only avoid by convention.

Identities are ``Class._attr`` / ``module._NAME`` (instances conflated —
the standard static approximation; the runtime twin
``utils/lockwatch.py`` confirms real per-instance orders under the
concurrency suites using the same identity vocabulary). Self-edges are
NOT flagged here: same-identity nesting across *different* instances
(per-shard locks in a loop) is a common legitimate pattern, direct
same-instance re-entry is BX401's ``*_locked`` convention, and the
runtime twin sees the truth. RLock edges stay in the graph — reentrancy
helps one thread, not an AB/BA pair of threads.

The full nesting inventory (every edge with one witness site + call
chain) is an operator artifact: ``python -m tools.boxlint --lock-graph``
writes it to ``tools/boxlint/lock_graph.txt`` (committed, so review sees
ordering changes as diffs).

Codes:
  BX701  cycle in the interprocedural lock-acquisition graph
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.callgraph import (FuncNode, PackageIndex, chain_str,
                                     get_index)

_EXEMPT_PARTS = {"tools", "tests", "examples"}

# edge -> witness: (rel, line, holder qual, chain to inner acquisition)
Edges = Dict[Tuple[str, str], Tuple[str, int, str, Tuple[str, ...]]]


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def collect_edges(files: Sequence[SourceFile]) -> Edges:
    index = get_index(files)
    lock_sum = index.lock_closure()
    edges: Edges = {}
    for node in index.nodes:
        if _exempt(node.file.rel):
            continue
        body = getattr(node.fn, "body", None)
        if not isinstance(body, list):
            continue
        for stmt in body:
            _walk(node, stmt, frozenset(), index, lock_sum, edges)
    return edges


def _add_edge(edges: Edges, outer: str, inner: str, rel: str, line: int,
              qual: str, chain: Tuple[str, ...]) -> None:
    if outer == inner:
        return  # self-nesting: see module docstring
    key = (outer, inner)
    cur = edges.get(key)
    # deterministic witness: shortest chain, then lowest (rel, line)
    cand = (len(chain), rel, line)
    if cur is None or cand < (len(cur[3]), cur[0], cur[1]):
        edges[key] = (rel, line, qual, chain)


def _walk(node: FuncNode, stmt: ast.AST, held: frozenset,
          index: PackageIndex, lock_sum: Dict[int, Dict[str, Tuple]],
          edges: Edges) -> None:
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return
    if isinstance(stmt, ast.With):
        acquired = [ident for _, ident, _ in index.with_locks(stmt, node)]
        for h in held:
            for a in acquired:
                _add_edge(edges, h, a, node.file.rel, stmt.lineno,
                          node.qual, ())
        inner = held | set(acquired)
        for item in stmt.items:
            _check_calls(node, item.context_expr, held, lock_sum, edges)
        for s in stmt.body:
            _walk(node, s, inner, index, lock_sum, edges)
        return
    _STMT_LIKE = (ast.stmt, ast.ExceptHandler, ast.match_case)
    for c in ast.iter_child_nodes(stmt):
        if isinstance(c, _STMT_LIKE):
            _walk(node, c, held, index, lock_sum, edges)
        elif held:
            _check_calls(node, c, held, lock_sum, edges)


def _check_calls(node: FuncNode, expr: ast.AST, held: frozenset,
                 lock_sum: Dict[int, Dict[str, Tuple]],
                 edges: Edges) -> None:
    if not held or expr is None:
        return
    for sub in ast.walk(expr):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        if not isinstance(sub, ast.Call):
            continue
        for callee in node.call_map.get(id(sub), []):
            for ident, (_l, _re, chain) in lock_sum.get(
                    id(callee), {}).items():
                for h in held:
                    _add_edge(edges, h, ident, node.file.rel, sub.lineno,
                              node.qual, (callee.qual,) + chain)


def _cycles(edges: Edges) -> List[List[str]]:
    """Strongly connected components with >1 node (Tarjan), each returned
    as a sorted identity list."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index_of: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (the graph is small, but recursion depth is
        # not worth betting on)
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index_of[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index_of:
                    index_of[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index_of[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index_of[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index_of:
            strongconnect(v)
    return sorted(sccs)


def check(files: Sequence[SourceFile]) -> List[Violation]:
    edges = collect_edges(files)
    out: List[Violation] = []
    for comp in _cycles(edges):
        comp_set = set(comp)
        witness_edges = sorted(
            (a, b) for (a, b) in edges
            if a in comp_set and b in comp_set)
        rel, line, qual, chain = edges[witness_edges[0]]
        ring = " -> ".join(comp + [comp[0]])
        sites = "; ".join(
            f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
            for a, b in witness_edges[:4])
        out.append(Violation(
            rel, line, "BX701",
            f"potential AB/BA deadlock: lock-order cycle {ring} "
            f"({sites}) — pick one global order (see "
            f"tools/boxlint/lock_graph.txt) or split the critical "
            f"sections"))
    return out


def render_inventory(files: Sequence[SourceFile]) -> str:
    """The full nesting inventory artifact (every edge, one witness)."""
    edges = collect_edges(files)
    lines = [
        "# Interprocedural lock-nesting inventory (boxlint BX7xx).",
        "# outer -> inner : witness site (holder function[, via chain])",
        "# Regenerate with: python -m tools.boxlint --lock-graph "
        "paddlebox_tpu/",
        "# An edge means: code holding `outer` acquires `inner`. Cycles",
        "# here are BX701 violations; this file is the committed record",
        "# of the repo's global lock order.",
        "",
    ]
    for (a, b) in sorted(edges):
        rel, line, qual, chain = edges[(a, b)]
        lines.append(f"{a} -> {b} : {rel}:{line} in {qual}"
                     f"{chain_str(chain)}")
    lines.append("")
    lines.append(f"# {len(edges)} edges, "
                 f"{len(_cycles(edges))} cycles")
    return "\n".join(lines) + "\n"
