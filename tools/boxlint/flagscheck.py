"""Pass 3 — flag registry hygiene (BX3xx).

The reference's gflags tier made flags a closed registry: every
``FLAGS_x`` read linked against a ``PADDLE_DEFINE_EXPORTED_*`` or the
build failed, and ``--help`` enumerated everything. Our
``config/flags.py`` registry is runtime-only, so a typo'd
``get_flag("incremental_pas")`` is a KeyError in production and a flag
nobody reads anymore silently rots with its env override. This pass
closes the registry statically.

Codes:
  BX301  get_flag/set_flag of a name no define_flag declares
  BX302  declared flag never read by any get_flag in the tree (dead flag)
  BX303  define_flag with an empty help string
  BX304  duplicate flag name / env-name collision (PBTPU_<UPPER> space)
  BX305  define_flag/get_flag with a non-literal name (unauditable)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.purity import dotted

_DECL_FILE_SUFFIX = "config/flags.py"


def _literal_name(call: ast.Call) -> Tuple[object, bool]:
    """(name, is_literal) for the first arg / name= kwarg."""
    arg = None
    if call.args:
        arg = call.args[0]
    else:
        for kw in call.keywords:
            if kw.arg == "name":
                arg = kw.value
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, True
    return None, arg is None


def _help_arg(call: ast.Call) -> Tuple[object, bool]:
    """(help_value, present) — 3rd positional or help= kwarg."""
    if len(call.args) >= 3:
        a = call.args[2]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value, True
        return None, True  # non-literal help: assume intentional
    for kw in call.keywords:
        if kw.arg == "help":
            if isinstance(kw.value, ast.Constant):
                return kw.value.value, True
            return None, True
    return "", False


def check(files: Sequence[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    declared: Dict[str, Tuple[str, int]] = {}   # name -> (file, line)
    env_names: Dict[str, Tuple[str, str, int]] = {}  # env -> (flag, file, line)
    reads: Set[str] = set()
    read_sites: List[Tuple[SourceFile, ast.Call, str, str]] = []

    for f in files:
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if not d:
                continue
            tail = d.split(".")[-1]
            if tail == "define_flag":
                name, lit = _literal_name(node)
                if not lit or name is None:
                    out.append(Violation(
                        f.rel, node.lineno, "BX305",
                        "define_flag with a non-literal name: the registry "
                        "is unauditable statically"))
                    continue
                if name in declared:
                    # cross-reference by file only: embedding the other
                    # site's line number would defeat the baseline's
                    # line-drift-immune matching (Violation.key)
                    df, _dl = declared[name]
                    out.append(Violation(
                        f.rel, node.lineno, "BX304",
                        f"flag {name!r} already declared in {df}"))
                else:
                    declared[name] = (f.rel, node.lineno)
                env = "PBTPU_" + str(name).upper()
                if env in env_names and env_names[env][0] != name:
                    of, off, _ofl = env_names[env]
                    out.append(Violation(
                        f.rel, node.lineno, "BX304",
                        f"flag {name!r} env name {env} collides with flag "
                        f"{of!r} ({off})"))
                else:
                    env_names.setdefault(env, (str(name), f.rel, node.lineno))
                hlp, present = _help_arg(node)
                if isinstance(hlp, str) and not hlp.strip():
                    out.append(Violation(
                        f.rel, node.lineno, "BX303",
                        f"flag {name!r} has an empty help string (the "
                        f"gflags --help contract: every flag documents "
                        f"itself)"))
            elif tail in ("get_flag", "set_flag"):
                name, lit = _literal_name(node)
                if name is None:
                    if not lit:
                        out.append(Violation(
                            f.rel, node.lineno, "BX305",
                            f"{tail} with a non-literal flag name: cannot "
                            f"be checked against the registry"))
                    continue
                read_sites.append((f, node, tail, str(name)))
                if tail == "get_flag":
                    reads.add(str(name))

    have_decl_file = any(f.rel.endswith(_DECL_FILE_SUFFIX) for f in files)
    for f, node, tail, name in read_sites:
        if name not in declared and have_decl_file:
            out.append(Violation(
                f.rel, node.lineno, "BX301",
                f"{tail}({name!r}) reads a flag config/flags.py never "
                f"declares (KeyError at runtime)"))
    if have_decl_file:
        for name, (df, dl) in sorted(declared.items()):
            if name not in reads:
                out.append(Violation(
                    df, dl, "BX302",
                    f"flag {name!r} is declared but never read by any "
                    f"get_flag in the analyzed tree (dead flag — delete "
                    f"it or wire it up)"))
    return out
