"""boxlint: repo-specific AST invariant checker.

The reference enforced this repo's load-bearing invariants mechanically —
the static graph verified op purity at build time, gflags collected every
flag into one registry (flags.cc), NCCL comm groups type-checked collective
membership, and C++ lock types documented which mutex guards which member.
The JAX port replaces all four mechanisms with conventions, and conventions
drift. boxlint is the lint gate that makes them mechanical again:

  BX1xx  jit-purity / static-shape: functions reachable from jax.jit /
         shard_map / lax.scan entry points must not host-sync (.item(),
         float()/int() on traced values, np.* on traced data,
         jax.device_get, print) or build data-dependent shapes
         (jnp.unique / nonzero without size=, boolean-mask indexing).
  BX2xx  collective-axis contracts: every lax.psum / all_to_all / ppermute
         / all_gather / pmean axis name must resolve to an axis declared
         by a Mesh / shard_map / PartitionSpec somewhere in the tree
         (parallel/mesh.py is the canonical declaration site).
  BX3xx  flag-registry hygiene: every flags.get_flag("x") resolves to a
         define_flag in config/flags.py, every declared flag is read
         somewhere, help strings are non-empty, env names are unique.
  BX4xx  lock discipline: attributes annotated ``# guarded-by: <lock>``
         must only be touched inside ``with self.<lock>:`` (outside
         __init__); deliberate lock-free boundary accesses carry an
         inline ``# boxlint: disable=BX401`` with a rationale.
  BX5xx  library print() hygiene: bare ``print(`` in paddlebox_tpu/
         library code must go through the rank-prefixed structured
         logging layer (obs/log.py) instead; tools/tests/examples are
         exempt (stdout is their contract). BX502 extends it to span
         discipline (a bare ``tracer.span(...)`` records nothing);
         BX503 to silent ``except Exception: pass`` swallows (log a
         counted warning or write a rationale comment).
  BX6xx  blocking-under-lock (round 19, interprocedural): from every
         ``with <lock>:`` body, transitive reach — through the
         package-wide call graph (callgraph.py) — into the curated
         blocking-sink list (sinks.py: socket ops, framed RPC/TcpStore
         via closure, channel get/put, time.sleep, bare join(),
         subprocess, fsync, cond/event waits, the trapezoid-AUC math)
         flags at the call site with the chain.
  BX7xx  lock-order deadlock graph: interprocedural lock-acquisition
         edges on ``Class._attr`` identities; cycles are potential
         AB/BA deadlocks; the full nesting inventory is the committed
         ``lock_graph.txt`` artifact (--lock-graph). The runtime twin
         (utils/lockwatch.py, flag debug_lock_order) validates the same
         identities dynamically under the concurrency suites.
  BX8xx  handler reentrancy: code reachable from sys/threading
         excepthooks, signal handlers, the watchdog fire path or
         ``__del__`` must not acquire a non-reentrant lock that
         non-handler code also takes (BX801 — the PR-9 seal-deadlock
         shape) nor call a blocking sink without a timeout (BX802).

Suppression: ``# boxlint: disable=BX101[,BX102]`` (or a bare ``disable``)
on the offending line, or on a ``def``/``class`` line to cover the whole
body. Pre-existing violations live in tools/boxlint/baseline.txt; the gate
(tests/test_boxlint.py) fails only on NEW violations.

CLI: ``python -m tools.boxlint [--baseline FILE] [--fix-baseline]
[--changed] [--no-cache] [--lock-graph] [--suggest-guards] PATH...``
An exact content-hash result cache (cache.py, gitignored .cache.json)
replays unchanged-tree runs in ~0.1s; ``--changed`` restricts the
per-file passes + reporting to the files differing from HEAD (or
``--changed-base REF``); ``--suggest-guards`` emits candidate
``# guarded-by:`` annotations for attrs touched >=90% under one lock.
"""

from tools.boxlint.core import (  # noqa: F401
    Violation, SourceFile, load_tree, run_passes, load_baseline,
    diff_against_baseline, format_baseline, ALL_PASSES,
)
