"""Pass 6 — span context-manager discipline (BX502).

``tracer.span("name")`` / ``obs.span("name")`` / ``obs_span("name")``
return a context manager; only ``__exit__`` records the span. Used as a
bare expression statement the call allocates the manager, times
nothing, records NOTHING, and raises nothing — the instrumentation
silently vanishes, which is the worst failure mode an observability
plane can have (round-14 satellite; the BX501 sibling keeps print()
out, this keeps span() honest).

Flagged: an ``ast.Expr`` statement whose value is a call to a name or
attribute literally called ``span`` or ``obs_span``. Legitimate uses —
``with ... :``, storing the manager for a later ``with``, passing it as
an argument — are not expression statements and never flag.
``record_span(...)`` (the post-hoc form) is a different name and is
exempt by construction.

Codes:
  BX502  tracer.span(...) as a bare expression — records nothing; use
         ``with`` (or record_span for post-hoc stamps)
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from tools.boxlint.core import SourceFile, Violation

_SPAN_NAMES = {"span", "obs_span"}


def _is_span_call(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Name):
        return func.id in _SPAN_NAMES
    if isinstance(func, ast.Attribute):
        return func.attr in _SPAN_NAMES
    return False


def check(files: Sequence[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for f in files:
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)
                    and _is_span_call(node.value)):
                out.append(Violation(
                    f.rel, node.lineno, "BX502",
                    "span(...) used as a bare expression records "
                    "NOTHING — enter it ('with tracer.span(...):') or "
                    "use record_span for post-hoc stamps"))
    return out
