"""Pass — donation contract at jit entry call sites (BX921).

The static twin of the PR-15 donation audit: ``InstrumentedJit`` keeps
the donated buffers' pointers and (debounced) alarms when a donated
input is still referenced after the call. That only fires after the
deleted-buffer error or the silent copy already happened in a real run;
this pass proves the two contract breaches at the call site:

  * **donated buffer read after the call** — an argument at a
    ``donate_argnums`` position whose name is read again after the call
    without being rebound first (including the next iteration of an
    enclosing loop: a donated arg that the loop never rebinds is read
    again at the top of the next pass through);
  * **step-shaped call without donation** — a call that rebinds its own
    ``state``/``params``-shaped arguments (``self.params, self.opt_state
    = step(self.params, self.opt_state, ...)``) against an entry that
    declares NO donation at all: the input buffers are provably dead
    after the statement, so not donating doubles the peak footprint of
    every step (the exact miss class the runtime audit debounces).
    Entries that already donate SOME positions made a reviewed choice
    and stay clean.

Reads/rebinds are matched on the dotted spelling of the argument
(``self.params`` / ``params``), line-ordered within the function — the
same approximation the donation audit validates dynamically.

Codes:
  BX921  donation contract breach at a jit entry call site
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile, Violation
from tools.boxlint.callgraph import FuncNode, get_index
from tools.boxlint.purity import dotted
from tools.boxlint.taint import JitEntry, get_contracts

_EXEMPT_PARTS = {"tools", "tests", "examples"}

# argument spellings whose rebind marks a step-shaped call: the training
# state that every step consumes and reproduces
_STATE_HINTS = ("param", "state", "slab", "opt")


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def check(files: Sequence[SourceFile]) -> List[Violation]:
    index = get_index(files)
    c = get_contracts(files)
    out: List[Violation] = []
    for node in index.nodes:
        if _exempt(node.file.rel):
            continue
        local = c._local_jits(node, direct_only=False)
        own = index._own_statement_ids(node)
        reads, rebinds = _name_sites(node, own)
        for sub in ast.walk(node.fn):
            if id(sub) not in own or not isinstance(sub, ast.Call):
                continue
            entry = c.entry_for_call(sub, node, local)
            if entry is None:
                continue
            stmt = _enclosing_stmt(node, sub)
            if entry.donate:
                _check_donated_reads(node, sub, stmt, entry, reads,
                                     rebinds, out)
            else:
                _check_step_shape(node, sub, stmt, entry, out)
    return out


def _name_sites(node: FuncNode, own: Set[int]
                ) -> Tuple[Dict[str, List[int]], Dict[str, List[int]]]:
    """Dotted name -> sorted lines of loads / stores in this function."""
    reads: Dict[str, List[int]] = {}
    rebinds: Dict[str, List[int]] = {}
    for sub in ast.walk(node.fn):
        if id(sub) not in own:
            continue
        if isinstance(sub, ast.Call) and isinstance(sub.func,
                                                    ast.Attribute) \
                and sub.func.attr.startswith("set_") and sub.args:
            # setter convention: ``table.set_slab(x)`` rebinds
            # ``table.slab`` — the functional-state classes expose their
            # buffer through a read property + set_<name> writer
            recv = dotted(sub.func.value)
            if recv:
                rebinds.setdefault(
                    f"{recv}.{sub.func.attr[4:]}", []).append(sub.lineno)
        if isinstance(sub, (ast.Name, ast.Attribute)):
            d = dotted(sub)
            if not d:
                continue
            ctx = getattr(sub, "ctx", None)
            if isinstance(ctx, ast.Store):
                rebinds.setdefault(d, []).append(sub.lineno)
            elif isinstance(ctx, ast.Load):
                reads.setdefault(d, []).append(sub.lineno)
    for k in reads:
        reads[k].sort()
    for k in rebinds:
        rebinds[k].sort()
    return reads, rebinds


def _enclosing_stmt(node: FuncNode, call: ast.Call) -> Optional[ast.stmt]:
    best: Optional[ast.stmt] = None
    for sub in ast.walk(node.fn):
        if isinstance(sub, ast.stmt) and sub.lineno <= call.lineno and \
                (sub.end_lineno or sub.lineno) >= (call.end_lineno
                                                   or call.lineno):
            if best is None or sub.lineno >= best.lineno:
                best = sub
    return best


def _enclosing_loop(node: FuncNode, call: ast.Call
                    ) -> Optional[ast.stmt]:
    best = None
    for sub in ast.walk(node.fn):
        if isinstance(sub, (ast.For, ast.While, ast.AsyncFor)) and \
                sub.lineno <= call.lineno and \
                (sub.end_lineno or sub.lineno) >= call.lineno:
            if best is None or sub.lineno >= best.lineno:
                best = sub
    return best


def _check_donated_reads(node: FuncNode, call: ast.Call,
                         stmt: Optional[ast.stmt], entry: JitEntry,
                         reads: Dict[str, List[int]],
                         rebinds: Dict[str, List[int]],
                         out: List[Violation]) -> None:
    stmt_end = (stmt.end_lineno or stmt.lineno) if stmt is not None \
        else (call.end_lineno or call.lineno)
    stmt_targets: Set[str] = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            for e in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                      else [t]):
                d = dotted(e)
                if d:
                    stmt_targets.add(d)
    loop = _enclosing_loop(node, call)
    for pos in entry.donate:
        if pos >= len(call.args):
            continue
        d = dotted(call.args[pos])
        if not d:
            continue
        if d in stmt_targets:
            # rebound by the call's own statement — safe, and in a loop
            # the rebind lands before the next iteration's read too
            continue
        # read after the statement, before any rebind?
        later_reads = [ln for ln in reads.get(d, []) if ln > stmt_end]
        later_rebinds = [ln for ln in rebinds.get(d, []) if ln > stmt_end]
        if later_reads and (not later_rebinds
                            or later_reads[0] <= later_rebinds[0]):
            out.append(Violation(
                node.file.rel, call.lineno, "BX921",
                f"donated buffer `{d}` (donate_argnums position {pos} of "
                f"jit entry {entry.describe()}) is read again at line "
                f"{later_reads[0]} without a rebind — the buffer is "
                f"deleted (or silently copied) after the call; rebind it "
                f"from the result or drop the donation"))
            continue
        if loop is not None:
            in_loop_rebinds = [
                ln for ln in rebinds.get(d, [])
                if loop.lineno <= ln <= (loop.end_lineno or loop.lineno)]
            if not in_loop_rebinds:
                out.append(Violation(
                    node.file.rel, call.lineno, "BX921",
                    f"donated buffer `{d}` (donate_argnums position "
                    f"{pos} of jit entry {entry.describe()}) is never "
                    f"rebound inside the enclosing loop — the next "
                    f"iteration reads the deleted buffer; rebind it from "
                    f"the call result"))


def _check_step_shape(node: FuncNode, call: ast.Call,
                      stmt: Optional[ast.stmt], entry: JitEntry,
                      out: List[Violation]) -> None:
    if not isinstance(stmt, ast.Assign):
        return
    targets: Set[str] = set()
    for t in stmt.targets:
        for e in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                  else [t]):
            d = dotted(e)
            if d:
                targets.add(d)
    rebound = []
    for i, arg in enumerate(call.args):
        d = dotted(arg)
        if d and d in targets and any(
                h in d.split(".")[-1].lower() for h in _STATE_HINTS):
            rebound.append((i, d))
    if rebound:
        names = ", ".join(f"`{d}` (pos {i})" for i, d in rebound)
        out.append(Violation(
            node.file.rel, call.lineno, "BX921",
            f"step-shaped call rebinds its own argument{'s' if len(rebound) > 1 else ''} "
            f"{names} but jit entry {entry.describe()} declares no "
            f"donation — the input buffers are dead after this "
            f"statement, so the step holds two copies of the state; "
            f"declare donate_argnums (the runtime donation audit "
            f"debounces exactly this miss)"))
