"""Pass 11 — jit entry-point registration (BX9xx).

The device plane (paddlebox_tpu/obs/device.py, round 20) only sees jit
entry points that were constructed through ``instrument_jit`` — a bare
``jax.jit(...)`` silently escapes the recompile sentinel, the donation
audit and the cost/memory snapshot, which is exactly how a new runner
re-opens the observability hole PRs 5/9/13 closed on the host side.
This pass makes the wrapper structurally unavoidable: any appearance of
the ``jax.jit`` attribute in library code is a violation — the direct
call form, the ``@jax.jit`` decorator form, and the
``functools.partial(jax.jit, ...)`` argument form all contain the same
AST node, so one Attribute detector covers those spellings; the
detector also resolves ``import jax as <alias>`` receivers, and a
``from jax import jit`` (aliased or not) is flagged at the import line
itself — jits built from it carry no Attribute node at the call site.

Scope: the same library scope as BX501 (paths with a ``tools``,
``tests`` or ``examples`` component are exempt — probes and fixtures
legitimately build bare jits to compare against), plus the implementing
module itself (``obs/device.py`` IS the instrumentation layer; its two
``jax.jit`` sites carry per-line disables anyway, belt and braces).
Deliberate exceptions carry a per-line rationale:
``# boxlint: disable=BX901 (<why this jit must stay bare>)``.

Codes:
  BX901  bare jax.jit in library code (use obs.device.instrument_jit)
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from tools.boxlint.core import SourceFile, Violation

_EXEMPT_PARTS = {"tools", "tests", "examples"}


def _exempt(rel: str) -> bool:
    if rel.replace("\\", "/").endswith("obs/device.py"):
        return True  # the instrumentation layer itself
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


_MSG = ("bare jax.jit in library code — construct the entry "
        "point with obs.device.instrument_jit(fn, name, ...) "
        "so it joins the device plane (recompile sentinel, "
        "donation audit, cost/memory snapshot); a deliberate "
        "bare jit needs a per-line rationale disable")


def check(files: Sequence[SourceFile]) -> List[Violation]:
    out: List[Violation] = []
    for f in files:
        if _exempt(f.rel):
            continue
        # every local name that resolves to the jax module: the
        # Attribute detector must see aliased spellings too
        # (`import jax as j; j.jit`) or they'd escape the device plane
        jax_names = {"jax"}
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" and a.asname:
                        jax_names.add(a.asname)
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr == "jit"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in jax_names):
                out.append(Violation(f.rel, node.lineno, "BX901", _MSG))
            elif (isinstance(node, ast.ImportFrom)
                    and node.module == "jax" and node.level == 0
                    and any(a.name == "jit" for a in node.names)):
                # `from jax import jit` builds bare jits with no
                # Attribute node at the call sites — flag the import
                out.append(Violation(f.rel, node.lineno, "BX901", _MSG))
    return out
