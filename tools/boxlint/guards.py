"""``--suggest-guards``: candidate ``# guarded-by:`` annotations.

BX4xx only audits attributes someone already annotated — the opt-in is
deliberate (annotating declares "shared across threads"), but it means
coverage grows only as fast as hand care does. This analysis inverts it:
for every class that owns at least one lock, count each ``self.<attr>``
access outside ``__init__``/``__del__``/``__repr__`` and partition by
the lock(s) statically held at the access. An attribute touched >= 90%
under exactly one lock (with enough evidence: >= 4 accesses, >= 2 under
the lock) is either already lock-disciplined — annotate it, making the
discipline mechanical — or the stray accesses are latent races worth a
look. Either way the report line is actionable.

The committed artifact (``tools/boxlint/guard_suggestions.txt``,
regenerated per round) records the frontier: 100%-consistent rows are
annotation candidates; sub-100% rows name the exact outside-lock sites.

This is a report, not a pass — it emits no violations. Adding an
annotation from it immediately turns the stray sites into BX401s, which
is the point: suggestion -> annotation -> machine-checked forever.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from tools.boxlint.core import SourceFile
from tools.boxlint.callgraph import PackageIndex, get_index

_EXEMPT_METHODS = {"__init__", "__del__", "__repr__"}
_EXEMPT_PARTS = {"tools", "tests", "examples"}
_MIN_ACCESSES = 4
_MIN_LOCKED = 2
_THRESHOLD = 0.90


def _exempt(rel: str) -> bool:
    return bool(_EXEMPT_PARTS.intersection(rel.split("/")[:-1]))


def suggest(files: Sequence[SourceFile]) -> List[str]:
    index = get_index(files)
    rows: List[Tuple[str, str, str, int, int, List[int], str]] = []
    for name, class_list in sorted(index.classes.items()):
        for cn in class_list:
            if _exempt(cn.file.rel) or not cn.lock_attrs:
                continue
            rows.extend(_suggest_class(cn, index))
    out = []
    for cls, attr, lock, locked, total, stray, rel in rows:
        pct = 100.0 * locked / total
        where = ("" if not stray else
                 " stray at " + ",".join(str(s) for s in stray[:4])
                 + ("..." if len(stray) > 4 else ""))
        out.append(f"{rel}: {cls}.{attr} -> # guarded-by: {lock} "
                   f"({locked}/{total} accesses under it, {pct:.0f}%"
                   f"{where})")
    return out


def _suggest_class(cn, index: PackageIndex):
    f = cn.file
    # attrs assigned anywhere in the class, minus locks and annotated ones
    assigned: Set[str] = set()
    annotated: Set[str] = set()
    for sub in ast.walk(cn.node):
        if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in ("self", "cls")):
                    assigned.add(t.attr)
                    if (t.lineno in f.guarded_by
                            or (sub.end_lineno or 0) in f.guarded_by):
                        annotated.add(t.attr)
    candidates = assigned - annotated - set(cn.lock_attrs)
    if not candidates:
        return []
    # counts[attr] = {lock_identity_or "": [lines]}
    counts: Dict[str, Dict[str, List[int]]] = {}
    for item in cn.node.body:
        if (not isinstance(item, ast.FunctionDef)
                or item.name in _EXEMPT_METHODS):
            continue
        node = index.node_for(item)
        if node is None:
            continue
        for stmt in item.body:
            _walk(cn, node, stmt, frozenset(), index, candidates, counts)
    rows = []
    for attr in sorted(counts):
        by_lock = counts[attr]
        total = sum(len(v) for v in by_lock.values())
        if total < _MIN_ACCESSES:
            continue
        best_lock, best_lines = max(
            ((lk, ls) for lk, ls in by_lock.items() if lk),
            key=lambda kv: len(kv[1]), default=("", []))
        if not best_lock or len(best_lines) < _MIN_LOCKED:
            continue
        if len(best_lines) / total < _THRESHOLD:
            continue
        stray = sorted(ln for lk, ls in by_lock.items() if lk != best_lock
                       for ln in ls)
        # identity Class._attr -> the annotation names the bare attr
        lock_attr = best_lock.split(".")[-1]
        rows.append((cn.name, attr, lock_attr, len(best_lines), total,
                     stray, f.rel))
    return rows


def _walk(cn, node, stmt, held: frozenset, index: PackageIndex,
          candidates: Set[str],
          counts: Dict[str, Dict[str, List[int]]]) -> None:
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return
    if isinstance(stmt, ast.With):
        inner = held | {ident for _, ident, _ in
                        index.with_locks(stmt, node)}
        for item in stmt.items:
            _count_expr(cn, item.context_expr, held, candidates, counts)
        for s in stmt.body:
            _walk(cn, node, s, inner, index, candidates, counts)
        return
    _STMT_LIKE = (ast.stmt, ast.ExceptHandler, ast.match_case)
    for c in ast.iter_child_nodes(stmt):
        if isinstance(c, _STMT_LIKE):
            _walk(cn, node, c, held, index, candidates, counts)
        else:
            _count_expr(cn, c, held, candidates, counts)


def _count_expr(cn, expr, held: frozenset, candidates: Set[str],
                counts: Dict[str, Dict[str, List[int]]]) -> None:
    if expr is None:
        return
    for sub in ast.walk(expr):
        if (isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id in ("self", "cls")
                and sub.attr in candidates):
            key = sorted(held)[0] if len(held) == 1 else (
                "+".join(sorted(held)) if held else "")
            counts.setdefault(sub.attr, {}).setdefault(
                key, []).append(sub.lineno)


def render_report(files: Sequence[SourceFile]) -> str:
    lines = suggest(files)
    head = [
        "# guarded-by annotation candidates (boxlint --suggest-guards).",
        "# attr touched >=90% under ONE lock outside __init__: either",
        "# annotate it (BX4xx then machine-checks it forever) or audit",
        "# the stray sites it names — they are where the race would be.",
        "# Regenerate with: python -m tools.boxlint --suggest-guards "
        "paddlebox_tpu/",
        "",
    ]
    return "\n".join(head + (lines or ["# (no candidates at thresholds)"])
                     ) + "\n"
